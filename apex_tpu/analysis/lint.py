"""AST lint engine: JAX/TPU hazard rules over the whole package.

The engine parses each module once and hands every registered rule a
:class:`ModuleContext` carrying the pieces JAX-aware rules keep needing:

* an import-alias map so ``jnp.zeros`` / ``from jax import jit`` /
  ``from jax.experimental import pallas as pl`` all resolve to full
  dotted paths;
* the set of *traced roots* — functions that run under a tracer
  (``@jax.jit`` / ``pjit`` decorators, ``f = jax.jit(f)`` wraps,
  ``shard_map`` / ``pallas_call`` / ``grad`` / ``scan`` function
  arguments) — plus the jit binding call so rules can read
  ``static_argnums`` / ``donate_argnums``;
* a conservative "traced locals" dataflow for a root: parameters (minus
  literal ``static_argnums``/``static_argnames``) and anything assigned
  from ``jnp.*``-family calls or expressions over traced names, with
  ``x.shape``-style static attribute reads filtered out.

Inline suppression: ``# apex-lint: disable=APX104`` on the offending
line, or ``# apex-lint: skip-file`` near the top of a module.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, Iterator, Optional, Sequence

from apex_tpu.analysis.finding import Finding, assign_indices

__all__ = ["ModuleContext", "JitInfo", "lint_source", "lint_paths",
           "JIT_WRAPPERS", "TRACED_WRAPPERS"]

# Wrappers that make their function argument a *jit* boundary (donation,
# static_argnums semantics apply).
JIT_WRAPPERS = {
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
}

# Wrappers under which the function body runs traced — host syncs,
# prints, and Python branching on values are hazards inside ANY of
# these, not only jit.
TRACED_WRAPPERS = JIT_WRAPPERS | {
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "jax.experimental.pallas.pallas_call",
    "jax.grad",
    "jax.value_and_grad",
    "jax.vmap",
    "jax.checkpoint",
    "jax.remat",
    "jax.lax.scan",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.cond",
    "jax.lax.switch",
    "jax.lax.map",
    "jax.lax.associative_scan",
}

_PARTIAL = {"functools.partial", "partial"}

# Namespaces whose call results are traced values inside a traced root.
TRACED_NAMESPACE_PREFIXES = (
    "jax.numpy.", "jax.lax.", "jax.nn.", "jax.random.", "jax.scipy.",
    "jax.image.", "jax.experimental.pallas.",
)

# Calls into traced namespaces that nevertheless return *static* Python
# values (safe to branch on).
STATIC_FNS = {
    "jax.lax.axis_size",
    "jax.numpy.ndim",
    "jax.numpy.shape",
    "jax.numpy.result_type",
    "jax.numpy.issubdtype",
    "jax.numpy.promote_types",
    "jax.numpy.dtype",
    "jax.numpy.iinfo",
    "jax.numpy.finfo",
}

# Attribute reads on a traced array that are static metadata, not data.
STATIC_ATTRS = {
    "shape", "ndim", "dtype", "size", "itemsize", "nbytes", "sharding",
    "weak_type", "aval", "at",
    # FlatState's static shard-layout fields (flax.struct
    # pytree_node=False): reading them off a traced state is a
    # static-metadata read, same category as .shape/.dtype — branching
    # on them is a config branch.  Only the DISTINCTIVE names are
    # listed (not generic ones like `sizes`/`offsets`, which would
    # blanket-exempt those attribute reads on arbitrary objects and
    # silence true positives — the lint is AST-based, untyped).
    "shard", "shard_axis", "shard_dp", "shard_len", "global_numel",
    "padded_numel", "spans", "span_sizes", "span_padded",
}

_DISABLE_RE = re.compile(r"#\s*apex-lint:\s*disable=([A-Z0-9_,\s]+)")
_SKIP_FILE_RE = re.compile(r"#\s*apex-lint:\s*skip-file")


@dataclass
class JitInfo:
    """One traced-wrapper binding of a function-ish AST node."""
    node: ast.AST                      # FunctionDef / AsyncFunctionDef / Lambda
    wrapper: str                       # resolved dotted wrapper name
    binding: Optional[ast.Call] = None  # call carrying kwargs, None for bare @jax.jit
    # partial(f, a, b, kw=c) binds f's leading params / named params to
    # static Python values — they are NOT tracers inside the kernel
    partial_pos: int = 0
    partial_kws: frozenset = frozenset()

    @property
    def is_jit(self) -> bool:
        return self.wrapper in JIT_WRAPPERS

    def binding_kwarg(self, *names: str) -> Optional[ast.expr]:
        if self.binding is None:
            return None
        for kw in self.binding.keywords:
            if kw.arg in names:
                return kw.value
        return None

    def static_params(self) -> Optional[set]:
        """Literal static_argnums/static_argnames → set of param positions
        (int) and names (str).  None means "spec present but not a
        literal" (caller should go quiet rather than guess)."""
        out: set = set()
        for key in ("static_argnums", "static_argnames"):
            val = self.binding_kwarg(key)
            if val is None:
                continue
            try:
                lit = ast.literal_eval(val)
            except (ValueError, SyntaxError):
                return None
            if isinstance(lit, (int, str)):
                lit = (lit,)
            try:
                out.update(lit)
            except TypeError:
                return None
        return out


class ModuleContext:
    def __init__(self, source: str, path: str = "<string>"):
        self.source = source
        self.path = path
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.aliases = self._build_aliases()
        self._defs = self._collect_defs()
        self.jit_infos: list[JitInfo] = self._collect_traced_roots()
        self._traced_region: Optional[set] = None

    # -- imports / name resolution ------------------------------------

    def _build_aliases(self) -> dict:
        aliases: dict = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def resolve(self, node: Optional[ast.expr]) -> Optional[str]:
        """Best-effort dotted path for a Name/Attribute chain, through
        import aliases; None for anything else."""
        if isinstance(node, ast.Name):
            return self.aliases.get(node.id, node.id)
        if isinstance(node, ast.Attribute):
            base = self.resolve(node.value)
            return f"{base}.{node.attr}" if base else None
        return None

    # -- traced-root discovery ----------------------------------------

    def _collect_defs(self) -> dict:
        defs: dict = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node  # last definition wins
        return defs

    def _wrapper_of(self, fn_expr: ast.expr) -> Optional[str]:
        r = self.resolve(fn_expr)
        if r in TRACED_WRAPPERS:
            return r
        return None

    def _unwrap_partial(self, node: ast.expr) -> ast.expr:
        """partial(f, ...) -> f (one level is all the codebase uses)."""
        if isinstance(node, ast.Call) and \
                self.resolve(node.func) in _PARTIAL and node.args:
            return node.args[0]
        return node

    def _fnish(self, node: ast.expr):
        """-> (function-ish AST node, partial_pos, partial_kws) or None."""
        pos, kws = 0, frozenset()
        inner = self._unwrap_partial(node)
        if inner is not node and isinstance(node, ast.Call):
            pos = len(node.args) - 1
            kws = frozenset(kw.arg for kw in node.keywords if kw.arg)
            node = inner
        if isinstance(node, ast.Lambda):
            return node, pos, kws
        if isinstance(node, ast.Name):
            target = self._defs.get(node.id)
            if target is not None:
                return target, pos, kws
        return None

    def _collect_traced_roots(self) -> list:
        infos: list[JitInfo] = []
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    w = self._wrapper_of(dec)
                    if w:
                        infos.append(JitInfo(node, w))
                        continue
                    if isinstance(dec, ast.Call):
                        w = self._wrapper_of(dec.func)
                        if w:
                            infos.append(JitInfo(node, w, dec))
                            continue
                        inner = self._unwrap_partial(dec)
                        if inner is not dec:
                            w = self._wrapper_of(inner)
                            if w:
                                infos.append(JitInfo(node, w, dec))
            elif isinstance(node, ast.Call):
                w = self._wrapper_of(node.func) or (
                    self._wrapper_of(self._unwrap_partial(node.func))
                    if isinstance(node.func, ast.Call) else None)
                if not w:
                    continue
                for arg in node.args:
                    hit = self._fnish(arg)
                    if hit is not None:
                        target, pos, kws = hit
                        infos.append(JitInfo(target, w, node,
                                             partial_pos=pos,
                                             partial_kws=kws))
        return infos

    def traced_roots(self) -> list:
        """JitInfos deduped by root node (first binding wins)."""
        seen, out = set(), []
        for info in self.jit_infos:
            if id(info.node) not in seen:
                seen.add(id(info.node))
                out.append(info)
        return out

    def jit_bindings(self, node: ast.AST) -> list:
        return [i for i in self.jit_infos if i.node is node and i.is_jit]

    def traced_region(self) -> set:
        """ids of every AST node lexically under a traced root's body
        (decorators excluded)."""
        if self._traced_region is None:
            region: set = set()
            for info in self.traced_roots():
                body = info.node.body
                nodes = body if isinstance(body, list) else [body]
                for stmt in nodes:
                    for sub in ast.walk(stmt):
                        region.add(id(sub))
            self._traced_region = region
        return self._traced_region

    def iter_traced(self, *types) -> Iterator[ast.AST]:
        """Yield nodes of the given types inside any traced region, once
        each, in source order."""
        region = self.traced_region()
        seen = set()
        for node in ast.walk(self.tree):
            if id(node) in region and id(node) not in seen and \
                    (not types or isinstance(node, tuple(types))):
                seen.add(id(node))
                yield node

    # -- traced-value dataflow ----------------------------------------

    def traced_locals(self, info: JitInfo) -> set:
        """Names holding traced values inside a traced root: non-static
        parameters + anything assigned from a traced expression."""
        traced: set = set()
        node = info.node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            a = node.args
            params = [p.arg for p in
                      a.posonlyargs + a.args + a.kwonlyargs]
            statics: set = set()
            unknown = False
            for b in (self.jit_bindings(node) or [info]):
                s = b.static_params()
                if s is None:
                    unknown = True
                else:
                    statics |= s
            # partial-bound leading/keyword params hold static Python
            # values (e.g. pallas kernel flags bound via
            # functools.partial(kernel, eps, rms))
            statics.update(range(info.partial_pos))
            statics.update(info.partial_kws)
            if not unknown:
                for i, p in enumerate(params):
                    if p in ("self", "cls") or i in statics or p in statics:
                        continue
                    traced.add(p)
        body = node.body if isinstance(node.body, list) else [node.body]
        for stmt in body:
            for sub in self._walk_in_order(stmt):
                if isinstance(sub, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                    targets = (sub.targets if isinstance(sub, ast.Assign)
                               else [sub.target])
                    value = sub.value
                    if value is None:
                        continue
                    is_traced = self.expr_is_traced(value, traced)
                    # `acc += 1`: the target is also an operand — an
                    # already-traced name stays traced regardless of the
                    # (possibly constant) RHS
                    aug_keeps = isinstance(sub, ast.AugAssign)
                    for t in targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                if is_traced or (aug_keeps
                                                 and n.id in traced):
                                    traced.add(n.id)
                                else:
                                    traced.discard(n.id)
        return traced

    @staticmethod
    def _walk_in_order(node: ast.AST) -> Iterator[ast.AST]:
        yield node
        for child in ast.iter_child_nodes(node):
            yield from ModuleContext._walk_in_order(child)

    def expr_is_traced(self, expr: ast.expr, traced: set) -> bool:
        """Does ``expr`` reference a traced value?  ``x.shape``-style
        static metadata reads and static jnp helpers don't count."""
        parents: dict = {}
        for node in ast.walk(expr):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and node.id in traced:
                parent = parents.get(id(node))
                if isinstance(parent, ast.Attribute) and \
                        parent.value is node and \
                        parent.attr in STATIC_ATTRS:
                    continue
                return True
            if isinstance(node, ast.Call):
                r = self.resolve(node.func)
                if r and r not in STATIC_FNS and \
                        r.startswith(TRACED_NAMESPACE_PREFIXES):
                    return True
        return False

    # -- findings ------------------------------------------------------

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        text = self.lines[line - 1].strip() if 0 < line <= len(self.lines) \
            else ""
        return Finding(rule, self.path, line, col, message, text)


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _suppressed(finding: Finding, lines: Sequence[str]) -> bool:
    if not (0 < finding.line <= len(lines)):
        return False
    m = _DISABLE_RE.search(lines[finding.line - 1])
    if not m:
        return False
    ids = {s.strip() for s in m.group(1).split(",")}
    return finding.rule in ids or "ALL" in ids


def lint_source(source: str, path: str = "<string>",
                rules: Optional[Iterable] = None) -> list:
    """Lint one module's source; returns indexed, suppression-filtered
    findings. Parse failures surface as rule APX000."""
    from apex_tpu.analysis.rules import all_rules
    head = "\n".join(source.splitlines()[:5])
    if _SKIP_FILE_RE.search(head):
        return []
    try:
        ctx = ModuleContext(source, path)
    except SyntaxError as e:
        return [Finding("APX000", path, e.lineno or 0, e.offset or 0,
                        f"syntax error: {e.msg}", (e.text or "").strip())]
    findings: list = []
    for rule in (rules if rules is not None else all_rules()):
        findings.extend(rule.check_module(ctx))
    findings = [f for f in findings if not _suppressed(f, ctx.lines)]
    return assign_indices(findings)


_SKIP_DIRS = {"__pycache__", ".git", ".eggs", "build", "dist",
              "node_modules", ".analysis_fixtures"}


def iter_py_files(paths: Sequence[str]) -> Iterator[Path]:
    for p in paths:
        path = Path(p)
        if path.is_file() and path.suffix == ".py":
            yield path
        elif path.is_dir():
            for sub in sorted(path.rglob("*.py")):
                if not any(part in _SKIP_DIRS for part in sub.parts):
                    yield sub


def lint_paths(paths: Sequence[str], root: Optional[str] = None,
               rules: Optional[Iterable] = None) -> list:
    """Lint every .py under ``paths``; finding paths are relative to
    ``root`` (default: cwd) so fingerprints are machine-independent."""
    rootp = Path(root) if root else Path.cwd()
    out: list = []
    for f in iter_py_files(paths):
        try:
            rel = f.resolve().relative_to(rootp.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        out.extend(lint_source(f.read_text(encoding="utf-8"),
                               rel, rules=rules))
    return out
