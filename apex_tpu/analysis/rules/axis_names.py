"""APX107 — hardcoded mesh-axis-name string literals in package code.

``jax.lax.psum(x, "data")`` works until someone renames or re-carves
the mesh; ``parallel_state`` exports the axis names as constants
(``DATA_AXIS``/``TENSOR_AXIS``/...) precisely so call sites and the
topology cannot drift apart.  The rule fires on a canonical axis-name
string literal used as a collective's axis argument (positional or
``axis_name=``) or as an ``axis_name`` parameter default, inside
``apex_tpu/`` package code only — tests and examples build their own
meshes and legitimately name their own axes.
"""
from __future__ import annotations

import ast

from apex_tpu.analysis.rules import Rule, register

_CANONICAL = {"data", "tensor", "pipe", "context", "expert"}

_CONSTANT_OF = {"data": "DATA_AXIS", "tensor": "TENSOR_AXIS",
                "pipe": "PIPE_AXIS", "context": "CONTEXT_AXIS",
                "expert": "EXPERT_AXIS"}

# collectives / axis queries whose axis argument is positional arg 1
_AXIS_ARG1_FNS = {
    "jax.lax.psum", "jax.lax.pmean", "jax.lax.pmax", "jax.lax.pmin",
    "jax.lax.all_gather", "jax.lax.psum_scatter", "jax.lax.ppermute",
    "jax.lax.all_to_all", "jax.lax.axis_index", "jax.lax.axis_size",
    "jax.lax.pswapaxes",
}

# tests/examples/bench build their OWN meshes and may name their own
# axes; the constants module defining the names is exempt
_OUT_OF_SCOPE = ("tests/", "examples/", "bench")
_EXEMPT = "apex_tpu/transformer/parallel_state.py"


def _axis_literal(node) -> str:
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value in _CANONICAL:
        return node.value
    return ""


@register
class HardcodedAxisName(Rule):
    id = "APX107"
    name = "hardcoded-axis-name"
    description = ("mesh axis name as a string literal in package code — "
                   "use the parallel_state constants (DATA_AXIS, "
                   "TENSOR_AXIS, ...) so call sites can't drift from the "
                   "topology")

    def _in_scope(self, path: str) -> bool:
        # package code + fixture sources ("<string>") are in scope
        return path != _EXEMPT and not path.startswith(_OUT_OF_SCOPE)

    def check_module(self, ctx):
        if not self._in_scope(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                yield from self._check_call(ctx, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_defaults(ctx, node)
            elif isinstance(node, ast.AnnAssign):
                # dataclass/flax-module field defaults:
                #     axis_name: Optional[str] = "data"
                if isinstance(node.target, ast.Name) and \
                        "axis" in node.target.id and node.value is not None:
                    ax = _axis_literal(node.value)
                    if ax:
                        yield self._finding(ctx, node.value, ax)

    def _check_call(self, ctx, call: ast.Call):
        resolved = ctx.resolve(call.func) or ""
        if resolved in _AXIS_ARG1_FNS and len(call.args) >= 2:
            ax = _axis_literal(call.args[1])
            if ax:
                yield self._finding(ctx, call.args[1], ax)
        for kw in call.keywords:
            if kw.arg in ("axis_name", "expert_axis", "tensor_axis"):
                ax = _axis_literal(kw.value)
                if ax:
                    yield self._finding(ctx, kw.value, ax)

    def _check_defaults(self, ctx, fn):
        a = fn.args
        params = a.posonlyargs + a.args + a.kwonlyargs
        defaults = ([None] * (len(a.posonlyargs + a.args)
                              - len(a.defaults)) + list(a.defaults)
                    + list(a.kw_defaults))
        for p, d in zip(params, defaults):
            if d is not None and "axis" in p.arg:
                ax = _axis_literal(d)
                if ax:
                    yield self._finding(ctx, d, ax)

    def _finding(self, ctx, node, ax: str):
        return ctx.finding(
            self.id, node,
            f"axis name {ax!r} hardcoded as a string literal — use "
            f"parallel_state.{_CONSTANT_OF[ax]} so the call site tracks "
            f"the mesh topology")
