"""APX112 — serving-state internals mutated from outside the owner.

The protocol audit (``apex-tpu-analyze --protocol``) model-checks the
conservation laws of ``PageAllocator`` (``_free``/``_refs``),
``HostPageStore`` (``_slabs``/``_next_handle``) and ``PrefixCache``
(``_root``/``_clock``/``_alloc``/``_host_store``/``_offload``) — but
only through their PUBLIC transitions.  Code elsewhere in the package
that assigns, deletes, or calls a mutating method on one of those
underscore attributes edits the books behind the model checker's back:
every pinned invariant would still "pass" while the running system
diverges from the checked protocol.  Observation is sanctioned through
the read-only surfaces (``snapshot()`` / ``walk_edges()`` /
``peek_resident()``); mutation belongs in ``apex_tpu/inference/``.
Tests are exempt (seeded-violation twins MUST reach in to break the
books on purpose).
"""
from __future__ import annotations

import ast
import posixpath

from apex_tpu.analysis.rules import Rule, register

#: underscore internals of the model-checked serving components; any
#: name here is distinctive enough repo-wide that attribute mutation
#: outside apex_tpu/inference/ is an error, not a coincidence
_PROTECTED = frozenset({
    "_free", "_refs",                      # PageAllocator
    "_slabs", "_next_handle",              # HostPageStore
    "_root", "_clock", "_alloc", "_host_store", "_offload",
})

#: method names that mutate a list/dict/set receiver in place
_MUTATORS = frozenset({
    "append", "extend", "insert", "remove", "pop", "clear", "update",
    "setdefault", "popitem", "add", "discard", "sort", "reverse",
})


def _is_test_path(path: str) -> bool:
    parts = posixpath.normpath(path.replace("\\", "/")).split("/")
    if any(p in ("tests", "test") for p in parts[:-1]):
        return True
    base = parts[-1]
    return base.startswith("test_") or base.endswith("_test.py")


def _is_owner_path(path: str) -> bool:
    parts = posixpath.normpath(path.replace("\\", "/")).split("/")
    for i, part in enumerate(parts[:-1]):
        if part == "apex_tpu" and i + 1 < len(parts) \
                and parts[i + 1] == "inference":
            return True
    return False


def _protected_attr(node) -> str:
    """The protected attribute an expression ultimately mutates:
    peels subscripts (``alloc._refs[p]``) down to the Attribute."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and node.attr in _PROTECTED:
        return node.attr
    return ""


@register
class ServingStateMutation(Rule):
    id = "APX112"
    name = "serving-state-mutation"
    description = ("PageAllocator/HostPageStore/PrefixCache underscore "
                   "internals mutated outside apex_tpu/inference/ — "
                   "the protocol audit can't see such edits")

    def check_module(self, ctx):
        if _is_test_path(ctx.path) or _is_owner_path(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            targets = ()
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = (node.target,)
            elif isinstance(node, ast.Delete):
                targets = node.targets
            for t in targets:
                attr = _protected_attr(t)
                if attr:
                    yield ctx.finding(
                        self.id, node,
                        f"direct write to {attr!r} — a serving-state "
                        f"internal the protocol audit model-checks; "
                        f"mutate through the owning class's public "
                        f"API (apex_tpu/inference/) or observe via "
                        f"snapshot()/walk_edges()")
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                attr = _protected_attr(node.func.value)
                if attr:
                    yield ctx.finding(
                        self.id, node,
                        f"in-place {node.func.attr}() on {attr!r} — a "
                        f"serving-state internal the protocol audit "
                        f"model-checks; use the owning class's public "
                        f"API (apex_tpu/inference/)")
