"""APX101 — host-sync calls inside traced code.

``.item()`` / ``.tolist()`` / ``float(tracer)`` / ``np.asarray`` /
``block_until_ready`` inside a jitted (or otherwise traced) function
either fail at trace time with a ConcretizationTypeError or — worse,
when the value is an abstract-safe constant — silently force a
host↔device round trip per step, serialising the dispatch pipeline.
"""
from __future__ import annotations

import ast

from apex_tpu.analysis.rules import Rule, register

# method calls that synchronise regardless of receiver type
_SYNC_METHODS = {"item", "tolist", "block_until_ready",
                 "copy_to_host_async"}
# module-level functions that pull data to host
_SYNC_FNS = {"jax.device_get", "numpy.asarray", "numpy.array",
             "numpy.frombuffer"}
# builtins that concretise — flagged only on traced-looking operands
_SYNC_BUILTINS = {"float", "int", "bool", "complex"}


@register
class HostSyncInJit(Rule):
    id = "APX101"
    name = "host-sync-in-jit"
    description = ("host-synchronising call inside a traced function "
                   "(.item()/.tolist()/float(tracer)/np.asarray/"
                   "block_until_ready)")

    def check_module(self, ctx):
        traced_by_root: dict = {}
        seen: set = set()   # nodes reported once — traced roots can nest
        for info in ctx.traced_roots():
            traced = ctx.traced_locals(info)
            # params are "maybe traced"; names *derived* from jnp math
            # are certainly traced — float()/int() only flags the latter
            params = set()
            if hasattr(info.node, "args"):
                a = info.node.args
                params = {p.arg for p in
                          a.posonlyargs + a.args + a.kwonlyargs}
            traced_by_root[id(info.node)] = (traced, traced - params)
            for node in self._walk_body(info.node):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                seen.add(id(node))
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS \
                        and not node.args:
                    yield ctx.finding(
                        self.id, node,
                        f".{f.attr}() synchronises with the host inside a "
                        f"traced function — hoist it out of the "
                        f"jit/shard_map boundary")
                    continue
                r = ctx.resolve(f)
                if r in _SYNC_FNS:
                    if self._arg_traced(ctx, node,
                                        traced_by_root[id(info.node)][0]):
                        yield ctx.finding(
                            self.id, node,
                            f"{r}() on a traced value forces a device→host "
                            f"transfer (use jnp.asarray / keep it a jax "
                            f"Array)")
                    continue
                if isinstance(f, ast.Name) and f.id in _SYNC_BUILTINS \
                        and r == f.id:
                    if self._arg_traced(ctx, node,
                                        traced_by_root[id(info.node)][1],
                                        require_derived=True):
                        yield ctx.finding(
                            self.id, node,
                            f"{f.id}() concretises a traced value "
                            f"(ConcretizationTypeError at trace time, or a "
                            f"silent sync) — keep it as an array or mark "
                            f"the argument static")

    @staticmethod
    def _walk_body(root):
        body = root.body if isinstance(root.body, list) else [root.body]
        for stmt in body:
            yield from ast.walk(stmt)

    @staticmethod
    def _arg_traced(ctx, call, traced, require_derived=False):
        if not call.args:
            return False
        arg = call.args[0]
        if isinstance(arg, ast.Constant):
            return False
        # require_derived passes the jnp-derived subset of traced names,
        # so float(eps)-style coercion of a plain param stays quiet while
        # float(jnp.sum(x)) and float(loss_value) fire.
        return ctx.expr_is_traced(arg, traced)
