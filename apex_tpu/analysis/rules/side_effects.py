"""APX102 — Python side effects under trace.

``print`` / ``logging`` calls inside a jitted function run ONCE at
trace time (printing tracer reprs, not values) and then never again —
the classic "why did my debug print show Traced<ShapedArray…>" trap.
``jax.debug.print`` / ``jax.debug.callback`` are the sanctioned
equivalents and are not flagged.
"""
from __future__ import annotations

import ast

from apex_tpu.analysis.rules import Rule, register

_LOGGING_PREFIXES = ("logging.",)


@register
class SideEffectUnderJit(Rule):
    id = "APX102"
    name = "print-in-jit"
    description = ("print/logging call inside a traced function — runs at "
                   "trace time only; use jax.debug.print")

    def check_module(self, ctx):
        for node in ctx.iter_traced(ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id == "print" \
                    and ctx.resolve(f) == "print":
                yield ctx.finding(
                    self.id, node,
                    "print() under trace fires once at trace time with "
                    "tracer reprs — use jax.debug.print(...)")
                continue
            r = ctx.resolve(f)
            if r and r.startswith(_LOGGING_PREFIXES) and \
                    isinstance(f, ast.Attribute) and \
                    f.attr in ("debug", "info", "warning", "error",
                               "critical", "exception", "log"):
                yield ctx.finding(
                    self.id, node,
                    f"{r}() under trace fires once at trace time — use "
                    f"jax.debug.print or log outside the jitted region")
