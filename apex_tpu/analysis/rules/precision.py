"""APX106 — fp32-defaulting array factories inside traced code.

``jnp.zeros(n)`` / ``jnp.array(0.5)`` / ``jnp.linspace(...)`` with no
``dtype=`` produce float32, and one fp32 operand silently promotes a
whole bf16 expression chain to fp32 — doubling the bytes every
downstream op moves and halving effective MXU throughput.  (Bare Python
float literals are weakly typed and do NOT promote, so they are not
flagged; the materialised-constant factories are the real hazard.)
Deliberate fp32 accumulators state their dtype and stay quiet.
"""
from __future__ import annotations

import ast

from apex_tpu.analysis.rules import Rule, register

# factories whose default dtype is float32 regardless of arguments
_ALWAYS_FLOAT = {"zeros", "ones", "empty", "eye", "identity", "linspace"}
# factories whose dtype follows a float argument
_VALUE_FLOAT = {"array", "asarray", "full", "arange"}
_NAMESPACES = ("jax.numpy.", "numpy.")


@register
class Fp32DefaultFactory(Rule):
    id = "APX106"
    name = "fp32-default-factory"
    description = ("array factory without dtype= inside traced code "
                   "defaults to float32 and silently upcasts bf16 math")

    def check_module(self, ctx):
        for node in ctx.iter_traced(ast.Call):
            r = ctx.resolve(node.func)
            if not r or not r.startswith(_NAMESPACES):
                continue
            member = r.rsplit(".", 1)[1]
            if member not in _ALWAYS_FLOAT and member not in _VALUE_FLOAT:
                continue
            if any(kw.arg == "dtype" for kw in node.keywords):
                continue
            # positional dtype: np.zeros(shape, dtype) / full(shape, v, dtype)
            limit = {"zeros": 1, "ones": 1, "empty": 1, "eye": 3,
                     "identity": 1, "linspace": 5, "array": 1,
                     "asarray": 1, "full": 2, "arange": 3}.get(member, 1)
            if len(node.args) > limit:
                continue
            if member in _VALUE_FLOAT and not self._has_float_const(node):
                continue
            yield ctx.finding(
                self.id, node,
                f"{r}(...) without dtype= materialises float32 — one fp32 "
                f"operand promotes the whole bf16 chain; pass dtype= "
                f"(or x.dtype) explicitly")

    @staticmethod
    def _has_float_const(call: ast.Call) -> bool:
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Constant) and \
                        isinstance(sub.value, float):
                    return True
        return False
