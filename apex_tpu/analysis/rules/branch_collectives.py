"""APX109 — collective call inside only one branch of a per-process
Python ``if``.

SPMD programs are the SAME program on every rank; a collective guarded
by a Python condition that can DIFFER across processes/ranks —
``jax.process_index()``, a ``parallel_state`` rank getter,
``is_pipeline_first/last_stage()`` — compiles different programs on
different hosts, and the ranks that skipped the branch deadlock the
ones inside the collective.  The sanctioned shape is the masked
collective every rank enters (``psum(where(member, x, 0))`` — see
``pipeline_parallel.embedding_grads_all_reduce``).

Static *topology* branches (``if cp == 1: ...``, ``if t < cp - 1``)
are identical on every rank and stay quiet.
"""
from __future__ import annotations

import ast
import re

from apex_tpu.analysis.rules import Rule, register

_COLLECTIVE_FNS = re.compile(
    r"jax\.lax\.(psum|pmean|pmax|pmin|all_gather|psum_scatter|ppermute|"
    r"all_to_all|pswapaxes)$")

# condition names that differ per process/rank
_PER_PROCESS = re.compile(
    r"(process_index|process_count|host_id|axis_index|"
    r"get_\w*rank|is_pipeline_(first|last)_stage)")


@register
class CollectiveInDivergentBranch(Rule):
    id = "APX109"
    name = "collective-in-divergent-branch"
    description = ("collective inside one branch of a Python if on a "
                   "per-process/rank condition — ranks that skip the "
                   "branch deadlock the ones inside; use a masked "
                   "collective every rank enters")

    def check_module(self, ctx):
        seen: set = set()
        for node in ctx.iter_traced(ast.If):
            if id(node) in seen:
                continue
            if not self._per_process_test(ctx, node.test):
                continue
            body_c = self._collectives(ctx, node.body)
            else_c = self._collectives(ctx, node.orelse)
            if body_c == else_c:
                continue
            seen.add(id(node))
            only = sorted((body_c or else_c))
            yield ctx.finding(
                self.id, node,
                f"collective {only} appears in only one branch of an if "
                f"on a per-process condition — ranks taking the other "
                f"branch deadlock it; restructure as a masked "
                f"collective (psum(where(member, x, 0))) every rank "
                f"executes")

    def _per_process_test(self, ctx, test: ast.expr) -> bool:
        for sub in ast.walk(test):
            name = None
            if isinstance(sub, ast.Call):
                name = ctx.resolve(sub.func)
            elif isinstance(sub, (ast.Name, ast.Attribute)):
                name = ctx.resolve(sub)
            if name and _PER_PROCESS.search(name):
                return True
        return False

    def _collectives(self, ctx, stmts) -> frozenset:
        out = set()
        for stmt in stmts or []:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    name = ctx.resolve(sub.func) or ""
                    if _COLLECTIVE_FNS.search(name):
                        out.add(name.rsplit(".", 1)[-1])
        return frozenset(out)
