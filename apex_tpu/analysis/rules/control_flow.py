"""APX104 — Python control flow branching on traced values.

``if x.sum() > 0:`` inside a jitted function raises
ConcretizationTypeError at trace time (or, with concrete tracing,
silently bakes one branch into the compiled program).  The fix is
``jax.lax.cond`` / ``jnp.where`` / ``lax.while_loop``.  Static branches
(shapes, dtypes, config flags, ``static_argnums`` parameters) are fine
and not flagged.
"""
from __future__ import annotations

import ast

from apex_tpu.analysis.rules import Rule, register


@register
class TracedControlFlow(Rule):
    id = "APX104"
    name = "traced-python-control-flow"
    description = ("Python if/while branching on a traced value — use "
                   "jax.lax.cond / jnp.where / lax.while_loop")

    def check_module(self, ctx):
        seen: set = set()
        for info in ctx.traced_roots():
            traced = ctx.traced_locals(info)
            body = info.node.body
            stmts = body if isinstance(body, list) else [body]
            for stmt in stmts:
                for node in ast.walk(stmt):
                    if id(node) in seen:
                        continue
                    if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                        test = node.test
                        if self._identity_test(test):
                            continue
                        if ctx.expr_is_traced(test, traced):
                            seen.add(id(node))
                            kind = {"If": "if", "While": "while",
                                    "IfExp": "conditional expression"}[
                                type(node).__name__]
                            yield ctx.finding(
                                self.id, node,
                                f"Python {kind} on a traced value inside a "
                                f"traced function — trace-time error or a "
                                f"baked-in branch; use jax.lax.cond / "
                                f"jnp.where")

    @staticmethod
    def _identity_test(test: ast.expr) -> bool:
        """``x is None`` / ``x is not None`` never concretises a tracer —
        the standard optional-argument idiom stays quiet."""
        return isinstance(test, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)
