"""Lint-rule plugin registry.

A rule is a class with ``id`` / ``name`` / ``description`` and a
``check_module(ctx)`` generator yielding :class:`~apex_tpu.analysis.
finding.Finding`.  Register with the :func:`register` decorator; the
engine instantiates every registered rule per run.  Adding a rule =
dropping a module in this package that defines + registers one class
and importing it at the bottom of this file (see README "Static
analysis").
"""
from __future__ import annotations

from typing import Dict, Iterable, Type

from apex_tpu.analysis.finding import Finding


class Rule:
    """Base class for AST lint rules (subclass + ``@register``)."""

    id: str = ""
    name: str = ""
    description: str = ""

    def check_module(self, ctx) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


REGISTRY: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.id in REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id}")
    REGISTRY[cls.id] = cls
    return cls


def all_rules() -> list[Rule]:
    return [REGISTRY[rid]() for rid in sorted(REGISTRY)]


# Import order defines nothing semantic; ids keep the report ordering.
from apex_tpu.analysis.rules import (  # noqa: E402,F401
    axis_names,
    branch_collectives,
    control_flow,
    donation,
    env_knobs,
    host_sync,
    pallas_flags,
    precision,
    prng,
    side_effects,
    state_mutation,
    step_timing,
)
