"""APX108 — unregistered ``APEX_TPU_*`` environment-knob reads.

Every env knob the package consumes must be declared in
:mod:`apex_tpu.analysis.env_registry` (one place), which the README
knob table is validated against — an ``os.environ.get("APEX_TPU_FOO")``
without a registry entry is a knob users can set but never discover.
The rule resolves simple module-level string constants
(``_ENV = "APEX_TPU_X"; os.environ.get(_ENV)`` — the package idiom), so
indirection doesn't launder a read past the registry.
"""
from __future__ import annotations

import ast

from apex_tpu.analysis.rules import Rule, register

_PREFIX = "APEX_TPU_"

_READ_FNS = {"os.environ.get", "os.getenv", "environ.get"}


@register
class UnregisteredEnvKnob(Rule):
    id = "APX108"
    name = "unregistered-env-knob"
    description = ("APEX_TPU_* environment variable read without an "
                   "apex_tpu.analysis.env_registry entry — register it "
                   "(and its README table row) so the knob is "
                   "discoverable")

    def check_module(self, ctx):
        from apex_tpu.analysis.env_registry import is_registered

        consts = self._module_str_consts(ctx.tree)

        def knob_name(node) -> str:
            """The APEX_TPU_* name an expression denotes, or ''."""
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                val = node.value
            elif isinstance(node, ast.Name):
                val = consts.get(node.id, "")
            else:
                return ""
            return val if val.startswith(_PREFIX) else ""

        for node in ast.walk(ctx.tree):
            name = ""
            if isinstance(node, ast.Call) and node.args:
                resolved = ctx.resolve(node.func) or ""
                if resolved in _READ_FNS or \
                        resolved.endswith(".environ.get"):
                    name = knob_name(node.args[0])
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Load):
                base = ctx.resolve(node.value) or ""
                if base.endswith("environ"):
                    sl = node.slice
                    sl = sl.value if isinstance(sl, ast.Index) else sl
                    name = knob_name(sl)
            if name and not is_registered(name):
                yield ctx.finding(
                    self.id, node,
                    f"env knob {name!r} is read here but has no "
                    f"apex_tpu.analysis.env_registry entry — register "
                    f"it (default + effect) and add the README table "
                    f"row")

    @staticmethod
    def _module_str_consts(tree) -> dict:
        out: dict = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                out[node.targets[0].id] = node.value.value
        return out
