"""APX103 — PRNG key consumed twice without a split.

JAX keys are values, not stateful generators: sampling twice with the
same key yields *identical* randomness (correlated dropout masks,
duplicated initialisations).  The rule tracks, per function, every name
bound to a key and flags a second consuming use — ``jax.random``
samplers and ``split`` consume; ``fold_in`` derives (safe) and
rebinding (``key, sub = jax.random.split(key)``) resets.

Loops are handled by visiting their bodies twice: a consumption whose
key isn't rebound within the body trips on the second pass, which is
exactly the runtime behaviour (same key every iteration).
"""
from __future__ import annotations

import ast

from apex_tpu.analysis.rules import Rule, register

_CONSUMERS = {
    "normal", "uniform", "bernoulli", "randint", "permutation", "shuffle",
    "categorical", "gumbel", "truncated_normal", "choice", "dirichlet",
    "beta", "gamma", "exponential", "laplace", "logistic", "poisson",
    "rademacher", "cauchy", "multivariate_normal", "t", "maxwell",
    "orthogonal", "ball", "bits", "split",
}
_DERIVERS = {"fold_in", "clone", "wrap_key_data"}
_KEY_SOURCES = {"PRNGKey", "key", "split", "fold_in", "clone"}


@register
class PRNGKeyReuse(Rule):
    id = "APX103"
    name = "prng-key-reuse"
    description = ("PRNG key consumed by two jax.random calls without an "
                   "intervening split — identical randomness both times")

    def check_module(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(ctx, node)

    def _check_function(self, ctx, func):
        findings: list = []
        reported: set = set()
        # uses: name -> first consuming call node since last rebind
        self._visit_block(ctx, func.body, {}, findings, reported, func)
        yield from findings

    def _random_member(self, ctx, call) -> str:
        r = ctx.resolve(call.func)
        if r and r.startswith("jax.random."):
            return r.rsplit(".", 1)[1]
        return ""

    def _key_arg(self, call):
        if call.args and isinstance(call.args[0], ast.Name):
            return call.args[0].id
        for kw in call.keywords:
            if kw.arg == "key" and isinstance(kw.value, ast.Name):
                return kw.value.id
        return None

    def _bound_names(self, target) -> set:
        return {n.id for n in ast.walk(target) if isinstance(n, ast.Name)}

    def _visit_block(self, ctx, stmts, uses, findings, reported, func):
        """uses maps key-name -> consuming call node (None once reported)."""
        for stmt in stmts:
            self._visit_stmt(ctx, stmt, uses, findings, reported, func)

    _COMPOUND = (ast.If, ast.For, ast.AsyncFor, ast.While, ast.With,
                 ast.AsyncWith, ast.Try)

    def _visit_stmt(self, ctx, stmt, uses, findings, reported, func):
        # consumptions in this statement's own expressions — for compound
        # statements only the header (test/iter/items), since their
        # bodies are recursed into separately below (walking the whole
        # subtree here would double-count every nested consumption)
        if isinstance(stmt, self._COMPOUND):
            headers = []
            if isinstance(stmt, (ast.If, ast.While)):
                headers = [stmt.test]
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                headers = [stmt.iter]
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                headers = [i.context_expr for i in stmt.items]
            scan = [n for h in headers for n in self._walk_no_nested(h)]
        else:
            scan = self._walk_no_nested(stmt)
        for node in scan:
            if isinstance(node, ast.Call):
                member = self._random_member(ctx, node)
                if member in _CONSUMERS:
                    name = self._key_arg(node)
                    if name:
                        prev = uses.get(name, None)
                        if prev is not None and id(node) not in reported:
                            reported.add(id(node))
                            findings.append(ctx.finding(
                                self.id, node,
                                f"key '{name}' already consumed at line "
                                f"{prev.lineno} — split it "
                                f"(jax.random.split) before reusing"))
                        elif prev is None:
                            uses[name] = node
        # rebindings reset consumption state
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                for name in self._bound_names(t):
                    uses.pop(name, None)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            for name in self._bound_names(stmt.target):
                uses.pop(name, None)
            # two passes: keys bound outside and consumed inside the
            # loop body without rebinding trip on the second pass
            for _ in range(2):
                self._visit_block(ctx, stmt.body, uses, findings,
                                  reported, func)
            self._visit_block(ctx, stmt.orelse, uses, findings,
                              reported, func)
            return
        elif isinstance(stmt, ast.While):
            for _ in range(2):
                self._visit_block(ctx, stmt.body, uses, findings,
                                  reported, func)
            self._visit_block(ctx, stmt.orelse, uses, findings,
                              reported, func)
            return
        elif isinstance(stmt, ast.If):
            # disjoint branches are not double-consumption: fork state
            before = dict(uses)
            self._visit_block(ctx, stmt.body, uses, findings, reported,
                              func)
            other = dict(before)
            self._visit_block(ctx, stmt.orelse, other, findings, reported,
                              func)
            for k, v in other.items():
                uses.setdefault(k, v)
            return
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._visit_block(ctx, stmt.body, uses, findings, reported,
                              func)
            return
        elif isinstance(stmt, ast.Try):
            for block in (stmt.body, stmt.orelse, stmt.finalbody):
                self._visit_block(ctx, block, uses, findings, reported,
                                  func)
            for h in stmt.handlers:
                self._visit_block(ctx, h.body, uses, findings, reported,
                                  func)
            return

    @staticmethod
    def _walk_no_nested(stmt):
        """ast.walk, but don't descend into nested function/class defs
        (those are analysed on their own)."""
        stack = [stmt]
        while stack:
            node = stack.pop()
            if node is not stmt and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef, ast.Lambda, ast.GeneratorExp,
                           ast.ListComp, ast.SetComp, ast.DictComp)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))
