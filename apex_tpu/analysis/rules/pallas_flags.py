"""APX111 — Pallas debug flags left on in package code.

``pallas_call(..., interpret=True)`` silently swaps the Mosaic kernel
for a pure-Python interpreter (orders of magnitude slower, and a
different numerics path), and ``debug=True`` dumps lowering artifacts
on every trace.  Both are development switches: shipping one in
package code means production runs the interpreter.  Test/fixture
files are exempt — the sanctioned toggle for CPU CI is
``apex_tpu.utils.interpret_mode()``, which resolves the
``APEX_TPU_INTERPRET`` knob instead of hard-coding ``True``.
"""
from __future__ import annotations

import ast
import posixpath

from apex_tpu.analysis.rules import Rule, register

_PALLAS_CALL = "jax.experimental.pallas.pallas_call"
_FLAGS = ("interpret", "debug")


def _is_test_path(path: str) -> bool:
    parts = posixpath.normpath(path.replace("\\", "/")).split("/")
    if any(p in ("tests", "test") for p in parts[:-1]):
        return True
    base = parts[-1]
    return base.startswith("test_") or base.endswith("_test.py")


@register
class PallasDebugFlags(Rule):
    id = "APX111"
    name = "pallas-debug-flag"
    description = ("interpret=True/debug=True left on a pallas_call in "
                   "package (non-test) code — use interpret_mode()")

    def check_module(self, ctx):
        if _is_test_path(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.resolve(node.func) != _PALLAS_CALL:
                continue
            for kw in node.keywords:
                if kw.arg in _FLAGS and \
                        isinstance(kw.value, ast.Constant) and \
                        kw.value.value is True:
                    yield ctx.finding(
                        self.id, node,
                        f"pallas_call({kw.arg}=True) in package code "
                        f"ships the {'interpreter' if kw.arg == 'interpret' else 'lowering dumps'}"
                        f" to production — gate it on "
                        f"apex_tpu.utils.interpret_mode() (the "
                        f"APEX_TPU_INTERPRET knob) or move it to a test")
