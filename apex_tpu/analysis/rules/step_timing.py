"""APX110 — raw wall-clock step-timing around jitted calls.

``t0 = time.perf_counter(); y = step(x); dt = time.perf_counter() - t0``
around an async-dispatched jitted call measures the *dispatch* (often
microseconds — the r5 ``flash_attn_us 0.0`` artifact's shape), or, when
the caller immediately reads a result, silently folds any recompile
into the sample.  Package code must time steps through
``apex_tpu.observability.StepTimer`` (dispatch-aware: reports the
compile-count delta and flags recompiles) — the pattern the training
and serving telemetry use.

The rule fires when one function body reads a raw clock at least
twice AND calls an AST-resolvable jit-bound callable between the
reads: a name assigned from ``jax.jit(...)``, a ``@jax.jit``-decorated
function, or an inline ``jax.jit(f)(...)``.  Opaque callables (method
calls, parameters) stay quiet — the lint is untyped and a guess would
blanket-flag ordinary host timing.
"""
from __future__ import annotations

import ast

from apex_tpu.analysis.lint import JIT_WRAPPERS
from apex_tpu.analysis.rules import Rule, register

_CLOCK_FNS = {"time.perf_counter", "time.monotonic", "time.time"}

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _walk_scope(node):
    """``ast.walk`` that does not descend into nested function scopes."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if not isinstance(child, _SCOPE_NODES):
                stack.append(child)


@register
class RawStepTimingAroundJit(Rule):
    id = "APX110"
    name = "raw-step-timing-around-jit"
    description = ("raw time.perf_counter()/monotonic() bracketing a "
                   "jitted call — async dispatch makes the reading "
                   "misleading and recompiles go unflagged; use "
                   "apex_tpu.observability.StepTimer")

    def check_module(self, ctx):
        jit_names = self._jit_bound_names(ctx)
        reported: set = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                continue
            body = node.body if isinstance(node.body, list) \
                else [node.body]
            clocks, jit_calls = [], []
            for stmt in body:
                # walk THIS function's scope only — nested defs/lambdas
                # are visited by the outer loop as their own scopes, and
                # a clock inside a nested helper cannot close a timing
                # bracket in the enclosing function
                if isinstance(stmt, _SCOPE_NODES):
                    continue
                for sub in _walk_scope(stmt):
                    if not isinstance(sub, ast.Call):
                        continue
                    if ctx.resolve(sub.func) in _CLOCK_FNS:
                        clocks.append(sub)
                    elif self._is_jit_dispatch(ctx, sub, jit_names):
                        jit_calls.append(sub)
            if len(clocks) < 2 or not jit_calls:
                continue
            clocks.sort(key=lambda c: (c.lineno, c.col_offset))
            first = clocks[0]
            for jc in jit_calls:
                if jc.lineno < first.lineno:
                    continue
                stop = next((c for c in clocks
                             if c.lineno > jc.lineno), None)
                if stop is not None and id(stop) not in reported:
                    reported.add(id(stop))
                    yield ctx.finding(
                        self.id, stop,
                        "raw clock read closes a timing bracket around "
                        "a jitted call — the sample is dispatch time "
                        "(or an unflagged recompile), not step time; "
                        "use apex_tpu.observability.StepTimer")
                    break              # one finding per function

    @staticmethod
    def _jit_bound_names(ctx) -> set:
        """Names that hold jit-compiled callables: ``f = jax.jit(g)``
        assignments + ``@jax.jit``-decorated defs."""
        names: set = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    ctx.resolve(node.value.func) in JIT_WRAPPERS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
        for info in ctx.jit_infos:
            if info.is_jit and isinstance(
                    info.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                names.add(info.node.name)
        return names

    @staticmethod
    def _is_jit_dispatch(ctx, call: ast.Call, jit_names: set) -> bool:
        f = call.func
        if isinstance(f, ast.Name) and f.id in jit_names:
            return True
        # inline jax.jit(f)(...)
        if isinstance(f, ast.Call) and \
                ctx.resolve(f.func) in JIT_WRAPPERS:
            return True
        return False
