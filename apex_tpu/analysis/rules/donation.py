"""APX105 — jitted train/update step without buffer donation.

A jitted step that takes params/optimizer state and returns their
updated versions holds BOTH copies live across the call unless the
inputs are donated — on TPU that is the difference between fitting a
model at N billion params and OOMing at N/2.  The rule fires on jit
bindings of step-shaped functions (a ``state``/``params``-style
parameter and a step-ish name) that declare no ``donate_argnums`` /
``donate_argnames``.
"""
from __future__ import annotations

import ast
import re

from apex_tpu.analysis.rules import Rule, register

_DONATABLE_PARAMS = {
    "state", "params", "opt_state", "train_state", "optimizer_state",
    "model_state", "carry",
}
_STEP_NAME_RE = re.compile(r"(train|update|optimi[sz]|step)", re.IGNORECASE)


@register
class MissingDonation(Rule):
    id = "APX105"
    name = "missing-donate-argnums"
    description = ("jitted train/update step returns new params/opt-state "
                   "but does not donate the old buffers "
                   "(donate_argnums/donate_argnames)")

    def check_module(self, ctx):
        reported: set = set()
        for info in ctx.jit_infos:
            if not info.is_jit or id(info.node) in reported:
                continue
            node = info.node
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _STEP_NAME_RE.search(node.name):
                continue
            a = node.args
            params = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
            hits = params & _DONATABLE_PARAMS
            if not hits:
                continue
            if any(b.binding_kwarg("donate_argnums", "donate_argnames")
                   is not None for b in ctx.jit_bindings(node)):
                continue
            reported.add(id(node))
            yield ctx.finding(
                self.id, node,
                f"jitted step '{node.name}' takes {sorted(hits)} but "
                f"donates nothing — pass donate_argnums so XLA reuses the "
                f"old buffers in place")
