"""SPMD soundness auditor over the registered multi-device executables.

The jaxpr precision auditor (:mod:`apex_tpu.analysis.jaxpr_audit`)
checks single-device properties — dtype policy, host-transfer
discipline.  This engine walks the *distributed* executables the repo
actually ships — dense and ZeRO train steps, DDP bucketed allreduce,
TP column/row layers, pipeline 1F1B, ring/Ulysses attention, MoE
expert dispatch, inference prefill/decode — and machine-checks the
invariants PRs 3–4 proved by hand, per registered executable:

* **APX211 — collective axis soundness.**  Every ``psum`` /
  ``all_gather`` / ``psum_scatter`` / ``ppermute`` / ``all_to_all`` /
  ``pmax`` names an axis the executable's mesh binds AND that belongs
  to ``parallel_state``'s canonical topology (``pipe/data/expert/
  context/tensor``).  A collective over a foreign axis is dead comm at
  best, a shape bug at worst.
* **APX212 — branch collective parity.**  All branches of a
  ``lax.cond``/``switch`` carry the SAME multiset of (collective,
  axes).  A collective in only one branch is the classic SPMD
  deadlock/divergence shape: ranks disagreeing on the predicate stall
  each other inside the collective.
* **APX213 — replica-uniform control values.**  A dataflow pass tracks
  which values VARY across mesh axes (sharded inputs, ``axis_index``,
  ``psum_scatter``/``ppermute``/``all_to_all`` outputs) and which are
  replica-uniform (replicated inputs, constants, reducing-collective
  outputs).  Predicates of conds whose branches contain collectives
  must be uniform, and so must the small hyperparameter/flag operands
  of the fused update kernels (``noop_flag`` — the exact invariant
  ZeRO's overflow skip rests on: drop the ``pmax`` on ``found_inf``
  and this fires).
* **APX214 — donation verification.**  The lowered executable's
  ``tf.aliasing_output`` attributes actually cover every large leaf of
  the declared donated arguments (FlatState slots, KV cache buffers);
  for step-shaped executables, a large UNdonated input whose aval
  exactly matches an output is flagged — XLA could have reused the
  buffer and silently is not.
* **APX215/APX216 — comm/HBM budget ledger.**  Per-executable
  analytic collective bytes + peak-live-buffer estimate
  (:mod:`~apex_tpu.analysis.comm_model`), ratcheted against the
  committed ``.analysis_budget.json``: growth (or an unbudgeted
  executable) exits nonzero, shrinkage is silent until re-pinned.
  APX216 machine-checks PERF.md round-6's ZeRO accounting on the zero
  step's own jaxpr: all-gather bytes == reduce-scatter bytes, i.e.
  RS + AG == the ring all-reduce of the same flat buffer.
* **APX218 — compiled-truth attribution + drift ratchet.**  Every
  registered executable's budget entry carries XLA's OWN numbers —
  ``lower().compile()``'s ``cost_analysis()`` FLOPs/bytes and
  ``memory_analysis()`` buffer sizes (via
  :mod:`apex_tpu.observability.xla_stats`, provenance-marked when a
  backend degrades) — next to the analytic estimates, plus the
  estimate/compiled drift ratios (APX215's linear-scan peak-live vs
  compiled peak bytes; ``comm_model``'s dot-FLOPs vs compiled FLOPs).
  :func:`compare_budget` ratchets the drift: an executable whose
  ratio moved further from 1 than the committed band (x
  :data:`DRIFT_RATCHET_SLACK`), lost its attribution, or was never
  pinned with one, fails the run — the estimates can no longer drift
  silently away from what XLA actually builds.
* **APX217 — comm/compute overlap (async scheduling).**  For
  executables restructured for overlap (ISSUE 7: the layered-prefetch
  zero step, the chunked TP ring), the COMPILED executable — the same
  lowered-HLO route APX214 takes for donation, one step further — must
  actually expose the overlap: on backends that schedule async
  collectives, a strict majority of ``*-start``/``*-done`` pairs with
  a compute op scheduled between start and done; on backends that
  lower collectives synchronously (the CPU host devices this audit
  runs on), the dependency-graph equivalent — a strict majority of the
  DOMINANT collectives must each have substantial compute that is
  mutually independent of them (exactly what a latency-hiding
  scheduler would run between that start and its done; a decomposed
  pipeline exposes only its boundary collectives, while a monolithic
  gather gates every consumer and a fused matmul+psum hides at most
  its wgrad half).  The pre-overlap lowerings fire this check — the
  seeded-violation tests keep it honest.

Everything is trace-only (``jax.make_jaxpr`` + ``jit(...).lower``) —
zero FLOPs, runs on the 8 forced host devices in seconds — except
APX217, which compiles its (two) flagged executables for the host.
"""
from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, List, Optional, Sequence

from apex_tpu.analysis.comm_model import (COLLECTIVE_PRIMS, collective_axes,
                                          comm_report, jaxpr_dot_flops,
                                          peak_live_bytes)
from apex_tpu.analysis.finding import Finding

__all__ = ["ExecSpec", "exec_specs", "run_spmd_audit", "compare_budget",
           "ensure_devices", "CANONICAL_AXES", "DONATION_FLOOR_BYTES",
           "BUDGET_NAME", "DRIFT_RATCHET_SLACK"]

BUDGET_NAME = ".analysis_budget.json"

#: APX218 drift ratchet slack: the estimate/compiled ratio's distance
#: from 1 may grow by at most this factor over the committed band
#: before the audit fails (identical backends reproduce the ratios
#: bit-for-bit; the slack only absorbs compiler-version scheduling
#: jitter, never a real new temporary).
DRIFT_RATCHET_SLACK = 1.05

#: parallel_state's mesh axis names — the only axes a registered
#: executable's collectives may ride (APX211).
CANONICAL_AXES = frozenset({"pipe", "data", "expert", "context", "tensor"})

#: donated/aliasable leaves smaller than this are noise (scalar step
#: counters, PRNG keys) — the donation checks ignore them.
DONATION_FLOOR_BYTES = 1024

# Fused optimizer/scaler kernel names whose small (<=16-element 1-D)
# operands — lr/beta/noop_flag hyperparameter vectors — must be
# replica-uniform: a rank-varying noop_flag silently diverges the
# masters (PR 3's hand-proved invariant, now enforced).
_UPDATE_KERNEL_MARKS = ("_adam_kernel", "_adagrad_kernel", "_sgd_kernel",
                        "_lamb1_kernel", "_scale_kernel",
                        "_l2norm_scale_kernel")
_UPDATE_OPERAND_MAX_ELEMS = 16

# Collectives that make their output replica-uniform over the reduced/
# gathered axes (every rank holds the identical result)...
_UNIFORMING = {"psum", "pmax", "pmin", "all_gather"}
# ...and collectives whose output stays (or becomes) rank-varying.
_VARYING = {"reduce_scatter", "psum_scatter", "ppermute", "all_to_all"}


def ensure_devices(n: int = 8) -> int:
    """Force ``n`` host devices BEFORE the backend initializes (the
    same ``xla_force_host_platform_device_count`` route the test
    conftest uses); returns the live device count.  A backend already
    pinned to fewer devices is left alone — callers decide whether
    that is fatal."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}").strip()
    import jax
    return len(jax.devices())


# ---------------------------------------------------------------------------
# executable registry
# ---------------------------------------------------------------------------

@dataclass
class ExecSpec:
    """One registered multi-device executable and its declared contract."""
    name: str
    path: str                        # module findings anchor to
    build: Callable[[], tuple]       # () -> (fn, args, axis_sizes)
    donate_argnums: tuple = ()       # declared donated args (jit-level)
    flag_undonated: bool = False     # step-shaped: flag alias-able args
    check_update_uniformity: bool = False
    rs_ag_identity: bool = False     # machine-check RS+AG==AR (PERF r6)
    check_overlap: bool = False      # APX217: comm/compute overlap


def _builders():
    """Lazy spec builders (importing this module stays jax-free).

    Each builder OWNS its ``parallel_state`` topology —
    :func:`run_spmd_audit` snapshots and restores the global mesh
    around the whole run so the audit composes with test harnesses.
    """
    import functools

    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.transformer import parallel_state as ps

    shard_map = functools.partial(jax.shard_map, check_vma=False)

    def _mlp_params(n_layers=8, d=8):
        out = {}
        for i in range(n_layers):
            base = np.linspace(-0.3, 0.3, d * d, dtype=np.float32)
            out[f"w{i}"] = jnp.asarray(np.roll(base, i).reshape(d, d))
            out[f"b{i}"] = jnp.asarray(
                np.linspace(-0.01, 0.01, d, dtype=np.float32))
        return out

    def _mlp_loss(params, batch):
        h = batch["x"]
        for i in range(sum(1 for k in params if k.startswith("w"))):
            h = jnp.tanh(h @ params[f"w{i}"] + params[f"b{i}"])
        return jnp.mean((h - batch["y"]) ** 2)

    def _mlp_batch(n=16, d=8):
        x = np.linspace(-1.0, 1.0, n * d, dtype=np.float32).reshape(n, d)
        return {"x": jnp.asarray(x), "y": jnp.asarray(np.tanh(x @ np.full(
            (d, d), 0.1, np.float32)))}

    def train_step_dense():
        from apex_tpu import train_step
        from apex_tpu.optimizers import functional
        tx = functional.fused_adam(lr=1e-2)
        state = train_step.init_train_state(tx, _mlp_params(),
                                            loss_scale="dynamic")
        step = train_step.make_train_step(_mlp_loss, tx)
        return step, (state, _mlp_batch()), {}

    def train_step_zero(prefetch=8, numerics=False):
        from apex_tpu import train_step
        from apex_tpu.optimizers import functional
        tx = functional.fused_adam(lr=1e-2)
        mesh = Mesh(np.array(jax.devices()[:2]), (ps.DATA_AXIS,))
        # layered prefetch ON (one gather span per layer): the param
        # all-gather decomposes into 8 independent per-span gathers the
        # scheduler can hide under the consuming layers (APX217), at
        # bytes identical to the monolithic gather (APX215 pins it).
        # The prefetch=0 twin (train_step_zero_mono) keeps the
        # production default — APEX_TPU_ZERO_PREFETCH=0, monolithic
        # gather — under APX211-APX216.  The numerics=True twin
        # (train_step_zero_numerics, ISSUE 11) pins that the numerics
        # probes add exactly one scalar-vector psum of comm and keep
        # donation + replica-uniformity intact.
        state, specs = train_step.init_zero_train_state(
            tx, _mlp_params(), ps.DATA_AXIS, 2, loss_scale="dynamic",
            prefetch=prefetch)
        step = train_step.make_train_step(_mlp_loss, tx, zero=True,
                                          numerics=numerics)
        fn = shard_map(step, mesh=mesh, in_specs=(specs, P()),
                       out_specs=(specs, P()))
        return fn, (state, _mlp_batch()), dict(mesh.shape)

    # --- chunked fused LM-head + CE twins (ISSUE 9) --------------------
    # A train-step fixture where the [tokens, vocab] logits DOMINATE
    # memory: tokens=512 x vocab=4096 fp32 logits are 8 MiB, while the
    # model/optimizer state is ~0.6 MiB.  The env-knob-selected lowering
    # ships as TWO registered executables — the fused scan (chunk=64,
    # peak-live O(chunk x vocab)) and its unfused twin (chunk=0, full
    # logits forward AND softmax-residual backward) — so the APX215
    # ledger pins the peak-live drop and a regression in either lowering
    # is caught (the tier-1 twin guard in
    # tests/L1/test_fused_lm_xent_budget.py asserts fused < unfused and
    # that the unfused logits alone exceed the fused twin's entire
    # peak).
    _LM_TOKENS, _LM_HID, _LM_VOCAB, _LM_CHUNK = 512, 32, 4096, 64

    def _lm_head_params():
        base = np.linspace(-0.05, 0.05, _LM_VOCAB * _LM_HID,
                           dtype=np.float32)
        proj = np.linspace(-0.3, 0.3, _LM_HID * _LM_HID,
                           dtype=np.float32)
        return {"head_w": jnp.asarray(base.reshape(_LM_VOCAB, _LM_HID)),
                "proj": jnp.asarray(proj.reshape(_LM_HID, _LM_HID))}

    def _lm_head_batch():
        x = np.linspace(-1.0, 1.0, _LM_TOKENS * _LM_HID,
                        dtype=np.float32).reshape(_LM_TOKENS, _LM_HID)
        y = (np.arange(_LM_TOKENS) * 37) % _LM_VOCAB
        return {"x": jnp.asarray(x),
                "y": jnp.asarray(y, dtype=jnp.int32)}

    def _lm_head_loss(chunk):
        from apex_tpu.ops.fused_lm_xent import fused_lm_head_cross_entropy

        def loss(params, batch):
            h = jnp.tanh(batch["x"] @ params["proj"])
            return fused_lm_head_cross_entropy(
                h, params["head_w"], batch["y"], smoothing=0.1,
                token_chunk=chunk, vocab_chunk=0).mean()
        return loss

    def lm_xent_step(chunk):
        from apex_tpu import train_step
        from apex_tpu.optimizers import functional
        tx = functional.fused_adam(lr=1e-2)
        state = train_step.init_train_state(tx, _lm_head_params(),
                                            loss_scale="dynamic")
        step = train_step.make_train_step(_lm_head_loss(chunk), tx)
        return step, (state, _lm_head_batch()), {}

    def tp_fused_lm_xent():
        from apex_tpu.ops.fused_lm_xent import (
            fused_lm_head_vocab_parallel_cross_entropy)
        ps.destroy_model_parallel()
        ps.initialize_model_parallel(tensor_model_parallel_size_=2)
        mesh = ps.get_mesh()
        tokens, hid, vocab, chunk = 64, 16, 256, 16

        def body(h, w, y):
            def loss(h, w):
                return fused_lm_head_vocab_parallel_cross_entropy(
                    h, w, y, smoothing=0.1, token_chunk=chunk,
                    grad_input_psum=True).mean()
            return jax.value_and_grad(loss, argnums=(0, 1))(h, w)

        fn = shard_map(body, mesh=mesh,
                       in_specs=(P(), P(ps.TENSOR_AXIS, None), P()),
                       out_specs=(P(), (P(), P(ps.TENSOR_AXIS, None))))
        h = jnp.asarray(np.linspace(-1, 1, tokens * hid,
                                    dtype=np.float32).reshape(tokens, hid))
        w = jnp.asarray(np.linspace(-0.2, 0.2, vocab * hid,
                                    dtype=np.float32).reshape(vocab, hid))
        y = jnp.asarray((np.arange(tokens) * 7) % vocab, dtype=jnp.int32)
        return fn, (h, w, y), dict(mesh.shape)

    def ddp_bucketed_allreduce():
        from apex_tpu.parallel.distributed import DistributedDataParallel
        mesh = Mesh(np.array(jax.devices()[:2]), (ps.DATA_AXIS,))
        # small message_size forces the bucketed multi-psum path
        ddp = DistributedDataParallel(axis_name=ps.DATA_AXIS,
                                      message_size=4096)
        grads = _mlp_params(n_layers=6, d=16)

        def body(grads):
            return ddp.reduce_gradients(grads)

        fn = shard_map(body, mesh=mesh, in_specs=(P(),), out_specs=P())
        return fn, (grads,), dict(mesh.shape)

    def tp_column_row(chunks=4):
        from apex_tpu.transformer import tensor_parallel
        ps.destroy_model_parallel()
        ps.initialize_model_parallel(tensor_model_parallel_size_=2)
        mesh = ps.get_mesh()
        # chunked overlap ON: the row matmul+psum becomes a 4-chunk
        # matmul/ppermute ring (+ all-gather) and the column backward
        # psum the matching ring pipeline — same ring bytes as the
        # fused psums (APX215), chunk GEMMs schedulable under the hops
        # (APX217).  Tokens 4 (was 3) so the ring chunks divide; 4
        # chunks (not 2) because at tp=2 a 2-chunk ring is ONE hop —
        # boundary-dominated at this fixture size, so only half its
        # collectives can overlap and APX217's majority bar
        # (correctly) treats that as not pipelined.  The chunks=1 twin
        # (tp_column_row_fused) keeps the production default —
        # APEX_TPU_TP_OVERLAP_CHUNKS=1, fused psums — under
        # APX211-APX216.
        col = tensor_parallel.ColumnParallelLinear(8, 16,
                                                   gather_output=False,
                                                   bias=False,
                                                   overlap_chunks=chunks)
        row = tensor_parallel.RowParallelLinear(16, 8,
                                                input_is_parallel=True,
                                                bias=False,
                                                overlap_chunks=chunks)

        def body(x):
            pc = col.init(jax.random.key(0), x)
            h, _ = col.apply(pc, x)
            pr = row.init(jax.random.key(1), h)

            def loss(x):
                h, _ = col.apply(pc, x)
                y, _ = row.apply(pr, h)
                return jnp.mean(y ** 2)

            return jax.value_and_grad(loss)(x)

        fn = shard_map(body, mesh=mesh, in_specs=(P(),),
                       out_specs=(P(), P()))
        x = jnp.asarray(np.linspace(-1, 1, 4 * 8,
                                    dtype=np.float32).reshape(4, 8))
        return fn, (x,), dict(mesh.shape)

    def pipeline_1f1b():
        from apex_tpu.transformer.pipeline_parallel.schedules import (
            forward_backward_pipelining_without_interleaving)
        ps.destroy_model_parallel()
        ps.initialize_model_parallel(pipeline_model_parallel_size_=2)
        mesh = ps.get_mesh()
        HID, N_MICRO, MB = 8, 2, 2
        params = {"w": jnp.stack([jnp.eye(HID) * 0.5] * 2),
                  "b": jnp.zeros((2, HID))}
        batch = {"x": jnp.asarray(np.linspace(
                     -1, 1, N_MICRO * MB * HID,
                     dtype=np.float32).reshape(N_MICRO, MB, HID)),
                 "target": jnp.full((N_MICRO, MB, HID), 0.1)}

        def stage_fn(p, x, mb):
            return jax.nn.gelu(x @ p["w"] + p["b"])

        def loss_fn(y, mb):
            return jnp.mean((y - mb["target"]) ** 2)

        def body(params, batch):
            local = jax.tree.map(lambda p: p[0], params)
            loss, grads = forward_backward_pipelining_without_interleaving(
                stage_fn, loss_fn, local, batch,
                num_microbatches=N_MICRO, input_fn=lambda mb: mb["x"])
            return loss, jax.tree.map(lambda g: g[None], grads)

        fn = shard_map(body, mesh=mesh,
                       in_specs=(P(ps.PIPE_AXIS), P()),
                       out_specs=(P(), P(ps.PIPE_AXIS)))
        return fn, (params, batch), dict(mesh.shape)

    def _cp_qkv():
        s = jax.ShapeDtypeStruct
        q = s((1, 2, 256, 64), jnp.bfloat16)
        return q, q, q

    def ring_attention_cp():
        from apex_tpu.ops import ring_attention as op
        ps.destroy_model_parallel()
        ps.initialize_model_parallel(context_parallel_size_=2)
        mesh = ps.get_mesh()
        fn = shard_map(lambda q, k, v: op(q, k, v, causal=True),
                       mesh=mesh,
                       in_specs=(P(None, None, ps.CONTEXT_AXIS, None),) * 3,
                       out_specs=P(None, None, ps.CONTEXT_AXIS, None))
        return fn, _cp_qkv(), dict(mesh.shape)

    def ulysses_attention_cp():
        from apex_tpu.ops import ulysses_attention as op
        ps.destroy_model_parallel()
        ps.initialize_model_parallel(context_parallel_size_=2)
        mesh = ps.get_mesh()
        fn = shard_map(lambda q, k, v: op(q, k, v, causal=True),
                       mesh=mesh,
                       in_specs=(P(None, None, ps.CONTEXT_AXIS, None),) * 3,
                       out_specs=P(None, None, ps.CONTEXT_AXIS, None))
        return fn, _cp_qkv(), dict(mesh.shape)

    def moe_dispatch():
        import flax  # noqa: F401 — optional dep; ImportError skips
        from apex_tpu.transformer.moe.layer import MoELayer
        ps.destroy_model_parallel()
        ps.initialize_model_parallel(expert_model_parallel_size_=2)
        mesh = ps.get_mesh()
        layer = MoELayer(num_experts=4, hidden_size=16, ffn_hidden_size=32,
                         top_k=1, capacity=4, expert_parallel_size=2)

        def body(x):
            params = layer.init(jax.random.key(3), x)
            y, _ = layer.apply(params, x)
            return y

        dp = mesh.shape[ps.DATA_AXIS]
        spec = P((ps.DATA_AXIS, ps.EXPERT_AXIS))
        fn = shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=spec)
        x = jax.ShapeDtypeStruct((dp * 2 * 4, 16), jnp.float32)
        return fn, (x,), dict(mesh.shape)

    def _inference(which):
        from apex_tpu.analysis import jaxpr_audit
        ps.destroy_model_parallel()
        fn, args = jaxpr_audit._builders()[which][0]()
        return fn, args, {}

    def _inference_tp2(which):
        """Tensor-parallel serving executables (ISSUE 17): build a REAL
        ``InferenceEngine(tp=2)`` on forced host devices and audit its
        own ``_*_raw`` shard_map step bodies with its own placed
        operands — the audited mesh program IS the one the engine
        dispatches, not a re-derived fixture.  GPT at the jaxpr-audit
        paged fixture geometry; the fused-decode entry compiles the
        sharded Pallas block (partial_out) + the out-of-kernel psum
        tail, the verify entry the k=4 sharded slab scoring."""
        from apex_tpu.inference import kv_cache
        from apex_tpu.inference.engine import InferenceEngine
        from apex_tpu.inference.sampling import SamplingConfig
        from apex_tpu.transformer.testing.standalone_gpt import (
            GPTConfig, gpt_model_provider)
        ps.destroy_model_parallel()
        ps.initialize_model_parallel(1)     # model.init's tp=1 world
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_attention_heads=4, max_seq_length=256,
                        hidden_dropout=0.0, attention_dropout=0.0,
                        params_dtype=jnp.bfloat16)
        model = gpt_model_provider(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))
        eng = InferenceEngine(
            "gpt", cfg, params, slots=4, paged=True, page_size=16,
            num_pages=20, sampling=SamplingConfig(),
            decode_fusion="1" if which == "decode_fused" else "0",
            spec_k=4 if which == "verify" else 0, tp=2)
        cache = eng.init_cache()
        key, step = eng._key, np.int32(0)
        shape = dict(eng.mesh.shape)
        if which == "prefill":
            row = kv_cache.page_row(list(range(4)),
                                    eng.max_pages_per_slot,
                                    eng.num_pages)
            return eng._prefill_raw, (
                cache, eng.params, np.zeros((64,), np.int32),
                np.int32(0), np.int32(10), row, np.int32(0), key,
                step), shape
        if which == "decode_fused":
            return eng._decode_raw, (
                cache, (eng.params, eng._fused_layers),
                np.zeros((4,), np.int32), np.ones((4,), bool), key,
                step), shape
        return eng._verify_raw, (
            cache, eng.params, np.zeros((4, 5), np.int32),
            np.ones((4,), bool), key, step), shape

    return {
        # name: (builder, path, donate, flag_undonated, update_unif,
        #        rs_ag, overlap)
        "train_step_dense": (train_step_dense, "apex_tpu/train_step.py",
                             (0,), True, True, False, False),
        "train_step_zero": (train_step_zero, "apex_tpu/train_step.py",
                            (0,), True, True, True, True),
        # production default (APEX_TPU_ZERO_PREFETCH=0): the monolithic
        # gather stays machine-checked even though the overlapped
        # fixture above is what APX217 verifies
        "train_step_zero_mono": (functools.partial(train_step_zero,
                                                   prefetch=0),
                                 "apex_tpu/train_step.py",
                                 (0,), True, True, True, False),
        # the numerics-probed zero step (ISSUE 11): same lowering as
        # train_step_zero plus compute_probes' single packed psum —
        # its APX215 ledger entry minus train_step_zero's IS the
        # mode's entire comm cost (the tier-1 twin guard asserts it),
        # and APX213/214 pin that the probes stay replica-uniform and
        # donation-intact
        "train_step_zero_numerics": (functools.partial(train_step_zero,
                                                       numerics=True),
                                     "apex_tpu/observability/"
                                     "numerics.py",
                                     (0,), True, True, True, False),
        # the fused/unfused LM-head+CE twins (ISSUE 9): the env-knob
        # (APEX_TPU_XENT_CHUNK) selects between these two lowerings, so
        # BOTH are budgeted — the twin guard compares their APX215
        # peak-live entries
        "lm_xent_fused": (functools.partial(lm_xent_step, _LM_CHUNK),
                          "apex_tpu/ops/fused_lm_xent.py",
                          (0,), True, True, False, False),
        "lm_xent_unfused": (functools.partial(lm_xent_step, 0),
                            "apex_tpu/ops/fused_lm_xent.py",
                            (0,), True, True, False, False),
        "tp_fused_lm_xent": (tp_fused_lm_xent,
                             "apex_tpu/ops/fused_lm_xent.py",
                             (), False, False, False, False),
        "ddp_allreduce": (ddp_bucketed_allreduce,
                          "apex_tpu/parallel/distributed.py",
                          (), False, False, False, False),
        "tp_column_row": (tp_column_row,
                          "apex_tpu/transformer/tensor_parallel/layers.py",
                          (), False, False, False, True),
        # production default (APEX_TPU_TP_OVERLAP_CHUNKS=1): the fused
        # psum lowering stays machine-checked alongside the ring twin
        "tp_column_row_fused": (functools.partial(tp_column_row,
                                                  chunks=1),
                                "apex_tpu/transformer/tensor_parallel/"
                                "layers.py",
                                (), False, False, False, False),
        "pipeline_1f1b": (pipeline_1f1b,
                          "apex_tpu/transformer/pipeline_parallel/"
                          "schedules.py",
                          (), False, False, False, False),
        "ring_attention_cp": (ring_attention_cp,
                              "apex_tpu/ops/ring_attention.py",
                              (), False, False, False, False),
        "ulysses_attention_cp": (ulysses_attention_cp,
                                 "apex_tpu/ops/ulysses_attention.py",
                                 (), False, False, False, False),
        "moe_dispatch": (moe_dispatch,
                         "apex_tpu/transformer/moe/layer.py",
                         (), False, False, False, False),
        "inference_prefill": (lambda: _inference("inference_prefill"),
                              "apex_tpu/inference/engine.py",
                              (0,), True, False, False, False),
        "inference_decode": (lambda: _inference("inference_decode"),
                             "apex_tpu/inference/engine.py",
                             (0,), True, False, False, False),
        # the paged serving memory model (ISSUE 6), registered at a
        # straggler-shaped fixture: the pool (+page table) is donated
        # like the dense cache, and its APX215 peak-live entry is the
        # number the paged-vs-dense HBM comparison test ratchets
        "inference_prefill_paged": (
            lambda: _inference("inference_prefill_paged"),
            "apex_tpu/inference/engine.py", (0,), True, False, False,
            False),
        "inference_decode_paged": (
            lambda: _inference("inference_decode_paged"),
            "apex_tpu/inference/engine.py", (0,), True, False, False,
            False),
        # ISSUE 15: the fused-block decode lowering
        # (APEX_TPU_DECODE_FUSION=1 twin of inference_decode_paged —
        # same signature, same donation, one Pallas kernel per layer)
        # and the speculative verify step (k=4 slab; lengths advance
        # by the accepted count in-program = the rollback), both
        # budgeted from day one like every serving executable
        "inference_decode_fused_paged": (
            lambda: _inference("inference_decode_fused_paged"),
            "apex_tpu/inference/engine.py", (0,), True, False, False,
            False),
        "inference_verify_paged": (
            lambda: _inference("inference_verify_paged"),
            "apex_tpu/inference/engine.py", (0,), True, False, False,
            False),
        # ISSUE 18: the host-tier copy programs.  The swap-out gather
        # deliberately does NOT donate (and must not be flagged for
        # it): the pool stays live — the evicted pages' contents are
        # read out while other pages keep serving.  The swap-in
        # scatter donates the pool like every mutating serving
        # program; its slab operands are small fixed-width staging
        # buffers, not aliasable state.
        "inference_swap_out_paged": (
            lambda: _inference("inference_swap_out_paged"),
            "apex_tpu/inference/kv_cache.py", (), False, False, False,
            False),
        "inference_swap_in_paged": (
            lambda: _inference("inference_swap_in_paged"),
            "apex_tpu/inference/kv_cache.py", (0,), True, False, False,
            False),
        # ISSUE 17: the tensor-parallel serving executables — the
        # engine's own shard_map mesh programs at tp=2, donated pool
        # and all; APX217 overlap verified on the sharded fused decode
        # (per-layer row psums vs the independent pool appends)
        "inference_prefill_paged_tp2": (
            lambda: _inference_tp2("prefill"),
            "apex_tpu/inference/engine.py", (0,), True, False, False,
            False),
        "inference_decode_fused_paged_tp2": (
            lambda: _inference_tp2("decode_fused"),
            "apex_tpu/inference/engine.py", (0,), True, False, False,
            True),
        "inference_verify_paged_tp2": (
            lambda: _inference_tp2("verify"),
            "apex_tpu/inference/engine.py", (0,), True, False, False,
            False),
    }


def exec_specs() -> List[ExecSpec]:
    return [ExecSpec(name, path, build, donate, undon, unif, rs_ag, ovl)
            for name, (build, path, donate, undon, unif, rs_ag, ovl)
            in _builders().items()]


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------

def _iter_jaxprs(jaxpr):
    from apex_tpu.analysis.jaxpr_audit import _iter_jaxprs as it
    return it(jaxpr)


def _collective_multiset(jaxpr) -> dict:
    """{(prim, axes): count} over a jaxpr INCLUDING nested jaxprs; scan
    bodies multiply by length (two psums == one psum scanned twice)."""
    import jax

    out: Dict[tuple, int] = {}

    def walk(j, mult):
        j = getattr(j, "jaxpr", j)
        for eqn in j.eqns:
            name = eqn.primitive.name
            if name in COLLECTIVE_PRIMS:
                key = (name, collective_axes(eqn))
                out[key] = out.get(key, 0) + mult
            m = mult
            if name == "scan":
                m = mult * int(eqn.params.get("length", 1))
            for v in eqn.params.values():
                items = v if isinstance(v, (list, tuple)) else [v]
                for item in items:
                    if isinstance(item, (jax.core.Jaxpr,
                                         jax.core.ClosedJaxpr)):
                        walk(item, m)

    walk(jaxpr, 1)
    return out


# ---------------------------------------------------------------------------
# replica-uniformity dataflow
# ---------------------------------------------------------------------------

class _Uniformity:
    """Per-executable varying-axes dataflow + the checks riding on it.

    ``vary[var]`` is the frozenset of mesh axes the value differs
    across; empty/absent means replica-uniform.  Conservative: unknown
    primitives union their inputs; unmappable subjaxprs seed every
    inner input with the union of the outer inputs.
    """

    def __init__(self, spec: ExecSpec, emit):
        self.spec = spec
        self.emit = emit            # (rule, message) -> None
        self._reported: set = set()

    # -- eqn transfer functions -----------------------------------------

    def run(self, jaxpr, seed: List[FrozenSet], checks: bool) -> list:
        import jax

        vary: dict = {}
        open_j = getattr(jaxpr, "jaxpr", jaxpr)
        for v, s in zip(open_j.invars, seed):
            vary[v] = s
        for v in open_j.constvars:
            vary[v] = frozenset()

        def vof(v):
            if isinstance(v, jax.core.Literal):
                return frozenset()
            return vary.get(v, frozenset())

        for eqn in open_j.eqns:
            name = eqn.primitive.name
            invary = frozenset().union(*[vof(v) for v in eqn.invars]) \
                if eqn.invars else frozenset()
            axes = set(collective_axes(eqn))
            if name in _UNIFORMING and \
                    eqn.params.get("axis_index_groups") is None:
                out = [invary - axes] * len(eqn.outvars)
            elif name in _VARYING:
                out = [invary | axes] * len(eqn.outvars)
            elif name == "axis_index":
                out = [frozenset(axes)] * len(eqn.outvars)
            elif name == "cond":
                out = self._cond(eqn, vof, checks)
            elif name == "scan":
                out = self._scan(eqn, vof, checks)
            elif name == "while":
                out = self._while(eqn, vof, checks)
            elif name == "pjit":
                sub = eqn.params["jaxpr"]
                out = self.run(sub, [vof(v) for v in eqn.invars], checks)
            elif name == "pallas_call":
                if checks:
                    self._pallas(eqn, vof)
                out = [invary] * len(eqn.outvars)
            else:
                out = [invary] * len(eqn.outvars)
                out = self._generic_subjaxprs(eqn, invary, out, checks)
            for v, s in zip(eqn.outvars, out):
                vary[v] = s
        return [vof(v) for v in open_j.outvars]

    def _cond(self, eqn, vof, checks) -> list:
        pred = vof(eqn.invars[0])
        branches = eqn.params.get("branches", ())
        seed = [vof(v) for v in eqn.invars[1:]]
        outs = None
        multisets = []
        for br in branches:
            sub_out = self.run(br, seed, checks)
            multisets.append(_collective_multiset(br))
            outs = sub_out if outs is None else [
                a | b for a, b in zip(outs, sub_out)]
        if checks and multisets:
            base = multisets[0]
            if any(m != base for m in multisets[1:]):
                self._emit_once(
                    "APX212",
                    "lax.cond/switch branches carry different collective "
                    f"multisets {[sorted(f'{p}@{a}' for (p, a) in m) for m in multisets]}"
                    " — ranks disagreeing on the predicate deadlock or "
                    "diverge inside the missing collective")
            if pred and any(multisets):
                self._emit_once(
                    "APX213",
                    f"cond predicate varies over mesh axes "
                    f"{sorted(pred)} while its branches contain "
                    f"collectives — rank-divergent collective entry is "
                    f"the SPMD deadlock shape; derive the predicate "
                    f"through a reducing collective (psum/pmax) or a "
                    f"constant")
        outs = outs or []
        return [o | pred for o in outs]

    def _scan(self, eqn, vof, checks) -> list:
        num_consts = eqn.params["num_consts"]
        num_carry = eqn.params["num_carry"]
        sub = eqn.params["jaxpr"]
        consts = [vof(v) for v in eqn.invars[:num_consts]]
        carry = [vof(v) for v in
                 eqn.invars[num_consts:num_consts + num_carry]]
        xs = [vof(v) for v in eqn.invars[num_consts + num_carry:]]
        for _ in range(8):  # fixpoint over the carried varying sets
            out = self.run(sub, consts + carry + xs, False)
            new_carry = [a | b for a, b in zip(carry, out[:num_carry])]
            if new_carry == carry:
                break
            carry = new_carry
        out = self.run(sub, consts + carry + xs, checks)
        return [a | b for a, b in zip(carry, out[:num_carry])] \
            + out[num_carry:]

    def _while(self, eqn, vof, checks) -> list:
        cn = eqn.params["cond_nconsts"]
        bn = eqn.params["body_nconsts"]
        cond_j = eqn.params["cond_jaxpr"]
        body_j = eqn.params["body_jaxpr"]
        cconsts = [vof(v) for v in eqn.invars[:cn]]
        bconsts = [vof(v) for v in eqn.invars[cn:cn + bn]]
        carry = [vof(v) for v in eqn.invars[cn + bn:]]
        for _ in range(8):
            out = self.run(body_j, bconsts + carry, False)
            new_carry = [a | b for a, b in zip(carry, out)]
            if new_carry == carry:
                break
            carry = new_carry
        out = self.run(body_j, bconsts + carry, checks)
        pred = self.run(cond_j, cconsts + carry, False)
        if checks and pred and pred[0] and _collective_multiset(body_j):
            self._emit_once(
                "APX213",
                f"while_loop predicate varies over mesh axes "
                f"{sorted(pred[0])} while the body contains collectives "
                f"— rank-divergent trip counts deadlock the collective")
        return [a | b for a, b in zip(carry, out)]

    def _pallas(self, eqn, vof) -> None:
        label = str(eqn.params.get("name_and_src_info")
                    or eqn.params.get("name") or "")
        if not any(mark in label for mark in _UPDATE_KERNEL_MARKS):
            return
        if not self.spec.check_update_uniformity:
            return
        for v in eqn.invars:
            aval = getattr(v, "aval", None)
            if aval is None or aval.ndim > 1:
                continue
            size = 1
            for d in aval.shape:
                size *= int(d)
            if size > _UPDATE_OPERAND_MAX_ELEMS:
                continue
            axes = vof(v)
            if axes:
                self._emit_once(
                    "APX213",
                    f"update kernel {label.split(' at ')[0]!r} consumes a "
                    f"hyperparameter/flag operand (shape "
                    f"{tuple(aval.shape)}) that varies over mesh axes "
                    f"{sorted(axes)} — a rank-local noop_flag/lr silently "
                    f"diverges the sharded masters; reduce it "
                    f"replica-uniform first (pmax/psum over the axis)")

    def _generic_subjaxprs(self, eqn, invary, out, checks) -> list:
        """custom_vjp/jvp, remat, closed_call, ...: recurse for the
        CHECKS with conservative seeding; outputs stay the input
        union (already set by the caller)."""
        import jax

        for v in eqn.params.values():
            items = v if isinstance(v, (list, tuple)) else [v]
            for item in items:
                if isinstance(item, (jax.core.Jaxpr, jax.core.ClosedJaxpr)):
                    open_j = getattr(item, "jaxpr", item)
                    self.run(item, [invary] * len(open_j.invars), checks)
        return out

    def _emit_once(self, rule: str, message: str) -> None:
        key = (rule, message)
        if key not in self._reported:
            self._reported.add(key)
            self.emit(rule, message)


# ---------------------------------------------------------------------------
# donation verification
# ---------------------------------------------------------------------------

_MLIR_DT = {"float32": "f32", "bfloat16": "bf16", "float16": "f16",
            "float64": "f64", "int8": "i8", "int16": "i16",
            "int32": "i32", "int64": "i64", "uint8": "ui8",
            "uint16": "ui16", "uint32": "ui32", "uint64": "ui64",
            "bool": "i1"}

# the attr dict may carry quoted values containing '}' (e.g.
# mhlo.sharding = "{devices=[2]<=[2]}") — match quoted spans atomically
_ARG_RE = re.compile(
    r"%arg\d+:\s*(tensor<[^>]*>)\s*(\{(?:[^{}\"]|\"[^\"]*\")*\})?")


def _mlir_type(aval) -> str:
    dims = "x".join(str(int(d)) for d in aval.shape)
    dt = _MLIR_DT.get(str(aval.dtype), str(aval.dtype))
    return f"tensor<{dims}x{dt}>" if dims else f"tensor<{dt}>"


def _aval_bytes(aval) -> int:
    size = 1
    for d in aval.shape:
        size *= int(d)
    return size * aval.dtype.itemsize


def _parse_main_args(text: str) -> list:
    """[(mlir type, donated?)] for @main's arguments, from the lowered
    StableHLO text.  Single-device lowerings mark donated-and-usable
    inputs ``tf.aliasing_output``; multi-device (mesh) lowerings defer
    the alias decision to XLA and mark ``jax.buffer_donor`` — either
    attribute proves the declared donation reached the executable."""
    start = text.index("@main(")
    depth, i = 0, start + len("@main")
    for i in range(start + len("@main"), len(text)):
        if text[i] == "(":
            depth += 1
        elif text[i] == ")":
            depth -= 1
            if depth == 0:
                break
    sig = text[start:i + 1]
    return [(m.group(1),
             any(mark in (m.group(2) or "")
                 for mark in ("tf.aliasing_output", "jax.buffer_donor")))
            for m in _ARG_RE.finditer(sig)]


def _check_donation(spec: ExecSpec, fn, args, emit) -> None:
    import jax

    jitted = jax.jit(fn, donate_argnums=spec.donate_argnums or ())
    try:
        text = jitted.lower(*args).as_text()
    except Exception as e:  # noqa: BLE001 — surfaced as a finding
        emit("APX210", f"lowering {spec.name} for donation verification "
                       f"failed: {type(e).__name__}: {e}")
        return
    sig = _parse_main_args(text)

    donated, undonated = [], []
    for i, a in enumerate(args):
        leaves = jax.tree.leaves(a)
        (donated if i in (spec.donate_argnums or ()) else
         undonated).extend(leaves)

    def aval_of(x):
        return jax.ShapeDtypeStruct(x.shape, x.dtype)

    out_types: Dict[str, int] = {}
    for o in jax.tree.leaves(jax.eval_shape(fn, *args)):
        t = _mlir_type(o)
        out_types[t] = out_types.get(t, 0) + 1

    # (a) every large declared-donated leaf (1) reached the lowered
    # executable as a donor/alias and (2) has a matching output XLA can
    # actually alias it to
    donor_pool: Dict[str, int] = {}
    for t, al in sig:
        if al:
            donor_pool[t] = donor_pool.get(t, 0) + 1
    alias_pool = dict(out_types)
    for leaf in donated:
        aval = aval_of(leaf)
        if _aval_bytes(aval) < DONATION_FLOOR_BYTES:
            continue
        t = _mlir_type(aval)
        has_donor = donor_pool.get(t, 0) > 0
        if has_donor:
            donor_pool[t] -= 1
        has_target = alias_pool.get(t, 0) > 0
        if has_target:
            alias_pool[t] -= 1
        if not has_target:
            emit("APX214",
                 f"{spec.name}: donated input {t} matches NO output aval "
                 f"— XLA cannot alias it, so the old buffer stays live "
                 f"across the step (a dtype/shape change between the "
                 f"donated input and its updated output defeats "
                 f"donation)")
        elif not has_donor:
            emit("APX214",
                 f"{spec.name}: declared-donated input {t} carries no "
                 f"donor/alias attribute in the lowered executable — the "
                 f"donation never reached XLA (wrong donate_argnums, or "
                 f"the arg was pruned)")

    # (b) step-shaped executables: a large undonated input whose aval
    # matches an output could have been reused and is not
    if spec.flag_undonated:
        spare = dict(out_types)
        for leaf in donated:
            t = _mlir_type(aval_of(leaf))
            if spare.get(t, 0) > 0:
                spare[t] -= 1
        for leaf in undonated:
            aval = aval_of(leaf)
            if _aval_bytes(aval) < DONATION_FLOOR_BYTES:
                continue
            t = _mlir_type(aval)
            if spare.get(t, 0) > 0:
                spare[t] -= 1
                emit("APX214",
                     f"{spec.name}: large undonated input {t} exactly "
                     f"matches an output — donate it so XLA reuses the "
                     f"buffer in place instead of holding both copies "
                     f"live")


# ---------------------------------------------------------------------------
# APX217 — comm/compute overlap verification on the COMPILED executable
# ---------------------------------------------------------------------------

#: collective HLO opcodes whose scheduling the overlap check reasons
#: about (the sync spellings; async backends suffix -start/-done).
_OVERLAP_COLL_OPS = frozenset({
    "all-gather", "all-reduce", "collective-permute", "reduce-scatter",
    "all-to-all", "collective-broadcast"})

#: HLO opcodes that count as REAL compute for "compute scheduled
#: between start and done" — data movement (bitcast/copy/slice/concat/
#: broadcast/transpose/tuple) deliberately does not.
_HLO_COMPUTE_OPS = frozenset({
    "fusion", "dot", "convolution", "reduce", "reduce-window", "add",
    "subtract", "multiply", "divide", "tanh", "exponential", "log",
    "rsqrt", "sqrt", "power", "negate", "maximum", "minimum", "select",
    "compare", "map", "sort", "scatter", "custom-call"})

_HLO_INSTR_RE = re.compile(r"^\s*(ROOT\s+)?%?([\w.\-]+)\s*=\s*(.*)$")
_HLO_OP_RE = re.compile(r"\s([a-z][a-z0-9\-]*)\(")
_HLO_REF_RE = re.compile(r"%([\w.\-]+)")
_HLO_SHAPE_RE = re.compile(r"([a-z]+[0-9]*)\[([0-9,]*)\]")
_HLO_CALLS_RE = re.compile(r"(?:calls|to_apply)=%?([\w.\-]+)")

_HLO_ITEMSIZE = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
                 "f16": 2, "bf16": 2, "s32": 4, "u32": 4, "f32": 4,
                 "s64": 8, "u64": 8, "f64": 8}


def _hlo_type_bytes(type_seg: str) -> int:
    total = 0
    for dt, dims in _HLO_SHAPE_RE.findall(type_seg):
        size = 1
        for d in dims.split(","):
            if d:
                size *= int(d)
        total += size * _HLO_ITEMSIZE.get(dt, 4)
    return total


def _parse_entry_instructions(text: str) -> list:
    """``[(name, opcode, operand_names, result_bytes)]`` for the
    compiled module's ENTRY computation, in schedule (program) order.
    Operand refs that don't name an earlier entry instruction
    (computation names in ``calls=``/``to_apply=``, metadata) drop out
    when the dependency graph resolves names.  Handles both HLO text
    spellings: ``%``-sigiled names, and the sigil-less dump (operand
    names are then the identifier tokens in the opcode's argument
    list)."""
    out = []
    in_entry = False
    for line in text.splitlines():
        if line.lstrip().startswith("ENTRY"):
            in_entry = True
            continue
        if not in_entry:
            continue
        if line.strip() == "}":
            break
        m = _HLO_INSTR_RE.match(line)
        if m is None:
            continue
        name, rest = m.group(2), m.group(3)
        om = _HLO_OP_RE.search(" " + rest)
        if om is None:
            continue
        type_seg = (" " + rest)[:om.start(1)]
        refs = _HLO_REF_RE.findall(rest)
        if not refs:
            seg = (" " + rest)[om.end(1):]
            seg = seg[:seg.index(")")] if ")" in seg else seg
            seg = re.sub(r"[a-z]+[0-9]*\[[0-9,]*\]\S*", " ", seg)
            refs = re.findall(r"[A-Za-z_][\w.\-]*", seg)
        cm = _HLO_CALLS_RE.search(rest)
        if cm and cm.group(1) not in refs:
            refs.append(cm.group(1))
        out.append((name, om.group(1), refs, _hlo_type_bytes(type_seg)))
    return out


def _computation_collectives(text: str) -> dict:
    """Non-ENTRY computation name -> set of collective opcodes in its
    body.  Resolves GENERIC ``async-start(...), calls=...`` wrappers —
    the spelling XLA uses to asyncify collectives without a dedicated
    fused opcode (e.g. reduce-scatter / all-to-all on TPU) — back to
    the collective they wrap."""
    out: dict = {}
    cur = None
    for line in text.splitlines():
        st = line.strip()
        if st.endswith("{") and "=" not in st:
            if st.startswith("ENTRY"):
                cur = None
                continue
            m = re.match(r"%?([\w.\-]+)", st)
            cur = m.group(1) if m else None
            if cur is not None:
                out[cur] = set()
            continue
        if st == "}":
            cur = None
            continue
        if cur is None:
            continue
        m = _HLO_INSTR_RE.match(line)
        if m is not None:
            om = _HLO_OP_RE.search(" " + m.group(3))
            if om is not None and om.group(1) in _OVERLAP_COLL_OPS:
                out[cur].add(om.group(1))
    return out


def _emit_compile_failed(emit, name: str, err) -> None:
    emit("APX210", f"compiling {name} for overlap verification "
                   f"failed: {type(err).__name__}: {err}")


def _compile_executable(spec: "ExecSpec", fn, args) -> tuple:
    """ONE XLA compile per executable, shared by APX217 (schedule
    inspection) and APX218 (cost/memory attribution) — compilation
    dominates audit wall time, so it must never run twice for the same
    spec.  Returns ``(Compiled or None, error or None)``."""
    import jax

    try:
        return (jax.jit(fn, donate_argnums=spec.donate_argnums or ())
                .lower(*args).compile(), None)
    except Exception as e:  # noqa: BLE001 — callers surface it
        return None, e


def _check_async_overlap(spec: "ExecSpec", fn, args, emit,
                         compiled=None) -> None:
    """APX217: the compiled executable of an overlap-restructured hot
    path must expose comm/compute overlap to the scheduler.
    ``compiled`` lets :func:`_audit_exec` share its one compile; when
    absent (direct callers, tests) this compiles itself.

    Async backends (TPU latency-hiding scheduler): find
    ``*-start``/``*-done`` collective pairs — dedicated fused opcodes
    AND generic ``async-start`` wrappers resolved through their
    ``calls=`` computation (XLA's spelling for reduce-scatter /
    all-to-all), following ``async-update`` chains to the done — and
    require a strict majority with at least one compute op scheduled
    between start and done.  Synchronous backends (the forced CPU host devices this audit
    runs on): the dependency-graph equivalent — a dominant collective
    counts as OVERLAPPED when some substantial compute op is mutually
    independent of it (exactly the op an async scheduler would place
    between its start and done), and a strict majority of the dominant
    collectives must be overlapped.  The majority bar is the pipeline
    bound: a K-way decomposition exposes only its schedule-boundary
    collectives (first gather, last scatter — < half for any K >= 2),
    while a monolithic gather gates every consumer and a fused
    matmul+psum hides at most its wgrad half (exactly half).  Two
    floors keep trivia out: collectives below 1/8 of the largest
    collective's payload (scalar loss pmeans, found_inf pmax) are not
    dominant, and witness compute below 1/8 of the collective's payload
    (scaler bookkeeping) does not count as hiding it."""
    if compiled is None:
        compiled, err = _compile_executable(spec, fn, args)
        if compiled is None:
            _emit_compile_failed(emit, spec.name, err)
            return
    _overlap_findings_from_hlo(spec.name, compiled.as_text(), emit)


def _overlap_findings_from_hlo(name: str, text: str, emit) -> None:
    """APX217 over already-compiled HLO text (split from
    :func:`_check_async_overlap` so the async route — which only real
    TPU lowerings produce — is testable from canned module text)."""
    instrs = _parse_entry_instructions(text)
    index = {name: i for i, (name, _, _, _) in enumerate(instrs)}

    def dominant(idxs):
        if not idxs:
            return idxs
        floor = max(instrs[i][3] for i in idxs) / 8
        return [i for i in idxs if instrs[i][3] >= floor]

    # -- async route: explicit start/done pairs in the schedule --------
    # two async spellings: dedicated fused opcodes (all-gather-start,
    # collective-permute-start, ...) and the generic async-start whose
    # calls= computation wraps the collective (reduce-scatter /
    # all-to-all on TPU)
    comp_colls = _computation_collectives(text)

    def async_coll(i):
        _, op, refs, _ = instrs[i]
        if op.endswith("-start") and op[:-6] in _OVERLAP_COLL_OPS:
            return op[:-6]
        if op == "async-start":
            for r in refs:
                if comp_colls.get(r):
                    return sorted(comp_colls[r])[0]
        return None

    start_coll = {i: c for i in range(len(instrs))
                  if (c := async_coll(i)) is not None}
    starts = dominant(list(start_coll))
    if starts:
        overlapped = 0
        for i in starts:
            done_ops = {start_coll[i] + "-done", "async-done"}
            # follow the start's async value through any async-update
            # links to its done
            aliases = {instrs[i][0]}
            done = None
            for j in range(i + 1, len(instrs)):
                nm, op, refs, _ = instrs[j]
                if op == "async-update" and aliases & set(refs):
                    aliases.add(nm)
                elif op in done_ops and aliases & set(refs):
                    done = j
                    break
            if done is None:
                continue
            # same witness floor as the sync route: scalar bookkeeping
            # scheduled between start and done is not hiding the comm
            wfloor = max(instrs[i][3] // 8, 16)
            if any(instrs[k][1] in _HLO_COMPUTE_OPS
                   and instrs[k][3] >= wfloor
                   for k in range(i + 1, done)):
                overlapped += 1
        if 2 * overlapped <= len(starts):
            emit("APX217",
                 f"{name}: only {overlapped}/{len(starts)} async "
                 f"collective pair(s) in the compiled schedule have a "
                 f"compute op between start and done — the comm is "
                 f"async in name only and still serializes the critical "
                 f"path")
        return

    # -- sync route: dependency-graph schedulability -------------------
    colls = dominant([i for i, (_, op, _, _) in enumerate(instrs)
                      if op in _OVERLAP_COLL_OPS])
    if len(colls) < 2:
        emit("APX217",
             f"{name}: the compiled executable carries "
             f"{len(colls)} dominant collective(s) — the overlap "
             f"restructuring (per-span gathers / ring chunks) did not "
             f"survive lowering, so there is nothing a scheduler could "
             f"overlap")
        return
    # ancestors as bitsets over instruction indices (defs precede uses)
    anc = [0] * len(instrs)
    for i, (_, _, refs, _) in enumerate(instrs):
        a = 0
        for rname in refs:
            j = index.get(rname)
            if j is not None and j < i:
                a |= anc[j] | (1 << j)
        anc[i] = a
    compute = [i for i, (_, op, _, _) in enumerate(instrs)
               if op in _HLO_COMPUTE_OPS]
    overlapped = 0
    for c in colls:
        wfloor = max(instrs[c][3] // 8, 16)
        if any(instrs[f][3] >= wfloor
               and not (anc[f] & (1 << c)) and not (anc[c] & (1 << f))
               for f in compute):
            overlapped += 1
    if 2 * overlapped <= len(colls):
        emit("APX217",
             f"{name}: only {overlapped}/{len(colls)} dominant "
             f"collective(s) in the compiled executable have substantial "
             f"compute a scheduler could run between their start and "
             f"done (the rest each gate — or hang off — every compute "
             f"op); decompose the collective along the consumption "
             f"order (per-span gathers, ring chunks) so comm hides "
             f"under compute")


# ---------------------------------------------------------------------------
# audit driver
# ---------------------------------------------------------------------------

def _audit_exec(spec: ExecSpec) -> tuple:
    """-> (findings, budget_entry or None)"""
    import jax

    findings: list = []

    def emit(rule, msg):
        findings.append(Finding(rule, spec.path, 0, 0, msg,
                                line_text=f"{spec.name}:{rule}"))

    try:
        fn, args, axis_sizes = spec.build()
    except ImportError:
        return [], None  # optional dependency absent
    except Exception as e:  # noqa: BLE001 — a broken builder is a finding
        emit("APX210", f"building {spec.name} failed: "
                       f"{type(e).__name__}: {e}")
        return findings, None
    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:  # noqa: BLE001 — any trace failure is a finding
        emit("APX210", f"tracing {spec.name} failed: "
                       f"{type(e).__name__}: {e}")
        return findings, None

    # APX211 — axis soundness over the whole program
    bound = set(axis_sizes)
    for j in _iter_jaxprs(closed.jaxpr):
        for eqn in j.eqns:
            if eqn.primitive.name in COLLECTIVE_PRIMS or \
                    eqn.primitive.name == "axis_index":
                for ax in collective_axes(eqn):
                    if ax not in CANONICAL_AXES:
                        emit("APX211",
                             f"{spec.name}: {eqn.primitive.name} rides "
                             f"axis {ax!r}, which is not one of "
                             f"parallel_state's mesh axes "
                             f"{sorted(CANONICAL_AXES)}")
                    elif bound and ax not in bound:
                        emit("APX211",
                             f"{spec.name}: {eqn.primitive.name} names "
                             f"axis {ax!r} but the executable's mesh "
                             f"binds only {sorted(bound)}")

    # APX212/APX213 — branch parity + replica-uniformity dataflow,
    # seeded from each shard_map eqn's in_names
    uni = _Uniformity(spec, emit)
    for eqn in closed.jaxpr.eqns:
        if eqn.primitive.name != "shard_map":
            continue
        seed = []
        for names in eqn.params["in_names"]:
            seed.append(frozenset(
                ax for axes in names.values() for ax in axes))
        uni.run(eqn.params["jaxpr"], seed, checks=True)

    # APX214 — donation verification on the lowered executable
    if spec.donate_argnums or spec.flag_undonated:
        _check_donation(spec, fn, args, emit)

    # ONE compile per executable: APX217 reads its schedule, APX218
    # its cost/memory numbers
    compiled, compile_err = _compile_executable(spec, fn, args)

    # APX217 — comm/compute overlap on the COMPILED executable
    if spec.check_overlap:
        if compiled is None:
            _emit_compile_failed(emit, spec.name, compile_err)
        else:
            _check_async_overlap(spec, fn, args, emit, compiled=compiled)

    # comm/HBM ledger entry
    sizes = dict(axis_sizes)
    report = comm_report(closed, sizes)
    entry = {
        "comm_bytes": int(report["total_bytes"]),
        "by_collective": {k: int(v)
                          for k, v in sorted(report["by_collective"].items())},
        "collective_counts": {k: int(v)
                              for k, v in sorted(report["counts"].items())},
        "peak_live_bytes": int(peak_live_bytes(closed.jaxpr)),
        "axes": {k: int(v) for k, v in sorted(sizes.items())},
    }

    # APX218 — compiled-truth attribution from the SAME compile the
    # overlap check read.  XLA's cost/memory numbers (or an explicit
    # degradation marker — never a silent zero) ride the entry, with
    # the estimate/compiled drift ratios the budget ratchet watches.
    from apex_tpu.observability.xla_stats import (
        CompiledStats, PROVENANCE_UNAVAILABLE_PREFIX,
        stats_from_compiled)
    if compiled is None:
        stats = CompiledStats(
            provenance=PROVENANCE_UNAVAILABLE_PREFIX
            + f"compile-failed:{type(compile_err).__name__}")
    else:
        stats = stats_from_compiled(compiled)
    compiled_entry = stats.asdict()
    est_flops = int(jaxpr_dot_flops(closed))
    compiled_entry["dot_flops_estimate"] = est_flops
    if stats.flops and est_flops > 0:
        compiled_entry["dot_flops_drift"] = round(
            est_flops / stats.flops, 4)
    if stats.peak_hbm_bytes:
        compiled_entry["peak_live_drift"] = round(
            entry["peak_live_bytes"] / stats.peak_hbm_bytes, 4)
    entry["compiled"] = compiled_entry

    # APX216 — the PERF.md round-6 identity on the zero step's own
    # jaxpr: params all-gather bytes == grad reduce-scatter bytes
    # (i.e. RS + AG == ring all-reduce of the same flat buffer)
    if spec.rs_ag_identity:
        by = entry["by_collective"]
        ag = sum(v for k, v in by.items() if k.startswith("all_gather@"))
        rs = sum(v for k, v in by.items()
                 if k.startswith(("reduce_scatter@", "psum_scatter@")))
        entry["rs_ag_equals_ar"] = bool(ag > 0 and ag == rs)
        if not entry["rs_ag_equals_ar"]:
            emit("APX216",
                 f"{spec.name}: ZeRO comm identity broken — all_gather "
                 f"moves {ag} B/chip vs reduce_scatter {rs} B/chip; "
                 f"RS+AG must equal the dense all-reduce (PERF.md "
                 f"round-6 accounting, machine-checked)")
    return findings, entry


def run_spmd_audit(execs: Optional[Sequence[str]] = None) -> tuple:
    """Audit every (or the named) registered multi-device executable.

    Returns ``(findings, report)`` where ``report`` is the budget
    ledger shape committed as ``.analysis_budget.json``:
    ``{"version": 1, "executables": {name: {comm_bytes, by_collective,
    collective_counts, peak_live_bytes, axes[, rs_ag_equals_ar]}}}``.
    """
    n = ensure_devices()
    if n < 2:
        raise RuntimeError(
            f"the SPMD audit needs >=2 host devices to bind mesh axes "
            f"(got {n}); the jax backend initialized before the audit "
            f"could request them — set XLA_FLAGS="
            f"--xla_force_host_platform_device_count=8")

    specs = exec_specs()
    if execs:
        wanted = set(execs)
        missing = wanted - {s.name for s in specs}
        if missing:
            raise ValueError(f"unknown executable(s): {sorted(missing)}")
        specs = [s for s in specs if s.name in wanted]

    from apex_tpu.transformer import parallel_state as ps
    saved_mesh = ps._MESH
    saved_vpp_rank = ps._VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    saved_vpp_world = ps._VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    findings: list = []
    executables: dict = {}
    try:
        for spec in specs:
            f, entry = _audit_exec(spec)
            findings.extend(f)
            if entry is not None:
                executables[spec.name] = entry
    finally:
        # the builders destroy/reinit topology freely; hand the caller
        # back EVERYTHING parallel_state tracks, not just the mesh
        ps._MESH = saved_mesh
        ps._VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = saved_vpp_rank
        ps._VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = saved_vpp_world
    return findings, {"version": 1, "executables": executables}


def _drift_distance(ratio: float) -> float:
    """Symmetric distance of an estimate/compiled ratio from 1 (2x over
    and 2x under are equally far); non-positive ratios are maximally
    wrong."""
    if ratio <= 0:
        return float("inf")
    return max(ratio, 1.0 / ratio)


def _compare_compiled(name: str, path: str, entry: dict, pinned: dict,
                      emit218) -> None:
    """APX218 half of the ratchet: compiled-truth attribution must
    exist (stats or an explicit degradation marker), must not silently
    degrade, and its drift ratios must stay inside the committed band."""
    comp = entry.get("compiled")
    if not isinstance(comp, dict) or "provenance" not in comp:
        emit218(name, path,
                f"{name}: budget entry carries no compiled-stats "
                f"attribution (neither XLA cost/memory numbers nor an "
                f"explicit degradation marker) — the auditor must "
                f"always attribute or mark, never skip silently")
        return
    pinned_comp = pinned.get("compiled")
    if not isinstance(pinned_comp, dict):
        emit218(name, path,
                f"{name}: executable has no committed compiled-stats "
                f"entry — run apex-tpu-analyze --spmd --write-budget "
                f"to pin its APX218 drift ledger")
        return
    # full > cost-only > unavailable: ANY slide down the provenance
    # ladder is a degradation (a full->cost-only slide silently
    # disables the peak-live drift ratchet, not just the cliff to
    # unavailable)
    from apex_tpu.observability.xla_stats import provenance_rank
    prov = comp["provenance"]
    pinned_prov = pinned_comp.get("provenance", "")
    if provenance_rank(prov) < provenance_rank(pinned_prov):
        emit218(name, path,
                f"{name}: compiled-stats attribution DEGRADED "
                f"({pinned_prov!r} -> {prov!r}) — the executable "
                f"stopped reporting stats it used to on this backend")
        return
    for key, est_name, truth_name in (
            ("peak_live_drift", "APX215 peak-live estimate",
             "compiled peak bytes"),
            ("dot_flops_drift", "comm_model dot-FLOPs",
             "compiled cost_analysis FLOPs")):
        cur, pin = comp.get(key), pinned_comp.get(key)
        if pin is not None and cur is None:
            emit218(name, path,
                    f"{name}: the {est_name} drift ratio vanished from "
                    f"the fresh entry (pinned {pin}) — the analytic "
                    f"estimate degenerated (e.g. to zero) and the "
                    f"ratchet lost its input; fix the model or re-pin "
                    f"consciously with --write-budget")
            continue
        if cur is None or pin is None:
            continue
        if _drift_distance(cur) > \
                _drift_distance(pin) * DRIFT_RATCHET_SLACK:
            emit218(name, path,
                    f"{name}: {est_name} drifted further from the "
                    f"{truth_name} ({pin} -> {cur}; band "
                    f"{_drift_distance(pin):.4f} x "
                    f"{DRIFT_RATCHET_SLACK}) — the analytic model and "
                    f"the compiled executable disagree more than they "
                    f"used to; fix the model or justify and re-pin "
                    f"with --write-budget")


def compare_budget(report: dict, committed: Optional[dict]) -> list:
    """Ratchet: findings for every executable whose comm bytes or peak
    estimate GREW vs the committed budget (or that the budget has never
    seen), APX215-coded; plus the APX218 compiled-truth checks — every
    entry must carry compiled stats (or an explicit degradation
    marker), and the estimate-vs-compiled drift ratios must stay inside
    the committed band.  Shrinkage is silent — re-pin with
    ``--write-budget``."""
    findings: list = []

    def emit(name, path, msg, rule="APX215"):
        findings.append(Finding(rule, path, 0, 0, msg,
                                line_text=f"{name}:{rule}"))

    def emit218(name, path, msg):
        emit(name, path, msg, rule="APX218")

    paths = {s.name: s.path for s in exec_specs()}
    base = (committed or {}).get("executables", {})
    for name, entry in report.get("executables", {}).items():
        path = paths.get(name, "<spmd_audit>")
        pinned = base.get(name)
        if pinned is None:
            emit(name, path,
                 f"{name}: executable has no committed budget entry — "
                 f"run apex-tpu-analyze --spmd --write-budget to pin "
                 f"its comm/HBM ledger")
            continue
        if entry["comm_bytes"] > pinned.get("comm_bytes", 0):
            emit(name, path,
                 f"{name}: collective bytes grew "
                 f"{pinned.get('comm_bytes', 0)} -> "
                 f"{entry['comm_bytes']} B/chip/step "
                 f"({entry['by_collective']}) — justify and re-pin with "
                 f"--write-budget, or remove the new collective")
        if entry["peak_live_bytes"] > pinned.get("peak_live_bytes", 0):
            emit(name, path,
                 f"{name}: peak-live-buffer estimate grew "
                 f"{pinned.get('peak_live_bytes', 0)} -> "
                 f"{entry['peak_live_bytes']} B — a new full-size "
                 f"temporary entered the executable")
        _compare_compiled(name, path, entry, pinned, emit218)
    return findings
