"""Finding record + stable fingerprinting for baseline suppression.

A finding's fingerprint must survive unrelated edits to the same file
(pure line-number shifts), so it is built from the *text* of the
offending line rather than its position: ``rule :: path :: sha1(line
text) :: occurrence-index``.  The index disambiguates several identical
lines tripping the same rule in one file (fingerprints stay stable as
long as their relative order does — the same contract pylint's
``symbol``-based baselines use).
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Finding:
    rule: str              # "APX101"
    path: str              # repo-relative posix path (or "<fixture>")
    line: int              # 1-based; 0 for whole-artifact findings
    col: int
    message: str
    line_text: str = ""    # stripped source of the offending line
    index: int = 0         # occurrence index among same (rule, path, line_text)

    @property
    def fingerprint(self) -> str:
        digest = hashlib.sha1(self.line_text.encode("utf-8")).hexdigest()[:12]
        return f"{self.rule}::{self.path}::{digest}::{self.index}"

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: {self.rule} {self.message}"


def assign_indices(findings: list[Finding]) -> list[Finding]:
    """Number findings that share (rule, path, line_text) by source order
    so their fingerprints are distinct and stable."""
    findings = sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))
    seen: dict[tuple[str, str, str], int] = {}
    out = []
    for f in findings:
        key = (f.rule, f.path, f.line_text)
        idx = seen.get(key, 0)
        seen[key] = idx + 1
        out.append(Finding(f.rule, f.path, f.line, f.col, f.message,
                           f.line_text, idx))
    return out
