"""The single registry of ``APEX_TPU_*`` environment knobs.

Every environment variable the package reads MUST have an entry here:
the APX108 lint rule flags any ``os.environ``/``os.getenv`` read of an
``APEX_TPU_``-prefixed name that is not registered, and the README
"Environment knobs" table is validated against this dict by
``tests/L0/run_analysis/test_env_registry.py`` — so the docs cannot
drift from the code, and a new knob cannot ship undocumented.

To add a knob: read it in code, add an :class:`EnvKnob` entry here,
and add the matching row to README.md; the lint + the doc test enforce
both halves.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

__all__ = ["EnvKnob", "KNOBS", "is_registered"]


@dataclass(frozen=True)
class EnvKnob:
    name: str
    default: str
    effect: str
    read_by: str          # module that consumes it


KNOBS: Dict[str, EnvKnob] = {k.name: k for k in [
    EnvKnob(
        name="APEX_TPU_CPP_EXT",
        default="0",
        effect="build-time: compile the optional C++ parity extension "
               "(csrc/) during `pip install`; everything degrades "
               "gracefully without it",
        read_by="setup.py"),
    EnvKnob(
        name="APEX_TPU_ATTN_XLA_MAX_SEQ",
        default="256",
        effect="flash_attention auto-dispatches padded sequences at or "
               "below this length to the fused-XLA path (measured "
               "kernel/XLA crossover, bench r5; 0 disables the XLA "
               "path); per-call override: flash_attention("
               "xla_max_seq=...)",
        read_by="apex_tpu/ops/attention.py"),
    EnvKnob(
        name="APEX_TPU_DECODE_XLA_MAX_SEQ",
        default="4096",
        effect="decode_attention uses the grouped-query XLA einsum "
               "chain at or below this cache length and the flash "
               "kernel above it (PROVISIONAL crossover, stamped into "
               "infer bench captures); per-call override: "
               "decode_attention(xla_max_seq=...)",
        read_by="apex_tpu/ops/attention.py"),
    EnvKnob(
        name="APEX_TPU_ZERO_PREFETCH",
        default="0",
        effect="number of layered-prefetch gather spans a ZeRO train "
               "state is built with when prefetch= is not passed: the "
               "flat master's param all-gather splits along leaf "
               "boundaries into this many independent per-span gathers "
               "XLA overlaps with the consuming layers (APX217-"
               "verified; 0/1 = monolithic gather); stamped into ZeRO "
               "bench captures as zero_prefetch",
        read_by="apex_tpu/train_step.py"),
    EnvKnob(
        name="APEX_TPU_TP_OVERLAP_CHUNKS",
        default="1",
        effect="default overlap_chunks for tensor-parallel Column/Row "
               "layers: >1 decomposes the row-parallel matmul+psum "
               "(and the column-parallel backward psum) into an "
               "N-chunk matmul/ppermute ring pipeline at identical "
               "ring bytes (1 = fused psum; must be a multiple of the "
               "tensor axis size); per-layer override: "
               "overlap_chunks=; stamped into TP bench captures as "
               "tp_overlap_chunks",
        read_by="apex_tpu/transformer/tensor_parallel/mappings.py"),
    EnvKnob(
        name="APEX_TPU_PAGE_SIZE",
        default="64",
        effect="default KV page size (tokens per page, power of two) "
               "for paged inference engines that don't pass "
               "page_size= explicitly; stamped into paged infer bench "
               "captures",
        read_by="apex_tpu/inference/kv_cache.py"),
    EnvKnob(
        name="APEX_TPU_XENT_CHUNK",
        default="0",
        effect="token-chunk size of the fused LM-head+cross-entropy "
               "(the [tokens, vocab] logits never materialize; the "
               "backward re-projects per chunk) used by loss heads "
               "when fused_head_xent=/token_chunk= is not passed; 0 "
               "keeps the unfused dense logits; stamped into "
               "xent_fused bench captures as xent_chunk",
        read_by="apex_tpu/ops/fused_lm_xent.py"),
    EnvKnob(
        name="APEX_TPU_XENT_VOCAB_CHUNK",
        default="0",
        effect="vocab-chunk size of the fused LM-head+cross-entropy's "
               "inner online-logsumexp scan (shrinks the per-chunk "
               "logits transient to [token_chunk, vocab_chunk]; must "
               "divide the vocab) when vocab_chunk= is not passed; 0 "
               "projects the whole vocab per token chunk; stamped "
               "into xent_fused bench captures as xent_vocab_chunk",
        read_by="apex_tpu/ops/fused_lm_xent.py"),
    EnvKnob(
        name="APEX_TPU_TELEMETRY",
        default="0",
        effect="runtime telemetry sink directory: a path attaches the "
               "JSONL event log (telemetry.jsonl) and the Prometheus "
               "text-exposition file (metrics.prom) to the global "
               "metrics registry at first use; 0 keeps telemetry "
               "in-process only (instruments still work, nothing is "
               "written); schema pinned by .telemetry_schema.json",
        read_by="apex_tpu/observability/__init__.py"),
    EnvKnob(
        name="APEX_TPU_PROFILE_DIR",
        default="0",
        effect="profiler capture directory: a path arms observability."
               "profile_capture() — bench legs and examples/generate.py "
               "drop jax.profiler (TensorBoard/xprof) traces there, and "
               "the main bench leg re-ingests them (trace_ingest) into "
               "measured attribution stamps; an unwritable or already-"
               "populated dir degrades to a no-op with a "
               "profile_skipped event (never shadows an old trace); 0 "
               "disables capture (the context manager is a no-op)",
        read_by="apex_tpu/observability/tracing.py"),
    EnvKnob(
        name="APEX_TPU_NUMERICS",
        default="0",
        effect="numerics observability mode (grad/param/update-norm "
               "probes + overflow autopsy) for instrumented_train_loop "
               "when numerics= is not passed: 1 computes the in-program "
               "probes as extra outputs of the same ONE donated step "
               "and arms the numerics metric families + JSONL events "
               "(zero added syncs, zero recompiles); 0 (default) off; "
               "stamped into train bench captures as numerics",
        read_by="apex_tpu/observability/numerics.py"),
    EnvKnob(
        name="APEX_TPU_NUMERICS_EVERY",
        default="1",
        effect="numerics NORM-probe sampling interval: observe the "
               "norm probes every Nth step (host-side choice of what "
               "the deferred collector enqueues — the compiled step is "
               "identical at every value, so flipping it can never "
               "recompile); the overflow autopsy's per-leaf nonfinite "
               "vector and loss-scale backoff/growth tracking ride "
               "every step regardless; stamped into train bench "
               "captures as numerics_every",
        read_by="apex_tpu/observability/numerics.py"),
    EnvKnob(
        name="APEX_TPU_PREFIX_CACHE",
        default="1",
        effect="shared-prefix KV page sharing for paged schedulers: 1 "
               "(default) matches each prompt against the host radix "
               "prefix cache and maps cached prefix pages into the "
               "slot's page-table row at one reference each "
               "(refcount + copy-on-write; only the uncached tail "
               "prefills); 0 disables matching and insertion (every "
               "admission prefills cold); per-scheduler override: "
               "SlotScheduler(prefix_cache=); stamped into paged "
               "infer bench captures as infer_prefix_cache",
        read_by="apex_tpu/inference/prefix_cache.py"),
    EnvKnob(
        name="APEX_TPU_PREFILL_CHUNK",
        default="0",
        effect="chunked-prefill chunk size in tokens for paged "
               "schedulers (must be a multiple of the page size): "
               "prompts longer than this prefill in chunks interleaved "
               "with decode steps so a long-prompt burst cannot stall "
               "in-flight decode tokens for a whole monolithic "
               "prefill; 0 (default) keeps monolithic prefill; "
               "per-scheduler override: SlotScheduler(prefill_chunk=); "
               "stamped into paged infer bench captures as "
               "infer_prefill_chunk",
        read_by="apex_tpu/inference/scheduler.py"),
    EnvKnob(
        name="APEX_TPU_TENANT_PRIORITY",
        default="0",
        effect="per-tenant admission-priority overrides for the "
               "SLO-aware scheduler, as 'tenantA=10,tenantB=-1' "
               "(added to each request's own priority when picking "
               "the next admission; ties go to the least recently "
               "admitted tenant, then FIFO); 0/empty (default) = no "
               "overrides; per-scheduler override: "
               "SlotScheduler(tenant_priority=)",
        read_by="apex_tpu/inference/scheduler.py"),
    EnvKnob(
        name="APEX_TPU_TRACE",
        default="0",
        effect="request-trace sampling for serving schedulers: 0 "
               "(default) off, 1 traces every request, N traces one "
               "request in N (uid % N == 0) — each sampled request's "
               "lifecycle lands in the JSONL stream as trace_span "
               "events (queued/admitted/prefill_chunk/cow_copy/"
               "first_token/decode/retired) rendered by `report "
               "--trace <uid>`; host-side only (the tracer never "
               "enters jitted code), so no value can add a sync or "
               "recompile; per-telemetry override: ServeTelemetry("
               "trace=); stamped into infer bench captures as "
               "infer_trace",
        read_by="apex_tpu/observability/spans.py"),
    EnvKnob(
        name="APEX_TPU_SLO_TTFT_US",
        default="0",
        effect="TTFT p99 SLO target in microseconds (0 = off): arms a "
               "ttft_p99 objective over serve_ttft_seconds — per-wave "
               "burn-rate/error-budget gauges, slo_violation events "
               "when a window burns faster than its 1% budget "
               "(bucket-resolution accounting off the pinned "
               "histogram; host-side only, can never recompile); "
               "per-scheduler override: SlotScheduler(slo=); stamped "
               "into infer bench captures as infer_slo_ttft (µs)",
        read_by="apex_tpu/observability/slo.py"),
    EnvKnob(
        name="APEX_TPU_SLO_DECODE_US",
        default="0",
        effect="decode-token p99 SLO target in microseconds (0 = "
               "off): arms a decode_token_p99 objective over "
               "serve_decode_token_seconds — same burn-rate/error-"
               "budget accounting as APEX_TPU_SLO_TTFT_US; stamped "
               "into infer bench captures as infer_slo_decode (µs)",
        read_by="apex_tpu/observability/slo.py"),
    EnvKnob(
        name="APEX_TPU_DECODE_FUSION",
        default="0",
        effect="fused transformer-block decode for paged engines: 1 "
               "lowers every decode-layer as ONE Pallas kernel (norm "
               "+ qkv + RoPE + paged attention incl. the current "
               "token + out-proj + MLP; weights resident in VMEM, "
               "activations never round-trip HBM between sublayers), "
               "0 (default) keeps the per-op XLA path bitwise, auto "
               "fuses when the per-slot window reaches "
               "APEX_TPU_FUSION_MIN_PAGES pages; resolved STATICALLY "
               "at engine construction (one decode executable either "
               "way); per-engine override: InferenceEngine("
               "decode_fusion=); stamped into paged infer bench "
               "captures as infer_decode_fusion",
        read_by="apex_tpu/ops/paged_attention.py"),
    EnvKnob(
        name="APEX_TPU_FUSION_MIN_PAGES",
        default="8",
        effect="auto-mode crossover for APEX_TPU_DECODE_FUSION: fuse "
               "the decode block when max_pages_per_slot is at least "
               "this many pages (PROVISIONAL, stamped into paged "
               "infer bench captures as infer_fusion_min_pages); "
               "per-engine override: InferenceEngine("
               "fusion_min_pages=)",
        read_by="apex_tpu/ops/paged_attention.py"),
    EnvKnob(
        name="APEX_TPU_SPEC_K",
        default="0",
        effect="speculative decoding: drafted tokens per decode round "
               "(0 = off).  Engines built with spec_k > 0 serve "
               "decode through ONE compiled verify executable per k "
               "(slab width k+1 is static) scoring all drafts + the "
               "bonus token in one batched paged-attention step; "
               "accept/reject is an in-program length rollback "
               "(pages already reserved, rejection releases "
               "nothing).  Per-engine override: InferenceEngine("
               "spec_k=); stamped into infer bench captures as "
               "infer_spec_k",
        read_by="apex_tpu/inference/speculative.py"),
    EnvKnob(
        name="APEX_TPU_SERVE_TP",
        default="0",
        effect="tensor-parallel serving width (ISSUE 17): 0/unset = "
               "single chip; N > 1 shards the engine's param mirrors "
               "column/row-wise and the paged kv pool over kv heads "
               "across an N-chip mesh — each step stays ONE donated "
               "executable (a shard_map mesh program), the page "
               "table/allocator/prefix cache stay replicated host-side "
               "logic.  Requires the paged cache; needs tp | heads and "
               "tp | kv_heads or kv_heads | tp (GQA/MQA replicate "
               "below tp).  Per-engine override: InferenceEngine(tp=); "
               "stamped into infer bench captures as infer_serve_tp",
        read_by="apex_tpu/inference/engine.py"),
    EnvKnob(
        name="APEX_TPU_HOST_KV_TIER_BYTES",
        default="0",
        effect="host-DRAM KV page tier byte budget for paged serving "
               "(ISSUE 18): > 0 arms a second cache tier under the "
               "prefix cache — LRU eviction copies full prefix pages "
               "to host RAM (the HBM page frees immediately) instead "
               "of discarding them, and a later hit uploads them back "
               "in fixed-width batches overlapped with chunked prefill "
               "of the uncached tail; 0 (default) keeps discard-on-"
               "evict.  Requires the paged cache.  Per-engine "
               "override: InferenceEngine(host_tier_bytes=); stamped "
               "into paged infer bench captures as "
               "infer_host_tier_bytes",
        read_by="apex_tpu/inference/engine.py"),
    EnvKnob(
        name="APEX_TPU_SWAP_BATCH_PAGES",
        default="8",
        effect="pages per swap copy batch for the host KV tier: both "
               "swap directions run ONE fixed-width executable each "
               "(shorter batches pad with the trash page / an OOB "
               "drop sentinel), so swap traffic can never recompile; "
               "per-engine override: InferenceEngine("
               "swap_batch_pages=); stamped into paged infer bench "
               "captures as infer_swap_batch_pages",
        read_by="apex_tpu/inference/kv_cache.py"),
    EnvKnob(
        name="APEX_TPU_FLEET_REPLICAS",
        default="0",
        effect="replica count for the fleet front door (ISSUE 19): "
               "> 0 makes bench's fleet leg / examples build this "
               "many engine+scheduler replicas behind one FleetRouter "
               "(process-local, equal aggregate HBM); 0 (default) "
               "serves behind one standalone scheduler.  Stamped into "
               "fleet bench captures as fleet_replicas",
        read_by="apex_tpu/fleet/router.py"),
    EnvKnob(
        name="APEX_TPU_FLEET_POLICY",
        default="prefix_affinity",
        effect="routing policy when FleetRouter(policy=None): "
               "round_robin, least_loaded, or prefix_affinity "
               "(read-only radix peek + swap-aware admission cost, "
               "with a load-aware spill threshold); stamped into "
               "fleet bench captures as fleet_policy",
        read_by="apex_tpu/fleet/router.py"),
    EnvKnob(
        name="APEX_TPU_PAGED_XLA_MAX_PAGES",
        default="64",
        effect="paged_decode_attention gathers slot windows through "
               "the XLA einsum chain at or below this many pages per "
               "slot and streams pages with the Pallas kernel above "
               "it (PROVISIONAL crossover, stamped into paged infer "
               "bench captures); per-call override: "
               "paged_decode_attention(xla_max_pages=...)",
        read_by="apex_tpu/ops/paged_attention.py"),
    EnvKnob(
        name="APEX_TPU_PROTOCOL_SCOPE",
        default="0",
        effect="comma-separated scope names `apex-tpu-analyze "
               "--protocol` restricts the protocol audit to "
               "(core/tiered/fleet; `0`/unset = all committed "
               "scopes); a restricted run refuses --write-protocol "
               "so the shared pin always covers every scope",
        read_by="apex_tpu/analysis/protocol_audit.py"),
]}


def is_registered(name: str) -> bool:
    return name in KNOBS
