"""``python -m apex_tpu.analysis`` / ``apex-tpu-analyze`` entry point.

Runs the engines over the package, subtracts the committed baseline
(``.analysis_baseline.json``), and exits nonzero only on NEW findings —
the ratchet pattern: pre-existing debt is pinned, regressions fail CI.
``--spmd`` adds the SPMD soundness auditor + the comm/HBM budget
ledger, ratcheted against the committed ``.analysis_budget.json``
(exit nonzero only when a registered executable's collective bytes or
peak-live estimate GROWS).  ``--kernels`` adds the Pallas kernel VMEM
auditor + the kernel budget ledger, ratcheted the same way against
``.analysis_kernel_budget.json`` (exit nonzero only when a kernel's
modeled VMEM footprint grows or a kernel is unbudgeted).
``--protocol`` adds the serving control-plane protocol auditor:
exhaustive small-scope model checking of the allocator/prefix-cache/
host-tier/scheduler/router state machines, pinned against
``.analysis_protocol.json`` (exit nonzero on an invariant violation —
with a minimized replayable counterexample — or when a scope's
canonical state space drifts from the pin).

    apex-tpu-analyze                       # lint + jaxpr audit, baseline-gated
    apex-tpu-analyze --spmd                # + SPMD audit, budget-gated
    apex-tpu-analyze --spmd --json         # machine-readable (schema: README)
    apex-tpu-analyze --kernels             # + Pallas VMEM audit, budget-gated
    apex-tpu-analyze --kernels --mesh tp=2 # + 1/tp-sharded fused-decode envelope
    apex-tpu-analyze --protocol            # + protocol audit, pin-gated
    apex-tpu-analyze --protocol --protocol-scope fleet   # one scope only
    apex-tpu-analyze path/ other.py        # restrict lint to paths
    apex-tpu-analyze --write-baseline      # re-pin current findings
    apex-tpu-analyze --spmd --write-budget # re-pin the comm/HBM ledger
    apex-tpu-analyze --kernels --write-budget  # re-pin the kernel VMEM ledger
    apex-tpu-analyze --protocol --write-protocol  # re-pin the protocol ledger
    apex-tpu-analyze --no-baseline         # show everything, exit 1 if any
    apex-tpu-analyze --list-rules
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Optional

from apex_tpu.analysis.finding import Finding
from apex_tpu.analysis.lint import lint_paths

BASELINE_NAME = ".analysis_baseline.json"
DEFAULT_SCAN = ("apex_tpu", "bench.py", "examples", "tests")


def repo_root() -> Path:
    """The tree the default scan targets.  Source checkouts (the normal
    case) resolve from the package location; for an installed wheel —
    whose parent is site-packages, which also contains an ``apex_tpu``
    dir — prefer a repo-shaped cwd so the *checkout* gets linted and its
    baseline found."""
    import apex_tpu
    pkg_parent = Path(apex_tpu.__file__).resolve().parent.parent
    if (pkg_parent / "pyproject.toml").is_file():
        return pkg_parent
    cwd = Path.cwd()
    if (cwd / "apex_tpu").is_dir():
        return cwd
    return pkg_parent


def load_baseline(path: Path) -> set:
    data = json.loads(path.read_text(encoding="utf-8"))
    return {e["fingerprint"] for e in data.get("findings", [])}


def write_baseline(path: Path, findings: list) -> None:
    entries = [{
        "fingerprint": f.fingerprint,
        "rule": f.rule,
        "path": f.path,
        "line": f.line,
        "message": f.message,
        "line_text": f.line_text,
    } for f in sorted(findings, key=lambda f: f.fingerprint)]
    path.write_text(
        json.dumps({"version": 1, "findings": entries}, indent=1) + "\n",
        encoding="utf-8")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="apex-tpu-analyze",
        description="JAX/TPU static analysis: AST lint + jaxpr audit")
    p.add_argument("paths", nargs="*",
                   help=f"files/dirs to lint (default: {DEFAULT_SCAN} "
                        f"under the repo root)")
    p.add_argument("--baseline", type=Path, default=None,
                   help=f"suppression file (default: <root>/{BASELINE_NAME})")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline; report everything")
    p.add_argument("--write-baseline", action="store_true",
                   help="pin the current findings as the new baseline")
    p.add_argument("--no-lint", action="store_true",
                   help="skip the AST lint engine")
    p.add_argument("--no-jaxpr", action="store_true",
                   help="skip the jaxpr precision/transfer audit")
    p.add_argument("--ops", default=None,
                   help="comma-separated op names for the jaxpr audit")
    p.add_argument("--spmd", action="store_true",
                   help="run the SPMD soundness auditor + comm/HBM "
                        "budget ledger over the registered multi-device "
                        "executables")
    p.add_argument("--execs", default=None,
                   help="comma-separated executable names for the SPMD "
                        "audit (default: all registered)")
    p.add_argument("--budget", type=Path, default=None,
                   help="comm/HBM ledger file (default: "
                        "<root>/.analysis_budget.json)")
    p.add_argument("--kernels", action="store_true",
                   help="run the Pallas kernel VMEM auditor + the "
                        "kernel budget ledger over the registered "
                        "Pallas kernel ops")
    p.add_argument("--kernel-ops", default=None,
                   help="comma-separated op names for the kernel audit "
                        "(default: all registered)")
    p.add_argument("--kernel-budget", type=Path, default=None,
                   help="kernel VMEM ledger file (default: "
                        "<root>/.analysis_kernel_budget.json)")
    p.add_argument("--mesh", default=None, metavar="tp=N",
                   help="with --kernels: also price the 1/tp-sharded "
                        "fused_block_decode VMEM envelope (ROADMAP "
                        "item 1's static feasibility check)")
    p.add_argument("--chip", default=None,
                   help="chip generation for VMEM capacity (default: "
                        "the chip_specs default)")
    p.add_argument("--write-budget", action="store_true",
                   help="pin the current ledger(s) as the new budget "
                        "(implies --spmd when --kernels is absent)")
    p.add_argument("--protocol", action="store_true",
                   help="run the serving control-plane protocol "
                        "auditor: exhaustive small-scope model "
                        "checking of the allocator/prefix-cache/"
                        "host-tier/scheduler/router state machines, "
                        "pinned against .analysis_protocol.json")
    p.add_argument("--protocol-scope", default=None,
                   help="comma-separated protocol scope names to "
                        "explore (default: APEX_TPU_PROTOCOL_SCOPE, "
                        "else all committed scopes)")
    p.add_argument("--protocol-pin", type=Path, default=None,
                   help="protocol pin file (default: "
                        "<root>/.analysis_protocol.json)")
    p.add_argument("--write-protocol", action="store_true",
                   help="pin the current protocol exploration "
                        "(scope configs + canonical state-space "
                        "sizes) as the new .analysis_protocol.json")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="only the summary line")
    return p


def main(argv: Optional[list] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        from apex_tpu.analysis.rules import all_rules
        for rule in all_rules():
            print(f"{rule.id}  {rule.name:<28} {rule.description}")
        print("APX200 audit-trace-failure         jaxpr audit: op failed "
              "to trace under the policy")
        print("APX201 unexplained-upcast          jaxpr audit: bf16→fp32 "
              "convert feeding no accumulator")
        print("APX202 host-transfer-in-kernel     jaxpr audit: callback/"
              "device_put in a fused op body")
        print("APX203 output-dtype-policy         jaxpr audit: op output "
              "dtype violates the declared policy")
        print("APX210 spmd-trace-failure          spmd audit: executable "
              "failed to trace/lower")
        print("APX211 unsound-collective-axis     spmd audit: collective "
              "axis not in parallel_state's mesh / not bound")
        print("APX212 branch-collective-mismatch  spmd audit: cond/switch "
              "branches carry different collective multisets")
        print("APX213 non-uniform-control-value   spmd audit: rank-varying "
              "cond predicate / update-kernel noop_flag")
        print("APX214 donation-violation          spmd audit: declared "
              "donation not lowered, unaliasable, or missing")
        print("APX215 budget-growth               spmd audit: comm bytes / "
              "peak-live estimate grew past .analysis_budget.json")
        print("APX216 comm-identity-violation     spmd audit: ZeRO "
              "RS+AG==AR accounting broken (PERF.md round-6)")
        print("APX217 comm-not-overlapped         spmd audit: overlapped "
              "executable's compiled HLO has no async start/done pair "
              "(or schedulable compute) between collectives")
        print("APX218 compiled-drift              spmd audit: compiled-"
              "stats attribution missing/degraded, or the estimate-vs-"
              "compiled drift ratio left the committed band")
        print("APX300 kernel-trace-failure        pallas audit: kernel "
              "fixture failed to trace")
        print("APX301 vmem-envelope               pallas audit: modeled "
              "per-grid-step VMEM footprint exceeds chip capacity or "
              "grew past .analysis_kernel_budget.json")
        print("APX302 non-fp32-accumulator        pallas audit: reduction "
              "kernel's scratch / revisited output block is not fp32")
        print("APX303 grid-divisibility           pallas audit: block dim "
              "doesn't divide its operand dim and the kernel declares "
              "no masked tail")
        print("APX304 traced-index-map            pallas audit: BlockSpec "
              "index map captures a traced value")
        print("APX305 unbudgeted-kernel           pallas audit: reachable "
              "Pallas kernel has no kernel-budget entry")
        print("APX400 protocol-audit-drift        protocol audit: "
              "exploration crashed/truncated, pin missing, or a "
              "scope's canonical state space drifted from "
              ".analysis_protocol.json")
        from apex_tpu.analysis.protocol_audit import INVARIANTS
        for code, inv in INVARIANTS.items():
            print(f"{code} {inv['name']:<30} protocol audit: "
                  f"{inv['description']}")
        return 0

    # arg-syntax validation happens before ANY engine runs or file is
    # written: a typo in --mesh must not exit 2 having already rewritten
    # the kernel-budget ledger under --write-budget
    mesh_tp = None
    if args.mesh is not None:
        key, _, val = args.mesh.partition("=")
        if key.strip() != "tp" or not val.strip().isdigit() \
                or int(val) < 1:
            print(f"apex-tpu-analyze: --mesh expects tp=N (got "
                  f"{args.mesh!r})", file=sys.stderr)
            return 2
        mesh_tp = int(val)

    if args.write_budget and not args.kernels:
        args.spmd = True
    if args.spmd:
        # must run before ANY engine touches the backend: the audit
        # binds 2-device meshes, which need the forced host devices
        from apex_tpu.analysis.spmd_audit import ensure_devices
        ensure_devices()

    root = repo_root()
    findings: list = []

    if not args.no_lint:
        if args.paths:
            paths = args.paths
        else:
            paths = [str(root / p) for p in DEFAULT_SCAN
                     if (root / p).exists()]
        findings.extend(lint_paths(paths, root=str(root)))

    if not args.no_jaxpr:
        from apex_tpu.analysis.jaxpr_audit import run_jaxpr_audit
        ops = args.ops.split(",") if args.ops else None
        findings.extend(run_jaxpr_audit(ops))

    spmd_report = None
    if args.spmd:
        from apex_tpu.analysis.spmd_audit import (BUDGET_NAME,
                                                  compare_budget,
                                                  run_spmd_audit)
        execs = args.execs.split(",") if args.execs else None
        spmd_findings, spmd_report = run_spmd_audit(execs)
        findings.extend(spmd_findings)
        budget_path = args.budget or (root / BUDGET_NAME)
        if args.write_budget:
            # a filtered run must not replace the shared full ledger —
            # same protection as --write-baseline below
            if execs and args.budget is None:
                print("apex-tpu-analyze: refusing --write-budget for a "
                      "restricted --execs run targeting the shared "
                      f"{BUDGET_NAME}; pass --budget <file> or run all "
                      "executables", file=sys.stderr)
                return 2
            budget_path.write_text(
                json.dumps(spmd_report, indent=1) + "\n",
                encoding="utf-8")
            # stderr under --json: stdout must stay one parseable object
            print(f"budget written: {budget_path} "
                  f"({len(spmd_report['executables'])} executable(s) "
                  f"pinned)",
                  file=sys.stderr if args.as_json else sys.stdout)
        else:
            committed = (json.loads(budget_path.read_text(
                encoding="utf-8")) if budget_path.is_file() else None)
            findings.extend(compare_budget(spmd_report, committed))

    kernel_report = None
    mesh_report = None
    if args.kernels:
        from apex_tpu.analysis.pallas_audit import (
            BUDGET_NAME as KERNEL_BUDGET_NAME, compare_kernel_budget,
            predict_fusion_max_hidden, run_kernel_audit)
        kernel_ops = ([s.strip() for s in args.kernel_ops.split(",")
                       if s.strip()] if args.kernel_ops else None)
        try:
            kernel_findings, kernel_report = run_kernel_audit(
                kernel_ops, chip=args.chip)
        except ValueError as e:   # unknown --kernel-ops / --chip name
            print(f"apex-tpu-analyze: {e}", file=sys.stderr)
            return 2
        findings.extend(kernel_findings)
        kernel_budget_path = (args.kernel_budget
                              or (root / KERNEL_BUDGET_NAME))
        if args.write_budget:
            if kernel_ops and args.kernel_budget is None:
                print("apex-tpu-analyze: refusing --write-budget for a "
                      "restricted --kernel-ops run targeting the shared "
                      f"{KERNEL_BUDGET_NAME}; pass --kernel-budget "
                      "<file> or run all kernel ops", file=sys.stderr)
                return 2
            kernel_budget_path.write_text(
                json.dumps(kernel_report, indent=1) + "\n",
                encoding="utf-8")
            print(f"kernel budget written: {kernel_budget_path} "
                  f"({len(kernel_report['ops'])} op(s) pinned)",
                  file=sys.stderr if args.as_json else sys.stdout)
        else:
            committed = (json.loads(kernel_budget_path.read_text(
                encoding="utf-8"))
                if kernel_budget_path.is_file() else None)
            findings.extend(
                compare_kernel_budget(kernel_report, committed))

    protocol_report = None
    if args.write_protocol:
        args.protocol = True
    if args.protocol:
        from apex_tpu.analysis.protocol_audit import (
            PIN_NAME as PROTOCOL_PIN_NAME, compare_protocol,
            protocol_scope_env, run_protocol_audit)
        raw = args.protocol_scope
        scopes = ([s.strip() for s in raw.split(",") if s.strip()]
                  if raw else protocol_scope_env())
        pin_path = args.protocol_pin or (root / PROTOCOL_PIN_NAME)
        if args.write_protocol and scopes is not None \
                and args.protocol_pin is None:
            # validated BEFORE exploring: a scope-restricted pin would
            # silently drop every other scope's proof obligation —
            # same protection as the budget/baseline writers
            print("apex-tpu-analyze: refusing --write-protocol for a "
                  "restricted --protocol-scope run targeting the "
                  f"shared {PROTOCOL_PIN_NAME}; pass --protocol-pin "
                  "<file> or run all scopes", file=sys.stderr)
            return 2
        try:
            proto_findings, protocol_report = run_protocol_audit(
                scopes, repro_dir=root)
        except ValueError as e:     # unknown --protocol-scope names
            print(f"apex-tpu-analyze: {e}", file=sys.stderr)
            return 2
        findings.extend(proto_findings)
        if args.write_protocol:
            if proto_findings:
                print("apex-tpu-analyze: refusing --write-protocol "
                      "with protocol findings outstanding — a pin "
                      "must certify a violation-free exploration",
                      file=sys.stderr)
                return 1
            pin_path.write_text(
                json.dumps(protocol_report, indent=1, sort_keys=True)
                + "\n", encoding="utf-8")
            print(f"protocol pin written: {pin_path} "
                  f"({len(protocol_report['scopes'])} scope(s) "
                  f"pinned)",
                  file=sys.stderr if args.as_json else sys.stdout)
        else:
            committed = (json.loads(pin_path.read_text(
                encoding="utf-8")) if pin_path.is_file() else None)
            findings.extend(compare_protocol(
                protocol_report, committed, full=scopes is None))

    if args.kernels:
        if mesh_tp is not None:
            tp = mesh_tp
            mesh_report = {
                "unsharded": predict_fusion_max_hidden(
                    tp=1, chip=args.chip),
                "sharded": predict_fusion_max_hidden(
                    tp=tp, chip=args.chip),
            }
            if not args.as_json:
                u, s = mesh_report["unsharded"], mesh_report["sharded"]
                print(f"fused_block_decode VMEM envelope on "
                      f"{u['chip']}: tp=1 max_hidden={u['max_hidden']} "
                      f"(crossover {u['crossover_hidden']}); tp={tp} "
                      f"max_hidden={s['max_hidden']} (crossover "
                      f"{s['crossover_hidden']})")

    baseline_path = args.baseline or (root / BASELINE_NAME)
    if args.write_baseline:
        # a restricted scan must not silently replace the shared
        # full-repo baseline — that would drop every pinned finding
        # outside the scan scope and re-fail the next full run
        restricted = bool(args.paths) or args.no_lint or args.no_jaxpr
        if restricted and args.baseline is None:
            print("apex-tpu-analyze: refusing --write-baseline for a "
                  "restricted scan (paths/--no-lint/--no-jaxpr) targeting "
                  f"the shared {BASELINE_NAME}; pass --baseline <file> "
                  "to write a scoped baseline, or run the full scan",
                  file=sys.stderr)
            return 2
        write_baseline(baseline_path, findings)
        print(f"baseline written: {baseline_path} "
              f"({len(findings)} finding(s) pinned)")
        return 0

    baseline: set = set()
    if not args.no_baseline and baseline_path.is_file():
        baseline = load_baseline(baseline_path)

    new = [f for f in findings if f.fingerprint not in baseline]
    suppressed = len(findings) - len(new)

    if args.as_json:
        out = {
            "new": [f.__dict__ for f in new],
            "suppressed": suppressed,
            "total": len(findings),
        }
        if spmd_report is not None:
            out["budget"] = spmd_report
        if kernel_report is not None:
            out["kernel_budget"] = kernel_report
        if mesh_report is not None:
            out["mesh"] = mesh_report
        if protocol_report is not None:
            out["protocol"] = protocol_report
        print(json.dumps(out, indent=1))
    else:
        if not args.quiet:
            for f in new:
                print(f.render())
        status = "FAIL" if new else "OK"
        print(f"apex-tpu-analyze: {status} — {len(new)} new finding(s), "
              f"{suppressed} baselined, {len(findings)} total")
    return 1 if new else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
