"""``python -m apex_tpu.analysis`` / ``apex-tpu-analyze`` entry point.

Runs the engines over the package, subtracts the committed baseline
(``.analysis_baseline.json``), and exits nonzero only on NEW findings —
the ratchet pattern: pre-existing debt is pinned, regressions fail CI.
``--spmd`` adds the SPMD soundness auditor + the comm/HBM budget
ledger, ratcheted against the committed ``.analysis_budget.json``
(exit nonzero only when a registered executable's collective bytes or
peak-live estimate GROWS).

    apex-tpu-analyze                       # lint + jaxpr audit, baseline-gated
    apex-tpu-analyze --spmd                # + SPMD audit, budget-gated
    apex-tpu-analyze --spmd --json         # machine-readable (schema: README)
    apex-tpu-analyze path/ other.py        # restrict lint to paths
    apex-tpu-analyze --write-baseline      # re-pin current findings
    apex-tpu-analyze --spmd --write-budget # re-pin the comm/HBM ledger
    apex-tpu-analyze --no-baseline         # show everything, exit 1 if any
    apex-tpu-analyze --list-rules
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Optional

from apex_tpu.analysis.finding import Finding
from apex_tpu.analysis.lint import lint_paths

BASELINE_NAME = ".analysis_baseline.json"
DEFAULT_SCAN = ("apex_tpu", "bench.py", "examples", "tests")


def repo_root() -> Path:
    """The tree the default scan targets.  Source checkouts (the normal
    case) resolve from the package location; for an installed wheel —
    whose parent is site-packages, which also contains an ``apex_tpu``
    dir — prefer a repo-shaped cwd so the *checkout* gets linted and its
    baseline found."""
    import apex_tpu
    pkg_parent = Path(apex_tpu.__file__).resolve().parent.parent
    if (pkg_parent / "pyproject.toml").is_file():
        return pkg_parent
    cwd = Path.cwd()
    if (cwd / "apex_tpu").is_dir():
        return cwd
    return pkg_parent


def load_baseline(path: Path) -> set:
    data = json.loads(path.read_text(encoding="utf-8"))
    return {e["fingerprint"] for e in data.get("findings", [])}


def write_baseline(path: Path, findings: list) -> None:
    entries = [{
        "fingerprint": f.fingerprint,
        "rule": f.rule,
        "path": f.path,
        "line": f.line,
        "message": f.message,
        "line_text": f.line_text,
    } for f in sorted(findings, key=lambda f: f.fingerprint)]
    path.write_text(
        json.dumps({"version": 1, "findings": entries}, indent=1) + "\n",
        encoding="utf-8")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="apex-tpu-analyze",
        description="JAX/TPU static analysis: AST lint + jaxpr audit")
    p.add_argument("paths", nargs="*",
                   help=f"files/dirs to lint (default: {DEFAULT_SCAN} "
                        f"under the repo root)")
    p.add_argument("--baseline", type=Path, default=None,
                   help=f"suppression file (default: <root>/{BASELINE_NAME})")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline; report everything")
    p.add_argument("--write-baseline", action="store_true",
                   help="pin the current findings as the new baseline")
    p.add_argument("--no-lint", action="store_true",
                   help="skip the AST lint engine")
    p.add_argument("--no-jaxpr", action="store_true",
                   help="skip the jaxpr precision/transfer audit")
    p.add_argument("--ops", default=None,
                   help="comma-separated op names for the jaxpr audit")
    p.add_argument("--spmd", action="store_true",
                   help="run the SPMD soundness auditor + comm/HBM "
                        "budget ledger over the registered multi-device "
                        "executables")
    p.add_argument("--execs", default=None,
                   help="comma-separated executable names for the SPMD "
                        "audit (default: all registered)")
    p.add_argument("--budget", type=Path, default=None,
                   help="comm/HBM ledger file (default: "
                        "<root>/.analysis_budget.json)")
    p.add_argument("--write-budget", action="store_true",
                   help="pin the current comm/HBM ledger as the new "
                        "budget (implies --spmd)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="only the summary line")
    return p


def main(argv: Optional[list] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        from apex_tpu.analysis.rules import all_rules
        for rule in all_rules():
            print(f"{rule.id}  {rule.name:<28} {rule.description}")
        print("APX200 audit-trace-failure         jaxpr audit: op failed "
              "to trace under the policy")
        print("APX201 unexplained-upcast          jaxpr audit: bf16→fp32 "
              "convert feeding no accumulator")
        print("APX202 host-transfer-in-kernel     jaxpr audit: callback/"
              "device_put in a fused op body")
        print("APX203 output-dtype-policy         jaxpr audit: op output "
              "dtype violates the declared policy")
        print("APX210 spmd-trace-failure          spmd audit: executable "
              "failed to trace/lower")
        print("APX211 unsound-collective-axis     spmd audit: collective "
              "axis not in parallel_state's mesh / not bound")
        print("APX212 branch-collective-mismatch  spmd audit: cond/switch "
              "branches carry different collective multisets")
        print("APX213 non-uniform-control-value   spmd audit: rank-varying "
              "cond predicate / update-kernel noop_flag")
        print("APX214 donation-violation          spmd audit: declared "
              "donation not lowered, unaliasable, or missing")
        print("APX215 budget-growth               spmd audit: comm bytes / "
              "peak-live estimate grew past .analysis_budget.json")
        print("APX216 comm-identity-violation     spmd audit: ZeRO "
              "RS+AG==AR accounting broken (PERF.md round-6)")
        print("APX217 comm-not-overlapped         spmd audit: overlapped "
              "executable's compiled HLO has no async start/done pair "
              "(or schedulable compute) between collectives")
        print("APX218 compiled-drift              spmd audit: compiled-"
              "stats attribution missing/degraded, or the estimate-vs-"
              "compiled drift ratio left the committed band")
        return 0

    if args.write_budget:
        args.spmd = True
    if args.spmd:
        # must run before ANY engine touches the backend: the audit
        # binds 2-device meshes, which need the forced host devices
        from apex_tpu.analysis.spmd_audit import ensure_devices
        ensure_devices()

    root = repo_root()
    findings: list = []

    if not args.no_lint:
        if args.paths:
            paths = args.paths
        else:
            paths = [str(root / p) for p in DEFAULT_SCAN
                     if (root / p).exists()]
        findings.extend(lint_paths(paths, root=str(root)))

    if not args.no_jaxpr:
        from apex_tpu.analysis.jaxpr_audit import run_jaxpr_audit
        ops = args.ops.split(",") if args.ops else None
        findings.extend(run_jaxpr_audit(ops))

    spmd_report = None
    if args.spmd:
        from apex_tpu.analysis.spmd_audit import (BUDGET_NAME,
                                                  compare_budget,
                                                  run_spmd_audit)
        execs = args.execs.split(",") if args.execs else None
        spmd_findings, spmd_report = run_spmd_audit(execs)
        findings.extend(spmd_findings)
        budget_path = args.budget or (root / BUDGET_NAME)
        if args.write_budget:
            # a filtered run must not replace the shared full ledger —
            # same protection as --write-baseline below
            if execs and args.budget is None:
                print("apex-tpu-analyze: refusing --write-budget for a "
                      "restricted --execs run targeting the shared "
                      f"{BUDGET_NAME}; pass --budget <file> or run all "
                      "executables", file=sys.stderr)
                return 2
            budget_path.write_text(
                json.dumps(spmd_report, indent=1) + "\n",
                encoding="utf-8")
            # stderr under --json: stdout must stay one parseable object
            print(f"budget written: {budget_path} "
                  f"({len(spmd_report['executables'])} executable(s) "
                  f"pinned)",
                  file=sys.stderr if args.as_json else sys.stdout)
        else:
            committed = (json.loads(budget_path.read_text(
                encoding="utf-8")) if budget_path.is_file() else None)
            findings.extend(compare_budget(spmd_report, committed))

    baseline_path = args.baseline or (root / BASELINE_NAME)
    if args.write_baseline:
        # a restricted scan must not silently replace the shared
        # full-repo baseline — that would drop every pinned finding
        # outside the scan scope and re-fail the next full run
        restricted = bool(args.paths) or args.no_lint or args.no_jaxpr
        if restricted and args.baseline is None:
            print("apex-tpu-analyze: refusing --write-baseline for a "
                  "restricted scan (paths/--no-lint/--no-jaxpr) targeting "
                  f"the shared {BASELINE_NAME}; pass --baseline <file> "
                  "to write a scoped baseline, or run the full scan",
                  file=sys.stderr)
            return 2
        write_baseline(baseline_path, findings)
        print(f"baseline written: {baseline_path} "
              f"({len(findings)} finding(s) pinned)")
        return 0

    baseline: set = set()
    if not args.no_baseline and baseline_path.is_file():
        baseline = load_baseline(baseline_path)

    new = [f for f in findings if f.fingerprint not in baseline]
    suppressed = len(findings) - len(new)

    if args.as_json:
        out = {
            "new": [f.__dict__ for f in new],
            "suppressed": suppressed,
            "total": len(findings),
        }
        if spmd_report is not None:
            out["budget"] = spmd_report
        print(json.dumps(out, indent=1))
    else:
        if not args.quiet:
            for f in new:
                print(f.render())
        status = "FAIL" if new else "OK"
        print(f"apex-tpu-analyze: {status} — {len(new)} new finding(s), "
              f"{suppressed} baselined, {len(findings)} total")
    return 1 if new else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
