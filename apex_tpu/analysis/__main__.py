from apex_tpu.analysis.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
