"""Action/state harness for the serving control-plane protocol
auditor (ISSUE 20): the REAL host-side components — ``PageAllocator``,
``PrefixCache`` (radix + host-edge states), ``HostPageStore`` (eager
and deferred slabs), ``SlotScheduler``, ``FleetRouter`` — driven under
a DEVICE-FREE stub engine as an explicit transition system, so the
fifth analysis engine (:mod:`~apex_tpu.analysis.protocol_audit`) can
exhaustively explore small scopes of the serving protocol and assert
its conservation laws at every reachable state.

Three layers:

* :class:`StubEngine` / :class:`StubKVCache` — the whole device
  surface the scheduler touches (prefill / decode / cow_page /
  swap_out_pages / swap_in_pages / evict_slot + the geometry attrs),
  in pure numpy on the host.  Pages carry CONTENT TAGS (a stable
  polynomial hash of the tokens they hold) instead of k/v tensors, so
  invariants can detect a clobbered shared page or a corrupted swap
  slab, not just broken books.  Token emission is a pure function of
  (prompt, position): no RNG, no wall clock — the whole model is
  deterministic.
* :class:`ProtocolHarness` — one small-scope serving system (1..N
  replicas, optionally fronted by the real :class:`FleetRouter`) plus
  the ACTION ALPHABET: submit / scheduler pass (admission + chunked
  prefill + decode + retire, the host's atomic execution unit) / wave
  boundary / evict-to-host / drain_pending_swaps / shed / route
  (fleet submits go through the router) / the abstract disaggregation
  handoff pair (``handoff_extract`` on A → ``handoff_restore`` on B,
  modeled on the ISSUE 18 copy programs — model-checked BEFORE the
  real cross-replica handoff is implemented).  ``canonical()``
  projects the state onto its protocol-relevant core (books, tree
  shape with LRU ranks, queue/slot contents, page contents) and away
  from monotonic counters (uids, clocks, telemetry totals, SLO
  histograms) that never influence a decision at the explored scopes.
* :func:`explore` / :func:`replay` / :func:`shrink` — deterministic
  bounded-exhaustive breadth-first exploration with canonical-state
  dedup (breadth-first so a state is always reached by a SHORTEST
  trace — a depth-bounded DFS could dedup a state at depth d and miss
  its shallower continuations), trace replay (branching re-executes
  the action prefix from the initial state: the components hold locks
  and device-shaped buffers, so replay IS the snapshot mechanism and
  doubles as the counterexample repro path), and action-deletion
  counterexample minimization.

Soundness notes for the canonical projection (why deduping on it
cannot hide a violation): telemetry counters and SLO state feed no
control decision here — the explored scopes keep every queue shorter
than the overload detector's trip threshold (asserted at harness
build), and ``shed_on_overload`` stays False (shedding is an explicit
action through the same code path).  Uid VALUES key dicts but order
no decision; template identity, which determines all future behavior,
is in the projection.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from apex_tpu.inference.kv_cache import PageAllocator

__all__ = [
    "StubEngine", "StubKVCache", "StubPendingSwapOut", "Template",
    "Scope", "ProtocolHarness", "Action", "explore", "replay",
    "shrink", "random_walk", "ExploreResult", "Violation",
    "write_repro", "load_repro",
]

#: Queue depth at/above which the overload detector MAY start seeing
#: sustained pressure (its default ``queue_high``).  Exhaustive scopes
#: must stay strictly below it so SLO state never influences routing —
#: that is what licenses projecting SLO state out of ``canonical()``.
_DETECTOR_QUEUE_HIGH = 4

_MASK = (1 << 63) - 1


_TAG_SEED = 0x9E3779B97F4A7C15 & _MASK


def _mix(tag: int, token: int) -> int:
    """Fold one appended token into a page's content tag."""
    return (int(tag) * 1000003 + int(token) * 31 + 7) & _MASK


def _tag(tokens: Sequence[int]) -> int:
    """Stable polynomial hash of a token slice — page content tags.
    Defined as the left fold of :func:`_mix` so a page filled
    token-by-token by decode carries EXACTLY the tag prefill writes
    for the same slice (that identity is what the content-integrity
    invariants check).  Explicit arithmetic (not ``hash()``) so tags
    are identical across processes regardless of
    ``PYTHONHASHSEED``."""
    h = _TAG_SEED
    for t in tokens:
        h = _mix(h, t)
    return h


class StubKVCache:
    """Host-side stand-in for the paged device cache: the page table
    and lengths the metadata ops maintain, plus one content TAG per
    page in place of the k/v slabs.  ``-1`` table entries are the
    trash page."""

    def __init__(self, slots: int, num_pages: int, page_size: int,
                 max_pages_per_slot: int):
        self.page_table = np.full((slots, max_pages_per_slot), -1,
                                  np.int32)
        self.lengths = np.zeros((slots,), np.int32)
        self.content = np.zeros((num_pages,), np.int64)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)


class StubPendingSwapOut:
    """Deferred device→host drain, stub-side: the content SNAPSHOT is
    taken at dispatch time (exactly like the real batched gather into
    fresh output buffers), so a page reused and overwritten between
    dispatch and resolve cannot corrupt the slab.  A broken twin that
    snapshots lazily (reads the cache at resolve time) reproduces the
    release-before-extract ordering bug the protocol audit exists to
    catch."""

    def __init__(self, k: np.ndarray, v: np.ndarray):
        self._k, self._v = k, v
        self._done = False

    @property
    def done(self) -> bool:
        return self._done

    def resolve(self):
        self._done = True
        return self._k, self._v


class StubEngine:
    """The full device surface :class:`SlotScheduler` touches, in pure
    host numpy — every page-table edit, content write, COW copy and
    swap mirrors the real engine's semantics at tag granularity.
    Token emission is deterministic: the prefill-sampled first token
    and each decode token are pure functions of the visible ints."""

    paged = True
    spec_k = 0
    kind = "stub"

    def __init__(self, *, slots: int, num_pages: int, page_size: int,
                 max_pages_per_slot: int, host_tier_pages: int = 0):
        self.slots = int(slots)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_pages_per_slot = int(max_pages_per_slot)
        self.max_seq = self.max_pages_per_slot * self.page_size
        self.host_tier_bytes = (int(host_tier_pages)
                                * self.page_host_bytes())
        #: every PendingSwapOut this engine ever issued — the APX407
        #: wave-boundary law walks it (a real engine would not need
        #: the log; the model checker does)
        self.pending_log: List[StubPendingSwapOut] = []

    # -- geometry -------------------------------------------------------------
    def page_host_bytes(self) -> int:
        return self.page_size * 16

    def bucket_for(self, n: int) -> int:
        b = max(1, self.page_size)
        while b < int(n):
            b *= 2
        return b

    def new_allocator(self) -> PageAllocator:
        return PageAllocator(self.num_pages, self.page_size,
                             self.max_pages_per_slot)

    def init_cache(self) -> StubKVCache:
        return StubKVCache(self.slots, self.num_pages, self.page_size,
                           self.max_pages_per_slot)

    # -- token emission (pure) ------------------------------------------------
    @staticmethod
    def _first_token(tokens: Sequence[int]) -> int:
        return (sum(int(t) for t in tokens) + len(tokens)) % 7 + 1

    @staticmethod
    def _next_token(last: int, length: int) -> int:
        return (int(last) * 3 + int(length)) % 7 + 1

    # -- device programs ------------------------------------------------------
    def prefill(self, cache: StubKVCache, tokens, slot: int, *,
                pages: Optional[Sequence[int]] = None,
                prefill_from: int = 0):
        toks = [int(t) for t in np.asarray(tokens).reshape(-1)]
        n = len(toks)
        if pages is None:
            raise ValueError("stub engine is paged: pages required")
        ps = self.page_size
        if len(pages) * ps < n:
            raise ValueError(
                f"reservation of {len(pages)} pages cannot cover "
                f"{n} tokens at page size {ps}")
        row = np.full((self.max_pages_per_slot,), -1, np.int32)
        row[:len(pages)] = np.asarray(pages, np.int32)
        cache.page_table[slot] = row
        # rewrite the content tags of every page the [prefill_from, n)
        # suffix touches: a page's tag is the stable hash of the token
        # slice it holds, so identical prefixes produce identical tags
        start = int(prefill_from)
        for j in range(start // ps, -(-n // ps)):
            cache.content[int(pages[j])] = np.int64(
                _tag(toks[j * ps:min(n, (j + 1) * ps)]))
        cache.lengths[slot] = n
        return cache, np.int32(self._first_token(toks)), None

    def decode(self, cache: StubKVCache, last, active):
        last = np.asarray(last)
        active = np.asarray(active, bool)
        toks = np.zeros((self.slots,), np.int32)
        truncated = np.zeros((self.slots,), bool)
        ps = self.page_size
        for s in range(self.slots):
            if not active[s]:
                continue
            length = int(cache.lengths[s])
            row = cache.page_table[s]
            capacity = int((row >= 0).sum()) * ps
            if length >= capacity or length >= self.max_seq:
                truncated[s] = True
                continue
            tok = self._next_token(int(last[s]), length)
            # the INPUT token's k/v lands at position ``length`` (the
            # emitted token is written by the NEXT step) — so the fold
            # extends the page with ``last``, keeping every page's tag
            # equal to _tag() of the token slice it actually holds
            page = int(row[length // ps])
            base = (_TAG_SEED if length % ps == 0
                    else int(cache.content[page]) & _MASK)
            cache.content[page] = np.int64(_mix(base, int(last[s])))
            cache.lengths[s] = length + 1
            toks[s] = tok
        return cache, toks, None, truncated

    def cow_page(self, cache: StubKVCache, src: int, dst: int):
        cache.content[int(dst)] = cache.content[int(src)]
        return cache

    def evict_slot(self, cache: StubKVCache, slot: int):
        cache.lengths[slot] = 0
        cache.page_table[slot] = -1
        return cache

    def swap_out_pages(self, cache: StubKVCache, page_ids,
                       defer: bool = False):
        ids = [int(p) for p in page_ids]
        k = np.array([[int(cache.content[p])] for p in ids], np.int64)
        v = k.copy()
        pending = StubPendingSwapOut(k, v)
        self.pending_log.append(pending)
        if defer:
            return pending
        return pending.resolve()

    def swap_in_pages(self, cache: StubKVCache, page_ids, k_slabs,
                      v_slabs):
        for i, p in enumerate(page_ids):
            cache.content[int(p)] = np.int64(int(
                np.asarray(k_slabs[i]).reshape(-1)[0]))
        return cache


@dataclasses.dataclass(frozen=True)
class Template:
    """One request shape the scope's submit actions can instantiate."""
    name: str
    prompt: Tuple[int, ...]
    max_new_tokens: int = 1
    tenant: str = "default"
    priority: int = 0
    eos_id: Optional[int] = None
    cap: int = 1                    # submit budget for this template


@dataclasses.dataclass(frozen=True)
class Scope:
    """One small-scope configuration of the serving control plane —
    the bounded universe an exhaustive exploration covers."""
    name: str
    replicas: int = 1
    slots: int = 2
    num_pages: int = 5
    page_size: int = 2
    max_pages_per_slot: int = 3
    host_tier_pages: int = 0
    prefill_chunk: int = 0
    max_chunks_per_pass: int = 1
    policy: str = "prefix_affinity"
    templates: Tuple[Template, ...] = ()
    evict_sizes: Tuple[int, ...] = ()   # evict-to-host action sizes
    evict_cap: int = 0                  # max evict actions per trace
    shed: bool = False                  # expose the shed action
    handoff: bool = False               # expose the handoff pair
    handoff_cap: int = 1
    max_depth: int = 10                 # exploration depth bound
    max_states: int = 50000             # safety valve (cap hit = error)

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["templates"] = [dataclasses.asdict(t)
                          for t in self.templates]
        # JSON-normalized (tuples -> lists) so a fresh report compares
        # equal to the committed pin after its disk round-trip
        return json.loads(json.dumps(d))


#: An action is a plain JSON-serializable tuple: (kind, *args).
Action = Tuple


class ProtocolHarness:
    """One live small-scope serving system plus its action alphabet.

    Construction hooks (``engine_factory`` / ``scheduler_factory`` /
    ``abort_transit_on_end_wave``) exist so the seeded-violation tests
    can swap in deliberately BROKEN component twins and watch the
    invariants catch them; the defaults build the real components.
    """

    def __init__(self, scope: Scope, *,
                 engine_factory: Optional[Callable] = None,
                 scheduler_factory: Optional[Callable] = None,
                 abort_transit_on_end_wave: bool = True):
        from apex_tpu.fleet.router import FleetRouter
        from apex_tpu.inference.scheduler import SlotScheduler
        from apex_tpu.observability import (FleetTelemetry,
                                            MetricsRegistry,
                                            ServeTelemetry)
        self.scope = scope
        total_cap = sum(t.cap for t in scope.templates)
        if total_cap >= _DETECTOR_QUEUE_HIGH and scope.replicas > 1:
            raise ValueError(
                f"scope {scope.name!r}: total submit cap {total_cap} "
                f"can reach the overload detector's trip threshold "
                f"({_DETECTOR_QUEUE_HIGH}) — routing would then depend "
                f"on SLO state the canonical projection drops; shrink "
                f"the caps or extend canonical() first")
        if engine_factory is None:
            engine_factory = lambda sc: StubEngine(          # noqa: E731
                slots=sc.slots, num_pages=sc.num_pages,
                page_size=sc.page_size,
                max_pages_per_slot=sc.max_pages_per_slot,
                host_tier_pages=sc.host_tier_pages)
        if scheduler_factory is None:
            scheduler_factory = SlotScheduler
        self.engines = [engine_factory(scope)
                        for _ in range(scope.replicas)]
        self.reps = [
            scheduler_factory(
                eng, ServeTelemetry(MetricsRegistry()),
                prefix_cache=True,
                prefill_chunk=scope.prefill_chunk,
                max_chunks_per_pass=scope.max_chunks_per_pass,
                tenant_priority={}, replica_id=i)
            for i, eng in enumerate(self.engines)]
        self.router = None
        if scope.replicas > 1:
            self.router = FleetRouter(
                self.reps, policy=scope.policy,
                telemetry=FleetTelemetry(MetricsRegistry()))
        self.abort_transit_on_end_wave = bool(abort_transit_on_end_wave)
        self.submitted: Dict[int, int] = {
            i: 0 for i in range(len(scope.templates))}
        self.uid_template: Dict[Tuple[int, int], int] = {}
        self.evicts_done = 0
        self.handoffs_done = 0
        #: in-flight abstract handoffs: extract-on-A done, restore not
        self.transit: List[dict] = []
        self.trace: List[Action] = []

    # -- the action alphabet --------------------------------------------------
    def enabled_actions(self) -> List[Action]:
        """Every action legal in the current state, in a FIXED
        deterministic order (the exploration order)."""
        sc = self.scope
        acts: List[Action] = []
        for ti, t in enumerate(sc.templates):
            if self.submitted[ti] < t.cap:
                acts.append(("submit", ti))
        for r, rep in enumerate(self.reps):
            if rep.queue or (rep.wave_open and rep.run_pending()):
                acts.append(("pass", r))
            if rep.wave_open and not rep.run_pending():
                acts.append(("end_wave", r))
            if rep.pending_swaps:
                acts.append(("drain", r))
            if sc.shed and rep.queue:
                acts.append(("shed", r))
            if sc.evict_cap and self.evicts_done < sc.evict_cap \
                    and rep.wave_open and rep.prefix is not None \
                    and rep.prefix.pinned_pages > 0:
                for n in sc.evict_sizes or (1,):
                    acts.append(("evict", r, n))
            if sc.handoff and self.handoffs_done < sc.handoff_cap \
                    and rep.wave_open \
                    and self._handoff_chain(r) is not None:
                acts.append(("handoff_extract", r))
        if self.transit:
            src = self.transit[0]["src"]
            n = self.transit[0]["n"]
            for r, rep in enumerate(self.reps):
                if r != src and rep.wave_open \
                        and rep.alloc.free_pages >= n:
                    acts.append(("handoff_restore", r))
        return acts

    def apply(self, action: Action) -> None:
        """Execute one action on the live components.  Actions are the
        host's atomic execution units — nothing in the real system
        interleaves inside one (the serving loop is single-threaded
        per replica)."""
        kind = action[0]
        getattr(self, f"_act_{kind}")(*action[1:])
        self.trace.append(tuple(action))

    def _act_submit(self, ti: int) -> None:
        t = self.scope.templates[int(ti)]
        self.submitted[int(ti)] += 1
        if self.router is not None:
            uid = self.router.submit(
                list(t.prompt), max_new_tokens=t.max_new_tokens,
                eos_id=t.eos_id, tenant=t.tenant, priority=t.priority)
            r, local = self.router.placements[uid]
            self.uid_template[(r, local)] = int(ti)
        else:
            uid = self.reps[0].submit(
                list(t.prompt), max_new_tokens=t.max_new_tokens,
                eos_id=t.eos_id, tenant=t.tenant, priority=t.priority)
            self.uid_template[(0, uid)] = int(ti)

    def _act_pass(self, r: int) -> None:
        rep = self.reps[r]
        if not rep.wave_open:
            rep.begin_run()
        if rep.run_pending():
            rep.run_pass()

    def _act_end_wave(self, r: int) -> None:
        if self.abort_transit_on_end_wave:
            # protocol rule under model check: a handoff extract rides
            # its source wave's dispatch queue, so it must complete
            # (restore) or ABORT before that wave closes — exactly the
            # no-unresolved-PendingSwapOut-across-a-wave-boundary law
            # extended to the disaggregation pair.
            kept = []
            for entry in self.transit:
                if entry["src"] == r:
                    entry["pending"].resolve()   # abort: fetch + drop
                else:
                    kept.append(entry)
            self.transit = kept
        self.reps[r].finish_run()

    def _act_drain(self, r: int) -> None:
        self.reps[r].drain_pending_swaps()

    def _act_shed(self, r: int) -> None:
        self.reps[r].shed_worst()

    def _act_evict(self, r: int, n: int) -> None:
        self.evicts_done += 1
        rep = self.reps[r]
        freed = rep.prefix.evict_lru(int(n))
        if freed:
            rep.telemetry.prefix_evicted(rep.prefix.evictions)

    # -- the abstract disaggregation handoff pair -----------------------------
    def _handoff_chain(self, r: int) -> Optional[Tuple[Tuple[int, ...],
                                                       List[int]]]:
        """Longest fully-HBM full-page chain from the root of replica
        ``r``'s radix tree, following the smallest-token edge at each
        level — the prefix a prefill replica would hand to a decode
        replica.  None when the root has no HBM full-page edge."""
        rep = self.reps[r]
        if rep.prefix is None:
            return None
        edges = {}
        for e in rep.prefix.walk_edges():
            if e["kind"] == "full" and e["page"] is not None:
                edges.setdefault(e["path"], []).append(
                    (e["tokens"], e["page"]))
        path: Tuple[int, ...] = ()
        tokens: List[int] = []
        pages: List[int] = []
        while path in edges:
            et, page = min(edges[path])
            tokens.extend(et)
            pages.append(int(page))
            path = path + et
        if not pages:
            return None
        return tuple(tokens), pages

    def _act_handoff_extract(self, r: int) -> None:
        """Extract-on-A: snapshot a cached prefix's page contents via
        the engine's deferred swap-out path (modeled on the ISSUE 18
        ``extract_pages`` program) — a pure read; A's pages stay
        pinned by its prefix cache."""
        self.handoffs_done += 1
        rep = self.reps[r]
        tokens, pages = self._handoff_chain(r)
        pending = rep.engine.swap_out_pages(rep.cache, pages,
                                            defer=True)
        self.transit.append({"src": int(r), "tokens": tuple(tokens),
                             "n": len(pages), "pending": pending})

    def _act_handoff_restore(self, r: int) -> None:
        """Restore-on-B: acquire fresh pages on the destination, land
        the extracted content (``restore_pages``-shaped), index the
        prefix in B's radix tree, then drop the request-level refs —
        the cache pin keeps exactly the pages B now serves from."""
        entry = self.transit.pop(0)
        rep = self.reps[r]
        k, v = entry["pending"].resolve()
        pages = rep.alloc.acquire(entry["n"])
        assert pages is not None, "enabled_actions checked free_pages"
        rep.cache = rep.engine.swap_in_pages(
            rep.cache, pages, k, v)
        rep.telemetry.page_swapped("in", len(pages))
        rep.prefix.insert(list(entry["tokens"]), pages)
        rep.alloc.release(pages)

    # -- canonical state ------------------------------------------------------
    def canonical(self) -> str:
        """Deterministic projection of the protocol state: allocator
        books (free-list ORDER kept — it picks the next acquire),
        radix shape with LRU STAMPS projected to ranks, host-store
        ledger with HANDLES projected to sorted ranks, queue/slot/
        pending/transit contents, page content tags.  Monotonic
        counters (uids, clocks, telemetry totals, SLO windows) are
        projected OUT — see the module docstring for why that is
        sound at these scopes."""
        parts: List = [tuple(sorted(self.submitted.items())),
                       self.evicts_done, self.handoffs_done]
        parts.append(tuple(
            (e["src"], e["n"], _tag(e["tokens"]),
             bool(e["pending"].done))
            for e in self.transit))
        for r, rep in enumerate(self.reps):
            snap = rep.alloc.snapshot()
            store = rep.host_store
            handles = (sorted(store.snapshot()) if store is not None
                       else [])
            hrank = {h: i for i, h in enumerate(handles)}
            edges = (rep.prefix.walk_edges()
                     if rep.prefix is not None else [])
            stamps = sorted({e["stamp"] for e in edges})
            srank = {s: i for i, s in enumerate(stamps)}
            etup = tuple(
                (e["path"], e["tokens"], e["kind"],
                 -1 if e["page"] is None else int(e["page"]),
                 -1 if e["host"] is None else hrank[e["host"]],
                 srank[e["stamp"]])
                for e in edges)
            if store is not None:
                stat = store.snapshot()
                stup = tuple(
                    (hrank[h], stat[h],
                     (int(store.peek_resident(h)[0].reshape(-1)[0])
                      if stat[h] == "resident" else -1))
                    for h in handles)
            else:
                stup = ()
            queue = tuple(
                (self.uid_template.get((r, req.uid), -1),
                 req.tenant, req.priority)
                for req in rep.queue)
            slots = tuple(
                None if st is None else
                (self.uid_template.get((r, st.uid), -1),
                 st.prefilled, tuple(st.generated), st.capacity,
                 tuple(int(p) for p in (st.pages or ())))
                for st in rep.slot_states())
            # per-tenant admission recency as a RANK order (the
            # fairness tiebreak reads only the order)
            tla = sorted(rep._tenant_last_admit.items(),
                         key=lambda kv: kv[1])
            cache = rep.cache
            ctup = (() if cache is None else
                    (tuple(int(x) for x in cache.content),
                     tuple(int(x) for x in cache.lengths),
                     tuple(int(x) for x in cache.page_table.ravel())))
            parts.append((
                snap["free"], tuple(sorted(snap["refs"].items())),
                etup, stup, queue, rep.wave_open, slots,
                tuple(rep._run_free), rep.pending_swaps,
                tuple(t for t, _ in tla), ctup))
        if self.router is not None:
            parts.append(self.router._rr_next % len(self.reps))
        return repr(tuple(parts))


# -- exploration / replay / shrinking ----------------------------------------

@dataclasses.dataclass
class Violation:
    """One invariant failure: the finding codes that fired, the
    per-code messages, and the (already truncated-at-failure) trace
    that reproduces them from a fresh harness."""
    codes: Tuple[str, ...]
    messages: Tuple[str, ...]
    trace: Tuple[Action, ...]


@dataclasses.dataclass
class ExploreResult:
    states: int                     # distinct canonical states visited
    transitions: int                # explored edges between them
    depth: int                      # depth bound applied
    truncated: bool                 # hit max_states (pin must be clean)
    violation: Optional[Violation]


def replay(build: Callable[[], ProtocolHarness],
           trace: Sequence[Action],
           check: Callable[[ProtocolHarness], List[Tuple[str, str]]],
           ) -> Tuple[ProtocolHarness, Optional[Violation]]:
    """Re-execute ``trace`` on a fresh harness, checking invariants
    after every action.  Actions no longer enabled (a shrink deleted a
    prerequisite) are SKIPPED, so every candidate trace stays legal.
    Returns the harness and the first violation (trace truncated at
    the failing action) or None."""
    h = build()
    vio = _check(h, check, ())
    if vio is not None:
        return h, vio
    applied: List[Action] = []
    for action in trace:
        if tuple(action) not in {tuple(a)
                                 for a in h.enabled_actions()}:
            continue
        h.apply(action)
        applied.append(tuple(action))
        vio = _check(h, check, tuple(applied))
        if vio is not None:
            return h, vio
    return h, None


def _check(h, check, trace) -> Optional[Violation]:
    found = check(h)
    if not found:
        return None
    return Violation(codes=tuple(c for c, _ in found),
                     messages=tuple(m for _, m in found),
                     trace=tuple(trace))


def _exec(build: Callable[[], ProtocolHarness],
          trace: Sequence[Action]) -> ProtocolHarness:
    """Re-execute an already-validated trace (every action was enabled
    when the edge was first explored, and the model is deterministic)
    without per-step invariant checks — the explorer's branch
    mechanism."""
    h = build()
    for action in trace:
        h.apply(action)
    return h


def explore(build: Callable[[], ProtocolHarness],
            check: Callable[[ProtocolHarness], List[Tuple[str, str]]],
            *, max_depth: int, max_states: int = 50000,
            ) -> ExploreResult:
    """Bounded exhaustive breadth-first exploration with canonical
    dedup.  Breadth-first + dedup means every state is reached (and
    invariant-checked) by a shortest trace, and a violation's raw
    counterexample is already depth-minimal.  Deterministic: action
    order is ``enabled_actions()`` order, queue order is FIFO, no wall
    clock, no RNG.  Stops at the FIRST violation (shrink it
    afterwards).  Invariants run once per explored EDGE — the prefix
    states were each checked when their own edge was explored."""
    h0 = build()
    vio = _check(h0, check, ())
    if vio is not None:
        return ExploreResult(1, 0, max_depth, False, vio)
    seen = {h0.canonical()}
    frontier: List[Tuple[Tuple[Action, ...], List[Action]]] = [
        ((), h0.enabled_actions())]
    states, transitions = 1, 0
    for _depth in range(max_depth):
        nxt: List[Tuple[Tuple[Action, ...], List[Action]]] = []
        for trace, actions in frontier:
            for action in actions:
                transitions += 1
                path = trace + (tuple(action),)
                h = _exec(build, path)
                vio = _check(h, check, path)
                if vio is not None:
                    return ExploreResult(states, transitions,
                                         max_depth, False, vio)
                key = h.canonical()
                if key in seen:
                    continue
                seen.add(key)
                states += 1
                if states > max_states:
                    return ExploreResult(
                        states, transitions, max_depth, True, None)
                nxt.append((path, h.enabled_actions()))
        if not nxt:
            break
        frontier = nxt
    return ExploreResult(states, transitions, max_depth, False, None)


def shrink(build: Callable[[], ProtocolHarness],
           violation: Violation,
           check: Callable[[ProtocolHarness], List[Tuple[str, str]]],
           ) -> Violation:
    """Action-deletion minimization: repeatedly try dropping each
    action; keep a deletion when the SAME primary finding code still
    fires.  Converges to a 1-minimal counterexample (no single action
    can be removed)."""
    target = violation.codes[0]
    best = violation
    changed = True
    while changed:
        changed = False
        for i in range(len(best.trace)):
            cand = best.trace[:i] + best.trace[i + 1:]
            _, vio = replay(build, cand, check)
            if vio is not None and vio.codes[0] == target \
                    and len(vio.trace) < len(best.trace):
                best = vio
                changed = True
                break
    return best


def random_walk(build: Callable[[], ProtocolHarness],
                check: Callable[[ProtocolHarness],
                                List[Tuple[str, str]]],
                *, steps: int, seed: int) -> int:
    """Seeded random long walk (the slow-lane smoke): ``steps``
    uniformly-chosen enabled actions, invariants checked after each.
    Deterministic per seed.  Returns the number of actions actually
    applied (the walk ends early only if nothing is enabled, which
    the scopes' submit caps eventually force).  Raises AssertionError
    on any violation, carrying the trace."""
    import random
    rng = random.Random(seed)
    h = build()
    applied = 0
    for _ in range(steps):
        acts = h.enabled_actions()
        if not acts:
            break
        h.apply(acts[rng.randrange(len(acts))])
        applied += 1
        found = check(h)
        if found:
            raise AssertionError(
                f"invariant {found[0][0]} violated at step {applied} "
                f"(seed {seed}): {found[0][1]}\ntrace: {h.trace}")
    return applied


# -- repro files -------------------------------------------------------------

def write_repro(path, scope: Scope, violation: Violation) -> None:
    """Persist a minimized counterexample as a replayable repro file:
    the scope config, the action trace, and the finding codes it must
    reproduce."""
    doc = {"scope": scope.to_json(),
           "codes": list(violation.codes),
           "messages": list(violation.messages),
           "trace": [list(a) for a in violation.trace]}
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")


def load_repro(path) -> Tuple[Scope, Tuple[str, ...],
                              Tuple[Action, ...]]:
    """Load a repro file back: ``(scope, codes, trace)``.  Re-execute
    with :func:`replay` (passing the same twin build used to produce
    it) and assert the primary code fires again."""
    with open(path) as f:
        doc = json.load(f)
    sd = dict(doc["scope"])
    sd["templates"] = tuple(Template(**t) for t in sd["templates"])
    for key in ("evict_sizes",):
        sd[key] = tuple(sd[key])
    scope = Scope(**sd)
    trace = tuple(tuple(a) for a in doc["trace"])
    return scope, tuple(doc["codes"]), trace
