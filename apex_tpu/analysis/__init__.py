"""apex_tpu.analysis — JAX-aware static analysis.

Two engines (see README "Static analysis"):

* :mod:`~apex_tpu.analysis.lint` — AST rules over the whole package
  (host syncs under jit, PRNG key reuse, traced Python branching,
  missing donation, fp32-defaulting factories, prints under trace).
* :mod:`~apex_tpu.analysis.jaxpr_audit` — traces each public fused op
  under a declared bf16 precision policy and asserts jaxpr invariants
  (no unexplained bf16→fp32 upcasts, no host callbacks / transfers in
  kernel bodies, output dtypes match the policy).

CLI: ``python -m apex_tpu.analysis`` or the ``apex-tpu-analyze`` entry
point; findings are gated by ``.analysis_baseline.json`` so only NEW
violations fail the run.
"""
from apex_tpu.analysis.finding import Finding
from apex_tpu.analysis.lint import lint_paths, lint_source

__all__ = ["Finding", "lint_paths", "lint_source", "run_jaxpr_audit"]


def run_jaxpr_audit(*args, **kwargs):
    """Lazy proxy — the auditor imports jax, the linter doesn't need to."""
    from apex_tpu.analysis.jaxpr_audit import run_jaxpr_audit as _run
    return _run(*args, **kwargs)
