"""apex_tpu.analysis — JAX-aware static analysis.

Five engines (see README "Static analysis"):

* :mod:`~apex_tpu.analysis.lint` — AST rules over the whole package
  (host syncs under jit, PRNG key reuse, traced Python branching,
  missing donation, fp32-defaulting factories, prints under trace,
  hardcoded axis names, unregistered env knobs, collectives in
  per-process branches).
* :mod:`~apex_tpu.analysis.jaxpr_audit` — traces each public fused op
  under a declared bf16 precision policy and asserts jaxpr invariants
  (no unexplained bf16→fp32 upcasts, no host callbacks / transfers in
  kernel bodies, output dtypes match the policy).
* :mod:`~apex_tpu.analysis.spmd_audit` — walks the registered
  multi-device executables (train steps, DDP, TP, pipeline,
  ring/Ulysses, MoE, inference) checking collective/axis soundness,
  cond-branch collective parity, replica-uniform control values,
  donation against the lowered executables, and the comm/HBM budget
  ledger (:mod:`~apex_tpu.analysis.comm_model`) ratcheted by
  ``.analysis_budget.json``.
* :mod:`~apex_tpu.analysis.pallas_audit` — decomposes every registered
  ``pallas_call`` (grid, BlockSpecs, scratch, scalar prefetch) into a
  static per-grid-step VMEM footprint priced against the chip's VMEM
  capacity, with soundness checks (fp32 reduction accumulators,
  grid/shape divisibility, index-map discipline) and the
  ``.analysis_kernel_budget.json`` ledger ratchet; also the
  fused-decode envelope model behind ``--mesh tp=N``.
* :mod:`~apex_tpu.analysis.protocol_audit` — bounded exhaustive model
  checking of the serving control plane: drives the real
  allocator/prefix-cache/host-tier/scheduler/router classes through a
  device-free stub engine (:mod:`~apex_tpu.analysis.protocol_model`)
  over tiny committed scopes, asserting conservation/content/lifecycle
  invariants (APX401–APX407) at every canonical state, with minimized
  replayable counterexamples and the ``.analysis_protocol.json``
  state-space pin.

CLI: ``python -m apex_tpu.analysis`` or the ``apex-tpu-analyze`` entry
point (``--spmd`` adds the third engine, ``--kernels`` the fourth,
``--protocol`` the fifth); findings are gated by
``.analysis_baseline.json`` so only NEW violations fail the run.
"""
from apex_tpu.analysis.finding import Finding
from apex_tpu.analysis.lint import lint_paths, lint_source

__all__ = ["Finding", "lint_paths", "lint_source", "run_jaxpr_audit",
           "run_spmd_audit", "run_kernel_audit", "run_protocol_audit"]


def run_kernel_audit(*args, **kwargs):
    """Lazy proxy — the kernel auditor traces Pallas ops under jax."""
    from apex_tpu.analysis.pallas_audit import run_kernel_audit as _run
    return _run(*args, **kwargs)


def run_jaxpr_audit(*args, **kwargs):
    """Lazy proxy — the auditor imports jax, the linter doesn't need to."""
    from apex_tpu.analysis.jaxpr_audit import run_jaxpr_audit as _run
    return _run(*args, **kwargs)


def run_spmd_audit(*args, **kwargs):
    """Lazy proxy — the SPMD auditor imports jax and binds meshes."""
    from apex_tpu.analysis.spmd_audit import run_spmd_audit as _run
    return _run(*args, **kwargs)


def run_protocol_audit(*args, **kwargs):
    """Lazy proxy — the protocol auditor imports the inference stack."""
    from apex_tpu.analysis.protocol_audit import run_protocol_audit \
        as _run
    return _run(*args, **kwargs)
