"""Serving control-plane protocol auditor — the FIFTH analysis engine
(``apex-tpu-analyze --protocol``).

Exhaustive small-scope model checking of the allocator / prefix-cache /
host-tier / scheduler / router state machines: the committed
:data:`SCOPES` are explored breadth-first to a depth bound with
canonical-state dedup (:mod:`~apex_tpu.analysis.protocol_model`), and
the pinned invariants APX401–APX407 are asserted at every explored
state.  The components under check are the REAL serving classes —
``PageAllocator``, ``PrefixCache``, ``HostPageStore``,
``SlotScheduler``, ``FleetRouter`` — only the device is a stub, so a
clean pin is a statement about the code that serves, not about a
parallel model of it.

The laws (each names the L0 churn-sweep law it subsumes):

=======  ==============================================================
APX401   allocator conservation: ``free + distinct live == num_pages``,
         free list duplicate- and overlap-free, every refcount >= 1
APX402   refcount-weighted conservation: ``sum(refcounts) ==`` slot-row
         holdings + cache-pinned edges
APX403   per-page holder books: every page's refcount equals the
         number of slot rows + cache edges holding it (no page
         reachable from two rows without matching share refs); no
         duplicate page inside one row; page CONTENT matches each
         row's token slice (a mismatch means another writer clobbered
         a page this row trusts — the skipped-COW signature)
APX404   no dangling references: no slot row, device page-table entry,
         or cache edge references a freed (refcount-0) page
APX405   radix tier invariant: page XOR host per edge, nothing below a
         host edge is HBM, one cache ref per indexed page/handle,
         ``pinned_pages``/``host_pages`` book consistency, full-HBM
         edge and resident host-slab content match their tokens
APX406   host-store byte budget: ``bytes_used == pages * page_bytes <=
         capacity``, store handles mirror the host edges exactly
APX407   lifecycle + wave-boundary + fleet: per-replica ``submitted ==
         finished + active + rejected``; NO unresolved PendingSwapOut
         (deferred offload or handoff extract) survives a wave
         boundary; the router's three-level conservation holds
=======  ==============================================================

On a violation the engine shrinks the trace by action deletion to a
1-minimal counterexample and writes a REPLAYABLE repro file
(``.protocol_repro_<scope>.json``) that :func:`replay_repro`
re-executes.  Clean results pin to ``.analysis_protocol.json`` (scope
configs + canonical state-space sizes, byte-identical across runs);
any drift — state count, config, a scope added or dropped — is an
APX400 finding until consciously re-pinned with ``--write-protocol``.

The abstract disaggregation handoff pair (``handoff_extract`` /
``handoff_restore`` in the ``fleet`` scope) model-checks ROADMAP
item 1's cross-replica prefix handoff protocol BEFORE its device
implementation exists: the pinned clean scope is the proof obligation
the real implementation must keep discharging.
"""
from __future__ import annotations

import collections
import json
import os
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

from apex_tpu.analysis.finding import Finding
from apex_tpu.analysis.protocol_model import (ExploreResult,
                                              ProtocolHarness, Scope,
                                              Template, _tag, explore,
                                              replay, shrink,
                                              write_repro)

__all__ = ["PIN_NAME", "SCOPES", "INVARIANTS", "check_harness",
           "audit_scope", "run_protocol_audit", "compare_protocol",
           "replay_repro", "protocol_scope_env"]

PIN_NAME = ".analysis_protocol.json"
_SCOPE_ENV = "APEX_TPU_PROTOCOL_SCOPE"

#: The pinned invariant battery.  ``covers`` names the conservation
#: laws the L0 churn sweeps assert wave-by-wave — the L1 guard test
#: checks this registry covers every one of them, so the protocol
#: audit can never silently check LESS than the runtime sweeps do.
INVARIANTS: Dict[str, dict] = {
    "APX401": {
        "name": "allocator-conservation",
        "description": "free + distinct live pages == num_pages; "
                       "free list has no duplicates and no overlap "
                       "with the ref table; every refcount >= 1",
        "covers": ("allocator-conservation",),
    },
    "APX402": {
        "name": "refcount-weighted-conservation",
        "description": "sum of refcounts == slot-row holdings + "
                       "cache-pinned edges",
        "covers": ("refcount-weighted-conservation",),
    },
    "APX403": {
        "name": "per-page-holder-books",
        "description": "each page's refcount equals its holder count "
                       "(slot rows + cache edges); no duplicate page "
                       "in a row; page content matches each row's "
                       "token slice",
        "covers": ("share-ref-matching", "cow-write-isolation"),
    },
    "APX404": {
        "name": "no-dangling-page-refs",
        "description": "no slot row, page-table entry, or cache edge "
                       "references a freed page",
        "covers": ("no-dangling-page-refs",),
    },
    "APX405": {
        "name": "radix-tier-invariant",
        "description": "page XOR host per edge; nothing below a host "
                       "edge is HBM; one cache ref per indexed "
                       "page/handle; pinned_pages/host_pages books; "
                       "full-edge and resident-slab content integrity",
        "covers": ("prefix-pin-books", "host-tier-shape"),
    },
    "APX406": {
        "name": "host-store-budget",
        "description": "bytes_used == pages * page_bytes <= capacity; "
                       "store handles mirror host edges exactly",
        "covers": ("host-byte-budget", "host-mirror"),
    },
    "APX407": {
        "name": "lifecycle-and-wave-boundary",
        "description": "submitted == finished + active + rejected per "
                       "replica; no unresolved PendingSwapOut across "
                       "a wave boundary (deferred offloads AND "
                       "handoff extracts); router three-level "
                       "conservation holds",
        "covers": ("lifecycle-conservation", "wave-boundary-swaps",
                   "fleet-three-level"),
    },
}


def protocol_scope_env() -> Optional[List[str]]:
    """``APEX_TPU_PROTOCOL_SCOPE``: comma-separated scope names the
    ``--protocol`` engine restricts to (``0``/empty/unset = all
    committed scopes; a restricted run refuses ``--write-protocol``)."""
    raw = os.environ.get(_SCOPE_ENV, "").strip()
    if not raw or raw == "0":
        return None
    return [s.strip() for s in raw.split(",") if s.strip()]


# -- the committed small scopes ----------------------------------------------
# Kept deliberately tiny: exhaustive exploration must finish in
# seconds, and small-scope coverage is the point (the "small scope
# hypothesis": protocol bugs that exist at all exist at tiny sizes).

SCOPES: Dict[str, Scope] = {
    # single replica, shared-prefix family with a COW boundary page,
    # chunked prefill, shed — the allocator/prefix/scheduler core
    "core": Scope(
        name="core", replicas=1, slots=2, num_pages=7, page_size=2,
        max_pages_per_slot=4, prefill_chunk=2, shed=True,
        evict_sizes=(1,), evict_cap=1,
        templates=(
            # budgets sized so A is still DECODING when A2's admission
            # matches A's inserted prefix: the explored states include
            # one page held by two slot rows plus the cache pin
            # (refcount 3) AND a COW of the shared boundary page —
            # multi-owner protocol states, not just cache pins
            Template("A", (1, 2, 3), max_new_tokens=4),
            Template("A2", (1, 2, 3, 4), max_new_tokens=3),
            Template("B", (5, 6), max_new_tokens=2, tenant="t2"),
        ),
        max_depth=9),
    # single replica over a 2-page host tier: evict-to-host (deferred
    # slabs), drain, swap-in on the repeat template's host hit
    "tiered": Scope(
        name="tiered", replicas=1, slots=1, num_pages=4, page_size=2,
        max_pages_per_slot=2, host_tier_pages=2,
        evict_sizes=(2,), evict_cap=2,
        templates=(
            Template("A", (1, 2, 3), max_new_tokens=1, cap=2),
            Template("B", (5, 6, 7), max_new_tokens=1, tenant="t2"),
        ),
        max_depth=10),
    # two replicas behind the real prefix-affinity router, plus the
    # abstract disaggregation handoff pair (ROADMAP item 1)
    "fleet": Scope(
        name="fleet", replicas=2, slots=1, num_pages=4, page_size=2,
        max_pages_per_slot=2, policy="prefix_affinity", shed=True,
        handoff=True, handoff_cap=1,
        templates=(
            Template("A", (1, 2), max_new_tokens=1),
            Template("B", (7, 8), max_new_tokens=1, tenant="t2"),
        ),
        max_depth=10),
}


# -- the invariant battery ---------------------------------------------------

def _occupied(rep) -> List[tuple]:
    return [(s, st) for s, st in enumerate(rep.slot_states())
            if st is not None]


def _edges(rep) -> List[dict]:
    return rep.prefix.walk_edges() if rep.prefix is not None else []


def _check_allocator(h: ProtocolHarness) -> List[Tuple[str, str]]:
    out = []
    n = h.scope.num_pages
    for r, rep in enumerate(h.reps):
        if rep.alloc is None:
            continue
        snap = rep.alloc.snapshot()
        free, refs = snap["free"], snap["refs"]
        if len(set(free)) != len(free):
            out.append(("APX401",
                        f"replica {r}: duplicate page in the free "
                        f"list {free}"))
        overlap = sorted(set(free) & set(refs))
        if overlap:
            out.append(("APX401",
                        f"replica {r}: pages {overlap} both free and "
                        f"ref-counted"))
        if len(set(free)) + len(refs) != n:
            out.append(("APX401",
                        f"replica {r}: {len(set(free))} free + "
                        f"{len(refs)} live != {n} pool pages"))
        bad = sorted(p for p, c in refs.items() if c < 1)
        if bad:
            out.append(("APX401",
                        f"replica {r}: pages {bad} held at "
                        f"refcount < 1"))
        oob = sorted(p for p in list(free) + list(refs)
                     if not 0 <= p < n)
        if oob:
            out.append(("APX401",
                        f"replica {r}: out-of-range page ids {oob}"))
    return out


def _holders(rep) -> collections.Counter:
    hold: collections.Counter = collections.Counter()
    for _s, st in _occupied(rep):
        for p in st.pages or ():
            hold[int(p)] += 1
    for e in _edges(rep):
        if e["page"] is not None:
            hold[int(e["page"])] += 1
    return hold


def _check_refcounts(h: ProtocolHarness) -> List[Tuple[str, str]]:
    out = []
    for r, rep in enumerate(h.reps):
        if rep.alloc is None:
            continue
        refs = rep.alloc.snapshot()["refs"]
        hold = _holders(rep)
        if sum(refs.values()) != sum(hold.values()):
            out.append(("APX402",
                        f"replica {r}: sum(refcounts) "
                        f"{sum(refs.values())} != slot-row + "
                        f"cache-edge holdings {sum(hold.values())}"))
    return out


def _check_rows(h: ProtocolHarness) -> List[Tuple[str, str]]:
    out = []
    for r, rep in enumerate(h.reps):
        if rep.alloc is None:
            continue
        refs = rep.alloc.snapshot()["refs"]
        hold = _holders(rep)
        for s, st in _occupied(rep):
            pages = [int(p) for p in st.pages or ()]
            if len(set(pages)) != len(pages):
                out.append(("APX403",
                            f"replica {r} slot {s}: page mapped "
                            f"twice in one row {pages}"))
        for p in sorted(set(hold) | set(refs)):
            if hold.get(p, 0) != refs.get(p, 0):
                out.append(("APX403",
                            f"replica {r}: page {p} held by "
                            f"{hold.get(p, 0)} slot-row/cache "
                            f"owner(s) but ref-counted "
                            f"{refs.get(p, 0)}"))
        cache = rep.cache
        if cache is None or not hasattr(cache, "content"):
            continue            # content laws are stub-cache only
        ps = h.scope.page_size
        for s, st in _occupied(rep):
            length = int(cache.lengths[s])
            if length == 0:
                continue        # admitted, first prefill piece pending
            seq = (list(st.prompt) + list(st.generated))[:length]
            if len(seq) < length:
                out.append(("APX403",
                            f"replica {r} slot {s}: cache length "
                            f"{length} exceeds the request's "
                            f"{len(seq)} known tokens"))
                continue
            row = [int(x) for x in cache.page_table[s]]
            pages = [int(p) for p in st.pages or ()]
            if row[:len(pages)] != pages:
                out.append(("APX403",
                            f"replica {r} slot {s}: device row "
                            f"{row[:len(pages)]} diverges from the "
                            f"slot books {pages}"))
                continue
            for j in range(-(-length // ps)):
                piece = seq[j * ps:min(length, (j + 1) * ps)]
                got = int(cache.content[row[j]])
                if got != _tag(piece):
                    out.append((
                        "APX403",
                        f"replica {r} slot {s}: page {row[j]} "
                        f"(ordinal {j}) content does not match the "
                        f"row's tokens {piece} — another writer "
                        f"clobbered a page this row holds"))
    return out


def _check_dangling(h: ProtocolHarness) -> List[Tuple[str, str]]:
    out = []
    for r, rep in enumerate(h.reps):
        if rep.alloc is None:
            continue
        live = set(rep.alloc.snapshot()["refs"])
        for s, st in _occupied(rep):
            dead = sorted({int(p) for p in st.pages or ()} - live)
            if dead:
                out.append(("APX404",
                            f"replica {r} slot {s}: row references "
                            f"freed page(s) {dead}"))
        for e in _edges(rep):
            if e["page"] is not None and int(e["page"]) not in live:
                out.append(("APX404",
                            f"replica {r}: cache edge at "
                            f"{e['path'] + e['tokens']} references "
                            f"freed page {e['page']}"))
        cache = rep.cache
        if cache is not None and hasattr(cache, "page_table"):
            occupied = {s for s, _ in _occupied(rep)}
            for s in range(cache.page_table.shape[0]):
                if s not in occupied:
                    continue    # idle rows are device-side trash
                dead = sorted({int(p) for p in cache.page_table[s]
                               if p >= 0} - live)
                if dead:
                    out.append(("APX404",
                                f"replica {r}: device page-table row "
                                f"{s} references freed page(s) "
                                f"{dead}"))
    return out


def _check_prefix(h: ProtocolHarness) -> List[Tuple[str, str]]:
    out = []
    for r, rep in enumerate(h.reps):
        if rep.prefix is None:
            continue
        edges = _edges(rep)
        pages: collections.Counter = collections.Counter()
        hosts: collections.Counter = collections.Counter()
        for e in edges:
            if (e["page"] is None) == (e["host"] is None):
                out.append(("APX405",
                            f"replica {r}: edge at "
                            f"{e['path'] + e['tokens']} violates "
                            f"page XOR host (page={e['page']}, "
                            f"host={e['host']})"))
            if e["page"] is not None:
                pages[int(e["page"])] += 1
            if e["host"] is not None:
                hosts[int(e["host"])] += 1
        for p, c in sorted(pages.items()):
            if c > 1:
                out.append(("APX405",
                            f"replica {r}: page {p} indexed by {c} "
                            f"cache edges"))
        for hd, c in sorted(hosts.items()):
            if c > 1:
                out.append(("APX405",
                            f"replica {r}: host handle {hd} carried "
                            f"by {c} cache edges"))
        host_roots = [tuple(e["path"]) + tuple(e["tokens"])
                      for e in edges if e["host"] is not None]
        for e in edges:
            if e["page"] is None:
                continue
            path = tuple(e["path"])
            for root in host_roots:
                if len(root) <= len(path) \
                        and path[:len(root)] == root:
                    out.append((
                        "APX405",
                        f"replica {r}: HBM edge at "
                        f"{path + tuple(e['tokens'])} sits below "
                        f"host edge {root} — tier invariant broken"))
        if rep.prefix.pinned_pages != sum(pages.values()):
            out.append(("APX405",
                        f"replica {r}: pinned_pages book "
                        f"{rep.prefix.pinned_pages} != {sum(pages.values())} "
                        f"HBM edges"))
        if rep.prefix.host_pages != sum(hosts.values()):
            out.append(("APX405",
                        f"replica {r}: host_pages book "
                        f"{rep.prefix.host_pages} != {sum(hosts.values())} "
                        f"host edges"))
        cache, store = rep.cache, rep.host_store
        if cache is None or not hasattr(cache, "content"):
            continue
        for e in edges:
            if e["kind"] != "full":
                continue        # partial tails legitimately extended
            want = _tag(e["tokens"])
            if e["page"] is not None:
                got = int(cache.content[int(e["page"])])
                if got != want:
                    out.append((
                        "APX405",
                        f"replica {r}: full edge at "
                        f"{e['path'] + e['tokens']} page {e['page']} "
                        f"content does not match its tokens"))
            elif store is not None:
                slab = store.peek_resident(int(e["host"]))
                if slab is None:
                    continue    # deferred and still in flight
                got = int(slab[0].reshape(-1)[0])
                if got != want:
                    out.append((
                        "APX405",
                        f"replica {r}: host slab {e['host']} for "
                        f"edge {e['path'] + e['tokens']} does not "
                        f"match its tokens — swap-out snapshotted "
                        f"after the page was reused?"))
    return out


def _check_store(h: ProtocolHarness) -> List[Tuple[str, str]]:
    out = []
    for r, rep in enumerate(h.reps):
        store = rep.host_store
        edge_handles = sorted(int(e["host"]) for e in _edges(rep)
                              if e["host"] is not None)
        if store is None:
            if edge_handles:
                out.append(("APX406",
                            f"replica {r}: host edges {edge_handles} "
                            f"with no host store"))
            continue
        if store.bytes_used != store.pages * store.page_bytes:
            out.append(("APX406",
                        f"replica {r}: bytes_used {store.bytes_used} "
                        f"!= {store.pages} pages * "
                        f"{store.page_bytes} B"))
        if store.bytes_used > store.capacity_bytes:
            out.append(("APX406",
                        f"replica {r}: host store over budget "
                        f"({store.bytes_used} > "
                        f"{store.capacity_bytes} B)"))
        handles = sorted(store.snapshot())
        if handles != edge_handles:
            out.append(("APX406",
                        f"replica {r}: store handles {handles} do "
                        f"not mirror the host edges {edge_handles}"))
    return out


def _check_lifecycle(h: ProtocolHarness) -> List[Tuple[str, str]]:
    out = []
    for r, rep in enumerate(h.reps):
        c = rep.telemetry.conservation()
        if c["submitted"] != c["finished"] + c["active"] \
                + c["rejected"]:
            out.append(("APX407",
                        f"replica {r}: lifecycle conservation broken "
                        f"({c})"))
        if rep.wave_open:
            continue
        if rep.pending_swaps:
            out.append(("APX407",
                        f"replica {r}: {rep.pending_swaps} deferred "
                        f"swap-out(s) unresolved across a wave "
                        f"boundary"))
        stranded = sum(1 for e in h.transit if e["src"] == r)
        if stranded:
            out.append(("APX407",
                        f"replica {r}: {stranded} handoff extract(s) "
                        f"in transit across the source's wave "
                        f"boundary"))
        log = getattr(rep.engine, "pending_log", None)
        if log is not None:
            open_n = sum(1 for p in log
                         if not getattr(p, "done", True))
            if open_n:
                out.append(("APX407",
                            f"replica {r}: {open_n} engine-issued "
                            f"PendingSwapOut(s) unresolved with the "
                            f"wave closed"))
    if h.router is not None:
        cons = h.router.conservation()
        if not cons["holds"]:
            out.append(("APX407",
                        f"fleet three-level conservation broken: "
                        f"{cons}"))
    return out


_CHECKERS = (_check_allocator, _check_refcounts, _check_rows,
             _check_dangling, _check_prefix, _check_store,
             _check_lifecycle)


def check_harness(h: ProtocolHarness) -> List[Tuple[str, str]]:
    """The full APX401–APX407 battery; returns EVERY violated law as
    ``(code, message)`` (one underlying bug usually breaks several
    books at once — tests assert the expected code is among them)."""
    out: List[Tuple[str, str]] = []
    for checker in _CHECKERS:
        out.extend(checker(h))
    return out


# -- running + pinning -------------------------------------------------------

def audit_scope(scope: Scope, *,
                build: Optional[Callable[[], ProtocolHarness]] = None,
                ) -> ExploreResult:
    """Explore one scope under the invariant battery; a violation
    comes back action-deletion MINIMIZED."""
    if build is None:
        build = lambda: ProtocolHarness(scope)      # noqa: E731
    res = explore(build, check_harness, max_depth=scope.max_depth,
                  max_states=scope.max_states)
    if res.violation is not None:
        res.violation = shrink(build, res.violation, check_harness)
    return res


def replay_repro(path, *,
                 build: Optional[Callable[[], ProtocolHarness]] = None,
                 ):
    """Re-execute a repro file written by the audit; returns the
    :class:`~apex_tpu.analysis.protocol_model.Violation` it reproduces
    (None if it no longer fires — the bug is fixed, delete the file).
    Pass the same twin ``build`` that produced it; default builds the
    clean harness from the embedded scope."""
    from apex_tpu.analysis.protocol_model import load_repro
    scope, _codes, trace = load_repro(path)
    if build is None:
        build = lambda: ProtocolHarness(scope)      # noqa: E731
    _h, vio = replay(build, trace, check_harness)
    return vio


def run_protocol_audit(scope_names: Optional[List[str]] = None, *,
                       repro_dir=None,
                       ) -> Tuple[List[Finding], dict]:
    """Run the protocol audit over ``scope_names`` (default: every
    committed scope) and return ``(findings, report)``.  The report is
    the pin payload: deterministic, timestamp-free, byte-identical
    across runs of the same code."""
    names = sorted(SCOPES) if scope_names is None else scope_names
    unknown = [n for n in names if n not in SCOPES]
    if unknown:
        raise ValueError(
            f"unknown protocol scope(s) {unknown}; "
            f"known: {sorted(SCOPES)}")
    findings: List[Finding] = []
    report: dict = {"version": 1, "scopes": {}}
    for name in names:
        scope = SCOPES[name]
        try:
            res = audit_scope(scope)
        except Exception as e:                      # noqa: BLE001
            findings.append(Finding(
                "APX400", f"<protocol:{name}>", 0, 0,
                f"exploration crashed: {type(e).__name__}: {e}",
                line_text=f"protocol scope {name}"))
            continue
        if res.truncated:
            findings.append(Finding(
                "APX400", f"<protocol:{name}>", 0, 0,
                f"state-space cap hit ({res.states} states > "
                f"max_states {scope.max_states}) — the scope is no "
                f"longer exhaustively explored; shrink it or raise "
                f"the cap", line_text=f"protocol scope {name}"))
            continue
        if res.violation is not None:
            vio = res.violation
            msg = (f"{vio.messages[0]} — minimized counterexample "
                   f"({len(vio.trace)} action(s)): "
                   f"{json.dumps([list(a) for a in vio.trace])}")
            if repro_dir is not None:
                repro = Path(repro_dir) / f".protocol_repro_{name}.json"
                write_repro(repro, scope, vio)
                msg += f" — repro: {repro}"
            findings.append(Finding(
                vio.codes[0], f"<protocol:{name}>", 0, 0, msg,
                line_text=f"protocol scope {name}"))
            continue
        report["scopes"][name] = {
            "states": res.states,
            "transitions": res.transitions,
            "depth": res.depth,
            "violations": 0,
            "config": scope.to_json(),
        }
    return findings, report


def compare_protocol(report: dict, committed: Optional[dict], *,
                     full: bool = True) -> List[Finding]:
    """Ratchet the fresh report against the committed pin: any drift
    — a scope's canonical state-space size, its config, a scope added
    or (on full runs) dropped — is an APX400 finding until consciously
    re-pinned with ``--write-protocol``."""
    out: List[Finding] = []
    if committed is None:
        if report["scopes"]:
            out.append(Finding(
                "APX400", f"<protocol>", 0, 0,
                f"no committed {PIN_NAME}; run --protocol "
                f"--write-protocol to pin the explored scopes",
                line_text="protocol pin missing"))
        return out
    pinned = committed.get("scopes", {})
    for name, fresh in sorted(report["scopes"].items()):
        if name not in pinned:
            out.append(Finding(
                "APX400", f"<protocol:{name}>", 0, 0,
                f"scope {name!r} is not in the committed pin; "
                f"--write-protocol to adopt it",
                line_text=f"protocol scope {name}"))
            continue
        for key in ("states", "transitions", "depth", "config"):
            if fresh[key] != pinned[name].get(key):
                out.append(Finding(
                    "APX400", f"<protocol:{name}>", 0, 0,
                    f"scope {name!r} {key} drifted from the pin "
                    f"({pinned[name].get(key)!r} -> {fresh[key]!r}): "
                    f"the explored protocol changed; review, then "
                    f"--write-protocol to re-pin",
                    line_text=f"protocol scope {name} {key}"))
    if full:
        for name in sorted(set(pinned) - set(report["scopes"])):
            out.append(Finding(
                "APX400", f"<protocol:{name}>", 0, 0,
                f"committed scope {name!r} was not produced by this "
                f"run (dropped or renamed?); --write-protocol to "
                f"re-pin", line_text=f"protocol scope {name}"))
    return out
