"""Analytic comm-bytes + peak-live-buffer model over jaxprs.

The SPMD auditor (:mod:`apex_tpu.analysis.spmd_audit`) prices every
collective in a registered executable with the standard ring-algorithm
per-chip byte counts — the same arithmetic PERF.md round-6 carries by
hand for the ZeRO RS+AG==AR argument, now machine-applied:

===============  ==========================================  ============
primitive        per-chip bytes (axis size n, payload B)     B measured at
===============  ==========================================  ============
psum/pmax/pmin   ``2 * (n-1)/n * B``  (ring all-reduce)      input
all_gather       ``(n-1) * B``  (== (n-1)/n * output)        input shard
reduce_scatter   ``(n-1)/n * B``                             input
all_to_all       ``(n-1)/n * B``                             input
ppermute         ``B``  (one neighbor hop)                   input
===============  ==========================================  ============

Multi-axis collectives (``psum(x, ("data", "expert"))``) price at the
PRODUCT of the axis sizes — one logical ring over the combined group.

The peak-live-buffer estimate is a linear-scan liveness walk over the
eqn sequence: at each program point the live set is every value already
produced (or an input) whose last consumer is still ahead, plus the
values the current eqn materializes; the peak is the max over points.
It deliberately ignores XLA fusion/rematerialization — the number is an
upper-bound *shape* metric whose job is to be deterministic and to move
when someone adds a full-size temporary to a registered executable, not
to predict an HBM high-water mark.

Both reports are pure functions of the jaxpr (+ static axis sizes), so
they are stable across runs and machines — the property the committed
``.analysis_budget.json`` ratchet needs.
"""
from __future__ import annotations

from typing import Dict, Optional

__all__ = ["COLLECTIVE_PRIMS", "collective_axes", "eqn_comm_bytes",
           "comm_report", "peak_live_bytes", "ring_allreduce_bytes",
           "jaxpr_dot_flops", "step_time_estimate"]

# Collective primitive name -> pricing kind.  ``psum_scatter`` traces as
# ``reduce_scatter`` on current jax; both spellings are kept so the
# walker survives either.
COLLECTIVE_PRIMS: Dict[str, str] = {
    "psum": "allreduce",
    "pmax": "allreduce",
    "pmin": "allreduce",
    "all_gather": "allgather",
    "reduce_scatter": "reducescatter",
    "psum_scatter": "reducescatter",
    "all_to_all": "alltoall",
    "ppermute": "ppermute",
}


def _aval_bytes(aval) -> int:
    size = 1
    for d in getattr(aval, "shape", ()):
        size *= int(d)
    return size * getattr(aval, "dtype", None).itemsize


def collective_axes(eqn) -> tuple:
    """The mesh axis name(s) a collective eqn reduces/reshards over.

    jax spells the parameter ``axes`` (psum/pmax/pmin) or ``axis_name``
    (all_gather/reduce_scatter/ppermute/all_to_all); either may be a
    bare name or a tuple.
    """
    axes = eqn.params.get("axes", eqn.params.get("axis_name"))
    if axes is None:
        return ()
    if isinstance(axes, (tuple, list)):
        return tuple(axes)
    return (axes,)


def ring_allreduce_bytes(n: int, payload: int) -> int:
    """Per-chip bytes of a ring all-reduce of ``payload`` bytes."""
    return 0 if n <= 1 else int(2 * (n - 1) * payload // n)


def eqn_comm_bytes(eqn, axis_sizes: Dict[str, int]) -> int:
    """Per-chip bytes for one collective eqn (0 for non-collectives).

    ``axis_sizes`` maps mesh axis name -> size; an axis the executable
    never declared prices at size 1 (zero bytes) — the *soundness* of
    such an axis is the auditor's APX211 check, not the price model's.
    """
    kind = COLLECTIVE_PRIMS.get(eqn.primitive.name)
    if kind is None:
        return 0
    n = 1
    for ax in collective_axes(eqn):
        n *= int(axis_sizes.get(ax, 1))
    if n <= 1:
        return 0
    payload = sum(_aval_bytes(v.aval) for v in eqn.invars
                  if getattr(v, "aval", None) is not None)
    if kind == "allreduce":
        return ring_allreduce_bytes(n, payload)
    if kind == "allgather":
        return (n - 1) * payload
    if kind in ("reducescatter", "alltoall"):
        return (n - 1) * payload // n
    return payload  # ppermute: one neighbor hop


def _subjaxpr_items(eqn, axis_sizes: Optional[Dict[str, int]] = None,
                    all_branches: bool = False):
    """(jaxpr, multiplier) pairs nested under one eqn.

    * ``scan`` bodies run ``length`` times — comm inside multiplies.
    * ``while`` bodies have an unknown trip count — priced ONCE (a
      lower bound; the budget ratchet still moves when the per-trip
      comm grows).
    * ``cond`` branches are alternatives — for comm the report prices
      the MOST expensive branch (a budget is a worst case, and pricing
      all branches would double-count mutually exclusive collectives);
      ``all_branches=True`` yields every branch instead, for callers
      that take a max over the yields themselves (the peak-live walk —
      selecting by comm bytes there would just pick branch 0).
    """
    import jax

    name = eqn.primitive.name
    if name == "scan":
        length = int(eqn.params.get("length", 1))
        yield eqn.params["jaxpr"], length
        return
    if name == "cond":
        if all_branches:
            for br in eqn.params.get("branches", ()):
                yield br, 1
            return
        best, best_bytes = None, -1
        for br in eqn.params.get("branches", ()):
            b = _jaxpr_comm_bytes(br, axis_sizes or {})
            if b > best_bytes:
                best, best_bytes = br, b
        if best is not None:
            yield best, 1
        return
    for v in eqn.params.values():
        items = v if isinstance(v, (list, tuple)) else [v]
        for item in items:
            if isinstance(item, (jax.core.Jaxpr, jax.core.ClosedJaxpr)):
                yield item, 1


def _open(jaxpr):
    return getattr(jaxpr, "jaxpr", jaxpr)


def _jaxpr_comm_bytes(jaxpr, axis_sizes) -> int:
    total = 0
    for eqn in _open(jaxpr).eqns:
        total += eqn_comm_bytes(eqn, axis_sizes)
        for sub, mult in _subjaxpr_items(eqn, axis_sizes):
            total += mult * _jaxpr_comm_bytes(sub, axis_sizes)
    return total


def comm_report(closed_jaxpr, axis_sizes: Dict[str, int]) -> dict:
    """``{"total_bytes", "by_collective": {"prim@axes": bytes},
    "counts": {"prim@axes": n}}`` for one traced executable.

    ``by_collective`` keys are ``"all_gather@data"``-style so the
    committed budget stays human-readable.  cond branches contribute
    their most expensive alternative; scan bodies multiply by length.
    """
    by: Dict[str, int] = {}
    counts: Dict[str, int] = {}

    def walk(jaxpr, mult):
        for eqn in _open(jaxpr).eqns:
            b = eqn_comm_bytes(eqn, axis_sizes)
            if b or eqn.primitive.name in COLLECTIVE_PRIMS:
                key = (f"{eqn.primitive.name}@"
                       f"{','.join(collective_axes(eqn))}")
                by[key] = by.get(key, 0) + mult * b
                counts[key] = counts.get(key, 0) + mult
            for sub, m in _subjaxpr_items(eqn, axis_sizes):
                walk(sub, mult * m)

    walk(closed_jaxpr, 1)
    return {"total_bytes": sum(by.values()), "by_collective": by,
            "counts": counts}


def _jaxpr_dot_flops(jaxpr, mult: int = 1) -> int:
    """Per-chip matmul FLOPs over a jaxpr (2·M·N·K per ``dot_general``,
    nested jaxprs included, scan bodies × length, cond = max branch).
    Conv/Pallas work is not counted — the number feeds a RELATIVE
    step-time model, and every registered executable's hot loops are
    dot-shaped."""
    total = 0
    for eqn in _open(jaxpr).eqns:
        if eqn.primitive.name == "dot_general":
            (lc, _), (lb, _) = eqn.params["dimension_numbers"]
            lhs = eqn.invars[0].aval
            out = eqn.outvars[0].aval
            k = 1
            for d in lc:
                k *= int(lhs.shape[d])
            size = 1
            for d in out.shape:
                size *= int(d)
            total += 2 * size * k
        subs = list(_subjaxpr_items(eqn, {}, all_branches=True))
        if eqn.primitive.name == "cond":
            total += max((_jaxpr_dot_flops(s) for s, _ in subs),
                         default=0)
        else:
            for sub, m in subs:
                total += m * _jaxpr_dot_flops(sub)
    return mult * total


def jaxpr_dot_flops(closed_jaxpr) -> int:
    """Public face of the analytic matmul-FLOP count — what the APX218
    drift ledger compares against the compiled ``cost_analysis()``
    truth (which counts EVERY op, so the pinned ratio also records how
    dot-dominated each executable is)."""
    return _jaxpr_dot_flops(_open(closed_jaxpr))


def step_time_estimate(closed_jaxpr, axis_sizes: Dict[str, int], *,
                       tflops: Optional[float] = None,
                       ici_gbps: float = 100.0) -> dict:
    """Analytic overlap-aware step-time model for one executable.

    Prices the jaxpr's ``dot_general`` FLOPs against ``tflops`` and its
    collective bytes (the APX215 ring formulas) against ``ici_gbps``,
    then reports both scheduling disciplines:

    * ``sequential_us`` — comm SERIAL with compute (every collective on
      the critical path): ``t_compute + t_comm``;
    * ``overlap_us`` — comm hidden under compute (the restructured
      prefetch/ring pipelines): ``max(t_compute, t_comm)`` per step,
      i.e. only the EXPOSED comm ``max(t_comm - t_compute, 0)`` adds to
      the roofline.

    The absolute numbers inherit the bandwidth constants' optimism —
    the pair is a MODEL whose job is the ratio (the step-time win a
    bench capture records next to the measured legs as
    ``overlap_step_time_model_us``), not a wall-clock prediction.

    ``tflops=None`` resolves to the :mod:`apex_tpu.chip_specs` default
    generation's bf16 peak — the one chip-spec table (callers with a
    live device pass ``find_spec(device_kind).bf16_tflops``).
    """
    if tflops is None:
        from apex_tpu.chip_specs import default_spec
        tflops = default_spec().bf16_tflops
    report = comm_report(closed_jaxpr, axis_sizes)
    flops = _jaxpr_dot_flops(closed_jaxpr)
    t_compute = flops / (tflops * 1e12)
    t_comm = report["total_bytes"] / (ici_gbps * 1e9)
    return {
        "compute_us": round(t_compute * 1e6, 3),
        "comm_us": round(t_comm * 1e6, 3),
        "comm_bytes": int(report["total_bytes"]),
        "dot_flops": int(flops),
        "sequential_us": round((t_compute + t_comm) * 1e6, 3),
        "overlap_us": round(max(t_compute, t_comm) * 1e6, 3),
        "exposed_comm_us": round(max(t_comm - t_compute, 0.0) * 1e6, 3),
    }


def peak_live_bytes(closed_jaxpr) -> int:
    """Linear-scan liveness upper bound on live buffer bytes.

    Inputs are live from entry until their last use; each eqn's outputs
    become live at its position; jaxpr outputs stay live to the end.
    An eqn carrying subjaxprs (cond/scan/pjit/custom_vjp) contributes
    the max of its branches' internal peaks as a transient at its
    position — nested intermediates don't outlive the eqn.
    """
    import jax

    jaxpr = _open(closed_jaxpr)
    eqns = jaxpr.eqns
    last_use: dict = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if not isinstance(v, jax.core.Literal):
                last_use[v] = i
    n_eqns = len(eqns)
    for v in jaxpr.outvars:
        if not isinstance(v, jax.core.Literal):
            last_use[v] = n_eqns

    live = 0
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        if v in last_use:
            live += _aval_bytes(v.aval)
    peak = live
    born_at: dict = {}
    for i, eqn in enumerate(eqns):
        transient = 0
        for sub, _ in _subjaxpr_items(eqn, all_branches=True):
            transient = max(transient, peak_live_bytes(sub))
        for v in eqn.outvars:
            if v in last_use:
                live += _aval_bytes(v.aval)
                born_at[v] = i
        peak = max(peak, live + transient)
        # free everything whose last consumer was this eqn
        for v in list(eqn.invars) + list(eqn.outvars):
            if not isinstance(v, jax.core.Literal) \
                    and last_use.get(v) == i and born_at.get(v, -1) <= i:
                live -= _aval_bytes(v.aval)
                last_use.pop(v)
    return peak
