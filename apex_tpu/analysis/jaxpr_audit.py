"""Jaxpr precision/transfer auditor for the public fused ops.

Each op in :data:`OPS` is traced (``jax.make_jaxpr`` — abstract, zero
FLOPs, runs in milliseconds on CPU) under the declared precision policy
(bf16 activations; optimizer math on fp32 master params; losses
reduce in fp32) and the whole jaxpr — including pallas kernel bodies,
``custom_vjp`` branches and nested ``pjit``/``cond`` jaxprs — is walked
to assert three invariants:

* **APX201 — upcast discipline.** Every ``convert_element_type``
  bf16→fp32 must either feed an accumulating primitive (reductions,
  ``dot_general``) or be one of the op's *declared* entry upcasts
  (``upcast_budget`` — e.g. LayerNorm applies γ/β in fp32 by design).
  A NEW unexplained upcast — someone dropping an fp32 constant into a
  bf16 kernel — fails the audit.
* **APX202 — transfer/callback discipline.** No host callbacks,
  ``device_put`` or infeed/outfeed anywhere in a kernel body.
* **APX203 — output dtype policy.** Outputs match the declared dtypes
  (bf16 in → bf16 out for kernels; losses and optimizer states fp32).

Trace failures surface as APX200 so a refactor that breaks an op's
public signature cannot silently drop it from the audit.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from apex_tpu.analysis.finding import Finding

__all__ = ["OpSpec", "OPS", "run_jaxpr_audit", "POLICY"]

POLICY = ("bf16 activations / fp32 accumulators and losses / "
          "fp32 optimizer master state")

# Primitives whose consumption of an fp32 value justifies the upcast:
# the whole point of fp32 inside a bf16 kernel is accumulation.
ACCUM_PRIMS = {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "dot_general", "add_any", "cumsum", "cumprod", "cumlogsumexp",
    "logsumexp",
}

# Host-transfer / callback primitives that must never appear in a fused
# op's body (they serialise the TPU pipeline or break AOT compilation).
FORBIDDEN_PRIMS = {
    "pure_callback", "io_callback", "callback", "debug_callback",
    "outside_call", "device_put", "infeed", "outfeed",
    "copy_to_host_async",
}


@dataclass
class OpSpec:
    """One audited op: how to trace it + its declared invariants."""
    name: str
    path: str                           # module the finding anchors to
    build: Callable[[], tuple]          # () -> (fn, args tuple)
    out_dtypes: Optional[tuple] = None  # expected output dtypes, None = skip
    # bf16->fp32 converts allowed beyond accumulator feeds (declared
    # entry upcasts, e.g. applying affine params in fp32)
    upcast_budget: Optional[int] = 0    # None = skip the upcast check


def _builders():
    """Specs are built lazily so importing this module stays jax-free
    until an audit actually runs."""
    import jax
    import jax.numpy as jnp

    bf16 = jnp.bfloat16
    f32 = jnp.float32

    def s(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype)

    def layer_norm():
        from apex_tpu.ops import layer_norm as op
        return (lambda x, w, b: op(x, w, b),
                (s((8, 256), bf16), s((256,), bf16), s((256,), bf16)))

    def rms_norm():
        from apex_tpu.ops import rms_norm as op
        return (lambda x, w: op(x, w), (s((8, 256), bf16), s((256,), bf16)))

    def flash_attention():
        from apex_tpu.ops import flash_attention as op
        qkv = s((1, 2, 128, 64), bf16)
        return (lambda q, k, v: op(q, k, v, causal=True), (qkv, qkv, qkv))

    def ring_attention():
        from apex_tpu.ops import ring_attention as op
        qkv = s((1, 2, 128, 64), bf16)
        # axis_name=None exercises the single-shard entry path without a
        # mesh; the collective path shares the same kernels
        return (lambda q, k, v: op(q, k, v, causal=True, axis_name=None),
                (qkv, qkv, qkv))

    def ulysses_attention():
        from apex_tpu.ops import ulysses_attention as op
        qkv = s((1, 2, 128, 64), bf16)
        # axis_name=None exercises the single-shard entry path without a
        # mesh (same contract as the ring entry); the cp>1 all_to_all
        # path is audited with a bound mesh by the SPMD auditor's
        # ulysses_attention_cp executable
        return (lambda q, k, v: op(q, k, v, causal=True, axis_name=None),
                (qkv, qkv, qkv))

    def xentropy():
        from apex_tpu.ops import softmax_cross_entropy_loss as op
        return (lambda l, y: op(l, y),
                (s((8, 128), bf16), s((8,), jnp.int32)))

    def fused_lm_xent():
        from apex_tpu.ops import fused_lm_head_cross_entropy as op
        # traced fused (chunked) so the scan + custom_vjp bodies are
        # walked; the chunk=0 lowering is the already-audited xentropy
        # op plus a matmul
        return (lambda h, w, y: op(h, w, y, token_chunk=32,
                                   vocab_chunk=0),
                (s((96, 64), bf16), s((512, 64), bf16),
                 s((96,), jnp.int32)))

    def fused_adam():
        from apex_tpu.ops import fused_adam_flat as op
        p = s((256,), f32)
        return (lambda p_, g, m, v: op(
            p_, g, m, v, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
            weight_decay=0.0, step=1), (p, p, p, p))

    def moe_layer():
        import flax  # noqa: F401 — optional dep; ImportError skips the op
        from apex_tpu.transformer.moe.layer import MoELayer
        layer = MoELayer(num_experts=4, hidden_size=64,
                         ffn_hidden_size=128, top_k=2)
        key = jax.random.PRNGKey(0)
        x = s((16, 64), bf16)
        variables = jax.eval_shape(layer.init, key, x)
        return (lambda v, x_: layer.apply(v, x_), (variables, x))

    def decode_attention():
        from apex_tpu.ops import decode_attention as op
        q = s((2, 4, 1, 64), bf16)
        kv = s((2, 2, 128, 64), bf16)
        return (lambda q_, k_, v_, n: op(q_, k_, v_, n),
                (q, kv, kv, s((2,), jnp.int32)))

    def _engine_audit_pieces():
        """Shared tiny-GPT engine fixture for the inference entries:
        abstract params (eval_shape — no FLOPs) + an abstract cache."""
        import flax  # noqa: F401 — optional dep; ImportError skips
        from apex_tpu.inference import kv_cache
        from apex_tpu.inference.sampling import SamplingConfig
        from apex_tpu.transformer import parallel_state
        from apex_tpu.transformer.testing import (GPTConfig,
                                                  gpt_model_provider)
        # the TP layers' tp=1 identity fast path reads parallel_state;
        # tracing outside a test harness needs it initialized (same
        # single-rank init every consumer of these models performs)
        if not parallel_state.model_parallel_is_initialized():
            parallel_state.initialize_model_parallel(1)
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_attention_heads=4, max_seq_length=64,
                        hidden_dropout=0.0, attention_dropout=0.0,
                        params_dtype=bf16)
        model = gpt_model_provider(cfg)
        params = jax.eval_shape(model.init, jax.random.PRNGKey(0),
                                s((1, 8), jnp.int32))
        cache = jax.eval_shape(
            lambda: kv_cache.init_cache(2, cfg.num_layers,
                                        cfg.num_attention_heads, 64,
                                        64 // cfg.num_attention_heads))
        key = s((2,), jnp.uint32)
        return cfg, SamplingConfig(), params, cache, key

    def inference_prefill():
        from apex_tpu.inference.engine import make_prefill_fn
        cfg, sampling, params, cache, key = _engine_audit_pieces()
        fn = make_prefill_fn("gpt", cfg, sampling)
        return (fn, (cache, params, s((16,), jnp.int32),
                     s((), jnp.int32), s((), jnp.int32), key,
                     s((), jnp.int32)))

    def inference_decode():
        from apex_tpu.inference.engine import make_decode_fn
        cfg, sampling, params, cache, key = _engine_audit_pieces()
        fn = make_decode_fn("gpt", cfg, sampling)
        return (fn, (cache, params, s((2,), jnp.int32), s((2,), bool),
                     key, s((), jnp.int32)))

    def _paged_engine_audit_pieces():
        """Straggler-shaped paged fixture (ISSUE 6): slots x max_seq
        would be 4 x 256 = 1024 cached tokens dense, but the pool holds
        only 20 pages x 16 = 320 (mean_seq << max_seq sizing) — the
        geometry the APX215 peak-live comparison test measures the
        paged win on.  attn_max_pages=0 pins the Pallas kernel path so
        the registered executable is the one with NO materialized
        gather window."""
        import flax  # noqa: F401 — optional dep; ImportError skips
        from apex_tpu.inference import kv_cache
        from apex_tpu.inference.sampling import SamplingConfig
        from apex_tpu.transformer import parallel_state
        from apex_tpu.transformer.testing import (GPTConfig,
                                                  gpt_model_provider)
        if not parallel_state.model_parallel_is_initialized():
            parallel_state.initialize_model_parallel(1)
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_attention_heads=4, max_seq_length=256,
                        hidden_dropout=0.0, attention_dropout=0.0,
                        params_dtype=bf16)
        model = gpt_model_provider(cfg)
        params = jax.eval_shape(model.init, jax.random.PRNGKey(0),
                                s((1, 8), jnp.int32))
        cache = jax.eval_shape(
            lambda: kv_cache.init_paged_cache(
                20, cfg.num_layers, cfg.num_attention_heads, 16,
                64 // cfg.num_attention_heads, slots=4,
                max_pages_per_slot=16))
        cache = cache.replace(attn_max_pages=0)
        key = s((2,), jnp.uint32)
        return cfg, SamplingConfig(), params, cache, key

    def inference_prefill_paged():
        # operand order: cache, params, tokens, slot, length, row,
        # prefill_from (ISSUE 12: the suffix-prefill position — 0 for
        # a cold prefill; the cond'd prefix-window path is part of the
        # ONE audited executable), key, step
        from apex_tpu.inference.engine import make_prefill_fn
        cfg, sampling, params, cache, key = _paged_engine_audit_pieces()
        fn = make_prefill_fn("gpt", cfg, sampling, paged=True)
        return (fn, (cache, params, s((64,), jnp.int32),
                     s((), jnp.int32), s((), jnp.int32),
                     s((16,), jnp.int32), s((), jnp.int32), key,
                     s((), jnp.int32)))

    def inference_decode_paged():
        from apex_tpu.inference.engine import make_decode_fn
        cfg, sampling, params, cache, key = _paged_engine_audit_pieces()
        fn = make_decode_fn("gpt", cfg, sampling)
        return (fn, (cache, params, s((4,), jnp.int32), s((4,), bool),
                     key, s((), jnp.int32)))

    def fused_block_decode_op():
        # the ISSUE 15 fused transformer-block decode kernel at an
        # op-level GPT-shaped fixture (LN + qkv + paged attention incl.
        # the current token + out proj + MLP in ONE pallas_call): the
        # kernel body's precision discipline is audited directly, the
        # whole-executable twin below covers the engine lowering
        from apex_tpu.ops.paged_attention import fused_block_decode as op
        hidden, heads, d, ps, mpps, slots = 64, 4, 16, 16, 4, 2
        hd = heads * d
        blk = {
            "ln1_w": s((1, hidden), bf16), "ln1_b": s((1, hidden), bf16),
            "wq": s((hidden, hd), bf16), "bq": s((1, hd), bf16),
            "wk": s((hidden, hd), bf16), "bk": s((1, hd), bf16),
            "wv": s((hidden, hd), bf16), "bv": s((1, hd), bf16),
            "wo": s((hd, hidden), bf16), "bo": s((1, hidden), bf16),
            "ln2_w": s((1, hidden), bf16), "ln2_b": s((1, hidden), bf16),
            "wu": s((hidden, 4 * hidden), bf16),
            "bu": s((1, 4 * hidden), bf16),
            "wd": s((4 * hidden, hidden), bf16),
            "bd": s((1, hidden), bf16),
        }
        pages = s((9, heads, ps, d), bf16)
        return (lambda x, b, kp, vp, pt, ln: op(
                    x, b, kp, vp, pt, ln, kind="gpt", eps=1e-5),
                (s((slots, hidden), bf16), blk, pages, pages,
                 s((slots, mpps), jnp.int32), s((slots,), jnp.int32)))

    def inference_decode_fused_paged():
        # the fused-block decode EXECUTABLE (APEX_TPU_DECODE_FUSION=1
        # lowering of the one donated decode step): same signature and
        # output pins as the per-op twin, params operand = (tree,
        # fused layout)
        from apex_tpu.inference import models
        from apex_tpu.inference.engine import make_decode_fn
        cfg, sampling, params, cache, key = _paged_engine_audit_pieces()
        fused = jax.eval_shape(
            lambda p: models.fused_layer_params("gpt", cfg, p), params)
        fn = make_decode_fn("gpt", cfg, sampling, fused=True)
        return (fn, (cache, (params, fused), s((4,), jnp.int32),
                     s((4,), bool), key, s((), jnp.int32)))

    def inference_verify_paged():
        # the speculative verify step (ISSUE 15): k drafts + bonus
        # scored in one batched executable, lengths advanced by the
        # accepted count in-program (the rollback)
        from apex_tpu.inference.engine import make_verify_fn
        cfg, sampling, params, cache, key = _paged_engine_audit_pieces()
        fn = make_verify_fn("gpt", cfg, sampling, k=4)
        return (fn, (cache, params, s((4, 5), jnp.int32),
                     s((4,), bool), key, s((), jnp.int32)))

    def inference_cow_page():
        # the ISSUE 12 copy-on-write barrier: one page duplicated
        # inside the donated pool — audited for precision/transfer
        # discipline like every serving program (it moves exactly one
        # page and adds no collectives, so it carries no budget entry)
        from apex_tpu.inference import kv_cache as kvc
        _, _, _, cache, _ = _paged_engine_audit_pieces()
        return (kvc.cow_page, (cache, s((), jnp.int32),
                               s((), jnp.int32)))

    def inference_swap_out_paged():
        # the ISSUE 18 host-tier offload gather: one fixed-width batch
        # of page slabs read out of the pool (D2H happens at the
        # dispatch boundary via device_get — the program itself must
        # stay free of host callbacks/transfers, which is exactly what
        # this audit pins)
        from apex_tpu.inference import kv_cache as kvc
        _, _, _, cache, _ = _paged_engine_audit_pieces()
        return (kvc.extract_pages, (cache, s((8,), jnp.int32)))

    def inference_swap_in_paged():
        # the ISSUE 18 swap-back scatter: one fixed-width batch of host
        # slabs written into the (donated) pool at their new page ids;
        # padding lanes carry an out-of-bounds id and drop
        from apex_tpu.inference import kv_cache as kvc
        _, _, _, cache, _ = _paged_engine_audit_pieces()
        slab = s((8, 2, 4, 16, 16), bf16)
        return (kvc.restore_pages, (cache, s((8,), jnp.int32),
                                    slab, slab))

    return {
        # budgets are the measured entry upcasts (γ/β applied in fp32 by
        # design — see the kernel docstrings); any increase fails
        "layer_norm": (layer_norm, "apex_tpu/ops/layer_norm.py",
                       ("bfloat16",), 2),
        "rms_norm": (rms_norm, "apex_tpu/ops/layer_norm.py",
                     ("bfloat16",), 3),
        "flash_attention": (flash_attention, "apex_tpu/ops/attention.py",
                            ("bfloat16",), 0),
        "ring_attention": (ring_attention, "apex_tpu/ops/ring_attention.py",
                           ("bfloat16",), 0),
        "ulysses_attention": (ulysses_attention,
                              "apex_tpu/ops/ulysses_attention.py",
                              ("bfloat16",), 0),
        "xentropy": (xentropy, "apex_tpu/ops/xentropy.py",
                     ("float32",), 0),
        "fused_lm_xent": (fused_lm_xent, "apex_tpu/ops/fused_lm_xent.py",
                          ("float32",), 0),
        "fused_adam": (fused_adam, "apex_tpu/ops/fused_update.py",
                       ("float32", "float32", "float32"), 0),
        # flax module: dtype promotion is the router's business — audit
        # transfer discipline only
        "moe_layer": (moe_layer, "apex_tpu/transformer/moe/layer.py",
                      None, None),
        # the inference subsystem's device programs (ISSUE 4/6): the
        # decode core holds the full bf16 policy; the whole prefill/
        # decode executables pin output dtypes (cache bf16 / page
        # table + lengths + capacity + sampled tokens int32 / logits
        # fp32 / truncated flags bool) and transfer discipline — a host
        # callback sneaking into the serving hot loop fails the audit.
        # Per-layer LN entry upcasts make a whole-model upcast budget
        # churn with depth, so the engine entries skip that one check
        # (decode_attention carries it).
        "decode_attention": (decode_attention,
                             "apex_tpu/ops/attention.py",
                             ("bfloat16",), 0),
        "inference_prefill": (inference_prefill,
                              "apex_tpu/inference/engine.py",
                              ("bfloat16", "bfloat16", "int32", "int32",
                               "float32"), None),
        "inference_decode": (inference_decode,
                             "apex_tpu/inference/engine.py",
                             ("bfloat16", "bfloat16", "int32", "int32",
                              "float32", "bool"), None),
        "inference_prefill_paged": (inference_prefill_paged,
                                    "apex_tpu/inference/engine.py",
                                    ("bfloat16", "bfloat16", "int32",
                                     "int32", "int32", "int32",
                                     "float32"), None),
        "inference_decode_paged": (inference_decode_paged,
                                   "apex_tpu/inference/engine.py",
                                   ("bfloat16", "bfloat16", "int32",
                                    "int32", "int32", "int32",
                                    "float32", "bool"), None),
        # ISSUE 15: the fused-block kernel (op-level; measured entry
        # upcasts = 11: the norm gains/biases and the projection/MLP
        # biases applied in fp32 by design — layer_norm's budget-2
        # pattern across the whole block — plus the fp32 residual
        # carry of x) + the two new serving executables.  The fused decode pins the SAME outputs as the
        # unfused paged decode (one signature, two lowerings behind
        # APEX_TPU_DECODE_FUSION); the verify step swaps logits for
        # the emitted token slab + accepted counts.
        "fused_block_decode": (fused_block_decode_op,
                               "apex_tpu/ops/paged_attention.py",
                               ("bfloat16", "bfloat16", "bfloat16"),
                               11),
        "inference_decode_fused_paged": (inference_decode_fused_paged,
                                         "apex_tpu/inference/engine.py",
                                         ("bfloat16", "bfloat16",
                                          "int32", "int32", "int32",
                                          "int32", "float32", "bool"),
                                         None),
        "inference_verify_paged": (inference_verify_paged,
                                   "apex_tpu/inference/engine.py",
                                   ("bfloat16", "bfloat16", "int32",
                                    "int32", "int32", "int32",
                                    "int32", "bool"), None),
        "inference_cow_page": (inference_cow_page,
                               "apex_tpu/inference/kv_cache.py",
                               ("bfloat16", "bfloat16", "int32",
                                "int32", "int32"), 0),
        # ISSUE 18: the two host-tier copy programs — pure gathers/
        # scatters over the pool (no collectives, no host callbacks,
        # no entry upcasts); the swap-in returns the whole cache (cow's
        # output pins), the swap-out returns the two page slabs
        "inference_swap_out_paged": (inference_swap_out_paged,
                                     "apex_tpu/inference/kv_cache.py",
                                     ("bfloat16", "bfloat16"), 0),
        "inference_swap_in_paged": (inference_swap_in_paged,
                                    "apex_tpu/inference/kv_cache.py",
                                    ("bfloat16", "bfloat16", "int32",
                                     "int32", "int32"), 0),
    }


def op_specs() -> list:
    return [OpSpec(name, path, build, out, budget)
            for name, (build, path, out, budget) in _builders().items()]


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------

def _subjaxprs(params: dict):
    import jax
    for v in params.values():
        items = v if isinstance(v, (list, tuple)) else [v]
        for item in items:
            if isinstance(item, jax.core.ClosedJaxpr):
                yield item.jaxpr
            elif isinstance(item, jax.core.Jaxpr):
                yield item


def _iter_jaxprs(jaxpr):
    yield jaxpr
    for eqn in jaxpr.eqns:
        for sub in _subjaxprs(eqn.params):
            yield from _iter_jaxprs(sub)


def _audit_jaxpr(closed) -> tuple:
    """-> (unexplained_upcast_count, forbidden_prim_names)"""
    import jax
    import jax.numpy as jnp
    unexplained = 0
    forbidden: list = []
    for jaxpr in _iter_jaxprs(closed.jaxpr):
        consumers: dict = {}
        for eqn in jaxpr.eqns:
            for var in eqn.invars:
                if not isinstance(var, jax.core.Literal):
                    consumers.setdefault(var, []).append(eqn.primitive.name)
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in FORBIDDEN_PRIMS:
                forbidden.append(name)
            if name == "convert_element_type" and \
                    eqn.params.get("new_dtype") == jnp.float32 and \
                    getattr(eqn.invars[0], "aval", None) is not None and \
                    eqn.invars[0].aval.dtype == jnp.bfloat16:
                outs = consumers.get(eqn.outvars[0], [])
                # escaping the subjaxpr (no local consumer) means the
                # fp32 value is an output/residual — a declared boundary
                if outs and not any(c in ACCUM_PRIMS for c in outs):
                    unexplained += 1
    return unexplained, forbidden


def audit_op(spec: OpSpec) -> list:
    """Audit one op; returns findings (empty = all invariants hold)."""
    import jax

    findings: list = []

    def finding(rule, msg):
        # line_text feeds the baseline fingerprint — keep it to the
        # stable (op, rule) identity; msg carries the volatile details
        # (exception strings, counts) that must not churn the ratchet
        return Finding(rule, spec.path, 0, 0, msg,
                       line_text=f"{spec.name}:{rule}")

    try:
        fn, args = spec.build()
    except ImportError:
        return []  # optional dependency absent — op not in this build
    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:  # noqa: BLE001 — any trace failure is a finding
        return [finding("APX200",
                        f"tracing {spec.name} under the precision policy "
                        f"failed: {type(e).__name__}: {e}")]

    unexplained, forbidden = _audit_jaxpr(closed)
    if forbidden:
        findings.append(finding(
            "APX202",
            f"{spec.name} jaxpr contains host-transfer/callback "
            f"primitive(s) {sorted(set(forbidden))} — fused op bodies "
            f"must stay on-device"))
    if spec.upcast_budget is not None and unexplained > spec.upcast_budget:
        findings.append(finding(
            "APX201",
            f"{spec.name} has {unexplained} bf16→fp32 upcast(s) that feed "
            f"no accumulator (budget {spec.upcast_budget}) — an fp32 "
            f"constant/operand is silently promoting the bf16 kernel "
            f"body"))
    if spec.out_dtypes is not None:
        got = tuple(str(v.aval.dtype) for v in closed.jaxpr.outvars)
        if got != tuple(spec.out_dtypes):
            findings.append(finding(
                "APX203",
                f"{spec.name} output dtypes {got} violate the declared "
                f"policy {tuple(spec.out_dtypes)}"))
    return findings


def run_jaxpr_audit(ops: Optional[Sequence[str]] = None) -> list:
    """Audit every (or the named) public fused op under the bf16 policy."""
    specs = op_specs()
    if ops:
        wanted = set(ops)
        missing = wanted - {s.name for s in specs}
        if missing:
            raise ValueError(f"unknown op(s): {sorted(missing)}")
        specs = [s for s in specs if s.name in wanted]
    out: list = []
    for spec in specs:
        out.extend(audit_op(spec))
    return out
