"""Pallas kernel VMEM auditor: the fourth analysis engine.

The other three engines stop at the ``pallas_call`` boundary — the AST
lint sees the call site, the jaxpr audit walks the kernel body's
arithmetic, the SPMD audit prices the collectives around it — but none
of them can answer the question the ROADMAP's hottest open items turn
on: *does this kernel's working set fit in VMEM?*  The fused block
decode is capped at hidden ≲ 2048 by a VMEM envelope that existed only
as a PERF.md comment; weight-TILE streaming (item 6) and TP-sharded
fused decode (item 1) are both justified by shrinking that envelope.
This engine makes the constraint machine-checked instead of folklore.

Every registered Pallas kernel is traced abstractly (``jax.make_jaxpr``
— zero FLOPs, CPU milliseconds) and each ``pallas_call`` equation is
decomposed into its grid, BlockSpec block shapes + index maps, VMEM
scratch shapes and scalar-prefetch operands.  From those pieces a
static per-grid-step VMEM footprint is modeled:

* **prefetch operands** — SMEM-resident whole arrays, counted once;
* **operand/output blocks** — ``prod(block_shape) · itemsize`` per
  buffer; a block whose index map *varies* with the grid is DMA'd per
  step and double-buffered (×2 — compute on buffer A while step i+1
  lands in buffer B), a block with a *constant* index map is fetched
  once and stays resident (×1 — the fused decode's weight blocks);
* **scratch** — full shapes, resident for the kernel's lifetime (the
  fp32 online-softmax accumulators).

The footprint is priced against per-core VMEM capacity from
:mod:`apex_tpu.chip_specs` and committed to the
``.analysis_kernel_budget.json`` ledger with the same ratchet /
no-suppression / conscious-re-pin discipline as the SPMD comm budget.

Checks:

* **APX300** — kernel trace failure (a refactor that breaks an op's
  signature cannot silently drop it from the audit; mirrors APX200/210).
* **APX301** — VMEM envelope: a kernel's modeled footprint exceeds the
  chip's VMEM capacity, or GREW past its committed ledger entry.
* **APX302** — reduction-kernel accumulator discipline: a kernel
  declared ``reduction`` in its module's ``PALLAS_AUDIT`` hook whose
  VMEM scratch (or revisited constant-index-map output block) is not
  fp32 — the online-softmax/wgrad rule, previously enforced only by
  convention.
* **APX303** — grid/BlockSpec divisibility: a block dim that doesn't
  divide its operand dim silently masks (or zero-pads) a remainder;
  flagged unless the kernel declares ``masked_tail`` in its module's
  ``PALLAS_AUDIT`` hook (the paged kernels' beyond-length page masking,
  the fused-update kernels' lane-padded single block).
* **APX304** — traced-value use in a BlockSpec index map: index maps
  must resolve from grid indices + scalar-prefetch operands only.  jax
  rejects a captured tracer at trace time, so in the wild this
  surfaces as a classified trace failure; the record-level check also
  covers captured non-grid constants.
* **APX305** — ledger completeness: a Pallas kernel reachable from a
  registered op with no kernel-budget entry (mirrors APX215's
  unbudgeted-executable check; the tier-1 exact-set guard catches the
  stale direction).

Ops modules declare the properties the trace can't reveal in a
module-level ``PALLAS_AUDIT`` dict (kernel name → ``{"reduction":
bool, "masked_tail": bool}``) — a registration hook only, no behavior
change.

``fused_block_envelope`` / ``predict_fusion_max_hidden`` expose the
model for the fused decode block directly: the hidden-size sweep that
must bracket the observed ~2048 fusion cap (tier-1 test; tolerance
documented in PERF.md round-16), and the ``--mesh tp=N`` mode pricing
the 1/tp-sharded weight-block envelope for ROADMAP item 1.
"""
from __future__ import annotations

import importlib
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from apex_tpu.analysis.finding import Finding
from apex_tpu.chip_specs import CHIP_SPECS, DEFAULT_CHIP, ChipSpec

__all__ = [
    "BUDGET_NAME", "DOUBLE_BUFFER", "KernelOpSpec", "BlockRecord",
    "KernelRecord", "kernel_specs", "extract_kernels",
    "check_kernel_record", "audit_kernel_op", "run_kernel_audit",
    "compare_kernel_budget", "fused_block_envelope",
    "predict_fusion_max_hidden", "FUSION_SWEEP",
]

BUDGET_NAME = ".analysis_kernel_budget.json"

#: buffer factor for grid-varying (DMA'd) blocks: the Pallas pipeline
#: overlaps step i's compute with step i+1's DMA, so two copies of the
#: block are live; constant-index-map blocks are fetched once (×1).
DOUBLE_BUFFER = 2

#: the default hidden-size sweep for the fused-decode crossover model
#: (all multiples of the flagship head_dim 64, heads even so tp=2
#: shards cleanly).
FUSION_SWEEP = (512, 768, 1024, 1536, 2048, 3072, 4096, 6144, 8192)


# ---------------------------------------------------------------------------
# records
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class BlockRecord:
    """One BlockSpec'd operand/output of a ``pallas_call``."""
    role: str               # "in" | "out"
    block_shape: tuple
    full_shape: tuple
    dtype: str
    block_bytes: int        # one buffer: prod(block_shape) * itemsize
    constant: bool          # constant index map -> resident, single copy
    traced_consts: int      # values the index map captured by closure
    nondividing: tuple      # dims where block_shape doesn't divide full

    @property
    def bytes_per_step(self) -> int:
        return self.block_bytes * (1 if self.constant else DOUBLE_BUFFER)


@dataclass(frozen=True)
class KernelRecord:
    """One ``pallas_call`` equation, decomposed for the VMEM model."""
    kernel: str             # kernel function name
    grid: tuple
    prefetch_bytes: int     # scalar-prefetch operands (SMEM), whole
    blocks: tuple           # BlockRecords, inputs then outputs
    scratch: tuple          # ((shape, dtype, bytes), ...)

    @property
    def block_bytes(self) -> int:
        return sum(b.bytes_per_step for b in self.blocks)

    @property
    def resident_bytes(self) -> int:
        return sum(b.block_bytes for b in self.blocks if b.constant)

    @property
    def scratch_bytes(self) -> int:
        return sum(s[2] for s in self.scratch)

    @property
    def vmem_bytes(self) -> int:
        """The modeled per-grid-step VMEM footprint."""
        return self.prefetch_bytes + self.block_bytes + self.scratch_bytes

    def entry(self) -> dict:
        """The ledger shape committed per kernel."""
        return {
            "grid": list(self.grid),
            "vmem_bytes": self.vmem_bytes,
            "resident_bytes": self.resident_bytes,
            "scratch_bytes": self.scratch_bytes,
            "prefetch_bytes": self.prefetch_bytes,
            "blocks": len(self.blocks),
        }


@dataclass(frozen=True)
class KernelOpSpec:
    """One registered kernel-bearing op: how to trace it + where its
    module's ``PALLAS_AUDIT`` declarations live."""
    name: str
    path: str                     # module path findings anchor to
    module: str                   # dotted module carrying PALLAS_AUDIT
    build: Callable[[], tuple]    # () -> (fn, args tuple)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def _builders():
    """Lazy fixtures (importing this module stays jax-free).  Every
    fixture pins the PALLAS path explicitly (``xla_max_seq=0`` /
    ``xla_max_pages=0``) — the auditor prices kernels, not the XLA
    twins the crossover knobs would otherwise dispatch these tiny
    shapes to.  Norm/attention ops trace fwd+bwd via ``jax.vjp`` so
    the backward kernels (the wgrad accumulators) are covered."""
    import jax
    import jax.numpy as jnp

    bf16 = jnp.bfloat16
    f32 = jnp.float32

    def s(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype)

    def layer_norm():
        from apex_tpu.ops import layer_norm as op

        def fn(x, w, b):
            y, vjp = jax.vjp(op, x, w, b)
            return vjp(y)
        return fn, (s((128, 256), bf16), s((256,), bf16), s((256,), bf16))

    def rms_norm():
        from apex_tpu.ops import rms_norm as op

        def fn(x, w):
            y, vjp = jax.vjp(op, x, w)
            return vjp(y)
        return fn, (s((128, 256), bf16), s((256,), bf16))

    def flash_attention():
        from apex_tpu.ops import flash_attention as op

        def fn(q, k, v):
            y, vjp = jax.vjp(
                lambda *a: op(*a, causal=True, xla_max_seq=0), q, k, v)
            return vjp(y)
        qkv = s((1, 2, 256, 64), bf16)
        return fn, (qkv, qkv, qkv)

    def decode_attention():
        from apex_tpu.ops import decode_attention as op
        return (lambda q, k, v, n: op(q, k, v, n, xla_max_seq=0),
                (s((2, 4, 1, 64), bf16), s((2, 2, 128, 64), bf16),
                 s((2, 2, 128, 64), bf16), s((2,), jnp.int32)))

    def paged_decode_attention():
        from apex_tpu.ops import paged_decode_attention as op
        pages = s((9, 4, 16, 64), bf16)
        return (lambda q, kp, vp, pt, n: op(q, kp, vp, pt, n,
                                            xla_max_pages=0),
                (s((2, 4, 64), bf16), pages, pages,
                 s((2, 4), jnp.int32), s((2,), jnp.int32)))

    def fused_block_decode():
        # the jaxpr-audit fixture geometry (hidden 64, GPT kind); the
        # flagship-shape envelope rides fused_block_envelope, not the
        # ledger entry
        return _fused_block_fixture(hidden=64, head_dim=16,
                                    page_size=16, max_pages=4, slots=2,
                                    pages=9)

    def fused_block_decode_tp2():
        # ISSUE 17: the tp=2 SERVING shard of the same fixture — the
        # --mesh pricing as a committed ledger row.  fuse_mlp off and
        # partial_out on, exactly the variant the sharded decode
        # dispatches (the out-proj psum + MLP tail run outside)
        return _fused_block_fixture(hidden=64, head_dim=16,
                                    page_size=16, max_pages=4, slots=2,
                                    pages=9, tp=2, partial_out=True)

    def fused_update():
        from apex_tpu.ops.fused_update import (
            fused_adagrad_flat, fused_adam_flat, fused_axpby,
            fused_l2norm, fused_l2norm_scale, fused_lamb_phase1_flat,
            fused_scale, fused_sgd_flat)

        def fn(p, g, m, v):
            out = [fused_scale(p, 0.5),
                   fused_axpby(1.0, p, 2.0, g),
                   fused_l2norm(p),
                   fused_l2norm_scale(p, 0.5)]
            out.extend(fused_adam_flat(
                p, g, m, v, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
                weight_decay=0.0, step=1))
            out.extend(fused_adagrad_flat(
                p, g, m, lr=1e-2, eps=1e-10, weight_decay=0.0))
            out.extend(fused_sgd_flat(
                p, g, m, lr=1e-2, momentum=0.9, dampening=0.0,
                weight_decay=0.0, nesterov=False))
            out.extend(fused_lamb_phase1_flat(
                p, g, m, v, beta1=0.9, beta2=0.999, eps=1e-8,
                weight_decay=0.01, step=1))
            return out
        p = s((2048,), f32)
        return fn, (p, p, p, p)

    def xentropy():
        # XLA-lowered (no pallas_call) — the zero-kernel entry
        # documents that; a Pallas rewrite lands in the ledger here
        from apex_tpu.ops import softmax_cross_entropy_loss as op
        return (lambda l, y: op(l, y),
                (s((8, 128), bf16), s((8,), jnp.int32)))

    def fused_lm_xent():
        from apex_tpu.ops import fused_lm_head_cross_entropy as op
        return (lambda h, w, y: op(h, w, y, token_chunk=32,
                                   vocab_chunk=0),
                (s((96, 64), bf16), s((512, 64), bf16),
                 s((96,), jnp.int32)))

    ops = "apex_tpu.ops."
    return {
        "layer_norm": (layer_norm, "apex_tpu/ops/layer_norm.py",
                       ops + "layer_norm"),
        "rms_norm": (rms_norm, "apex_tpu/ops/layer_norm.py",
                     ops + "layer_norm"),
        "flash_attention": (flash_attention, "apex_tpu/ops/attention.py",
                            ops + "attention"),
        "decode_attention": (decode_attention, "apex_tpu/ops/attention.py",
                             ops + "attention"),
        "paged_decode_attention": (paged_decode_attention,
                                   "apex_tpu/ops/paged_attention.py",
                                   ops + "paged_attention"),
        "fused_block_decode": (fused_block_decode,
                               "apex_tpu/ops/paged_attention.py",
                               ops + "paged_attention"),
        "fused_block_decode_tp2": (fused_block_decode_tp2,
                                   "apex_tpu/ops/paged_attention.py",
                                   ops + "paged_attention"),
        "fused_update": (fused_update, "apex_tpu/ops/fused_update.py",
                         ops + "fused_update"),
        "xentropy": (xentropy, "apex_tpu/ops/xentropy.py",
                     ops + "xentropy"),
        "fused_lm_xent": (fused_lm_xent, "apex_tpu/ops/fused_lm_xent.py",
                          ops + "fused_lm_xent"),
    }


def kernel_specs() -> list:
    return [KernelOpSpec(name, path, module, build)
            for name, (build, path, module) in _builders().items()]


def _op_meta(spec: KernelOpSpec) -> dict:
    """The op module's ``PALLAS_AUDIT`` declarations ({} if absent)."""
    try:
        mod = importlib.import_module(spec.module)
    except ImportError:
        return {}
    return getattr(mod, "PALLAS_AUDIT", {}) or {}


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------

def _itemsize(dtype) -> int:
    import numpy as np
    return int(np.dtype(dtype).itemsize)


def _prod(shape) -> int:
    out = 1
    for d in shape:
        out *= int(d)
    return out


def _record_from_eqn(eqn) -> KernelRecord:
    import jax

    gm = eqn.params["grid_mapping"]
    nsi = eqn.params.get("name_and_src_info")
    kname = getattr(nsi, "name", None) or str(nsi).split(" at ")[0]

    npre = gm.num_index_operands
    prefetch = sum(_prod(sh.shape) * _itemsize(sh.dtype)
                   for sh in list(gm.in_shapes)[:npre])

    blocks = []
    for i, bm in enumerate(gm.block_mappings):
        full = bm.array_shape_dtype
        # mapped/None dims contribute one element to the block
        bshape = tuple(int(b) if isinstance(b, int) else 1
                       for b in bm.block_shape)
        imj = bm.index_map_jaxpr
        constant = (not imj.jaxpr.eqns) and all(
            isinstance(v, jax.core.Literal) for v in imj.jaxpr.outvars)
        nondiv = tuple(
            d for d, (b, n) in enumerate(zip(bshape, full.shape))
            if b > 0 and int(n) % b)
        blocks.append(BlockRecord(
            role="in" if i < gm.num_inputs else "out",
            block_shape=bshape,
            full_shape=tuple(int(n) for n in full.shape),
            dtype=str(full.dtype),
            block_bytes=_prod(bshape) * _itemsize(full.dtype),
            constant=constant,
            traced_consts=len(imj.consts),
            nondividing=nondiv))

    kj = eqn.params["jaxpr"]
    nscr = gm.num_scratch_operands
    scratch = tuple(
        (tuple(int(d) for d in v.aval.shape), str(v.aval.dtype),
         _prod(v.aval.shape) * _itemsize(v.aval.dtype))
        for v in (kj.invars[len(kj.invars) - nscr:] if nscr else []))

    grid = tuple(int(g) if isinstance(g, int) else -1 for g in gm.grid)
    return KernelRecord(kname, grid, prefetch, tuple(blocks), scratch)


def extract_kernels(closed) -> list:
    """Every ``pallas_call`` reachable from a closed jaxpr (including
    inside ``custom_vjp`` branches / nested ``pjit`` bodies), as
    :class:`KernelRecord` s in trace order."""
    from apex_tpu.analysis.jaxpr_audit import _iter_jaxprs
    records = []
    for jaxpr in _iter_jaxprs(closed.jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "pallas_call":
                records.append(_record_from_eqn(eqn))
    return records


# ---------------------------------------------------------------------------
# checks
# ---------------------------------------------------------------------------

def _chip(chip: Optional[str]) -> ChipSpec:
    key = chip or DEFAULT_CHIP
    if key not in CHIP_SPECS:
        raise ValueError(
            f"unknown chip {key!r}; known: {sorted(CHIP_SPECS)}")
    return CHIP_SPECS[key]


def check_kernel_record(rec: KernelRecord, meta: dict, chip: ChipSpec,
                        op_name: str, path: str) -> list:
    """The per-kernel check battery (APX301 capacity half, APX302,
    APX303, APX304) over one extracted record.  ``meta`` is the op
    module's ``PALLAS_AUDIT`` dict."""
    findings: list = []
    decl = meta.get(rec.kernel, {})

    def emit(rule, msg):
        findings.append(Finding(
            rule, path, 0, 0, msg,
            line_text=f"{op_name}:{rec.kernel}:{rule}"))

    if rec.vmem_bytes > chip.vmem_bytes:
        emit("APX301",
             f"{op_name}: kernel {rec.kernel} models {rec.vmem_bytes} B "
             f"of VMEM per grid step ({rec.resident_bytes} resident + "
             f"{rec.block_bytes - rec.resident_bytes} streamed + "
             f"{rec.scratch_bytes} scratch) against {chip.key}'s "
             f"{chip.vmem_bytes} B capacity — shrink the blocks or "
             f"stream the resident operands through the grid")

    if decl.get("reduction"):
        for shape, dtype, _ in rec.scratch:
            if dtype != "float32":
                emit("APX302",
                     f"{op_name}: reduction kernel {rec.kernel} "
                     f"accumulates in {dtype} scratch {shape} — online-"
                     f"softmax/wgrad accumulators must be fp32")
        for b in rec.blocks:
            if b.role == "out" and b.constant and b.dtype != "float32":
                emit("APX302",
                     f"{op_name}: reduction kernel {rec.kernel} "
                     f"revisits output block {b.block_shape} across the "
                     f"grid (constant index map) in {b.dtype} — the "
                     f"accumulated output must be fp32")

    if not decl.get("masked_tail"):
        for b in rec.blocks:
            if b.nondividing:
                emit("APX303",
                     f"{op_name}: kernel {rec.kernel} {b.role}-block "
                     f"{b.block_shape} does not divide operand "
                     f"{b.full_shape} on dim(s) {list(b.nondividing)} — "
                     f"the remainder is silently masked/zero-padded; "
                     f"handle the tail in-kernel and declare "
                     f"masked_tail in the module's PALLAS_AUDIT")

    for b in rec.blocks:
        if b.traced_consts:
            emit("APX304",
                 f"{op_name}: kernel {rec.kernel} {b.role}-block index "
                 f"map captures {b.traced_consts} closure value(s) — "
                 f"index maps must resolve from grid indices + scalar-"
                 f"prefetch operands only")
    return findings


# jax's own trace-time rejection of a tracer captured by an index map
# (the APX304 condition caught upstream) — classify it, don't bury it
# in a generic APX300.
_INDEX_MAP_CAPTURE = ("Index map function", "capture")


def audit_kernel_op(spec: KernelOpSpec, chip: Optional[str] = None):
    """Audit one registered op; -> ``(findings, ledger entry | None)``."""
    import jax

    chip_spec = _chip(chip)
    try:
        fn, args = spec.build()
    except ImportError:
        return [], None  # optional dependency absent — op not in build
    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:  # noqa: BLE001 — any trace failure is a finding
        msg = f"{type(e).__name__}: {e}"
        if all(t in str(e) for t in _INDEX_MAP_CAPTURE):
            return [Finding(
                "APX304", spec.path, 0, 0,
                f"{spec.name}: a BlockSpec index map captured a traced "
                f"value — index maps must resolve from grid indices + "
                f"scalar-prefetch operands only ({msg})",
                line_text=f"{spec.name}:APX304")], None
        return [Finding(
            "APX300", spec.path, 0, 0,
            f"{spec.name}: tracing the kernel fixture failed: {msg}",
            line_text=f"{spec.name}:APX300")], None

    findings: list = []
    meta = _op_meta(spec)
    kernels: dict = {}
    for rec in extract_kernels(closed):
        findings.extend(check_kernel_record(
            rec, meta, chip_spec, spec.name, spec.path))
        key, n = rec.kernel, 2
        while key in kernels:
            key, n = f"{rec.kernel}#{n}", n + 1
        kernels[key] = rec.entry()

    entry = {
        "kernels": kernels,
        "max_kernel_vmem_bytes": max(
            (k["vmem_bytes"] for k in kernels.values()), default=0),
    }
    return findings, entry


def run_kernel_audit(ops: Optional[Sequence[str]] = None,
                     chip: Optional[str] = None) -> tuple:
    """Audit every (or the named) registered Pallas kernel op.

    Returns ``(findings, report)`` where ``report`` is the ledger shape
    committed as ``.analysis_kernel_budget.json``: ``{"version": 1,
    "chip", "vmem_capacity_bytes", "ops": {name: {kernels: {kernel:
    {grid, vmem_bytes, resident_bytes, scratch_bytes, prefetch_bytes,
    blocks}}, max_kernel_vmem_bytes}}}``.
    """
    chip_spec = _chip(chip)
    specs = kernel_specs()
    if ops:
        wanted = set(ops)
        missing = wanted - {s.name for s in specs}
        if missing:
            raise ValueError(f"unknown kernel op(s): {sorted(missing)}")
        specs = [s for s in specs if s.name in wanted]

    findings: list = []
    entries: dict = {}
    for spec in specs:
        f, entry = audit_kernel_op(spec, chip=chip)
        findings.extend(f)
        if entry is not None:
            entries[spec.name] = entry
    report = {
        "version": 1,
        "chip": chip_spec.key,
        "vmem_capacity_bytes": chip_spec.vmem_bytes,
        "ops": entries,
    }
    return findings, report


def compare_kernel_budget(report: dict, committed: Optional[dict]) -> list:
    """Ratchet: APX301 for every kernel whose modeled VMEM footprint
    GREW vs the committed budget, APX305 for kernels/ops the budget has
    never seen.  Shrinkage is silent — re-pin with ``--kernels
    --write-budget``.  (The stale direction — a budgeted kernel that no
    longer exists — is the tier-1 exact-set guard's job, mirroring the
    SPMD ledger.)"""
    findings: list = []
    paths = {s.name: s.path for s in kernel_specs()}

    def emit(rule, op_name, key, msg):
        findings.append(Finding(
            rule, paths.get(op_name, "<pallas_audit>"), 0, 0, msg,
            line_text=f"{op_name}:{key}:{rule}"))

    base = (committed or {}).get("ops", {})
    for op_name, entry in report.get("ops", {}).items():
        pinned = base.get(op_name)
        if pinned is None:
            emit("APX305", op_name, "<op>",
                 f"{op_name}: registered Pallas op has no committed "
                 f"kernel-budget entry — run apex-tpu-analyze --kernels "
                 f"--write-budget to pin its VMEM ledger")
            continue
        pk = pinned.get("kernels", {})
        for key, k in entry.get("kernels", {}).items():
            kp = pk.get(key)
            if kp is None:
                emit("APX305", op_name, key,
                     f"{op_name}: kernel {key} is reachable from the "
                     f"registered op but has no kernel-budget entry — "
                     f"pin it with --kernels --write-budget")
                continue
            if k["vmem_bytes"] > kp.get("vmem_bytes", 0):
                emit("APX301", op_name, key,
                     f"{op_name}: kernel {key} VMEM footprint grew "
                     f"{kp.get('vmem_bytes', 0)} -> {k['vmem_bytes']} "
                     f"B/grid-step — justify and re-pin with --kernels "
                     f"--write-budget, or shrink the block/scratch "
                     f"footprint")
    return findings


# ---------------------------------------------------------------------------
# the fused-decode envelope model (--mesh tp=N / crossover prediction)
# ---------------------------------------------------------------------------

def _fused_block_fixture(hidden: int, head_dim: int = 64,
                         kv_heads: Optional[int] = None,
                         page_size: int = 64, max_pages: int = 8,
                         slots: int = 8, pages: Optional[int] = None,
                         tp: int = 1, partial_out: bool = False):
    """Abstract GPT fused-block fixture at the given geometry, with the
    head and ffn dims sharded 1/tp (the TP layout: wq/wk/wv shard
    out-features, wo in-features, wu/wd the ffn dim — each chip holds
    its heads' slice, exactly ROADMAP item 1's shard)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.paged_attention import fused_block_decode as op

    bf16 = jnp.bfloat16

    def s(shape, dtype=bf16):
        return jax.ShapeDtypeStruct(shape, dtype)

    if hidden % head_dim:
        raise ValueError(f"hidden {hidden} must be a multiple of "
                         f"head_dim {head_dim}")
    heads = hidden // head_dim
    kvh = kv_heads or heads
    ffn = 4 * hidden
    if heads % tp or kvh % tp or ffn % tp:
        raise ValueError(
            f"tp={tp} must divide heads ({heads}), kv_heads ({kvh}) "
            f"and ffn ({ffn})")
    hd = (heads // tp) * head_dim
    kvd = (kvh // tp) * head_dim
    ffn //= tp
    npages = pages if pages is not None else slots * max_pages + 1
    blk = {
        "ln1_w": s((1, hidden)), "ln1_b": s((1, hidden)),
        "wq": s((hidden, hd)), "bq": s((1, hd)),
        "wk": s((hidden, kvd)), "bk": s((1, kvd)),
        "wv": s((hidden, kvd)), "bv": s((1, kvd)),
        "wo": s((hd, hidden)), "bo": s((1, hidden)),
        "ln2_w": s((1, hidden)), "ln2_b": s((1, hidden)),
        "wu": s((hidden, ffn)), "bu": s((1, ffn)),
        "wd": s((ffn, hidden)), "bd": s((1, hidden)),
    }
    pg = s((npages, kvh // tp, page_size, head_dim))
    args = (s((slots, hidden)), blk, pg, pg,
            s((slots, max_pages), jnp.int32),
            s((slots,), jnp.int32))
    if partial_out:
        # the SERVED tp shard (ISSUE 17): MLP out of the kernel, the
        # rank-partial out-proj product emitted for the external psum
        return (lambda x, b, kp, vp, pt, ln: op(
            x, b, kp, vp, pt, ln, kind="gpt", eps=1e-5,
            fuse_mlp=False, partial_out=True), args)
    return (lambda x, b, kp, vp, pt, ln: op(x, b, kp, vp, pt, ln,
                                            kind="gpt", eps=1e-5),
            args)


def fused_block_envelope(hidden: int, *, tp: int = 1,
                         chip: Optional[str] = None,
                         head_dim: int = 64,
                         kv_heads: Optional[int] = None,
                         page_size: int = 64, max_pages: int = 8,
                         slots: int = 8,
                         pages: Optional[int] = None) -> dict:
    """Price the fused decode block's VMEM envelope at a geometry.

    Traces the real ``fused_block_decode`` abstractly with the weight
    dims sharded 1/tp and runs the extractor over the resulting
    ``pallas_call`` — the model and the kernel cannot drift apart.
    Returns the envelope dict (``vmem_bytes``, ``resident_bytes``,
    ``scratch_bytes``, ``capacity_bytes``, ``fits``)."""
    import jax

    chip_spec = _chip(chip)
    fn, args = _fused_block_fixture(
        hidden, head_dim=head_dim, kv_heads=kv_heads,
        page_size=page_size, max_pages=max_pages, slots=slots,
        pages=pages, tp=tp)
    records = extract_kernels(jax.make_jaxpr(fn)(*args))
    if len(records) != 1:
        raise RuntimeError(
            f"expected exactly one pallas_call in fused_block_decode, "
            f"found {len(records)}")
    rec = records[0]
    return {
        "hidden": hidden,
        "tp": tp,
        "chip": chip_spec.key,
        "vmem_bytes": rec.vmem_bytes,
        "resident_bytes": rec.resident_bytes,
        "scratch_bytes": rec.scratch_bytes,
        "capacity_bytes": chip_spec.vmem_bytes,
        "fits": rec.vmem_bytes <= chip_spec.vmem_bytes,
    }


def predict_fusion_max_hidden(*, tp: int = 1, chip: Optional[str] = None,
                              sweep: Optional[Sequence[int]] = None) -> dict:
    """Sweep hidden sizes through the envelope model: the largest
    hidden whose fused block fits the chip's VMEM, and the first that
    doesn't (the crossover the tier-1 test asserts brackets the
    observed ~2048 cap; see PERF.md round-16 for the tolerance)."""
    sizes = tuple(sweep or FUSION_SWEEP)
    priced: dict = {}
    max_hidden = None
    crossover = None
    for hidden in sizes:
        env = fused_block_envelope(hidden, tp=tp, chip=chip)
        priced[hidden] = env["vmem_bytes"]
        if env["fits"]:
            if max_hidden is None or hidden > max_hidden:
                max_hidden = hidden
        elif crossover is None or hidden < crossover:
            crossover = hidden
    return {
        "tp": tp,
        "chip": _chip(chip).key,
        "sweep": priced,
        "max_hidden": max_hidden,
        "crossover_hidden": crossover,
    }
