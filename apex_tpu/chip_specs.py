"""The single source of truth for TPU chip peak specs.

Every capacity number the repo prices against hardware — bench MFU and
HBM rooflines, ``comm_model.step_time_estimate``'s compute roofline,
the ``train_mfu`` telemetry gauge, and the capture-hygiene scrub bound
on compiled peak-HBM stamps — resolves through this table.  Before
ISSUE 10 the numbers lived twice (``bench.py::_CHIP_SPECS`` and a bare
``tflops=197.0`` default inside ``comm_model``) and could drift apart
silently; ``tests/L1/test_chip_specs.py`` now pins that no second copy
exists.

Conservative public figures: bf16 matmul peak (TFLOP/s), HBM bandwidth
(GB/s), and HBM capacity (bytes) per chip generation.  Pure data — this
module must import without jax so the trace-only analysis engines can
use it.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

__all__ = ["ChipSpec", "CHIP_SPECS", "DEFAULT_CHIP", "match_spec",
           "find_spec", "default_spec", "local_spec"]

_GiB = 1024 ** 3


_MiB = 1024 ** 2


@dataclass(frozen=True)
class ChipSpec:
    key: str                 # substring matched against device_kind
    bf16_tflops: float       # peak bf16 matmul TFLOP/s per chip
    hbm_gbps: float          # peak HBM bandwidth GB/s per chip
    hbm_bytes: int           # HBM capacity per chip
    vmem_bytes: int          # VMEM capacity per core (the pallas_audit
    #                          envelope bound; the ceiling production
    #                          kernels compile against via
    #                          vmem_limit_bytes, NOT the compiler's
    #                          conservative per-buffer scoping default)


CHIP_SPECS: Dict[str, ChipSpec] = {s.key: s for s in [
    ChipSpec("v4", 275.0, 1228.0, 32 * _GiB, 128 * _MiB),
    ChipSpec("v5e", 197.0, 819.0, 16 * _GiB, 128 * _MiB),
    # "v5lite"/"v6lite" are alternate device_kind SPELLINGS of v5e/v6e
    # ("TPU v5 lite" is what real v5e hosts report — PERF.md round-3),
    # not smaller parts: every figure must match the e-series twin or
    # capacity-bound scrubs resolve differently by spelling.
    ChipSpec("v5lite", 197.0, 819.0, 16 * _GiB, 128 * _MiB),
    ChipSpec("v5p", 459.0, 2765.0, 95 * _GiB, 128 * _MiB),
    ChipSpec("v6e", 918.0, 1640.0, 32 * _GiB, 128 * _MiB),
    ChipSpec("v6lite", 918.0, 1640.0, 32 * _GiB, 128 * _MiB),
]}

#: the generation assumed when the device kind matches nothing (CPU
#: dryruns, unknown tunnels) — the same v5e default the bench always had.
DEFAULT_CHIP = "v5e"


def default_spec() -> ChipSpec:
    return CHIP_SPECS[DEFAULT_CHIP]


def match_spec(device_kind: Optional[str]) -> Optional[ChipSpec]:
    """The spec whose key substring-matches ``device_kind`` (the
    ``jax.Device.device_kind`` string, any case/spacing), or ``None``
    on a miss — the one matching loop; callers choose their own miss
    policy (:func:`find_spec` defaults, bench's scrub bound takes the
    largest capacity)."""
    kind = (device_kind or "").lower().replace(" ", "")
    for key, spec in CHIP_SPECS.items():
        if key in kind:
            return spec
    return None


def find_spec(device_kind: Optional[str]) -> ChipSpec:
    """Like :func:`match_spec`, but a miss resolves to the
    :data:`DEFAULT_CHIP` spec."""
    return match_spec(device_kind) or default_spec()


def local_spec() -> ChipSpec:
    """The spec of the first live jax device (initializes the backend;
    host loops only — trace-only code passes a device_kind to
    :func:`find_spec` or takes the default)."""
    import jax

    return find_spec(jax.devices()[0].device_kind)
