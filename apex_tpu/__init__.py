"""apex_tpu — a TPU-native framework with the capabilities of NVIDIA Apex.

Reference: shawnwang18/apex (fork of NVIDIA/apex).  Layer map (see SURVEY.md):

* ``apex_tpu.ops``            — L0': Pallas TPU kernels + pure-jnp oracle twins
  (replaces ``csrc/`` CUDA: fused LayerNorm/RMSNorm, multi-tensor optimizer
  functors, scaled-masked softmax, RoPE, fused attention, xentropy).
* ``apex_tpu.multi_tensor_apply`` — ``MultiTensorApply`` parity shim.
* ``apex_tpu.optimizers``     — FusedAdam / FusedLAMB / FusedSGD / FusedNovoGrad
  / FusedAdagrad over the fused-update kernel (reference: ``apex/optimizers``),
  plus ``optimizers.functional`` — the flat-native pure init/update core.
* ``apex_tpu.train_step``     — flat-native train-step builder: forward,
  backward, loss scaling, and the fused update as ONE donated XLA program.
* ``apex_tpu.normalization``  — FusedLayerNorm / FusedRMSNorm modules
  (reference: ``apex/normalization/fused_layer_norm.py``).
* ``apex_tpu.amp``            — opt-level O0–O3 mixed precision with functional
  dynamic loss scaling (reference: ``apex/amp``).
* ``apex_tpu.fp16_utils``     — legacy manual mixed-precision helpers.
* ``apex_tpu.parallel``       — DistributedDataParallel (bucketed psum),
  SyncBatchNorm (psum Welford), LARC (reference: ``apex/parallel``).
* ``apex_tpu.transformer``    — Megatron-style TP/PP/SP toolkit on
  jax.sharding meshes (reference: ``apex/transformer``).
* ``apex_tpu.contrib``        — DistributedFusedAdam (ZeRO), clip_grad,
  xentropy, fmha/flash attention, groupnorm, focal loss, ...
* ``apex_tpu.models``         — flagship model zoo (GPT, BERT) built on the
  transformer toolkit (reference: ``apex/transformer/testing/standalone_*``).

Subpackages are imported lazily to keep ``import apex_tpu`` cheap.
"""

import importlib

# Eager on purpose, although it pulls in jax: submodules reference the
# modern jax surface at import time (e.g. ops/fused_update builds
# pltpu.CompilerParams at module level), so the grafts must be installed
# before ANY submodule import path runs — lazy installation per-subpackage
# would have to cover every entry point and fail silently when one is
# missed on an old jax.
from apex_tpu import _jax_compat  # noqa: F401  (side effect: old-jax aliases)

__version__ = "0.1.0"

_SUBMODULES = (
    "ops",
    "multi_tensor_apply",
    "optimizers",
    "normalization",
    "amp",
    "fp16_utils",
    "parallel",
    "transformer",
    "contrib",
    "models",
    "train_step",
    "utils",
)


def __getattr__(name):
    if name in _SUBMODULES:
        try:
            mod = importlib.import_module(f"{__name__}.{name}")
        except ModuleNotFoundError as e:
            raise AttributeError(
                f"module {__name__!r} has no attribute {name!r}") from e
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_SUBMODULES))
