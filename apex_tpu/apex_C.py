"""apex_C — tensor-level flat-buffer pack/unpack (reference:
``csrc/flatten_unflatten.cpp``: ``apex_C.flatten(tensors) -> flat``,
``apex_C.unflatten(flat, tensors) -> list`` wrapping torch's
``_flatten_dense_tensors``/``_unflatten_dense_tensors`` for DDP buckets).

Dispatch order:
1. torch tensors -> the compiled ``apex_tpu._apex_C`` C extension
   (byte-level memcpy pack over the buffer protocol; built with
   ``APEX_TPU_CPP_EXT=1``), falling back to ``torch._utils``;
2. jax arrays -> ``jax.flatten_util.ravel_pytree`` (device-side concat —
   packing happens on-chip, there is no host memcpy to replace).
"""
from __future__ import annotations

from typing import List, Sequence

__all__ = ["flatten", "unflatten", "HAVE_CPP_EXT"]

try:
    from apex_tpu import _apex_C
    HAVE_CPP_EXT = True
except ImportError:  # pragma: no cover - built only with APEX_TPU_CPP_EXT=1
    _apex_C = None
    HAVE_CPP_EXT = False


def _is_torch(x) -> bool:
    m = type(x).__module__
    return m == "torch" or m.startswith("torch.")


def flatten(tensors: Sequence):
    """Concatenate same-dtype tensors into one flat 1-D tensor."""
    first = tensors[0]
    if _is_torch(first):
        import torch
        # the C ext path needs the buffer protocol; torch bf16 (the amp
        # half dtype here) has no numpy view, so it falls through
        numpy_ok = first.dtype not in (torch.bfloat16,)
        if HAVE_CPP_EXT and first.device.type == "cpu" and numpy_ok:
            total = sum(t.numel() for t in tensors)
            out = torch.empty(total, dtype=first.dtype)
            _apex_C.flatten_into(
                [t.detach().contiguous().view(-1).numpy() for t in tensors],
                out.numpy())
            return out
        from torch._utils import _flatten_dense_tensors
        return _flatten_dense_tensors(tuple(tensors))
    import jax.numpy as jnp
    return jnp.concatenate([jnp.ravel(t) for t in tensors])


def unflatten(flat, tensors: Sequence) -> List:
    """Split ``flat`` back into views/arrays shaped like ``tensors``."""
    if _is_torch(flat):
        from torch._utils import _unflatten_dense_tensors
        return list(_unflatten_dense_tensors(flat, tuple(tensors)))
    outs = []
    off = 0
    for t in tensors:
        n = t.size
        outs.append(flat[off:off + n].reshape(t.shape))
        off += n
    return outs
