"""RNN-T transducer joint + loss (reference: ``apex/contrib/transducer``
over ``transducer_joint_cuda``/``transducer_loss_cuda``).

* ``TransducerJoint``: f[B,T,H] + g[B,U,H] broadcast-add (the CUDA ext's
  fused add+optional relu/dropout+packing); one XLA fusion here.
* ``TransducerLoss``: the RNN-T forward-backward loss.  The CUDA ext
  hand-writes alpha/beta kernels and the analytic gradient; here the alpha
  recursion is a ``lax.scan`` over time (log-space) and autodiff of the
  scan IS the beta pass (reverse-mode replays the recursion backward), so
  the gradient is exact without hand-written kernels.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["TransducerJoint", "TransducerLoss", "transducer_joint",
           "transducer_loss"]


def transducer_joint(f, g, f_len=None, g_len=None, *, relu=False,
                     dropout_rate: float = 0.0, key=None):
    """h[b,t,u,:] = f[b,t,:] + g[b,u,:] (+relu, +dropout)."""
    h = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        h = jax.nn.relu(h)
    if dropout_rate > 0.0:
        if key is None:
            raise ValueError(
                "transducer_joint: dropout_rate > 0 requires an explicit "
                "PRNG key (JAX has no global RNG; silently skipping "
                "dropout would lose regularization)")
        keep = jax.random.bernoulli(key, 1.0 - dropout_rate, h.shape)
        h = jnp.where(keep, h / (1.0 - dropout_rate), 0.0)
    return h


def transducer_loss(log_probs, labels, f_len, y_len, blank_idx: int = 0):
    """RNN-T negative log-likelihood.

    ``log_probs``: [B, T, U+1, V] log-softmax over vocab; ``labels``:
    [B, U] int targets; ``f_len``: [B] valid frames; ``y_len``: [B] valid
    label lengths.  Returns per-sample loss [B].
    """
    b, t_max, u_max1, v = log_probs.shape
    u_max = u_max1 - 1
    # blank/emit transition log-probs
    blank_lp = log_probs[..., blank_idx]                     # [B,T,U+1]
    lbl = jnp.broadcast_to(jnp.clip(labels, 0, v - 1)[:, None, :],
                           (b, t_max, u_max))
    emit_lp = jnp.take_along_axis(
        log_probs[:, :, :u_max, :], lbl[..., None], axis=-1)[..., 0]
    # alpha recursion over t (log-space); u handled vectorized with a
    # cumulative "emit along u" inner scan expressed as associative ops

    def t_step(alpha_prev, inputs):
        blank_t, emit_t = inputs                 # [B,U+1], [B,U]
        # vertical: blank from t-1
        from_blank = alpha_prev + blank_t        # arrive at (t, u)
        # chain emissions within this t? RNN-T allows multiple emits per
        # frame boundary: alpha[t,u] = logaddexp(alpha[t-1,u]+blank,
        #                                        alpha[t,u-1]+emit)
        def chain(carry, x):
            fb, em = x
            val = jnp.logaddexp(fb, carry + em)
            return val, val
        first = from_blank[:, 0]                 # u=0: only blank path
        _, rest = jax.lax.scan(
            chain,
            first,
            (from_blank[:, 1:].T, emit_t.T))
        alpha = jnp.concatenate([first[:, None], rest.T], axis=1)
        return alpha, alpha

    # alpha[0]: t=0 row — emits only
    def chain0(carry, em):
        val = carry + em
        return val, val
    a00 = jnp.zeros((b,), jnp.float32)
    _, row0 = jax.lax.scan(chain0, a00, emit_lp[:, 0, :].T)
    alpha0 = jnp.concatenate([a00[:, None], row0.T], axis=1)  # [B,U+1]

    _, alphas = jax.lax.scan(
        t_step, alpha0,
        (blank_lp[:, :-1].transpose(1, 0, 2),
         emit_lp[:, 1:].transpose(1, 0, 2)))
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T,B,U+1]

    # final: alpha[f_len-1, y_len] + blank(f_len-1, y_len)
    t_idx = jnp.clip(f_len - 1, 0, t_max - 1)
    u_idx = jnp.clip(y_len, 0, u_max)
    a_fin = alphas[t_idx, jnp.arange(b), u_idx]
    lp_blank_fin = blank_lp[jnp.arange(b), t_idx, u_idx]
    return -(a_fin + lp_blank_fin)


class TransducerJoint:
    """Parity shim (reference: ``TransducerJoint(pack_output=...,
    relu=..., dropout=...)`` module with ``forward(f, g, f_len, g_len)``)."""

    def __init__(self, pack_output: bool = False, relu: bool = False,
                 dropout: bool = False, dropout_prob: float = 0.0,
                 **_parity):
        if pack_output:
            raise NotImplementedError(
                "packed output layout is a CUDA memory-format "
                "optimization; dense [B,T,U,H] is the TPU-native layout")
        self.relu = relu
        self.dropout_prob = dropout_prob if dropout else 0.0

    def __call__(self, f, g, f_len=None, g_len=None, key=None):
        return transducer_joint(f, g, f_len, g_len, relu=self.relu,
                                dropout_rate=self.dropout_prob, key=key)


class TransducerLoss:
    """Parity shim (reference: ``TransducerLoss()(x, label, f_len, y_len,
    blank_idx)``); expects log-probs input like the reference's
    ``packed_input=False`` path."""

    def __init__(self, fuse_softmax_backward: bool = True, **_parity):
        self.fuse_softmax_backward = fuse_softmax_backward

    def __call__(self, x, label, f_len, y_len, blank_idx: int = 0):
        return transducer_loss(x, label, f_len, y_len, blank_idx)
