"""ASP — automatic structured (2:4) sparsity.

Reference: ``apex/contrib/sparsity/asp.py :: ASP`` + ``sparse_masklib`` —
computes 2:4 magnitude masks for weights (and optimizer state), patches the
optimizer so masks are re-applied after every step, with CUDA permutation-
search kernels for better mask quality.

Functional TPU rebuild: masks are a pytree of 0/1 arrays; the core mask
rule (``m4n2_1d``: per group of 4 along the input dim keep the 2 largest
|w|) is a vectorized jnp expression.  Permutation search (reference:
``apex/contrib/sparsity/permutation_search_kernels`` — reorder input
channels so 2:4 pruning keeps more magnitude, per NVIDIA's "Channel
Permutations for N:M Sparsity") is :func:`search_for_good_permutation`:
a jit-compiled stochastic hill-climb that proposes disjoint column-pair
swaps each round and accepts every swap that increases kept magnitude —
the whole sweep evaluated as one batched top-2-of-4 reduction instead of
the reference's CUDA per-candidate kernels.  Applying the permutation to
the surrounding network (permute this layer's inputs = permute the
previous layer's outputs) is the caller's model-level rewiring, as in the
reference's ``Permutation`` module.

``ASP`` keeps the reference's classmethod surface where it maps: compute
masks, apply masks, and a functional "masked step" hook in place of
optimizer monkey-patching.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp

__all__ = ["mask_2to4_1d", "compute_sparse_masks", "apply_masks", "ASP",
           "sparsity_efficacy", "search_for_good_permutation",
           "accelerated_search_for_good_permutation"]


def mask_2to4_1d(w):
    """2:4 mask along the LAST dim (reference: ``mn_1d_best`` with m=4,
    n=2): in every contiguous group of 4, keep the 2 largest magnitudes.

    Last dim must be divisible by 4 (the reference rejects such layers
    too; caller filters).
    """
    *lead, n = w.shape
    g = w.reshape(*lead, n // 4, 4)
    mag = jnp.abs(g)
    # rank within each group of 4; keep top-2
    order = jnp.argsort(mag, axis=-1)          # ascending
    rank = jnp.argsort(order, axis=-1)
    mask = (rank >= 2).astype(w.dtype)
    return mask.reshape(*lead, n)


def sparsity_efficacy(w) -> jax.Array:
    """Magnitude kept by 2:4 pruning, as a fraction of total magnitude
    (reference: ``permutation_search_kernels``' "efficacy" objective)."""
    kept = jnp.sum(jnp.abs(w) * mask_2to4_1d(w).astype(jnp.float32))
    return kept / jnp.maximum(jnp.sum(jnp.abs(w)), 1e-30)


def _kept_mass_grouped(mag):
    """Sum of the top-2 magnitudes per group of 4 along the last dim;
    ``mag`` is [..., n//4, 4]."""
    top2 = jax.lax.top_k(mag, 2)[0]
    return jnp.sum(top2, axis=(-1, -2))


@functools.partial(jax.jit, static_argnames=("iters",))
def search_for_good_permutation(w, *, iters: int = 100, key=None):
    """Find a column permutation improving 2:4 efficacy (reference:
    ``permutation_search_kernels.accelerated_search_for_good_permutation``).

    Strategy (TPU-vectorized hill-climb): each round draws ONE random
    disjoint pairing of all columns and evaluates every pair's swap —
    columns a and b trade groups — with a single batched top-2-of-4
    reduction over all rows; every swap whose isolated delta is positive
    is applied.  Because several accepted swaps can touch the same group,
    per-round improvement is heuristic, so the carry tracks the
    best-efficacy permutation seen and THAT is returned — the result is
    monotonically >= identity by construction.  The reference's CUDA
    kernels brute-force candidate swaps per thread-block; one round here
    is the same bounded-window greedy move, batched.

    Returns ``perm`` (int32 [n]) such that ``w[..., perm]`` is the
    permuted matrix; deterministic for a given ``key``.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    n = w.shape[-1]
    assert n % 4 == 0, "column count must be divisible by 4"
    w2d = jnp.abs(w.reshape(-1, n).astype(jnp.float32))

    def _kept(perm):
        mag = w2d[:, perm]
        return jnp.sum(_kept_mass_grouped(mag.reshape(-1, n // 4, 4)))

    def round_(carry, k):
        perm, best_perm, best_kept = carry
        mag = w2d[:, perm]                       # [r, n]
        # random disjoint pairing: pos i pairs with its partner
        shuf = jax.random.permutation(k, n)      # pairing in shuffled space
        partner_shuf = shuf.reshape(n // 2, 2)[:, ::-1].reshape(n)
        partner = jnp.zeros((n,), jnp.int32).at[shuf].set(partner_shuf)

        grp = jnp.arange(n) // 4
        # candidate: swap column position i with position partner[i]
        # new kept mass of i's group when i's column is replaced by
        # partner's column (gather the partner column into i's slot)
        swapped_cols = mag[:, partner]           # column at pos i <- partner
        g = mag.reshape(-1, n // 4, 4)
        # for each position i, rebuild i's group with slot i swapped
        slot = jnp.arange(n) % 4
        onehot = jax.nn.one_hot(slot, 4, dtype=mag.dtype)  # [n, 4]
        # groups_for_pos: [r, n, 4] = the group containing each position
        groups_for_pos = g[:, grp, :]
        new_groups = (groups_for_pos * (1 - onehot)[None]
                      + swapped_cols[:, :, None] * onehot[None])
        # top-2 kept mass of each position's group (last axis only)
        old_kept = jnp.sum(jax.lax.top_k(groups_for_pos, 2)[0], -1)  # [r,n]
        new_kept = jnp.sum(jax.lax.top_k(new_groups, 2)[0], -1)      # [r,n]
        # delta for the swap PAIR (i, partner): both groups change; sum
        # both sides (each position sees its own group's delta)
        delta_pos = jnp.sum(new_kept - old_kept, axis=0)   # [n]
        pair_delta = delta_pos + delta_pos[partner]
        # a swap within the same group is a no-op for the mask: reject
        same_group = grp == grp[partner]
        # scale-invariant acceptance: require a gain of at least 1e-6 of
        # an average column's mass (an absolute epsilon would freeze the
        # search to identity on small-magnitude matrices)
        tol = 1e-6 * jnp.sum(w2d) / n
        accept = (pair_delta > tol) & ~same_group
        # both endpoints must agree (they do, pair_delta is symmetric)
        new_perm = jnp.where(accept, perm[partner], perm)
        kept = _kept(new_perm)
        better = kept > best_kept
        best_perm = jnp.where(better, new_perm, best_perm)
        best_kept = jnp.where(better, kept, best_kept)
        return (new_perm, best_perm, best_kept), None

    perm0 = jnp.arange(n, dtype=jnp.int32)
    (_, best_perm, _), _ = jax.lax.scan(
        round_, (perm0, perm0, _kept(perm0)), jax.random.split(key, iters))
    return best_perm


def accelerated_search_for_good_permutation(w, *, iters: int = 100,
                                            key=None):
    """Name-parity alias (reference:
    ``permutation_search_kernels.accelerated_search_for_good_permutation``
    returns the permuted matrix's permutation)."""
    return search_for_good_permutation(w, iters=iters, key=key)


def _maskable(path: tuple, leaf) -> bool:
    """Weights with >= 2 dims and last dim % 4 == 0 (reference:
    ``eligible_modules`` — Linear/Conv weights, not biases/norms)."""
    name = "/".join(str(p) for p in path).lower()
    if "bias" in name or "norm" in name or "embed" in name:
        return False
    return leaf.ndim >= 2 and leaf.shape[-1] % 4 == 0


def compute_sparse_masks(params, allowed_fn: Optional[Callable] = None):
    """Mask pytree: 2:4 masks for eligible leaves, ones elsewhere
    (reference: ``ASP.compute_sparse_masks``)."""
    allowed = allowed_fn or _maskable

    def per_leaf(path, leaf):
        if allowed(path, leaf):
            return mask_2to4_1d(leaf)
        return jnp.ones_like(leaf)

    return jax.tree_util.tree_map_with_path(per_leaf, params)


def apply_masks(params, masks):
    """Prune: elementwise multiply (reference: in-place ``mul_(mask)``)."""
    return jax.tree.map(lambda p, m: p * m, params, masks)


class ASP:
    """Classmethod surface parity with ``apex.contrib.sparsity.ASP``.

    Functional usage::

        masks = ASP.compute_sparse_masks(params)
        params = ASP.prune_trained_model(params, masks)
        # in the train loop, after every optimizer step:
        params = ASP.apply_masks(params, masks)
    """

    _masks = None

    @classmethod
    def compute_sparse_masks(cls, params, allowed_fn=None):
        cls._masks = compute_sparse_masks(params, allowed_fn)
        return cls._masks

    @classmethod
    def apply_masks(cls, params, masks=None):
        return apply_masks(params, masks if masks is not None else cls._masks)

    @classmethod
    def prune_trained_model(cls, params, masks=None):
        """Reference: ``ASP.prune_trained_model(model, optimizer)`` —
        compute + apply in one call for post-training pruning."""
        if masks is None:
            masks = cls.compute_sparse_masks(params)
        return apply_masks(params, masks)

    @classmethod
    def is_sparsity_enabled(cls) -> bool:
        return cls._masks is not None
