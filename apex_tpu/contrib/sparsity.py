"""ASP — automatic structured (2:4) sparsity.

Reference: ``apex/contrib/sparsity/asp.py :: ASP`` + ``sparse_masklib`` —
computes 2:4 magnitude masks for weights (and optimizer state), patches the
optimizer so masks are re-applied after every step, with CUDA permutation-
search kernels for better mask quality.

Functional TPU rebuild: masks are a pytree of 0/1 arrays; the core mask
rule (``m4n2_1d``: per group of 4 along the input dim keep the 2 largest
|w|) is a vectorized jnp expression.  Permutation search is channel
reordering ahead of masking — an offline quality refinement, deliberately
out of scope (documented, like the reference's non-default strategies).

``ASP`` keeps the reference's classmethod surface where it maps: compute
masks, apply masks, and a functional "masked step" hook in place of
optimizer monkey-patching.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

__all__ = ["mask_2to4_1d", "compute_sparse_masks", "apply_masks", "ASP"]


def mask_2to4_1d(w):
    """2:4 mask along the LAST dim (reference: ``mn_1d_best`` with m=4,
    n=2): in every contiguous group of 4, keep the 2 largest magnitudes.

    Last dim must be divisible by 4 (the reference rejects such layers
    too; caller filters).
    """
    *lead, n = w.shape
    g = w.reshape(*lead, n // 4, 4)
    mag = jnp.abs(g)
    # rank within each group of 4; keep top-2
    order = jnp.argsort(mag, axis=-1)          # ascending
    rank = jnp.argsort(order, axis=-1)
    mask = (rank >= 2).astype(w.dtype)
    return mask.reshape(*lead, n)


def _maskable(path: tuple, leaf) -> bool:
    """Weights with >= 2 dims and last dim % 4 == 0 (reference:
    ``eligible_modules`` — Linear/Conv weights, not biases/norms)."""
    name = "/".join(str(p) for p in path).lower()
    if "bias" in name or "norm" in name or "embed" in name:
        return False
    return leaf.ndim >= 2 and leaf.shape[-1] % 4 == 0


def compute_sparse_masks(params, allowed_fn: Optional[Callable] = None):
    """Mask pytree: 2:4 masks for eligible leaves, ones elsewhere
    (reference: ``ASP.compute_sparse_masks``)."""
    allowed = allowed_fn or _maskable

    def per_leaf(path, leaf):
        if allowed(path, leaf):
            return mask_2to4_1d(leaf)
        return jnp.ones_like(leaf)

    return jax.tree_util.tree_map_with_path(per_leaf, params)


def apply_masks(params, masks):
    """Prune: elementwise multiply (reference: in-place ``mul_(mask)``)."""
    return jax.tree.map(lambda p, m: p * m, params, masks)


class ASP:
    """Classmethod surface parity with ``apex.contrib.sparsity.ASP``.

    Functional usage::

        masks = ASP.compute_sparse_masks(params)
        params = ASP.prune_trained_model(params, masks)
        # in the train loop, after every optimizer step:
        params = ASP.apply_masks(params, masks)
    """

    _masks = None

    @classmethod
    def compute_sparse_masks(cls, params, allowed_fn=None):
        cls._masks = compute_sparse_masks(params, allowed_fn)
        return cls._masks

    @classmethod
    def apply_masks(cls, params, masks=None):
        return apply_masks(params, masks if masks is not None else cls._masks)

    @classmethod
    def prune_trained_model(cls, params, masks=None):
        """Reference: ``ASP.prune_trained_model(model, optimizer)`` —
        compute + apply in one call for post-training pruning."""
        if masks is None:
            masks = cls.compute_sparse_masks(params)
        return apply_masks(params, masks)

    @classmethod
    def is_sparsity_enabled(cls) -> bool:
        return cls._masks is not None
