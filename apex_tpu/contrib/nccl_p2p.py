"""Raw p2p send/recv (reference: ``apex/contrib/csrc/nccl_p2p`` — grouped
``ncclSend``/``ncclRecv`` used by halo exchange and pipeline stages).

TPU-native equivalent: ``jax.lax.ppermute`` over a mesh axis (a
collective-permute rides ICI).  These wrappers keep the left/right halo
call shapes."""
from __future__ import annotations

import jax

__all__ = ["left_right_halo_exchange", "ppermute_send"]


def ppermute_send(x, axis_name: str, perm):
    """Direct parity for grouped send/recv: one collective-permute."""
    return jax.lax.ppermute(x, axis_name, perm)


def left_right_halo_exchange(top_halo, btm_halo, axis_name: str):
    """Send my top row up and bottom row down; receive neighbors'
    (reference: ``nccl_p2p_cuda.left_right_halo_exchange``).  Wrap-around
    entries are the callers' concern (the reference zeroes them too)."""
    n = jax.lax.axis_size(axis_name)
    up = [(i, (i - 1) % n) for i in range(n)]
    down = [(i, (i + 1) % n) for i in range(n)]
    from_next = jax.lax.ppermute(top_halo, axis_name, up)
    from_prev = jax.lax.ppermute(btm_halo, axis_name, down)
    return from_prev, from_next
