"""Peer-memory halo exchange (reference: ``apex/contrib/peer_memory ::
PeerMemoryPool`` + ``PeerHaloExchanger1d`` over ``peer_memory_cuda`` —
CUDA IPC VMM pools for direct cross-GPU halo pushes).

On TPU, ICI *is* peer memory: a ``ppermute`` neighbor exchange moves data
chip-to-chip without host involvement, and XLA owns the buffers (there is
nothing to pool).  ``PeerMemoryPool`` is therefore a no-op allocator kept
for API shape; the halo exchange maps to
``apex_tpu.contrib.bottleneck.halo_exchange``.
"""
from __future__ import annotations

from apex_tpu.contrib.bottleneck import halo_exchange
from apex_tpu.transformer.parallel_state import DATA_AXIS

__all__ = ["PeerMemoryPool", "PeerHaloExchanger1d", "halo_exchange"]


class PeerMemoryPool:
    """No-op pool (reference: raw/static VMM allocations per peer group).
    XLA's runtime owns device buffers; allocation knobs are accepted and
    ignored."""

    def __init__(self, static_size: int = 0, dynamic_size: int = 0,
                 peer_ranks=None):
        self.static_size = static_size
        self.dynamic_size = dynamic_size
        self.peer_ranks = peer_ranks

    def allocate_peer_tensors(self, shape, dtype, channels_last,
                              dynamic):  # pragma: no cover - parity stub
        raise NotImplementedError(
            "explicit peer tensors have no TPU analog; use "
            "halo_exchange()/ppermute — buffers are XLA-managed")


class PeerHaloExchanger1d:
    """Parity: ``PeerHaloExchanger1d(ranks, rank_in_group, pool,
    half_halo)``; call performs the neighbor exchange over the mesh axis."""

    def __init__(self, ranks=None, rank_in_group=None, peer_pool=None,
                 half_halo: int = 1, axis_name: str = DATA_AXIS):
        self.half_halo = half_halo
        self.axis_name = axis_name

    def __call__(self, x, H_split: bool = True):
        if not H_split:
            x = x.swapaxes(1, 2)
        out = halo_exchange(x, self.axis_name, halo=self.half_halo)
        if not H_split:
            out = out.swapaxes(1, 2)
        return out
