"""Reference: ``apex/contrib/layer_norm/layer_norm.py :: FastLayerNorm`` —
hand-tuned per-hidden-size LN kernels (768..65536 table) over the
``fast_layer_norm`` ext.

On TPU one autotiled Pallas kernel (``apex_tpu.ops.layer_norm``) covers
every hidden size, so ``FastLayerNorm`` is the same module as
``FusedLayerNorm`` with the contrib class's restricted signature (no
elementwise-affine toggle; hidden size only).
"""
from __future__ import annotations

from apex_tpu.normalization import FusedLayerNorm as _FusedLayerNorm

__all__ = ["FastLayerNorm"]


class FastLayerNorm(_FusedLayerNorm):
    pass
