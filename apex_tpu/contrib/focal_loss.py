"""Focal loss (reference: ``apex/contrib/focal_loss/focal_loss.py`` over
``focal_loss_cuda`` — fused sigmoid focal loss for dense detection heads,
label smoothing included).

One fused XLA expression; autodiff supplies the backward the CUDA ext
hand-writes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["focal_loss", "FocalLoss"]


def focal_loss(cls_output, cls_targets_at_level, num_positives_sum,
               num_real_classes: int, alpha: float = 0.25,
               gamma: float = 2.0, label_smoothing: float = 0.0):
    """Sigmoid focal loss, detection convention (reference signature).

    ``cls_output``: [..., num_anchors, num_classes_padded] logits.
    ``cls_targets_at_level``: [..., num_anchors] int class ids, -1 =
    background, -2 = ignore.
    Returns the scalar loss normalized by ``num_positives_sum``.
    """
    t = cls_targets_at_level
    c = cls_output.shape[-1]
    onehot = jax.nn.one_hot(jnp.clip(t, 0, None), c,
                            dtype=cls_output.dtype)
    onehot = jnp.where((t >= 0)[..., None], onehot, 0.0)
    if label_smoothing:
        onehot = onehot * (1.0 - label_smoothing) + label_smoothing / 2.0
    x = cls_output.astype(jnp.float32)
    y = onehot.astype(jnp.float32)
    p = jax.nn.sigmoid(x)
    # standard numerically-stable BCE-with-logits
    bce = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
    p_t = p * y + (1.0 - p) * (1.0 - y)
    a_t = alpha * y + (1.0 - alpha) * (1.0 - y)
    loss = a_t * jnp.power(1.0 - p_t, gamma) * bce
    # ignore entries (-2) and classes beyond num_real_classes contribute 0
    loss = jnp.where((t != -2)[..., None], loss, 0.0)
    if num_real_classes < c:
        loss = loss.at[..., num_real_classes:].set(0.0)
    return jnp.sum(loss) / num_positives_sum


class FocalLoss:
    """Autograd-Function-shaped shim (reference exposes ``.apply``)."""

    @staticmethod
    def apply(cls_output, cls_targets_at_level, num_positives_sum,
              num_real_classes, alpha, gamma, label_smoothing=0.0):
        return focal_loss(cls_output, cls_targets_at_level,
                          num_positives_sum, num_real_classes, alpha,
                          gamma, label_smoothing)
