"""apex_tpu.contrib — rebuilds of the reference's contrib islands
(``apex/contrib/``), each a thin Python surface over a TPU-native core.

Tier-1 islands (full behavior):

* :mod:`clip_grad` — multi-tensor-kernel ``clip_grad_norm_``
* :mod:`xentropy` — fused softmax cross-entropy (Pallas streaming lse)
* :mod:`multihead_attn` — Self/Encdec fused attention modules (flash kernel)
* :mod:`layer_norm` — ``FastLayerNorm`` (alias of the Pallas LN kernel; the
  reference ships a second per-hidden-size tuned CUDA LN, one kernel covers
  both here)
* :mod:`optimizers` — ``DistributedFusedAdam``/``DistributedFusedLAMB``
  (ZeRO-style reduce-scatter/shard-update/all-gather over the data axis)

Tier-2 islands:

* :mod:`group_norm` — NHWC GroupNorm (+fused silu)
* :mod:`groupbn` — ``BatchNorm2d_NHWC`` (+fused add/relu, mesh group stats)
* :mod:`focal_loss`, :mod:`index_mul_2d` — small fusions (XLA-native)
* :mod:`sparsity` — ASP 2:4 structured sparsity masks
* :mod:`transducer` — RNN-T joint + scan-based forward-backward loss
* :mod:`bottleneck` — ResNet bottleneck + spatial parallelism via
  ppermute halo exchange
"""
