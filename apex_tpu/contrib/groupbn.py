"""NHWC BatchNorm with fused add+ReLU (reference: ``apex/contrib/groupbn``
— ``BatchNorm2d_NHWC(planes, fuse_relu, bn_group)`` over the ``bnp`` ext:
NHWC BN with cross-GPU group stats via CUDA IPC).

TPU-native: NHWC is the default layout; group stats map to
``SyncBatchNorm``'s psum over the data axis (``bn_group`` ≡ syncing across
the mesh instead of an IPC clique); the add+relu epilogue is fused by XLA.
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import DATA_AXIS

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm

__all__ = ["BatchNorm2d_NHWC"]


class BatchNorm2d_NHWC(nn.Module):
    """NHWC BN (+optional residual add and fused ReLU).

    ``bn_group > 1`` syncs stats over ``axis_name`` (the reference's
    multi-GPU BN group); 1 keeps stats local.
    """
    planes: int
    fuse_relu: bool = False
    bn_group: int = 1
    axis_name: Optional[str] = DATA_AXIS
    eps: float = 1e-5
    momentum: float = 0.1
    params_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, z=None, use_running_average: bool = False):
        groups = None
        axis = self.axis_name if self.bn_group > 1 else None
        if axis is not None:
            # reference semantics: stats sync within cliques of bn_group
            # consecutive ranks, not the whole axis
            try:
                n = jax.lax.axis_size(axis)
            except NameError:
                n = None
            if n is not None and self.bn_group < n:
                if n % self.bn_group:
                    raise ValueError(
                        f"bn_group ({self.bn_group}) must divide the "
                        f"'{axis}' axis size ({n})")
                groups = [list(range(i, i + self.bn_group))
                          for i in range(0, n, self.bn_group)]
        bn = SyncBatchNorm(
            num_features=self.planes, eps=self.eps, momentum=self.momentum,
            axis_name=axis, axis_index_groups=groups,
            channel_last=True, name="bn")
        y = bn(x, use_running_average=use_running_average)
        if z is not None:                     # fused residual add (bn_add_relu)
            y = y + z
        if self.fuse_relu:
            y = jax.nn.relu(y)
        return y
