"""clip_grad_norm_ over the fused L2-norm kernel.

Reference: ``apex/contrib/clip_grad/clip_grad.py :: clip_grad_norm_`` —
drop-in for ``torch.nn.utils.clip_grad_norm_`` using
``amp_C.multi_tensor_l2norm`` + ``multi_tensor_scale``.

Functional JAX contract: takes a grad pytree, returns
``(clipped_grads, total_norm)`` instead of mutating ``.grad`` in place.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops.fused_update import fused_l2norm, fused_scale
from apex_tpu.utils import tree_ravel

__all__ = ["clip_grad_norm_"]


def clip_grad_norm_(grads, max_norm: float, norm_type: float = 2.0,
                    error_if_nonfinite: bool = False):
    """Clip the global grad norm (reference semantics incl. inf-norm).

    Returns ``(clipped_grads, total_norm)``; the total norm is computed by
    the fused kernel for ``norm_type == 2`` (one pass, no per-leaf op
    chain), by jnp reductions otherwise (matching the reference, which only
    fuses the L2 case).
    """
    flat, unravel = tree_ravel(grads)
    if norm_type == 2.0:
        total_norm = fused_l2norm(flat)
    elif norm_type == float("inf"):
        total_norm = jnp.max(jnp.abs(flat))
    else:
        total_norm = jnp.sum(jnp.abs(flat) ** norm_type) ** (1.0 / norm_type)
    if error_if_nonfinite:
        # jit-safe contract: poison the output instead of raising (host
        # sync inside jit is impossible); eager callers can check the norm
        total_norm = jnp.where(jnp.isfinite(total_norm), total_norm,
                               jnp.float32(jnp.nan))
    clip_coef = max_norm / (total_norm + 1e-6)
    coef = jnp.minimum(clip_coef, 1.0)
    clipped, _ = fused_scale(flat, coef)
    return unravel(clipped), total_norm
