"""Group BatchNorm via backend API (reference: ``apex/contrib/cudnn_gbn ::
GroupBatchNorm2d`` over ``cudnn_gbn_lib`` — the cuDNN-backend flavor of
``groupbn``'s NHWC group BN).

On TPU both contrib BN islands collapse onto the same mesh-synced BN; this
class keeps the cudnn_gbn constructor (``group_size``/``group_rank`` naming
instead of ``bn_group``)."""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn

from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
from apex_tpu.transformer.parallel_state import DATA_AXIS

__all__ = ["GroupBatchNorm2d"]


class GroupBatchNorm2d(nn.Module):
    """Parity: ``GroupBatchNorm2d(num_features, group_size, ...)``."""
    num_features: int
    group_size: int = 1
    eps: float = 1e-5
    momentum: float = 0.1
    axis_name: Optional[str] = DATA_AXIS
    params_dtype: Any = None

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        import jax.numpy as jnp
        return BatchNorm2d_NHWC(
            planes=self.num_features, fuse_relu=False,
            bn_group=self.group_size, axis_name=self.axis_name,
            eps=self.eps, momentum=self.momentum,
            params_dtype=self.params_dtype or jnp.float32,
            name="bn")(x, use_running_average=use_running_average)
