"""GDS file I/O (reference: ``apex/contrib/gpu_direct_storage`` over
cuFile — direct storage<->GPU DMA for torch tensors).

TPU has no user-visible direct-storage path (transfers stage through host
RAM under XLA's control), so the equivalent capability is overlap: async
host-side file I/O feeding ``jax.device_put``.  ``load_data``/``save_data``
keep the reference's names; the async variants return futures.

Native path: when the ``_gds_C`` extension is built
(``APEX_TPU_CPP_EXT=1``, ``csrc/async_io.c``), reads/writes go through
GIL-releasing pread/pwrite loops so the thread pool overlaps storage I/O
with compute and device transfers — the role cuFile's DMA engine plays in
the reference.  Falls back to plain Python file I/O.
"""
from __future__ import annotations

import concurrent.futures
import os

import jax
import numpy as np

try:
    from apex_tpu import _gds_C
    HAVE_GDS_C = True
except ImportError:
    _gds_C = None
    HAVE_GDS_C = False

__all__ = ["load_data", "save_data", "load_data_async", "save_data_async",
           "HAVE_GDS_C"]

_POOL = concurrent.futures.ThreadPoolExecutor(max_workers=4)


def save_data(t, filename: str, offset: int = 0):
    """Write a device array's bytes to file (reference:
    ``gds.save_data(tensor, filename)``)."""
    arr = np.ascontiguousarray(np.asarray(t))
    if HAVE_GDS_C:
        _gds_C.write_from(filename, memoryview(arr).cast("B"), offset)
        return
    mode = "r+b" if os.path.exists(filename) else "wb"
    with open(filename, mode) as f:
        f.seek(offset)
        f.write(memoryview(arr).cast("B"))


def load_data(t, filename: str, offset: int = 0):
    """Read bytes into a NEW device array shaped/typed like ``t``
    (functional: JAX arrays are immutable; the reference fills in place)."""
    # only the template's shape/dtype are needed — never copy it to host
    shape, dtype = t.shape, np.dtype(t.dtype)
    nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    if HAVE_GDS_C:
        arr = np.empty(shape, dtype)
        nread = _gds_C.read_into(
            filename, memoryview(arr).cast("B"), offset)
        if nread != nbytes:
            raise EOFError(
                f"{filename}: read {nread} of {nbytes} bytes "
                f"at offset {offset}")
        return jax.device_put(arr)
    with open(filename, "rb") as f:
        f.seek(offset)
        buf = f.read(nbytes)
    if len(buf) != nbytes:
        raise EOFError(
            f"{filename}: read {len(buf)} of {nbytes} bytes "
            f"at offset {offset}")
    arr = np.frombuffer(buf, dtype=dtype).reshape(shape)
    return jax.device_put(arr)


def save_data_async(t, filename: str, offset: int = 0):
    return _POOL.submit(save_data, t, filename, offset)


def load_data_async(t, filename: str, offset: int = 0):
    return _POOL.submit(load_data, t, filename, offset)
