"""GDS file I/O (reference: ``apex/contrib/gpu_direct_storage`` over
cuFile — direct storage<->GPU DMA for torch tensors).

TPU has no user-visible direct-storage path (transfers stage through host
RAM under XLA's control), so the equivalent capability is overlap: async
host-side file I/O feeding ``jax.device_put``.  ``load_data``/``save_data``
keep the reference's names; the async variants return futures.
"""
from __future__ import annotations

import concurrent.futures
import os

import jax
import numpy as np

__all__ = ["load_data", "save_data", "load_data_async", "save_data_async"]

_POOL = concurrent.futures.ThreadPoolExecutor(max_workers=4)


def save_data(t, filename: str, offset: int = 0):
    """Write a device array's bytes to file (reference:
    ``gds.save_data(tensor, filename)``)."""
    arr = np.asarray(t)
    mode = "r+b" if os.path.exists(filename) else "wb"
    with open(filename, mode) as f:
        f.seek(offset)
        f.write(arr.tobytes())


def load_data(t, filename: str, offset: int = 0):
    """Read bytes into a NEW device array shaped/typed like ``t``
    (functional: JAX arrays are immutable; the reference fills in place)."""
    like = np.asarray(t)
    with open(filename, "rb") as f:
        f.seek(offset)
        buf = f.read(like.nbytes)
    arr = np.frombuffer(buf, dtype=like.dtype).reshape(like.shape)
    return jax.device_put(arr)


def save_data_async(t, filename: str, offset: int = 0):
    return _POOL.submit(save_data, t, filename, offset)


def load_data_async(t, filename: str, offset: int = 0):
    return _POOL.submit(load_data, t, filename, offset)
