"""Fused multi-head attention modules.

Reference: ``apex/contrib/multihead_attn/`` — ``SelfMultiheadAttn`` /
``EncdecMultiheadAttn`` over the ``fast_multihead_attn`` ext (fused
QKV GEMM → scaled masked softmax(+dropout) → AV → out-proj, with
``include_norm_add`` pre-LN + residual variants and ``impl='fast'|'default'``).

TPU-native: the GEMM chain is XLA dots, the softmax·V core is the Pallas
flash kernel (``apex_tpu.ops.attention``), and the norm-add variant is the
fused Pallas LayerNorm + residual.  ``impl`` selects kernel vs jnp-oracle
core (the reference's fast/default split).
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.ops.attention import flash_attention, mha_reference

__all__ = ["SelfMultiheadAttn", "EncdecMultiheadAttn"]


def _core(q, k, v, mask, impl, dropout=0.0, seed=None):
    """Attention core with reference-parity dropout placement: the
    probabilities are dropped (``fast_multihead_attn``'s in-kernel
    philox softmax+dropout fusion — here the Pallas kernel's counter
    hash), NOT the context output.  The two impls draw different masks
    (kernel blocks vs one full-matrix block), matching the reference,
    where the 'default' impl uses torch's own RNG."""
    if impl == "fast":
        return flash_attention(q, k, v, mask=mask, dropout_rate=dropout,
                               dropout_seed=seed)
    return mha_reference(q, k, v, mask=mask, dropout_rate=dropout,
                         dropout_seed=seed)


def _dropout_seed(mod, dropout):
    if not dropout:
        return None
    return jax.random.bits(mod.make_rng("dropout"),
                           dtype=jnp.uint32).astype(jnp.int32)


class SelfMultiheadAttn(nn.Module):
    """Self-attention with packed QKV projection (reference:
    ``SelfMultiheadAttn(embed_dim, num_heads, dropout, bias,
    include_norm_add, impl)``).  Layout ``[seq, batch, hidden]`` like the
    reference; returns ``(output, attn_weights=None)``."""
    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"
    params_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, query, key_padding_mask=None, attn_mask=None,
                 is_training: bool = True):
        s, b, h = query.shape
        nh = self.num_heads
        hd = h // nh
        residual = query
        x = query
        if self.include_norm_add:
            x = FusedLayerNorm(normalized_shape=h, name="lyr_norm")(x)
        qkv = nn.Dense(3 * h, use_bias=self.bias,
                       param_dtype=self.params_dtype,
                       name="qkv_proj")(x)
        qkv = qkv.reshape(s, b, nh, 3 * hd)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k, v = (t.transpose(1, 2, 0, 3) for t in (q, k, v))  # [b,nh,s,d]
        mask = None
        if key_padding_mask is not None:
            # [b, s] True = pad (reference convention)
            mask = jnp.broadcast_to(
                key_padding_mask[:, None, None, :].astype(bool),
                (b, 1, s, s))
        elif attn_mask is not None:
            mask = jnp.broadcast_to(attn_mask.astype(bool)[None, None],
                                    (1, 1, s, s))
        drop = self.dropout if (is_training and self.dropout > 0.0) else 0.0
        ctx = _core(q, k, v, mask, self.impl, drop,
                    _dropout_seed(self, drop))
        out = ctx.transpose(2, 0, 1, 3).reshape(s, b, h)
        out = nn.Dense(h, use_bias=self.bias,
                       param_dtype=self.params_dtype,
                       name="out_proj")(out)
        if self.include_norm_add:
            out = out + residual
        return out, None


class EncdecMultiheadAttn(nn.Module):
    """Encoder-decoder attention: Q from the decoder stream, packed KV from
    the encoder stream (reference: ``EncdecMultiheadAttn``)."""
    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"
    params_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, query, key, key_padding_mask=None, attn_mask=None,
                 is_training: bool = True):
        sq, b, h = query.shape
        sk = key.shape[0]
        nh = self.num_heads
        hd = h // nh
        residual = query
        x = query
        if self.include_norm_add:
            x = FusedLayerNorm(normalized_shape=h, name="lyr_norm")(x)
        q = nn.Dense(h, use_bias=self.bias, param_dtype=self.params_dtype,
                     name="q_proj")(x)
        kv = nn.Dense(2 * h, use_bias=self.bias,
                      param_dtype=self.params_dtype,
                      name="kv_proj")(key)
        kv = kv.reshape(sk, b, nh, 2 * hd)
        k, v = jnp.split(kv, 2, axis=-1)
        q = q.reshape(sq, b, nh, hd).transpose(1, 2, 0, 3)
        k, v = (t.transpose(1, 2, 0, 3) for t in (k, v))
        mask = None
        if key_padding_mask is not None:
            mask = jnp.broadcast_to(
                key_padding_mask[:, None, None, :].astype(bool),
                (b, 1, sq, sk))
        elif attn_mask is not None:
            mask = jnp.broadcast_to(attn_mask.astype(bool)[None, None],
                                    (1, 1, sq, sk))
        drop = self.dropout if (is_training and self.dropout > 0.0) else 0.0
        ctx = _core(q, k, v, mask, self.impl, drop,
                    _dropout_seed(self, drop))
        out = ctx.transpose(2, 0, 1, 3).reshape(sq, b, h)
        out = nn.Dense(h, use_bias=self.bias,
                       param_dtype=self.params_dtype,
                       name="out_proj")(out)
        if self.include_norm_add:
            out = out + residual
        return out, None
