"""ResNet bottleneck block + spatial parallelism with halo exchange.

Reference: ``apex/contrib/bottleneck/bottleneck.py`` (``Bottleneck``,
``SpatialBottleneck`` over the ``fast_bottleneck`` cuDNN fusion ext) and
``halo_exchangers.py`` (``HaloExchangerPeer``/``HaloExchangerNCCL`` pushing
1-row halos through CUDA IPC peer memory / raw NCCL p2p).

TPU-native: conv+bn+relu fusion is XLA's job (NHWC convs on the MXU); the
peer-memory/NCCL halo machinery collapses to ``jax.lax.ppermute`` on a mesh
axis — ICI *is* peer memory on TPU.  The spatial variant shards H across
the axis, exchanges 1-row halos with neighbors, and runs the 3x3 conv
VALID over the haloed slab so results equal the unsharded conv.
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import DATA_AXIS

__all__ = ["Bottleneck", "SpatialBottleneck", "halo_exchange"]


def halo_exchange(x, axis_name: Optional[str], halo: int = 1):
    """Exchange ``halo`` edge rows (dim 1 = H) with ring neighbors.

    Returns x padded to ``H + 2*halo`` with the neighbors' rows (zeros at
    the global top/bottom edge).  Reference: ``HaloExchangerPeer.
    left_right_halo_exchange`` — here a pair of ppermutes over ICI.
    ``axis_name=None`` (or an unbound axis, e.g. during ``init``) degrades
    to plain zero halos — the unsharded SAME-padding behavior.
    """
    if axis_name is not None:
        try:
            n = jax.lax.axis_size(axis_name)
        except NameError:       # unbound (e.g. during init outside a mesh)
            axis_name = None
    if axis_name is None:
        z = jnp.zeros_like(x[:, :halo])
        return jnp.concatenate([z, x, z], axis=1)
    idx = jax.lax.axis_index(axis_name)
    top = x[:, :halo]          # my first rows -> previous rank's bottom halo
    bot = x[:, -halo:]         # my last rows  -> next rank's top halo
    up = [(i, (i - 1) % n) for i in range(n)]     # send to rank-1
    down = [(i, (i + 1) % n) for i in range(n)]   # send to rank+1
    from_next = jax.lax.ppermute(top, axis_name, up)    # next's top rows
    from_prev = jax.lax.ppermute(bot, axis_name, down)  # prev's bottom rows
    # zero the wrap-around at the global edges
    from_prev = jnp.where(idx == 0, jnp.zeros_like(from_prev), from_prev)
    from_next = jnp.where(idx == n - 1, jnp.zeros_like(from_next),
                          from_next)
    return jnp.concatenate([from_prev, x, from_next], axis=1)


class _ConvBN(nn.Module):
    features: int
    kernel: tuple
    strides: tuple = (1, 1)
    padding: str = "SAME"
    params_dtype: Any = jnp.float32
    use_running_average: bool = False

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.features, self.kernel, strides=self.strides,
                    padding=self.padding, use_bias=False,
                    param_dtype=self.params_dtype, name="conv")(x)
        return nn.BatchNorm(use_running_average=self.use_running_average,
                            param_dtype=self.params_dtype, name="bn")(x)


class Bottleneck(nn.Module):
    """NHWC bottleneck: 1x1 -> 3x3 -> 1x1 convs with BN+ReLU and residual
    (reference: ``Bottleneck(in_channels, bottleneck_channels,
    out_channels, stride)``)."""
    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    params_dtype: Any = jnp.float32
    use_running_average: bool = False

    @nn.compact
    def __call__(self, x):
        s = (self.stride, self.stride)
        idt = x
        if self.stride != 1 or self.in_channels != self.out_channels:
            idt = _ConvBN(self.out_channels, (1, 1), s,
                          params_dtype=self.params_dtype,
                          use_running_average=self.use_running_average,
                          name="downsample")(x)
        h = jax.nn.relu(_ConvBN(self.bottleneck_channels, (1, 1),
                                params_dtype=self.params_dtype,
                                use_running_average=self.use_running_average,
                                name="conv1")(x))
        h = jax.nn.relu(_ConvBN(self.bottleneck_channels, (3, 3), s,
                                params_dtype=self.params_dtype,
                                use_running_average=self.use_running_average,
                                name="conv2")(h))
        h = _ConvBN(self.out_channels, (1, 1),
                    params_dtype=self.params_dtype,
                    use_running_average=self.use_running_average,
                    name="conv3")(h)
        return jax.nn.relu(h + idt)


class SpatialBottleneck(nn.Module):
    """Bottleneck with H sharded over ``axis_name``: the 3x3 conv sees
    1-row halos from neighbors (reference: ``SpatialBottleneck`` +
    ``HaloExchanger*``; stride-1 spatial groups).

    Output equals the unsharded Bottleneck on the gathered input; in
    training mode this relies on BatchNorm stats being psum'd over the
    spatial axis (``sync_bn=True``, the default — the reference's
    ``SpatialBottleneck`` likewise group-syncs its BNs)."""
    in_channels: int
    bottleneck_channels: int
    out_channels: int
    axis_name: str = DATA_AXIS
    params_dtype: Any = jnp.float32
    use_running_average: bool = False
    sync_bn: bool = True      # psum BN stats over axis_name in training

    def _bn_axis(self):
        if not self.sync_bn or self.axis_name is None:
            return None
        try:
            jax.lax.axis_size(self.axis_name)
        except NameError:
            return None
        return self.axis_name

    @nn.compact
    def __call__(self, x):
        bn_axis = None if self.use_running_average else self._bn_axis()

        def conv_bn(feat, kern, name, padding="SAME"):
            def f(h):
                h = nn.Conv(feat, kern, padding=padding, use_bias=False,
                            param_dtype=self.params_dtype,
                            name=f"{name}_conv")(h)
                return nn.BatchNorm(
                    use_running_average=self.use_running_average,
                    axis_name=bn_axis, param_dtype=self.params_dtype,
                    name=f"{name}_bn")(h)
            return f

        idt = x
        if self.in_channels != self.out_channels:
            idt = conv_bn(self.out_channels, (1, 1), "downsample")(x)
        h = jax.nn.relu(conv_bn(self.bottleneck_channels, (1, 1),
                                "conv1")(x))
        # halo exchange, then VALID 3x3 over the haloed slab: rows at the
        # global edge see zeros, exactly like SAME padding unsharded
        h = halo_exchange(h, self.axis_name, halo=1)
        h = jax.nn.relu(conv_bn(self.bottleneck_channels, (3, 3), "conv2",
                                padding=((0, 0), (1, 1)))(h))
        h = conv_bn(self.out_channels, (1, 1), "conv3")(h)
        return jax.nn.relu(h + idt)
