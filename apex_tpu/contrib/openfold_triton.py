"""OpenFold Triton kernels (reference: ``apex/contrib/openfold_triton`` —
Triton implementations of OpenFold's MHA/layernorm, CUDA-only).

Not rebuilt as a distinct island: Triton does not target TPU, and every
kernel in it is covered by this package's Pallas equivalents —
``apex_tpu.ops.attention`` (MHA) and ``apex_tpu.ops.layer_norm`` — which
is where OpenFold-on-TPU should route."""


def __getattr__(name):
    raise NotImplementedError(
        f"openfold_triton.{name}: Triton is CUDA-only; use "
        "apex_tpu.ops.attention / apex_tpu.ops.layer_norm (Pallas) instead")
