"""Reference: ``apex/contrib/xentropy/softmax_xentropy.py ::
SoftmaxCrossEntropyLoss`` over the ``xentropy_cuda`` ext."""
from __future__ import annotations

from apex_tpu.ops.xentropy import softmax_cross_entropy_loss

__all__ = ["SoftmaxCrossEntropyLoss", "softmax_cross_entropy_loss"]


class SoftmaxCrossEntropyLoss:
    """Class-shaped parity shim: the reference exposes an autograd Function
    used as ``SoftmaxCrossEntropyLoss.apply(logits, labels, smoothing,
    padding_idx, half_to_float)``; here ``apply`` is the fused function."""

    @staticmethod
    def apply(logits, labels, smoothing=0.0, padding_idx=-100,
              half_to_float=False):
        return softmax_cross_entropy_loss(
            logits, labels, smoothing=smoothing, padding_idx=padding_idx,
            half_to_float=half_to_float)
