"""Fused conv+bias(+relu/+mask) (reference: ``apex/contrib/conv_bias_relu``
over cuDNN-frontend fusion descriptors).  XLA fuses conv+bias+relu
epilogues natively on TPU; these functional forms keep the contrib names.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["ConvBiasReLU", "ConvBias", "ConvBiasMaskReLU", "ConvFrozenScaleBiasReLU"]


def _conv_nhwc(x, w, stride, padding):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


class _Fun:
    def __init__(self, f):
        self._f = f

    def apply(self, *args):
        return self._f(*args)

    __call__ = apply


ConvBias = _Fun(lambda x, w, b, pad, stride:
                _conv_nhwc(x, w, stride, [(pad, pad), (pad, pad)])
                + b.reshape(1, 1, 1, -1))

ConvBiasReLU = _Fun(lambda x, w, b, pad, stride: jax.nn.relu(
    _conv_nhwc(x, w, stride, [(pad, pad), (pad, pad)])
    + b.reshape(1, 1, 1, -1)))

ConvBiasMaskReLU = _Fun(lambda x, w, b, mask, pad, stride: jax.nn.relu(
    (_conv_nhwc(x, w, stride, [(pad, pad), (pad, pad)])
     + b.reshape(1, 1, 1, -1)) * mask))

ConvFrozenScaleBiasReLU = _Fun(lambda x, w, scale, b, pad, stride:
                               jax.nn.relu(
    _conv_nhwc(x, w, stride, [(pad, pad), (pad, pad)])
    * scale.reshape(1, 1, 1, -1) + b.reshape(1, 1, 1, -1)))
