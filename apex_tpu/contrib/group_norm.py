"""Optimized NHWC GroupNorm (reference: ``apex/contrib/group_norm/`` over
the ``group_norm`` ext — one/two-pass NHWC kernels with optional fused
swish, built for diffusion workloads).

NHWC is the native TPU layout and XLA fuses normalize+activation, so the
module is the idiomatic expression of the same fusion; the reference's
``act="silu"`` fused activation is a flag here too.
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["GroupNorm", "group_norm_nhwc"]


def group_norm_nhwc(x, num_groups: int, weight=None, bias=None,
                    eps: float = 1e-5, act: str = ""):
    """Functional NHWC group norm (+optional fused silu/swish)."""
    n, h, w, c = x.shape
    xg = x.astype(jnp.float32).reshape(n, h, w, num_groups, c // num_groups)
    mean = jnp.mean(xg, axis=(1, 2, 4), keepdims=True)
    var = jnp.mean(jnp.square(xg - mean), axis=(1, 2, 4), keepdims=True)
    y = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(n, h, w, c)
    if weight is not None:
        y = y * weight.reshape(1, 1, 1, c)
    if bias is not None:
        y = y + bias.reshape(1, 1, 1, c)
    if act in ("silu", "swish"):
        y = y * jax.nn.sigmoid(y)
    elif act:
        raise ValueError(f"unsupported act {act!r} (reference supports "
                         "'' and 'silu'/'swish')")
    return y.astype(x.dtype)


class GroupNorm(nn.Module):
    """Parity: ``apex.contrib.group_norm.GroupNorm(num_groups,
    num_channels, eps, affine, act)`` in NHWC."""
    num_groups: int
    num_channels: int
    eps: float = 1e-5
    affine: bool = True
    act: str = ""
    params_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = b = None
        if self.affine:
            w = self.param("weight", nn.initializers.ones,
                           (self.num_channels,), self.params_dtype)
            b = self.param("bias", nn.initializers.zeros,
                           (self.num_channels,), self.params_dtype)
        return group_norm_nhwc(x, self.num_groups, w, b, self.eps, self.act)
