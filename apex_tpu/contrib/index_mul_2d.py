"""index_mul_2d (reference: ``apex/contrib/index_mul_2d`` over
``fused_index_mul_2d`` — fused ``out = in1[idx] * in2`` used by OpenFold;
the CUDA ext fuses the gather with the multiply and hand-writes the
scatter-add backward).

XLA fuses gather+multiply natively and autodiff emits the scatter-add, so
the functional form is the whole implementation.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["index_mul_2d"]


def index_mul_2d(in1, in2, idx1):
    """``out[i, :] = in1[idx1[i], :] * in2[i, :]`` (2-D rows)."""
    return jnp.take(in1, idx1, axis=0) * in2
