"""FMHA — fixed-shape fused attention (reference: ``apex/contrib/fmha/
fmha.py :: FMHAFun`` over ``fmhalib``: packed-QKV fp16 attention for
seqlen ≤ 512, head dim 64, varlen via cu_seqlens).

The Pallas flash kernel (``apex_tpu.ops.attention``) subsumes the fixed
shape table; this shim keeps the reference's packed-QKV varlen calling
convention: ``qkv [total_tokens, 3, h, d]`` + ``cu_seqlens [b+1]``.
Varlen is expressed as a padding mask over the repacked dense batch —
XLA/Pallas prefer static shapes, so the dense layout IS the fast path on
TPU (the CUDA varlen packing exists to dodge padding waste on ragged
batches; with a mask the flash kernel skips no work either way).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import flash_attention

__all__ = ["FMHAFun", "fmha_packed"]


def fmha_packed(qkv, cu_seqlens, max_s: int, *, is_training: bool = True,
                p_dropout: float = 0.0, dropout_seed=None):
    """Packed-varlen attention (reference: ``fmhalib.fwd`` signature).

    ``qkv``: [total, 3, h, d]; ``cu_seqlens``: [b+1] token offsets.
    Returns [total, h, d] context in the packed layout.

    ``p_dropout`` drops attention probabilities in-kernel during
    training (the reference kernels' philox softmax+dropout fusion —
    here the counter-hash stream in ``ops/attention.py``).  JAX has no
    ambient RNG to pull from, so training-time dropout needs an explicit
    ``dropout_seed`` (int32; pass a fresh value per step).
    """
    if p_dropout and is_training and dropout_seed is None:
        raise ValueError(
            "fmha_packed: p_dropout > 0 with is_training requires "
            "dropout_seed (JAX has no implicit philox state to draw "
            "from; pass a per-step int32 seed)")
    total, three, h, d = qkv.shape
    b = cu_seqlens.shape[0] - 1
    # unpack to dense [b, max_s] with a validity mask
    starts = cu_seqlens[:-1]
    lens = cu_seqlens[1:] - starts
    pos = jnp.arange(max_s)
    token_idx = jnp.clip(starts[:, None] + pos[None, :], 0, total - 1)
    valid = pos[None, :] < lens[:, None]                     # [b, max_s]
    dense = jnp.take(qkv, token_idx.reshape(-1), axis=0).reshape(
        b, max_s, 3, h, d)
    q, k, v = (dense[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
    mask = jnp.broadcast_to((~valid)[:, None, None, :],
                            (b, 1, max_s, max_s))
    rate = p_dropout if is_training else 0.0     # eval ignores dropout
    ctx = flash_attention(q, k, v, mask=mask, dropout_rate=rate,
                          dropout_seed=dropout_seed)         # [b,h,s,d]
    ctx = ctx.transpose(0, 2, 1, 3)                          # [b,s,h,d]
    # repack: scatter each valid dense token to its packed offset; invalid
    # positions index `total`, which mode="drop" discards
    dense_pos = starts[:, None] + pos[None, :]               # [b, max_s]
    out = jnp.zeros((total, h, d), ctx.dtype).at[
        jnp.where(valid, dense_pos, total)].set(
        jnp.where(valid[..., None, None], ctx, 0.0),
        mode="drop")
    return out


class FMHAFun:
    """Autograd-Function-shaped shim (reference exposes ``FMHAFun.apply``)."""

    @staticmethod
    def apply(qkv, cu_seqlens, seqlens, p_dropout, max_s, is_training,
              zero_tensors=False, dropout_seed=None):
        return fmha_packed(qkv, cu_seqlens, max_s,
                           is_training=is_training, p_dropout=p_dropout,
                           dropout_seed=dropout_seed)
