"""ZeRO-sharded fused optimizers over the data-parallel mesh axis.

Reference: ``apex/contrib/optimizers/distributed_fused_adam.py ::
DistributedFusedAdam`` and ``distributed_fused_lamb.py ::
DistributedFusedLAMB`` — ZeRO-2-style: grads bucketed → reduce-scatter
across the DP group → fused update on the owned shard → all-gather updated
params; fp32 master shards under fp16/bf16 params.

TPU-native design: the whole sequence is THREE ops inside the jitted step —
``psum_scatter`` (reduce-scatter over the ``data`` axis), the Pallas fused
update on the local 1/dp shard, ``all_gather`` — and XLA overlaps the
collectives with neighbouring compute.  Since ISSUE 3 these classes are
THIN SHELLS over the dp-sharded functional core
(:mod:`apex_tpu.optimizers.functional`): ``init_state`` builds a sharded
``FlatState`` (static-slice sharding of the contiguous flat master),
``step`` reduce-scatters the raveled grads and delegates the math —
including LAMB's exact per-tensor trust ratios via the
``lax.switch``-over-ranks static-span machinery in
:mod:`apex_tpu.optimizers.base` — to the same ``_AdamTx``/``_LambTx``
transforms the dense ``FusedAdam``/``FusedLAMB`` run, so ZeRO-vs-dense
equivalence is structural rather than re-implemented.  State lives as
explicit pytrees (functional JAX): construct the optimizer OUTSIDE
shard_map (static layout only), call ``init_state`` / ``step`` INSIDE
shard_map with the data axis bound.  Memory per rank: params +
(master, m, v)/dp — the ZeRO property.

Checkpointing is shard-aware: ``state_dict(state)`` reassembles the full
unpadded flat master (accepting the global view a ``P(axis)`` out-spec
returns, a ``[dp, shard_len]`` stack, or a dp=1 local state), and
``load_state_dict`` + ``shard_state`` re-pad and re-slice it for any dp —
a checkpoint taken at dp=4 restores onto dp=8.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import DATA_AXIS
import numpy as np

from apex_tpu.optimizers import functional as _functional
from apex_tpu.utils import cdiv, tree_ravel

__all__ = ["DistributedFusedAdam", "DistributedFusedLAMB"]


class _DistributedOptimizerBase:
    """Static layout holder; all state is explicit (functional).

    Subclasses set ``self._tx`` (a functional transform) and
    ``_state_keys`` (the slot names, matching ``FlatState.slots``)."""

    _state_keys: tuple = ()

    def __init__(self, shard_size_divisor: int, axis_name: str = DATA_AXIS,
                 grad_average: bool = True):
        self.axis_name = axis_name
        self.dp = int(shard_size_divisor)
        self.grad_average = grad_average
        self._numel: Optional[int] = None
        self._sizes: Optional[tuple] = None

    # -- layout helpers ------------------------------------------------------
    def _padded(self, n: int) -> int:
        return cdiv(n, self.dp) * self.dp

    def _record_layout(self, tree) -> tuple:
        leaves = jax.tree_util.tree_leaves(tree)
        sizes = tuple(int(x.size) for x in leaves)
        self._sizes = sizes
        self._numel = sum(sizes)
        return sizes

    def _shard(self) -> tuple:
        return (self.axis_name, self.dp)

    def _flat_state(self, state: dict, sizes: tuple):
        """Legacy state dict -> sharded FlatState (zero-copy views)."""
        return _functional.FlatState(
            master=state["master"],
            count=state["step"].astype(jnp.float32),
            slots={k: state[k] for k in self._state_keys},
            sizes=sizes,
            shard=self._shard() if self.dp > 1 else ())

    def init_state(self, params) -> dict:
        """Build the sharded state for my rank (call inside shard_map)."""
        sizes = self._record_layout(params)
        fs = self._tx.init(params, shard=self._shard())
        return {"step": jnp.zeros((), jnp.int32), "master": fs.master,
                **{k: fs.slots[k] for k in self._state_keys}}

    def _shard_grads(self, grads):
        """ravel + reduce-scatter: returns (grad shard [n_pad/dp], n,
        unravel).  ``unravel`` expects the ravel dtype (bf16 for
        homogeneous-bf16 trees) — ``_gather_params`` casts the fp32 master
        back before unraveling so params keep their construction dtypes."""
        gflat, unravel = tree_ravel(grads)
        self._flat_dtype = gflat.dtype
        n = gflat.shape[0]
        if self.dp == 1:
            return gflat, n, unravel
        pad = self._padded(n) - n
        if pad:
            gflat = jnp.concatenate(
                [gflat, jnp.zeros((pad,), gflat.dtype)])
        gshard = jax.lax.psum_scatter(
            gflat, self.axis_name, scatter_dimension=0, tiled=True)
        if self.grad_average:
            gshard = gshard / self.dp
        return gshard, n, unravel

    def _gather_params(self, pshard, n, unravel):
        if self.dp == 1:
            return unravel(pshard[:n].astype(self._flat_dtype))
        pfull = jax.lax.all_gather(
            pshard, self.axis_name, axis=0, tiled=True)[:n]
        return unravel(pfull.astype(self._flat_dtype))

    def step(self, state: dict, grads, *, lr: Optional[float] = None,
             noop_flag=0.0, grad_scale=1.0):
        """One ZeRO step (inside shard_map binding the data axis).

        Returns ``(params, new_state)``; params in the original dtypes.
        """
        sizes = self._record_layout(grads)
        gshard, n, unravel = self._shard_grads(grads)
        fs = self._flat_state(state, sizes)
        fs = self._tx.update(fs, gshard,
                             noop_flag=jnp.asarray(noop_flag, jnp.float32),
                             grad_scale=jnp.asarray(grad_scale,
                                                    jnp.float32),
                             lr=lr)
        new_state = {"step": state["step"] + 1, "master": fs.master,
                     **{k: fs.slots[k] for k in self._state_keys}}
        params = self._gather_params(fs.master, n, unravel)
        return params, new_state

    # -- checkpointing (shard-aware: reassembles the full flat master) ------
    def _full_buffer(self, buf) -> np.ndarray:
        """Accept the global 1-D padded view (``P(axis)`` out-spec), a
        stacked ``[dp, shard_len]`` per-rank view, or a dp=1 local
        buffer; return the UNPADDED full fp-precision vector."""
        arr = np.asarray(buf)
        if arr.ndim == 2:                      # [dp, shard_len] stack
            arr = arr.reshape(-1)
        n = self._numel
        if arr.shape[0] < n:
            raise ValueError(
                f"state buffer has {arr.shape[0]} elements < numel {n}; "
                "pass the GLOBAL view (out_specs=P(axis_name)) or the "
                "[dp, shard_len] stack, not one rank's shard")
        return arr[:n].copy()

    def state_dict(self, state: dict) -> dict:
        """Shard-aware checkpoint: the full (reassembled, unpadded) flat
        master + slots.  ``state`` must be the post-``shard_map`` global
        view (``out_specs=P(axis_name)`` on the sharded leaves) or a
        ``[dp, shard_len]`` stack; a dp=1 state passes through."""
        if self._numel is None:
            raise ValueError(
                "state_dict before init_state/step: the optimizer has "
                "not seen the parameter layout yet")
        return {"step": int(np.asarray(state["step"])),
                "numel": int(self._numel),
                "master": self._full_buffer(state["master"]),
                **{k: self._full_buffer(state[k])
                   for k in self._state_keys}}

    def load_state_dict(self, sd: dict) -> dict:
        """Full-buffer checkpoint -> padded GLOBAL state for THIS
        optimizer's dp (re-pads, so the saving and restoring dp may
        differ).  Feed the result through ``shard_state`` inside
        shard_map (or use directly when dp == 1)."""
        n = int(sd["numel"])
        self._numel = n

        def pad_full(v):
            v = jnp.asarray(v, jnp.float32)
            pad = self._padded(n) - n
            if pad:
                v = jnp.concatenate([v, jnp.zeros((pad,), v.dtype)])
            return v

        return {"step": jnp.asarray(int(sd["step"]), jnp.int32),
                "master": pad_full(sd["master"]),
                **{k: pad_full(sd[k]) for k in self._state_keys}}

    def shard_state(self, full_state: dict) -> dict:
        """Slice MY rank's shard out of a padded GLOBAL state (call
        inside shard_map with the axis bound)."""
        if self.dp == 1:
            return dict(full_state)
        shard_len = full_state["master"].shape[0] // self.dp
        idx = jax.lax.axis_index(self.axis_name)

        def slc(v):
            return jax.lax.dynamic_slice_in_dim(
                v, idx * shard_len, shard_len)

        return {"step": full_state["step"],
                "master": slc(full_state["master"]),
                **{k: slc(full_state[k]) for k in self._state_keys}}


class DistributedFusedAdam(_DistributedOptimizerBase):
    """Parity surface for ``DistributedFusedAdam(params, lr, bias_correction,
    betas, eps, adam_w_mode, weight_decay, ...)``; distribution knobs
    (process groups, bucket sizes, overlap flags) collapse into the mesh
    axis name — XLA owns bucketing/overlap.  The update math is the
    functional ``_AdamTx`` the dense ``FusedAdam`` runs, applied to the
    local shard."""

    _state_keys = ("exp_avg", "exp_avg_sq")

    def __init__(self, shard_size_divisor: int, lr: float = 1e-3,
                 bias_correction: bool = True, betas=(0.9, 0.999),
                 eps: float = 1e-8, adam_w_mode: bool = True,
                 weight_decay: float = 0.0, axis_name: str = DATA_AXIS,
                 grad_average: bool = True, **_parity_kwargs):
        super().__init__(shard_size_divisor, axis_name, grad_average)
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self._tx = _functional.fused_adam(
            lr=lr, betas=betas, eps=eps, weight_decay=weight_decay,
            adam_w_mode=adam_w_mode, bias_correction=bias_correction)


class DistributedFusedLAMB(_DistributedOptimizerBase):
    """ZeRO LAMB (reference: ``DistributedFusedLAMB``): phase-1 Adam-style
    direction on the shard, per-shard norms psum'd into GLOBAL per-tensor
    norms for the trust ratio, phase-2 scaled apply, then all-gather.

    The reference computes exact per-tensor norms across shards
    (``multi_tensor_l2norm`` + group allreduce); the functional
    ``_LambTx`` does the same on sharded state — shard-local per-tensor
    partial sums of squares over the static leaf-span layout (a
    ``lax.switch`` over ranks keeps every slice static — per-element
    gathers measure seconds on TPU, see
    ``optimizers.base.shard_leaf_spans``), psum'd over the data axis —
    same math, one collective, EXACT per-tensor trust ratios.  Above
    ``optimizers.base._SWITCH_MAX_DP`` the switch path (O(dp·n_leaves)
    compiled branches) gives way to a bounded-compile global-buffer
    fallback.
    """

    _state_keys = ("exp_avg", "exp_avg_sq")

    def __init__(self, shard_size_divisor: int, lr: float = 1e-3,
                 bias_correction: bool = True, betas=(0.9, 0.999),
                 eps: float = 1e-6, weight_decay: float = 0.01,
                 max_grad_norm: float = 1.0, axis_name: str = DATA_AXIS,
                 grad_average: bool = True, use_nvlamb: bool = False,
                 **_parity_kwargs):
        super().__init__(shard_size_divisor, axis_name, grad_average)
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb
        self._tx = _functional.fused_lamb(
            lr=lr, betas=betas, eps=eps, weight_decay=weight_decay,
            max_grad_norm=max_grad_norm, bias_correction=bias_correction,
            use_nvlamb=use_nvlamb)
