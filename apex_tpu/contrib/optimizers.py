"""ZeRO-sharded fused optimizers over the data-parallel mesh axis.

Reference: ``apex/contrib/optimizers/distributed_fused_adam.py ::
DistributedFusedAdam`` and ``distributed_fused_lamb.py ::
DistributedFusedLAMB`` — ZeRO-2-style: grads bucketed → reduce-scatter
across the DP group → fused update on the owned shard → all-gather updated
params; fp32 master shards under fp16/bf16 params.

TPU-native design: the whole sequence is THREE ops inside the jitted step —
``psum_scatter`` (reduce-scatter over the ``data`` axis), the Pallas fused
update on the local 1/dp shard, ``all_gather`` — and XLA overlaps the
collectives with neighbouring compute.  State lives as explicit pytrees
(functional JAX): construct the optimizer OUTSIDE shard_map (static layout
only), call ``init_state`` / ``step`` INSIDE shard_map with the data axis
bound.  Memory per rank: params + (master, m, v)/dp — the ZeRO property.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.fused_update import (
    fused_adam_flat,
    fused_lamb_phase1_flat,
)
import numpy as np

from apex_tpu.optimizers.base import broadcast_leaf_scalars
from apex_tpu.utils import cdiv, tree_ravel

__all__ = ["DistributedFusedAdam", "DistributedFusedLAMB"]

#: above this DP width the lax.switch-over-ranks trust-ratio path
#: (O(dp * n_leaves) compiled branches) gives way to the global-buffer
#: fallback (O(n) extra HBM traffic, compile size independent of dp)
_SWITCH_MAX_DP = 32


class _DistributedOptimizerBase:
    """Static layout holder; all state is explicit (functional)."""

    def __init__(self, shard_size_divisor: int, axis_name: str = "data"):
        self.axis_name = axis_name
        self.dp = shard_size_divisor

    # -- layout helpers ------------------------------------------------------
    def _padded(self, n: int) -> int:
        return cdiv(n, self.dp) * self.dp

    def _shard_grads(self, grads):
        """ravel + reduce-scatter: returns (grad shard [n_pad/dp], n,
        unravel).  ``unravel`` expects the ravel dtype (bf16 for
        homogeneous-bf16 trees) — ``_gather_params`` casts the fp32 master
        back before unraveling so params keep their construction dtypes."""
        gflat, unravel = tree_ravel(grads)
        self._flat_dtype = gflat.dtype
        n = gflat.shape[0]
        pad = self._padded(n) - n
        if pad:
            gflat = jnp.concatenate(
                [gflat, jnp.zeros((pad,), gflat.dtype)])
        if self.dp == 1:
            return gflat, n, unravel
        gshard = jax.lax.psum_scatter(
            gflat, self.axis_name, scatter_dimension=0, tiled=True)
        return gshard, n, unravel

    def _gather_params(self, pshard, n, unravel):
        if self.dp == 1:
            return unravel(pshard[:n].astype(self._flat_dtype))
        pfull = jax.lax.all_gather(
            pshard, self.axis_name, axis=0, tiled=True)[:n]
        return unravel(pfull.astype(self._flat_dtype))

    def init_state(self, params) -> dict:
        """Build the sharded state for my rank (call inside shard_map)."""
        flat, _ = tree_ravel(params)
        n = flat.shape[0]
        npad = self._padded(n)
        if npad != n:
            flat = jnp.concatenate(
                [flat, jnp.zeros((npad - n,), flat.dtype)])
        shard_len = npad // self.dp
        idx = jax.lax.axis_index(self.axis_name) if self.dp > 1 else 0
        master = jax.lax.dynamic_slice_in_dim(
            flat.astype(jnp.float32), idx * shard_len, shard_len)
        return {
            "step": jnp.zeros((), jnp.int32),
            "master": master,
            **{k: jnp.zeros_like(master) for k in self._state_keys},
        }


class DistributedFusedAdam(_DistributedOptimizerBase):
    """Parity surface for ``DistributedFusedAdam(params, lr, bias_correction,
    betas, eps, adam_w_mode, weight_decay, ...)``; distribution knobs
    (process groups, bucket sizes, overlap flags) collapse into the mesh
    axis name — XLA owns bucketing/overlap."""

    _state_keys = ("exp_avg", "exp_avg_sq")

    def __init__(self, shard_size_divisor: int, lr: float = 1e-3,
                 bias_correction: bool = True, betas=(0.9, 0.999),
                 eps: float = 1e-8, adam_w_mode: bool = True,
                 weight_decay: float = 0.0, axis_name: str = "data",
                 grad_average: bool = True, **_parity_kwargs):
        super().__init__(shard_size_divisor, axis_name)
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.grad_average = grad_average

    def step(self, state: dict, grads, *, lr: Optional[float] = None,
             noop_flag=0.0, grad_scale=1.0):
        """One ZeRO step (inside shard_map binding the data axis).

        Returns ``(params, new_state)``; params in the original dtypes.
        """
        gshard, n, unravel = self._shard_grads(grads)
        if self.grad_average and self.dp > 1:
            gshard = gshard / self.dp
        step = state["step"] + 1
        p, m, v = fused_adam_flat(
            state["master"], gshard.astype(jnp.float32),
            state["exp_avg"], state["exp_avg_sq"],
            lr=self.lr if lr is None else lr,
            beta1=self.betas[0], beta2=self.betas[1], eps=self.eps,
            weight_decay=self.weight_decay, step=step,
            adam_w_mode=self.adam_w_mode,
            bias_correction=self.bias_correction,
            noop_flag=noop_flag, grad_scale=grad_scale)
        new_state = {"step": step, "master": p, "exp_avg": m,
                     "exp_avg_sq": v}
        params = self._gather_params(p, n, unravel)
        return params, new_state


class DistributedFusedLAMB(_DistributedOptimizerBase):
    """ZeRO LAMB (reference: ``DistributedFusedLAMB``): phase-1 Adam-style
    direction on the shard, per-shard norms psum'd into GLOBAL per-tensor
    norms for the trust ratio, phase-2 scaled apply, then all-gather.

    The reference computes exact per-tensor norms across shards
    (``multi_tensor_l2norm`` + group allreduce); here each shard computes
    per-tensor partial sums of squares over the static leaf-span layout
    (a ``lax.switch`` over ranks keeps every slice static — per-element
    gathers measure seconds on TPU, see ``_shard_leaf_spans``), psum'd
    over the data axis — same math, one collective, EXACT per-tensor
    trust ratios.
    """

    _state_keys = ("exp_avg", "exp_avg_sq")

    def __init__(self, shard_size_divisor: int, lr: float = 1e-3,
                 bias_correction: bool = True, betas=(0.9, 0.999),
                 eps: float = 1e-6, weight_decay: float = 0.01,
                 max_grad_norm: float = 1.0, axis_name: str = "data",
                 grad_average: bool = True, use_nvlamb: bool = False,
                 **_parity_kwargs):
        super().__init__(shard_size_divisor, axis_name)
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.max_grad_norm = max_grad_norm
        self.grad_average = grad_average
        self.use_nvlamb = use_nvlamb

    def _shard_leaf_spans(self, sizes, n: int):
        """Static leaf spans per rank: ``spans[r]`` lists
        ``(leaf_id, lo, hi)`` — the intersection of each leaf's
        ``[offset, offset+size)`` with rank r's padded shard window, in
        shard-local coordinates.  The padding tail is covered by no span.

        Leaf boundaries AND the shard length are static, so every rank's
        spans are plain Python — only *which* rank we are is dynamic, and
        a ``lax.switch`` over ranks keeps every slice static.  This is
        load-bearing for TPU: per-element gathers (``segment_sum`` /
        ``trust[seg]``) over a BERT-large-sized shard measure seconds per
        call (see ``broadcast_leaf_scalars``), while static slices +
        concat are copies.

        Compile cost is O(dp · n_leaves) HLO ops (dead branches are
        compiled, not executed); above ``_SWITCH_MAX_DP`` ``step``
        switches to the global-buffer fallback — the leaf layout is
        globally static and only the shard offset is dynamic, so the
        shard is placed into a zeroed full-size buffer (norms) and the
        full-size static scale vector is dynamically sliced (apply),
        bounding compile size at the cost of O(n) extra HBM traffic."""
        shard_len = self._padded(n) // self.dp
        offs = [0]
        for s in sizes:
            offs.append(offs[-1] + s)
        spans = []
        for r in range(self.dp):
            start, end = r * shard_len, (r + 1) * shard_len
            rs = [(i, max(o, start) - start, min(o + s, end) - start)
                  for i, (o, s) in enumerate(zip(offs, sizes))
                  if min(o + s, end) > max(o, start)]
            spans.append(rs)
        return spans, shard_len

    def step(self, state: dict, grads, *, lr: Optional[float] = None,
             noop_flag=0.0, grad_scale=1.0):
        leaves = jax.tree.leaves(grads)
        gshard, n, unravel = self._shard_grads(grads)
        if self.grad_average and self.dp > 1:
            gshard = gshard / self.dp
        # global grad-norm clip (reference: pre-LAMB global L2 clip)
        sq = jnp.sum(jnp.square(gshard.astype(jnp.float32)))
        if self.dp > 1:
            sq = jax.lax.psum(sq, self.axis_name)
        gnorm = jnp.sqrt(sq)
        # same formula as optimizers.FusedLAMB._lamb_step for equivalence
        clip = jnp.where(gnorm > self.max_grad_norm,
                         self.max_grad_norm / (gnorm + 1e-6), 1.0) \
            if self.max_grad_norm else 1.0
        step = state["step"] + 1
        m, v, u = fused_lamb_phase1_flat(
            state["master"], gshard * clip, state["exp_avg"],
            state["exp_avg_sq"], beta1=self.betas[0], beta2=self.betas[1],
            eps=self.eps, weight_decay=self.weight_decay, step=step,
            bias_correction=self.bias_correction, grad_scale=grad_scale)
        # EXACT per-tensor trust ratios (reference: multi_tensor_l2norm per
        # tensor + group allreduce): shard-local per-tensor partial sq-sums
        # over static leaf spans (lax.switch over ranks — no per-element
        # gathers, see _shard_leaf_spans), psum over dp, per-tensor ratio
        # broadcast back through static-slice concatenation.
        p32 = state["master"]
        sizes = [int(l.size) for l in leaves]
        n_tensors = len(sizes)
        large_dp = self.dp > _SWITCH_MAX_DP
        if large_dp:        # spans unused — skip the O(dp*n_leaves) build
            spans, shard_len = None, self._padded(n) // self.dp
        else:
            spans, shard_len = self._shard_leaf_spans(sizes, n)
        idx = jax.lax.axis_index(self.axis_name) if self.dp > 1 else 0

        def _norms_branch(rs):
            def f(pu):
                p_, u_ = pu
                out = []
                for vec in (p_, u_):
                    row = [jnp.float32(0.0)] * n_tensors
                    for i, lo, hi in rs:
                        row[i] = jnp.sum(jnp.square(
                            jax.lax.dynamic_slice_in_dim(vec, lo, hi - lo)))
                    out.append(jnp.stack(row))
                return jnp.stack(out)
            return f

        if large_dp:
            # bounded-compile fallback: only the shard's OFFSET is
            # dynamic (idx * shard_len) — place the shard into a
            # zeroed GLOBAL buffer at that offset, then every leaf
            # reduction is a static slice.  Costs one full-buffer temp
            # (O(n) HBM traffic, ~3 ms on a 335M tree) instead of the
            # switch path's O(dp * n_leaves) compiled branches.
            npad = self._padded(n)
            offs = list(np.cumsum([0] + sizes[:-1]))

            def global_sq_norms(vec):
                full = jax.lax.dynamic_update_slice_in_dim(
                    jnp.zeros((npad,), jnp.float32), jnp.square(vec),
                    idx * shard_len, axis=0)
                return jnp.stack([
                    jnp.sum(jax.lax.dynamic_slice_in_dim(full, o, s))
                    for o, s in zip(offs, sizes)])
            sq = jnp.stack([global_sq_norms(p32), global_sq_norms(u)])
            sq = jax.lax.psum(sq, self.axis_name)
        elif self.dp > 1:
            sq = jax.lax.switch(idx, [_norms_branch(rs) for rs in spans],
                                (p32, u))
            sq = jax.lax.psum(sq, self.axis_name)
        else:
            sq = _norms_branch(spans[0])((p32, u))
        psq, usq = sq[0], sq[1]
        pnorm, unorm = jnp.sqrt(psq), jnp.sqrt(usq)
        if self.use_nvlamb:
            trust = pnorm / jnp.maximum(unorm, 1e-12)
        else:
            trust = jnp.where((pnorm > 0) & (unorm > 0), pnorm / unorm, 1.0)

        def _scale_branch(rs):
            def f(trust):
                vals = [trust[i] for i, _, _ in rs]
                span_sizes = [hi - lo for _, lo, hi in rs]
                covered = sum(span_sizes)
                if covered < shard_len:     # padding tail: ratio 1
                    vals.append(jnp.float32(1.0))
                    span_sizes.append(shard_len - covered)
                return broadcast_leaf_scalars(jnp.stack(vals), span_sizes)
            return f

        if large_dp:
            # global scale vector is static-structured (leaf layout);
            # my shard's window is one dynamic slice of it
            npad = self._padded(n)
            gsizes = list(sizes)
            if npad > n:
                gsizes.append(npad - n)
            gtrust = (jnp.concatenate([trust, jnp.ones((1,), jnp.float32)])
                      if npad > n else trust)
            scale = jax.lax.dynamic_slice_in_dim(
                broadcast_leaf_scalars(gtrust, gsizes),
                idx * shard_len, shard_len)
        elif self.dp > 1:
            scale = jax.lax.switch(
                idx, [_scale_branch(rs) for rs in spans], trust)
        else:
            scale = _scale_branch(spans[0])(trust)
        p = p32 - (self.lr if lr is None else lr) * scale * u
        skip = jnp.asarray(noop_flag, jnp.float32) > 0
        p = jnp.where(skip, p32, p)
        m = jnp.where(skip, state["exp_avg"], m)
        v = jnp.where(skip, state["exp_avg_sq"], v)
        new_state = {"step": step, "master": p, "exp_avg": m,
                     "exp_avg_sq": v}
        params = self._gather_params(p, n, unravel)
        return params, new_state
