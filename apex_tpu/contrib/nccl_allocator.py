"""NCCL window allocator (reference: ``apex/contrib/nccl_allocator`` — a
torch pluggable allocator over ``ncclMemAlloc`` so comm buffers live in
NVLS-registered windows).

ABSORBED on TPU: the XLA runtime owns all device buffers and collectives
run over ICI with no user-registered windows, so there is nothing to
allocate.  ``nccl_mem`` is a no-op context manager and ``init`` a no-op,
keeping ported call sites working (SURVEY.md §2.3 maps this ext to "n/a —
document as absorbed")."""
from __future__ import annotations

import contextlib

__all__ = ["init", "nccl_mem"]


def init(*_a, **_k) -> None:
    return None


@contextlib.contextmanager
def nccl_mem(*_a, **_k):
    yield
