"""Tombstone for the legacy per-GPU process launcher (reference:
``apex/parallel/multiproc.py :: main`` — forks one ``python main.py``
per device with ``--world-size``/``--rank`` argv appended).

The reference itself deprecates this in favour of
``torch.distributed.launch``.  On TPU there is nothing to launch: a
single SPMD Python process drives every local chip through one
``jax.sharding.Mesh``, and multi-host jobs are started by the cluster
runtime (one process per host, ``jax.distributed.initialize()``), not by
a fork loop.  Importing this module raises with that guidance so stale
``python -m apex.parallel.multiproc train.py`` recipes fail loudly
instead of silently running one unsharded process.
"""

raise ImportError(
    "apex_tpu.parallel.multiproc does not exist: the reference's per-GPU "
    "fork launcher has no TPU equivalent. A single process drives all "
    "local chips via jax.sharding.Mesh; for multi-host, start one process "
    "per host and call jax.distributed.initialize(). See MIGRATION.md."
)
