"""Data-parallel gradient communication (reference:
``apex/parallel/distributed.py :: DistributedDataParallel, flat_dist_call``).

The reference registers per-param backward hooks that pack gradients into
~10 MB buckets and launch async NCCL allreduces overlapping backward.  On
TPU the whole train step is one XLA program: gradients are reduced with
``psum`` over the ``data`` mesh axis *inside* the jitted step, and XLA's
scheduler overlaps the collectives with remaining backward compute (the
latency-hiding the reference hand-builds).  The knobs are kept:

* ``message_size`` — bucket size; grads are bucketed along LEAF boundaries
  into ~this many bytes and psum'd per bucket.  Because each bucket's
  collective depends only on its own leaves' gradients — not on a
  whole-tree ravel that finishes with the backward — XLA launches it as
  soon as those grads are final, overlapping comm with the rest of the
  backward exactly like the reference's hooks (per-bucket dtype follows
  the bucket's leaves, as the reference's per-dtype buckets do).
* ``delay_allreduce=True`` — single fused psum of the whole flat buffer
  (reference: one flat allreduce after backward; no overlap).
* ``allreduce_always_fp32``, ``gradient_average``,
  ``gradient_predivide_factor`` — same semantics as the reference.

Use inside ``shard_map``/``pjit`` over a mesh with a data axis::

    ddp = DistributedDataParallel(axis_name="data")
    grads = ddp.reduce_gradients(grads)   # inside the sharded train step
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.utils import tree_ravel

__all__ = ["DistributedDataParallel", "flat_allreduce"]

_DEFAULT_MESSAGE_SIZE = 10_000_000  # bytes, reference default ~10MB


def _resolve_data_axes(axis_name):
    """``None`` -> the FULL data-parallel group for DENSE params: they
    replicate over the ``expert`` axis when expert parallelism is
    active AND over the ``context`` axis when context parallelism is
    active (each cp rank sees a different sequence shard, so its dense
    grads are partial — Megatron likewise allreduces grads over the
    dp-cp group), so the grad reduction must span every such axis —
    reducing over the bare ``data`` axis silently desyncs the replicas.
    An explicit ``axis_name`` is passed through untouched (expert
    params, custom topologies)."""
    if axis_name is not None:
        return axis_name
    from apex_tpu.transformer import parallel_state as ps
    if not ps.model_parallel_is_initialized():
        return ps.DATA_AXIS
    return ps.get_dense_param_grad_axes()


def _psum_checked(x, axis_name, was_default: bool):
    """``psum`` with a diagnosable failure when a resolved axis is not
    bound in the caller's ``shard_map``.

    The ``axis_name=None`` default resolves through ``parallel_state`` —
    if that was initialized with ``ep``/``cp`` > 1 but the caller runs
    inside their OWN mesh without those axes, the bare JAX error
    ("unbound axis name") does not say where the extra axes came from.
    An explicitly passed axis that is unbound re-raises untouched (the
    parallel_state explanation would send the user down the wrong path)."""
    if not was_default:
        return jax.lax.psum(x, axis_name)
    try:
        return jax.lax.psum(x, axis_name)
    except NameError as e:
        raise NameError(
            f"{e}. apex_tpu resolved the data-parallel reduction axes to "
            f"{axis_name!r} (from parallel_state — the expert/context axes "
            "join automatically when ep/cp > 1). If you are running inside "
            "your own mesh without those axes, pass an explicit "
            "axis_name='data' (or your axis) to DistributedDataParallel/"
            "flat_allreduce. See MIGRATION.md."
        ) from e


def _axes_size(axis_name):
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    world = 1
    for a in axes:
        world *= jax.lax.axis_size(a)
    return world


def flat_allreduce(tree, axis_name=None):
    """Flatten a pytree, one psum, unflatten (reference: ``flat_dist_call``
    over ``apex_C.flatten``/``unflatten`` + ``dist.all_reduce``).

    ``axis_name=None`` resolves to the full dense-param data-parallel
    group (``parallel_state.get_dense_param_grad_axes``): the ``expert``
    and ``context`` axes join automatically when those parallelisms are
    active."""
    flat, unravel = tree_ravel(tree)
    return unravel(_psum_checked(flat, _resolve_data_axes(axis_name),
                                 was_default=axis_name is None))


class DistributedDataParallel:
    """Gradient-averaging data parallelism over a mesh axis.

    Unlike the reference this does not wrap a module — forward needs no
    hooks in JAX; only the gradient reduction exists.  Call
    :meth:`reduce_gradients` on the grad pytree inside the sharded step.
    """

    def __init__(self, module=None, message_size: int = _DEFAULT_MESSAGE_SIZE,
                 delay_allreduce: bool = False,
                 allreduce_always_fp32: bool = False,
                 gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 axis_name=None,
                 num_allreduce_streams: int = 1,
                 allreduce_communicators=None,
                 shared_param=None):
        self.module = module  # pass-through for API parity
        self.message_size = message_size
        self.delay_allreduce = delay_allreduce
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        # raw arg kept; resolution happens at reduce time — resolving
        # here would freeze 'data' for the usual wrap-then-init ordering
        # (DDP constructed before initialize_model_parallel) and miss an
        # expert axis created later
        self._axis_name = axis_name

    @property
    def axis_name(self):
        return _resolve_data_axes(self._axis_name)

    @axis_name.setter
    def axis_name(self, value):
        # pre-r3 this was a plain attribute; keep the mutation surface
        self._axis_name = value

    def __call__(self, *args, **kw):
        if self.module is None:
            raise TypeError("DistributedDataParallel was constructed without "
                            "a module; call reduce_gradients on grads "
                            "instead.")
        return self.module(*args, **kw)

    def _reduce_flat(self, flat):
        dtype = flat.dtype
        if self.allreduce_always_fp32:
            flat = flat.astype(jnp.float32)
        if self.gradient_predivide_factor != 1.0:
            flat = flat / self.gradient_predivide_factor
        flat = _psum_checked(flat, self.axis_name,
                             was_default=self._axis_name is None)
        if self.gradient_average:
            world = _axes_size(self.axis_name)
            post = self.gradient_predivide_factor / world
            if post != 1.0:
                flat = flat * post
        # gradient_average=False: no post-scaling (reference semantics —
        # pre-divided grads stay as psum(g / predivide)).
        return flat.astype(dtype)

    def _leaf_buckets(self, leaves):
        """Greedy ~message_size buckets of LEAF INDICES, in leaf order,
        split at DTYPE boundaries (the reference buckets per dtype —
        a mixed bucket would silently promote its low-precision leaves
        through the ravel and reduce them at fp32 bytes/rounding).  A
        leaf larger than the bucket size gets a bucket of its own (the
        reference's hooks likewise never split a tensor)."""
        buckets, cur, cur_bytes, cur_dtype = [], [], 0, None
        for i, leaf in enumerate(leaves):
            nbytes = leaf.size * leaf.dtype.itemsize
            if cur and (cur_bytes + nbytes > self.message_size
                        or leaf.dtype != cur_dtype):
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += nbytes
            cur_dtype = leaf.dtype
        if cur:
            buckets.append(cur)
        return buckets

    def reduce_gradients(self, grads):
        """psum-average a grad pytree over the data axis (bucketed).

        Must be called inside ``shard_map``/``pjit`` where ``axis_name`` is
        bound.  Equivalent of the reference's hook-driven bucketed allreduce
        (``create_hooks`` / ``allreduce_bucket``) — including its OVERLAP:
        buckets are formed along LEAF boundaries, so each bucket's psum
        depends only on its own leaves' gradients and XLA launches it as
        soon as those grads are final (reverse-mode autodiff finishes the
        last layers' grads first), instead of every collective waiting
        behind a whole-tree ravel ``concatenate`` that completes only when
        the full backward does.  ``delay_allreduce=True`` keeps the single
        fused flat psum (the reference's post-backward mode).  Total psum
        bytes are identical either way; APX215 holds the ledger to it.
        """
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        total_bytes = sum(x.size * x.dtype.itemsize for x in leaves)
        if self.delay_allreduce or total_bytes <= self.message_size \
                or len(leaves) == 1:
            flat, unravel = tree_ravel(grads)
            return unravel(self._reduce_flat(flat))
        out = list(leaves)
        for bucket in self._leaf_buckets(leaves):
            flat, unravel = tree_ravel([leaves[i] for i in bucket])
            for i, leaf in zip(bucket, unravel(self._reduce_flat(flat))):
                out[i] = leaf
        return jax.tree_util.tree_unflatten(treedef, out)
