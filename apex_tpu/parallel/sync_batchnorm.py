"""SyncBatchNorm — cross-replica batch norm over a mesh axis.

Reference: ``apex/parallel/sync_batchnorm.py`` / ``optimized_sync_batchnorm*``
over the ``syncbn`` CUDA ext (``csrc/welford.cu``): per-GPU Welford stats,
allreduce of (mean, var, count), then the BN apply; backward allreduces the
two reduction terms (Σdy, Σdy·x̂).

TPU-native design: the stats are ``psum`` of (Σx, Σx², n) over the ``data``
mesh axis inside the jitted step — autodiff of that psum reproduces the
reference's backward collectives automatically, so there is no hand-written
backward.  Channel-last layouts are native on TPU (the reference's
``channel_last=True`` fast path is the default here).
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import DATA_AXIS

__all__ = ["SyncBatchNorm", "convert_syncbn_model"]


class SyncBatchNorm(nn.Module):
    """BatchNorm synchronized across the ``axis_name`` mesh axis.

    Parity kwargs follow ``torch.nn.BatchNorm`` /
    ``apex.parallel.SyncBatchNorm``: ``momentum`` is the running-stat update
    rate, ``use_running_average`` selects eval behavior.  ``process_group``
    maps to ``axis_name`` (+ optional ``axis_index_groups`` subsets — the
    reference's ``create_syncbn_process_group`` grouping).
    """
    num_features: int
    eps: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    track_running_stats: bool = True
    axis_name: Optional[str] = DATA_AXIS
    axis_index_groups: Any = None
    channel_last: bool = True  # NHWC; TPU-native layout

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        feat_ax = -1 if self.channel_last else 1
        reduce_axes = tuple(i for i in range(x.ndim)
                            if i != (feat_ax % x.ndim))
        dtype = x.dtype
        x32 = x.astype(jnp.float32)

        ra_mean = self.variable(
            "batch_stats", "running_mean",
            lambda: jnp.zeros((self.num_features,), jnp.float32))
        ra_var = self.variable(
            "batch_stats", "running_var",
            lambda: jnp.ones((self.num_features,), jnp.float32))

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            # Parallel Welford merge (the syncbn ext's numerics,
            # csrc/welford.cu): local centered stats, then psum-combine —
            # avoids the catastrophic cancellation of E[x²] − mean².
            n_local = jnp.asarray(x32.size // self.num_features, jnp.float32)
            mean_local = jnp.mean(x32, axis=reduce_axes)
            var_local = jnp.mean(
                jnp.square(x32 - mean_local.reshape(
                    [1 if i in reduce_axes else -1
                     for i in range(x.ndim)])), axis=reduce_axes)
            sync = self.axis_name is not None and not self.is_initializing()
            if sync:
                n, nm = jax.lax.psum(
                    (n_local, n_local * mean_local), self.axis_name,
                    axis_index_groups=self.axis_index_groups)
                mean = nm / n
                m2 = jax.lax.psum(
                    n_local * (var_local + jnp.square(mean_local - mean)),
                    self.axis_name,
                    axis_index_groups=self.axis_index_groups)
                var = m2 / n
            else:
                n, mean, var = n_local, mean_local, var_local
            if self.track_running_stats and not self.is_initializing():
                m = self.momentum
                # unbiased var for running stats (torch semantics)
                unbiased = var * n / jnp.maximum(n - 1.0, 1.0)
                ra_mean.value = (1 - m) * ra_mean.value + m * mean
                ra_var.value = (1 - m) * ra_var.value + m * unbiased

        shape = [1] * x.ndim
        shape[feat_ax] = self.num_features
        inv = jax.lax.rsqrt(var + self.eps).reshape(shape)
        y = (x32 - mean.reshape(shape)) * inv
        if self.affine:
            weight = self.param("weight", nn.initializers.ones,
                                (self.num_features,), jnp.float32)
            bias = self.param("bias", nn.initializers.zeros,
                              (self.num_features,), jnp.float32)
            y = y * weight.reshape(shape) + bias.reshape(shape)
        return y.astype(dtype)


def convert_syncbn_model(module, process_group=None, channel_last=False):
    """Recursively swap BatchNorm for SyncBatchNorm (torch modules only).

    Parity: ``apex.parallel.convert_syncbn_model``.  This is a
    single-process CPU shim: params/stats are preserved but no cross-process
    sync occurs (there is no multi-process torch on TPU), so
    ``process_group``/``channel_last`` are accepted for signature parity and
    ignored.  Flax models are immutable — instantiate
    :class:`SyncBatchNorm` directly instead; passing a flax module raises.
    """
    try:
        import torch
        if isinstance(module, torch.nn.Module):
            return _convert_torch(module)
    except ImportError:  # pragma: no cover
        pass
    raise TypeError(
        "convert_syncbn_model converts torch modules; flax models should "
        "use apex_tpu.parallel.SyncBatchNorm directly (flax modules are "
        "immutable).")


def _convert_torch(module):
    import torch
    mod = module
    if isinstance(module, torch.nn.modules.batchnorm._BatchNorm):
        # keep torch-side sync off (single-process CPU shim) but preserve
        # ALL state (params, running stats, num_batches_tracked) — the
        # conversion contract from the reference.  torch SyncBatchNorm maps
        # to a dimension-agnostic BatchNorm (SyncBatchNorm accepts 2D-5D
        # input; every fixed-rank class would reject some of those).
        # Subclasses with a nonstandard __init__ are passed through
        # unchanged.
        class _AnyDimBatchNorm(torch.nn.modules.batchnorm._BatchNorm):
            def _check_input_dim(self, input):
                if input.dim() < 2:
                    raise ValueError(
                        f"expected at least 2D input (got {input.dim()}D)")

        cls = type(module)
        if isinstance(module, torch.nn.SyncBatchNorm):
            cls = _AnyDimBatchNorm
        try:
            mod = cls(module.num_features, module.eps, module.momentum,
                      module.affine, module.track_running_stats)
        except TypeError:
            mod = module
        if module.affine:
            with torch.no_grad():
                mod.weight = module.weight
                mod.bias = module.bias
        mod.running_mean = module.running_mean
        mod.running_var = module.running_var
        if module.track_running_stats and \
                module.num_batches_tracked is not None:
            mod.num_batches_tracked = module.num_batches_tracked
    for name, child in module.named_children():
        new = _convert_torch(child)
        if new is not child:
            setattr(mod, name, new)
    return mod
