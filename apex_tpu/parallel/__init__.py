"""apex_tpu.parallel — data parallelism (reference: ``apex/parallel``).

* :class:`DistributedDataParallel` — bucketed grad psum over the data axis.
* :class:`SyncBatchNorm` + :func:`convert_syncbn_model` — cross-replica BN.
* :class:`LARC` — layer-wise adaptive rate clipping.
"""
from apex_tpu.parallel.distributed import (
    DistributedDataParallel, flat_allreduce)
from apex_tpu.parallel.sync_batchnorm import (
    SyncBatchNorm, convert_syncbn_model)
from apex_tpu.parallel.LARC import LARC

__all__ = ["DistributedDataParallel", "flat_allreduce", "SyncBatchNorm",
           "convert_syncbn_model", "LARC"]
