"""LARC — layer-wise adaptive rate clipping (reference:
``apex/parallel/LARC.py :: LARC``).

Wraps an ``apex_tpu.optimizers`` optimizer; before delegating to
``inner.step`` it rescales each parameter tensor's gradient by the local
adaptive rate  ``eta * ||p|| / (||g|| + wd * ||p|| + eps)``, clipped to the
group lr when ``clip=True`` — exactly the reference's algorithm, computed
per-leaf with XLA-fused reductions.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["LARC"]


class LARC:
    def __init__(self, optimizer, trust_coefficient=0.02, clip=True,
                 eps=1e-8):
        self.optim = optimizer
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps
        # Absorb weight decay: the reference zeroes the group's wd and folds
        # wd*p into the grad BEFORE trust-ratio scaling, so the decay term is
        # scaled too (apex/parallel/LARC.py :: LARC.step).
        self._group_wd = []
        for group in self.optim.param_groups:
            self._group_wd.append(group.options.get("weight_decay", 0.0))
            group.options["weight_decay"] = 0.0

    @property
    def param_groups(self):
        return self.optim.param_groups

    @property
    def inner(self):
        return self.optim

    def state_dict(self):
        return self.optim.state_dict()

    def load_state_dict(self, sd):
        self.optim.load_state_dict(sd)

    def zero_grad(self, set_to_none=True):
        self.optim.zero_grad(set_to_none)

    def _scale_group(self, group, wd, grads):
        lr = group.options["lr"]
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        scaled = []
        for g, off, size in zip(leaves, group.offsets, group.sizes):
            p = jax.lax.dynamic_slice_in_dim(
                group.master, off, size).reshape(g.shape)
            g32 = g.astype(jnp.float32)
            pn = jnp.sqrt(jnp.sum(jnp.square(p)))
            gn = jnp.sqrt(jnp.sum(jnp.square(g32)))
            adaptive = self.trust_coefficient * pn / \
                (gn + wd * pn + self.eps)
            if self.clip:
                adaptive = jnp.minimum(adaptive / lr, 1.0)
            # zero-norm params: grad passes through unscaled (reference skips)
            mult = jnp.where((pn > 0) & (gn > 0), adaptive, 1.0)
            scaled.append(((g32 + wd * p) * mult).astype(g.dtype))
        return jax.tree_util.tree_unflatten(treedef, scaled)

    def step(self, grads, **kw):
        groups = self.optim.param_groups
        if len(groups) == 1:
            grads_list = [grads]
        else:
            grads_list = list(grads)
        out = [self._scale_group(g, wd, gr)
               for g, wd, gr in zip(groups, self._group_wd, grads_list)]
        return self.optim.step(out[0] if len(groups) == 1 else out, **kw)
