"""LARC — layer-wise adaptive rate clipping (reference:
``apex/parallel/LARC.py :: LARC``).

Wraps an ``apex_tpu.optimizers`` optimizer; before delegating to
``inner.step`` it rescales each parameter tensor's gradient by the local
adaptive rate  ``eta * ||p|| / (||g|| + wd * ||p|| + eps)``, clipped to the
group lr when ``clip=True`` — exactly the reference's algorithm, computed
per-leaf with XLA-fused reductions.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["LARC"]


class LARC:
    def __init__(self, optimizer, trust_coefficient=0.02, clip=True,
                 eps=1e-8):
        self.optim = optimizer
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps

    @property
    def param_groups(self):
        return self.optim.param_groups

    @property
    def inner(self):
        return self.optim

    def state_dict(self):
        return self.optim.state_dict()

    def load_state_dict(self, sd):
        self.optim.load_state_dict(sd)

    def zero_grad(self, set_to_none=True):
        self.optim.zero_grad(set_to_none)

    def _scale_group(self, group, wd, grads, grad_scale):
        lr = group.options["lr"]
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        scaled = []
        for g, off, size in zip(leaves, group.offsets, group.sizes):
            p = jax.lax.dynamic_slice_in_dim(
                group.master, off, size).reshape(g.shape)
            # unscale BEFORE the norm/fold so amp loss scaling doesn't
            # distort the trust ratio or the folded wd*p term
            g32 = g.astype(jnp.float32) / grad_scale
            pn = jnp.sqrt(jnp.sum(jnp.square(p)))
            gn = jnp.sqrt(jnp.sum(jnp.square(g32)))
            adaptive = self.trust_coefficient * pn / \
                (gn + wd * pn + self.eps)
            if self.clip:
                adaptive = jnp.minimum(adaptive / lr, 1.0)
            # zero-norm params: grad passes through untouched — no scaling,
            # no wd fold (reference only acts when both norms are nonzero)
            apply = (pn > 0) & (gn > 0)
            mult = jnp.where(apply, adaptive, 1.0)
            folded = jnp.where(apply, g32 + wd * p, g32)
            scaled.append((folded * mult).astype(g.dtype))
        return jax.tree_util.tree_unflatten(treedef, scaled)

    def step(self, grads, grad_scale=None, **kw):
        """Scale grads by the per-param trust ratio, then delegate.

        The reference zeroes each group's ``weight_decay`` and folds
        ``wd*p`` into the grad before scaling, restoring wd afterwards —
        same here, so ``state_dict`` still records the true wd.
        ``grad_scale`` (amp's loss-scale) is consumed here: grads are
        unscaled before the norm computation, and the inner step runs with
        scale 1.
        """
        groups = self.optim.param_groups
        if len(groups) == 1:
            grads_list = [grads]
        else:
            grads_list = list(grads)
        scale = 1.0 if grad_scale is None else grad_scale
        saved_wd = [g.options.get("weight_decay", 0.0) for g in groups]
        try:
            for g in groups:
                g.options["weight_decay"] = 0.0
            out = [self._scale_group(g, wd, gr, scale)
                   for g, wd, gr in zip(groups, saved_wd, grads_list)]
            return self.optim.step(out[0] if len(groups) == 1 else out, **kw)
        finally:
            for g, wd in zip(groups, saved_wd):
                g.options["weight_decay"] = wd
