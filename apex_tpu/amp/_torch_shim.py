"""torch-CPU amp shim — lets the reference's training scripts
(``examples/imagenet/main_amp.py``) run unmodified on this framework.

Reference behavior being mirrored (``apex/amp/_initialize.py``,
``_process_optimizer.py``, ``scaler.py``):

* O0 — no-op fp32; static loss scale 1.0.
* O1 — autocast around the model's forward (torch CPU autocast, bf16 —
  there is no CUDA in this environment), dynamic loss scaling.
* O2 — model cast to bf16 with BatchNorm kept fp32, fp32 master weights in
  the patched optimizer, dynamic loss scaling.
* O3 — pure bf16, static scale 1.0.

``optimizer.step`` is patched to (a) step master weights where applicable
and (b) skip the step entirely when the last unscale saw inf/nan, halving
the scale — exactly the reference's skip-on-overflow contract.
"""
from __future__ import annotations

import contextlib
import functools
import types

import torch

from apex_tpu.amp import _amp_state

__all__ = ["initialize_torch", "torch_scale_loss"]

_DEFAULT_SCALE = 2.0 ** 16
_GROWTH_INTERVAL = 2000


class _TorchScaler:
    """Dynamic loss scaler over torch tensors (reference: LossScaler)."""

    def __init__(self, loss_scale, min_scale=1.0, max_scale=2.0 ** 24):
        self.dynamic = loss_scale == "dynamic"
        self._scale = _DEFAULT_SCALE if self.dynamic else float(loss_scale)
        self._unskipped = 0
        self.found_inf = False
        self._min_scale = min_scale if min_scale is not None else 1.0
        self._max_scale = max_scale if max_scale is not None else 2.0 ** 24

    def loss_scale(self):
        return self._scale

    def unscale_grads(self, params):
        inv = 1.0 / self._scale
        found = False
        for p in params:
            if p.grad is not None:
                p.grad.mul_(inv)
                if not torch.isfinite(p.grad).all():
                    found = True
        self.found_inf = found

    def update(self):
        # one iteration boundary: drop the O1 weight-cast cache (reference:
        # handle._clear_cache() on every scaler update)
        from apex_tpu.amp import amp as _amp_mod
        if _amp_mod.current_handle() is not None:
            _amp_mod.current_handle()._clear_cache()
        if not self.dynamic:
            self.found_inf = False
            return
        if self.found_inf:
            self._scale = max(self._scale / 2.0, self._min_scale)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= _GROWTH_INTERVAL:
                self._scale = min(self._scale * 2.0, self._max_scale)
                self._unskipped = 0
        self.found_inf = False

    def state_dict(self):
        return {"loss_scale": self._scale, "unskipped": self._unskipped,
                "dynamic": self.dynamic}

    def load_state_dict(self, sd):
        self._scale = sd["loss_scale"]
        self._unskipped = sd.get("unskipped", 0)
        self.dynamic = sd.get("dynamic", self.dynamic)


def _cast_module(model: torch.nn.Module, dtype, keep_batchnorm_fp32: bool):
    """Cast params/buffers to ``dtype``; optionally keep *Norm layers fp32."""
    norm_types = (torch.nn.modules.batchnorm._BatchNorm,
                  torch.nn.LayerNorm, torch.nn.GroupNorm)
    for module in model.modules():
        if keep_batchnorm_fp32 and isinstance(module, norm_types):
            continue
        for name, p in module.named_parameters(recurse=False):
            p.data = p.data.to(dtype)
        for name, b in module.named_buffers(recurse=False):
            if b.is_floating_point():
                module._buffers[name] = b.to(dtype)
    return model


def _wrap_forward_cast_inputs(model, dtype):
    orig = model.forward

    @functools.wraps(orig)
    def forward(*args, **kw):
        def cast(x):
            if isinstance(x, torch.Tensor) and x.is_floating_point():
                return x.to(dtype)
            return x
        args = [cast(a) for a in args]
        kw = {k: cast(v) for k, v in kw.items()}
        return orig(*args, **kw)

    model.forward = forward
    return model


def _wrap_forward_autocast(model, dtype):
    orig = model.forward

    @functools.wraps(orig)
    def forward(*args, **kw):
        with torch.autocast(device_type="cpu", dtype=dtype):
            return orig(*args, **kw)

    model.forward = forward
    return model


def _patch_optimizer(optimizer, scaler: _TorchScaler, master_weights: bool):
    optimizer._amp_scaler = scaler
    optimizer._amp_stash = types.SimpleNamespace(already_patched=True)

    if master_weights:
        # fp32 master copy per param; grads land on the 16-bit model params
        # and are copied (already unscaled) onto the masters before stepping.
        masters = []
        for group in optimizer.param_groups:
            group_masters = []
            for i, p in enumerate(group["params"]):
                m = p.detach().clone().float()
                m.requires_grad_(True)
                group_masters.append(m)
            masters.append(group_masters)
            group["params"] = group_masters
        optimizer._amp_masters = masters

    if master_weights:
        # zero_grad must clear the 16-bit MODEL params' grads too (autograd
        # accumulates there), or stale grads leak into every later step —
        # the reference patches zero_grad the same way
        # (apex/amp/_process_optimizer.py).
        orig_zero = optimizer.zero_grad

        @functools.wraps(orig_zero)
        def zero_grad(set_to_none=True):
            orig_zero(set_to_none)
            for model_group in optimizer._amp_model_groups:
                for p in model_group:
                    if p.grad is not None:
                        if set_to_none:
                            p.grad = None
                        else:
                            p.grad.detach_()
                            p.grad.zero_()

        optimizer.zero_grad = zero_grad

    orig_step = optimizer.step

    @functools.wraps(orig_step)
    def step(closure=None):
        if scaler.found_inf:
            _amp_state.maybe_print(
                f"Gradient overflow.  Skipping step, loss scaler reducing "
                f"loss scale to {scaler._scale / 2.0}")
            scaler.update()
            return None
        if master_weights:
            for group_masters, model_group in zip(
                    optimizer._amp_masters, optimizer._amp_model_groups):
                for m, p in zip(group_masters, model_group):
                    if p.grad is not None:
                        m.grad = p.grad.detach().float()
            out = orig_step(closure)
            for group_masters, model_group in zip(
                    optimizer._amp_masters, optimizer._amp_model_groups):
                for m, p in zip(group_masters, model_group):
                    p.data.copy_(m.data.to(p.dtype))
        else:
            out = orig_step(closure)
        scaler.update()
        return out

    optimizer.step = step
    return optimizer


def initialize_torch(model, optimizer, props, num_losses=1,
                     min_loss_scale=None, max_loss_scale=None):
    """Apply an opt level to a torch module (+ optimizer)."""
    opt_level = props.opt_level
    scaler = _TorchScaler(props.loss_scale, min_scale=min_loss_scale,
                          max_scale=max_loss_scale)

    if opt_level == "O1":
        # O1 = patch the torch/Tensor/functional namespaces with the cast
        # lists (reference: amp.init + lists/*); patch_torch_functions=False
        # degrades to the autocast wrap.
        if getattr(props, "patch_torch_functions", True):
            from apex_tpu.amp import amp as amp_mod
            amp_mod.init()
        else:
            _wrap_forward_autocast(model, torch.bfloat16)
    elif opt_level in ("O2", "O3"):
        keep_bn = bool(props.keep_batchnorm_fp32) and opt_level == "O2"
        _cast_module(model, torch.bfloat16, keep_bn)
        _wrap_forward_cast_inputs(model, torch.bfloat16)

    if optimizer is None:
        return model

    optimizers = optimizer if isinstance(optimizer, (list, tuple)) \
        else [optimizer]
    for opt in optimizers:
        use_masters = bool(props.master_weights) and opt_level == "O2"
        if use_masters:
            opt._amp_model_groups = [list(g["params"])
                                     for g in opt.param_groups]
        _patch_optimizer(opt, scaler, use_masters)
    _amp_state.amp_state.loss_scalers = [scaler]
    _amp_state.amp_state.optimizers = list(optimizers)
    return (model, optimizer) if not isinstance(optimizer, (list, tuple)) \
        else (model, optimizers)


@contextlib.contextmanager
def torch_scale_loss(loss, optimizers, delay_unscale=False):
    opts = optimizers if isinstance(optimizers, (list, tuple)) \
        else [optimizers]
    scaler = getattr(opts[0], "_amp_scaler", None)
    if scaler is None:
        yield loss
        return
    yield loss * scaler.loss_scale()
    if not delay_unscale:
        for opt in opts:
            params = [p for g in getattr(opt, "_amp_model_groups",
                                         [g["params"]
                                          for g in opt.param_groups])
                      for p in g]
            scaler.unscale_grads(params)
