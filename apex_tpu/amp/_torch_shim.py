"""torch-CPU amp shim — lets the reference's training scripts
(``examples/imagenet/main_amp.py``) run unmodified on this framework.

Reference behavior being mirrored (``apex/amp/_initialize.py``,
``_process_optimizer.py``, ``scaler.py``):

* O0 — no-op fp32; static loss scale 1.0.
* O1 — autocast around the model's forward (torch CPU autocast, bf16 —
  there is no CUDA in this environment), dynamic loss scaling.
* O2 — model cast to bf16 with BatchNorm kept fp32, fp32 master weights in
  the patched optimizer, dynamic loss scaling.
* O3 — pure bf16, static scale 1.0.

``optimizer.step`` is patched to (a) step master weights where applicable
and (b) skip the step entirely when the last unscale saw inf/nan, halving
the scale — exactly the reference's skip-on-overflow contract.
"""
from __future__ import annotations

import contextlib
import copy
import functools
import types

import torch

from apex_tpu.amp import _amp_state

__all__ = ["initialize_torch", "torch_scale_loss"]

_DEFAULT_SCALE = 2.0 ** 16
_GROWTH_INTERVAL = 2000


class _TorchScaler:
    """Dynamic loss scaler over torch tensors (reference: LossScaler)."""

    def __init__(self, loss_scale, min_scale=1.0, max_scale=2.0 ** 24):
        self.dynamic = loss_scale == "dynamic"
        self._scale = _DEFAULT_SCALE if self.dynamic else float(loss_scale)
        self._unskipped = 0
        self.found_inf = False
        self._min_scale = min_scale if min_scale is not None else 1.0
        self._max_scale = max_scale if max_scale is not None else 2.0 ** 24

    def loss_scale(self):
        return self._scale

    def unscale_grads(self, params):
        inv = 1.0 / self._scale
        found = False
        for p in params:
            if p.grad is not None:
                p.grad.mul_(inv)
                if not torch.isfinite(p.grad).all():
                    found = True
        self.found_inf = found

    def update(self):
        # one iteration boundary: drop the O1 weight-cast cache (reference:
        # handle._clear_cache() on every scaler update)
        _clear_o1_cache()
        if not self.dynamic:
            self.found_inf = False
            return
        if self.found_inf:
            self._scale = max(self._scale / 2.0, self._min_scale)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= _GROWTH_INTERVAL:
                self._scale = min(self._scale * 2.0, self._max_scale)
                self._unskipped = 0
        self.found_inf = False

    def state_dict(self):
        return {"loss_scale": self._scale, "unskipped": self._unskipped,
                "dynamic": self.dynamic}

    def load_state_dict(self, sd):
        self._scale = sd["loss_scale"]
        self._unskipped = sd.get("unskipped", 0)
        self.dynamic = sd.get("dynamic", self.dynamic)


def _to_torch_dtype(cast_model_type):
    """Map a frontend ``cast_model_type`` (a jnp dtype or None) to the
    torch half type for the shim.  Default is bf16 (TPU-native half);
    fp16 is selectable for reference-exact regimes (e.g. BERT phase 1).
    Unknown names raise — a typo ('fp16') silently training in bf16
    would defeat the point of selecting the regime."""
    import numpy as np
    if cast_model_type is None:
        return torch.bfloat16
    if isinstance(cast_model_type, torch.dtype):
        # reference scripts pass torch dtypes here (np.dtype can't
        # interpret them)
        name = str(cast_model_type).removeprefix("torch.")
    elif isinstance(cast_model_type, str):
        name = cast_model_type
    else:
        try:
            name = np.dtype(cast_model_type).name
        except TypeError:
            name = str(cast_model_type)
    table = {"float16": torch.float16, "bfloat16": torch.bfloat16,
             "float32": torch.float32}
    if name not in table:
        raise ValueError(
            f"cast_model_type {cast_model_type!r} is not supported; use "
            "one of float16/bfloat16/float32 (jnp dtype or name).")
    return table[name]


def _cast_module(model: torch.nn.Module, dtype, keep_batchnorm_fp32: bool):
    """Cast params/buffers to ``dtype``; optionally keep *Norm layers fp32."""
    norm_types = (torch.nn.modules.batchnorm._BatchNorm,
                  torch.nn.LayerNorm, torch.nn.GroupNorm)
    for module in model.modules():
        if keep_batchnorm_fp32 and isinstance(module, norm_types):
            continue
        for name, p in module.named_parameters(recurse=False):
            p.data = p.data.to(dtype)
        for name, b in module.named_buffers(recurse=False):
            if b.is_floating_point():
                module._buffers[name] = b.to(dtype)
    return model


def _wrap_forward_cast_inputs(model, dtype):
    orig = model.forward

    @functools.wraps(orig)
    def forward(*args, **kw):
        def cast(x):
            if isinstance(x, torch.Tensor) and x.is_floating_point():
                return x.to(dtype)
            return x
        args = [cast(a) for a in args]
        kw = {k: cast(v) for k, v in kw.items()}
        return orig(*args, **kw)

    model.forward = forward
    return model


def _wrap_forward_cast_outputs(model, dtype):
    """Cast every floating tensor in the model's output structure to
    ``dtype`` (reference: ``amp.initialize(cast_model_outputs=...)`` —
    applies regardless of opt level)."""
    orig = model.forward
    dtype = _to_torch_dtype(dtype)   # accept jnp/np dtypes like cast_model_type

    def cast(x):
        if isinstance(x, torch.Tensor) and x.is_floating_point():
            return x.to(dtype)
        if isinstance(x, tuple) and hasattr(x, "_fields"):   # namedtuple
            return type(x)(*(cast(v) for v in x))
        if isinstance(x, (list, tuple)):
            return type(x)(cast(v) for v in x)
        if isinstance(x, dict):
            # copy-then-assign preserves subclass state that pair-style
            # reconstruction loses (defaultdict's default_factory,
            # ModelOutput internals)
            out = copy.copy(x)
            for k, v in x.items():
                out[k] = cast(v)
            return out
        return x

    @functools.wraps(orig)
    def forward(*args, **kw):
        return cast(orig(*args, **kw))

    model.forward = forward
    return model


def _wrap_forward_autocast(model, dtype):
    orig = model.forward

    @functools.wraps(orig)
    def forward(*args, **kw):
        with torch.autocast(device_type="cpu", dtype=dtype):
            return orig(*args, **kw)

    model.forward = forward
    return model


def _clear_o1_cache():
    """Drop the O1 weight-cast cache at an iteration boundary (reference:
    ``handle._clear_cache()``) — must happen even when the user ran every
    backward with ``delay_unscale=True`` (no scaler update fired)."""
    from apex_tpu.amp import amp as _amp_mod
    if _amp_mod.current_handle() is not None:
        _amp_mod.current_handle()._clear_cache()


def _patch_optimizer(optimizer, master_weights: bool):
    optimizer._amp_stash = types.SimpleNamespace(already_patched=True)

    if master_weights:
        # fp32 master copy per param; grads land on the 16-bit model params
        # and are copied (already unscaled) onto the masters before stepping.
        masters = []
        for group in optimizer.param_groups:
            group_masters = []
            for i, p in enumerate(group["params"]):
                m = p.detach().clone().float()
                m.requires_grad_(True)
                group_masters.append(m)
            masters.append(group_masters)
            group["params"] = group_masters
        optimizer._amp_masters = masters

    # zero_grad re-arms the double-unscale guard (a fresh accumulation
    # begins), and under master weights must also clear the 16-bit MODEL
    # params' grads (autograd accumulates there), or stale grads leak into
    # every later step — the reference patches zero_grad the same way
    # (apex/amp/_process_optimizer.py).
    orig_zero = optimizer.zero_grad

    @functools.wraps(orig_zero)
    def zero_grad(set_to_none=True):
        orig_zero(set_to_none)
        optimizer._amp_grads_unscaled = False
        optimizer._amp_pending_scales = []
        if master_weights:
            for model_group in optimizer._amp_model_groups:
                for p in model_group:
                    if p.grad is not None:
                        if set_to_none:
                            p.grad = None
                        else:
                            p.grad.detach_()
                            p.grad.zero_()

    optimizer.zero_grad = zero_grad

    orig_step = optimizer.step

    @functools.wraps(orig_step)
    def step(closure=None):
        # stepping closes the iteration for this optimizer: clear the O1
        # cast cache and re-arm the unscale guard
        _clear_o1_cache()
        optimizer._amp_grads_unscaled = False
        optimizer._amp_pending_scales = []
        # one-shot skip set by scale_loss's exit when ITS loss overflowed
        # (reference: _process_optimizer's skip patch) — scaler updates
        # happen per scale_loss exit, so multiple losses/optimizers each
        # adjust their own scaler exactly once per iteration
        if getattr(optimizer, "_amp_skip_next_step", False):
            optimizer._amp_skip_next_step = False
            _amp_state.maybe_print(
                f"Gradient overflow.  Skipping step, loss scaler reduced "
                f"loss scale to "
                f"{getattr(optimizer, '_amp_skip_scale', 'n/a')}")
            return None
        if master_weights:
            for group_masters, model_group in zip(
                    optimizer._amp_masters, optimizer._amp_model_groups):
                for m, p in zip(group_masters, model_group):
                    if p.grad is not None:
                        m.grad = p.grad.detach().float()
            out = orig_step(closure)
            for group_masters, model_group in zip(
                    optimizer._amp_masters, optimizer._amp_model_groups):
                for m, p in zip(group_masters, model_group):
                    p.data.copy_(m.data.to(p.dtype))
        else:
            out = orig_step(closure)
        return out

    optimizer.step = step
    return optimizer


def initialize_torch(model, optimizer, props, num_losses=1,
                     min_loss_scale=None, max_loss_scale=None,
                     cast_model_outputs=None):
    """Apply an opt level to torch module(s) (+ optimizer(s)).

    Lists are the reference's multi-model/multi-optimizer contract
    (``amp.initialize([m1, m2], [o1, o2], num_losses=2)``): each model is
    cast/wrapped, each optimizer patched, and ``num_losses`` independent
    scalers are created — ``scale_loss(..., loss_id=k)`` scales/unscales
    with scaler ``k`` (reference: one ``LossScaler`` per loss_id).
    """
    opt_level = props.opt_level
    scalers = [_TorchScaler(props.loss_scale, min_scale=min_loss_scale,
                            max_scale=max_loss_scale)
               for _ in range(max(1, num_losses))]
    # honor cast_model_type (frontend documents fp16 as selectable; the
    # reference's O2 regime IS fp16 — BERT phase 1 trains under it)
    half = _to_torch_dtype(getattr(props, "cast_model_type", None))

    models_in_list = isinstance(model, (list, tuple))
    models = list(model) if models_in_list else [model]
    if opt_level == "O1":
        # O1 = patch the torch/Tensor/functional namespaces with the cast
        # lists (reference: amp.init + lists/*); patch_torch_functions=False
        # degrades to the autocast wrap.
        if half == torch.float32:
            raise ValueError(
                "cast_model_type=float32 is incompatible with O1 (the "
                "patch lists half-cast by design); use O0 for pure fp32.")
        if getattr(props, "patch_torch_functions", True):
            from apex_tpu.amp import amp as amp_mod
            amp_mod.init(half_dtype="float16" if half == torch.float16
                         else "bfloat16")
        else:
            for m in models:
                _wrap_forward_autocast(m, half)
    elif opt_level in ("O2", "O3"):
        # honor the properties table as merged by the frontend: O2
        # defaults keep_batchnorm_fp32=True, O3 defaults False, and an
        # EXPLICIT keep_batchnorm_fp32=True with O3 is the reference's
        # canonical "speed of light" mode (main_amp.py --opt-level O3
        # --keep-batchnorm-fp32 True) — discarding it here ran BN
        # statistics in bf16 and measurably degraded the O3 loss trace
        # (tests/L1/test_cross_run_compare.py caught the drift)
        keep_bn = bool(props.keep_batchnorm_fp32)
        for m in models:
            _cast_module(m, half, keep_bn)
            _wrap_forward_cast_inputs(m, half)
    if cast_model_outputs is not None:
        # outermost wrapper: applies regardless of opt level (reference
        # contract)
        for m in models:
            _wrap_forward_cast_outputs(m, cast_model_outputs)
    model_out = models if models_in_list else models[0]

    if optimizer is None:
        return model_out

    optimizers = optimizer if isinstance(optimizer, (list, tuple)) \
        else [optimizer]
    for opt in optimizers:
        use_masters = bool(props.master_weights) and opt_level == "O2"
        if use_masters:
            opt._amp_model_groups = [list(g["params"])
                                     for g in opt.param_groups]
        opt._amp_scalers = scalers
        _patch_optimizer(opt, use_masters)
    _amp_state.amp_state.loss_scalers = list(scalers)
    _amp_state.amp_state.optimizers = list(optimizers)
    return (model_out, optimizer) \
        if not isinstance(optimizer, (list, tuple)) \
        else (model_out, list(optimizers))


@contextlib.contextmanager
def torch_scale_loss(loss, optimizers, loss_id=0, delay_unscale=False):
    """Scale/unscale around one backward (reference: ``handle.scale_loss``).

    On exit: unscale every listed optimizer's grads with loss ``loss_id``'s
    scaler, update THAT scaler, and on overflow arm each optimizer's
    one-shot step skip — the reference's per-loss_id scaler + skip-patch
    flow, so multiple losses each manage their own dynamic scale.

    Accumulating SEVERAL backwards into one optimizer before its step
    requires ``delay_unscale=True`` on all but the last scale_loss (the
    reference documents the same contract): a second unscale of already-
    unscaled grads would silently divide the first loss's contribution
    away, so that case raises instead.
    """
    opts = optimizers if isinstance(optimizers, (list, tuple)) \
        else [optimizers]
    scalers = getattr(opts[0], "_amp_scalers", None)
    if not scalers:
        yield loss
        return
    scaler = scalers[loss_id]
    yield loss * scaler.loss_scale()
    if delay_unscale:
        # record the scale the accumulated grads carry so the final eager
        # exit can verify it unscales by the SAME factor
        for opt in opts:
            pending = getattr(opt, "_amp_pending_scales", None)
            if pending is None:
                pending = opt._amp_pending_scales = []
            pending.append(scaler.loss_scale())
        return
    for opt in opts:
        if getattr(opt, "_amp_grads_unscaled", False):
            raise RuntimeError(
                "scale_loss exit would unscale this optimizer's "
                "gradients a second time before its step() — grads "
                "already unscaled by an earlier loss's exit would be "
                "silently annihilated.  When accumulating multiple "
                "backwards into one optimizer, pass "
                "delay_unscale=True for all but the last scale_loss "
                "(the reference's documented contract).")
        bad = [s for s in getattr(opt, "_amp_pending_scales", [])
               if s != scaler.loss_scale()]
        if bad:
            raise RuntimeError(
                "delayed-unscale gradients were scaled by "
                f"{bad} but the final scale_loss would unscale by "
                f"{scaler.loss_scale()} (loss_id={loss_id}) — diverged "
                "per-loss scales would silently mis-weight the "
                "accumulated losses.  Use ONE loss_id (shared scaler) "
                "when accumulating into the same optimizer.")
    found = False
    for opt in opts:
        params = [p for g in getattr(opt, "_amp_model_groups",
                                     [g["params"]
                                      for g in opt.param_groups])
                  for p in g]
        scaler.unscale_grads(params)
        found = found or scaler.found_inf
        opt._amp_grads_unscaled = True
        opt._amp_pending_scales = []
    scaler.found_inf = found
    scaler.update()
    if found:
        for opt in opts:
            opt._amp_skip_next_step = True
            opt._amp_skip_scale = scaler._scale
