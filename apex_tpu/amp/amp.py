"""O1 patch registry (reference: ``apex/amp/amp.py``).

``init()`` monkeypatches the functions named in ``apex_tpu.amp.lists``
(torch, torch.Tensor, torch.nn.functional) with cast wrappers and returns
an :class:`AmpHandle` owning the per-iteration weight-cast cache.
``half_function`` / ``float_function`` / ``promote_function`` are the user
decorators (work on torch AND jax functions — the cast helpers dispatch on
array type); ``register_*_function(module, name)`` queues extra patches
applied at the next ``init()`` (the reference's pre-``initialize``
registration API).
"""
from __future__ import annotations

import functools
import importlib
from typing import Optional

from apex_tpu.amp.wrap import (
    make_cast_wrapper,
    make_inplace_promote_wrapper,
    make_promote_wrapper,
    make_sequence_promote_wrapper,
)

__all__ = [
    "init", "AmpHandle",
    "half_function", "float_function", "promote_function",
    "register_half_function", "register_float_function",
    "register_promote_function",
]

# queued (module, fn_name, category) from register_* calls
_USER_REGISTRY: list = []

_current_handle: Optional["AmpHandle"] = None


def current_handle():
    return _current_handle


def _is_active() -> bool:
    return _current_handle is not None and _current_handle.is_active


def _get_cache():
    return _current_handle.cache if _current_handle is not None else None


class AmpHandle:
    """Owns the patch set + the per-iteration cast cache (reference:
    ``apex/amp/handle.py :: AmpHandle``)."""

    def __init__(self, verbose: bool = False):
        self.is_active = True
        self.cache: dict = {}
        self._patches: list = []          # (obj, name, original)
        self.verbose = verbose

    # reference: handle._clear_cache(), called when the scaler updates
    def _clear_cache(self) -> None:
        self.cache.clear()

    def _patch(self, obj, name: str, wrapper) -> None:
        self._patches.append((obj, name, getattr(obj, name)))
        setattr(obj, name, wrapper)

    def _deactivate(self) -> None:
        """Restore every patched function (reference: ``handle._deactivate``)."""
        global _current_handle
        for obj, name, orig in reversed(self._patches):
            setattr(obj, name, orig)
        self._patches.clear()
        self.is_active = False
        if _current_handle is self:
            _current_handle = None

    def wrap_optimizer(self, optimizer, num_loss: int = 1):
        """Patch ``optimizer.step`` to clear the per-iteration weight-cast
        cache after every update (reference: ``OptimWrapper``).  Without
        this, the old-style API (``amp.init()`` + ``wrap_optimizer`` +
        ``scale_loss``, no ``amp.initialize``) keeps serving stale bf16
        weight copies after in-place parameter updates — ``cached_cast``'s
        identity check passes because the parameter object is mutated in
        place, so training silently freezes."""
        if getattr(optimizer, "_amp_cache_patched", False):
            return optimizer
        orig_step = optimizer.step

        @functools.wraps(orig_step)
        def step(*args, **kwargs):
            out = orig_step(*args, **kwargs)
            # resolve the LIVE handle at call time (same pattern as
            # _torch_shim): after a re-init, self may be a dead handle
            # while a new one owns the active cache.
            live = current_handle()
            if live is not None:
                live._clear_cache()
            return out

        optimizer.step = step
        optimizer._amp_cache_patched = True
        return optimizer

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._deactivate()


def _apply_lists(handle: AmpHandle, obj, lists_mod) -> None:
    for name in getattr(lists_mod, "FP16_FUNCS", []):
        if hasattr(obj, name):
            handle._patch(obj, name, make_cast_wrapper(
                getattr(obj, name), True, _get_cache, _is_active))
    for name in getattr(lists_mod, "FP32_FUNCS", []):
        if hasattr(obj, name):
            handle._patch(obj, name, make_cast_wrapper(
                getattr(obj, name), False, _get_cache, _is_active))
    for name in getattr(lists_mod, "CASTS", []):
        if hasattr(obj, name):
            handle._patch(obj, name, make_promote_wrapper(
                getattr(obj, name), _is_active))
    for name in getattr(lists_mod, "INPLACE_CASTS", []):
        if hasattr(obj, name):
            handle._patch(obj, name, make_inplace_promote_wrapper(
                getattr(obj, name), _is_active))
    for name in getattr(lists_mod, "SEQUENCE_CASTS", []):
        if hasattr(obj, name):
            handle._patch(obj, name, make_sequence_promote_wrapper(
                getattr(obj, name), _is_active))


def init(enabled: bool = True, verbose: bool = False,
         half_dtype: str = None) -> AmpHandle:
    """Apply the O1 patch lists; returns the handle (reference:
    ``amp.init``).  Re-entrant: a live handle is deactivated first.
    ``half_dtype`` ("bfloat16" | "float16") sets the type the half cast
    lists cast to — threaded from the frontend's ``cast_model_type`` so
    fp16 is honored on the patched-O1 path too."""
    global _current_handle
    if _current_handle is not None:
        _current_handle._deactivate()
    # default restores bf16 — a prior fp16 init must not leak into later
    # plain init() calls (the half type is a module global in wrap)
    from apex_tpu.amp.wrap import set_half_dtype
    set_half_dtype(half_dtype if half_dtype is not None else "bfloat16")
    handle = AmpHandle(verbose=verbose)
    if not enabled:
        handle.is_active = False
        return handle

    try:
        import torch
        import torch.nn.functional as F

        from apex_tpu.amp.lists import (
            functional_overrides,
            tensor_overrides,
            torch_overrides,
        )

        _apply_lists(handle, torch, torch_overrides)
        _apply_lists(handle, torch.Tensor, tensor_overrides)
        _apply_lists(handle, F, functional_overrides)

        # RNN family: nn.{RNN,GRU,LSTM}/*Cell dispatch through _VF, not
        # the public namespaces above (reference: new_rnn_cast)
        from apex_tpu.amp import rnn_compat
        rnn_compat.whitelist_rnn_cells(handle, verbose)

        for module, name, category in _USER_REGISTRY:
            if isinstance(module, str):
                module = importlib.import_module(module)
            if not hasattr(module, name):
                continue
            orig = getattr(module, name)
            if category == "half":
                handle._patch(module, name, make_cast_wrapper(
                    orig, True, _get_cache, _is_active))
            elif category == "float":
                handle._patch(module, name, make_cast_wrapper(
                    orig, False, _get_cache, _is_active))
            else:
                handle._patch(module, name, make_promote_wrapper(
                    orig, _is_active))
    except Exception:
        # failed half-way: restore everything, don't leak a live handle
        handle._deactivate()
        raise
    # publish only once fully patched
    _current_handle = handle
    return handle


# ---- user decorators (usable on torch or jax functions) -------------------

def half_function(fn):
    """Run ``fn`` with all floating args cast to bf16 while amp is active."""
    return make_cast_wrapper(fn, True, _get_cache, _is_active)


def float_function(fn):
    """Run ``fn`` with all floating args cast to fp32 while amp is active."""
    return make_cast_wrapper(fn, False, _get_cache, _is_active)


def promote_function(fn):
    """Run ``fn`` with floating args promoted to their widest dtype."""
    return make_promote_wrapper(fn, _is_active)


# ---- pre-initialize registration (reference API) --------------------------

def register_half_function(module, name: str) -> None:
    _USER_REGISTRY.append((module, name, "half"))


def register_float_function(module, name: str) -> None:
    _USER_REGISTRY.append((module, name, "float"))


def register_promote_function(module, name: str) -> None:
    _USER_REGISTRY.append((module, name, "promote"))
