"""O1 function-wrapping machinery (reference: ``apex/amp/wrap.py``).

Works over BOTH torch tensors (the CPU parity shim) and jax arrays (the
TPU path): the cast helpers dispatch on type.  The weight-cast cache is
the reference's ``cached_cast`` — casting an fp32 *leaf* (parameter) to
bf16 is memoized per iteration so every consumer of the same weight in a
step reuses one cast (and one autograd cast-node); the handle clears the
cache when the loss scaler updates.
"""
from __future__ import annotations

import functools
from typing import Optional

__all__ = [
    "cached_cast", "make_cast_wrapper", "make_promote_wrapper",
    "make_sequence_promote_wrapper", "make_inplace_promote_wrapper",
]


def _torch():
    import torch
    return torch


def _is_arraylike(x) -> bool:
    """Cheap pre-filter so plain ints/floats/strings passed to patched
    ops never reach the jax branch (the torch-only O1 path must not
    hard-require jax at call time)."""
    return hasattr(x, "dtype") and hasattr(x, "ndim")


def _is_fp_tensor(x) -> bool:
    try:
        torch = _torch()
        if isinstance(x, torch.Tensor):
            return x.is_floating_point()
    except ImportError:  # pragma: no cover
        pass
    if not _is_arraylike(x):
        return False
    try:
        import jax.numpy as jnp
    except ImportError:  # pragma: no cover
        return False
    return jnp.issubdtype(getattr(x, "dtype", None), jnp.floating)


#: the O1 half type ("bfloat16" | "float16"), set by ``amp.init`` /
#: ``set_half_dtype`` from the frontend's ``cast_model_type``; bf16 is
#: the TPU-native default, fp16 the reference-exact regime.
_HALF_NAME = "bfloat16"


def set_half_dtype(name: str) -> None:
    if name not in ("bfloat16", "float16"):
        raise ValueError(
            f"O1 half dtype must be 'bfloat16' or 'float16', got {name!r}")
    global _HALF_NAME
    _HALF_NAME = name


def _to_dtype(x, want_half: bool):
    """Cast a floating tensor/array to the 16-bit or fp32 type."""
    try:
        torch = _torch()
        if isinstance(x, torch.Tensor):
            half = getattr(torch, _HALF_NAME)
            return x.to(half if want_half else torch.float32)
    except ImportError:  # pragma: no cover
        pass
    if not _is_arraylike(x):
        return x
    try:
        import jax.numpy as jnp
    except ImportError:  # pragma: no cover
        return x
    half = getattr(jnp, _HALF_NAME)
    return x.astype(half if want_half else jnp.float32)


def _is_half(x) -> bool:
    """True iff ``x`` is already the SELECTED half type — under the fp16
    regime a bf16 tensor must still be cast (mixed fp16/bf16 matmuls
    error in torch and silently betray the selected regime in jax)."""
    try:
        torch = _torch()
        if isinstance(x, torch.Tensor):
            return x.dtype == getattr(torch, _HALF_NAME)
    except ImportError:  # pragma: no cover
        pass
    if not _is_arraylike(x):
        return False
    try:
        import jax.numpy as jnp
    except ImportError:  # pragma: no cover
        return False
    return x.dtype == getattr(jnp, _HALF_NAME)


def _cast_like(x, ref):
    """Cast ``x`` to ``ref``'s exact dtype (torch or jax)."""
    try:
        torch = _torch()
        if isinstance(x, torch.Tensor):
            return x.to(ref.dtype)
    except ImportError:  # pragma: no cover
        pass
    return x.astype(ref.dtype)


def cached_cast(x, want_half: bool, cache: Optional[dict]):
    """Cast one tensor, memoizing leaf-parameter casts in ``cache``
    (reference: ``wrap.py :: cached_cast``).  Cache hits verify identity —
    a replaced parameter with a recycled ``id`` misses cleanly."""
    if not _is_fp_tensor(x):
        return x
    if _is_half(x) == want_half:
        return x
    cacheable = False
    try:
        torch = _torch()
        cacheable = (cache is not None and isinstance(x, torch.Tensor)
                     and x.requires_grad and x.is_leaf)
    except ImportError:  # pragma: no cover
        pass
    if cacheable:
        key = id(x)
        hit = cache.get(key)
        if hit is not None and hit[0] is x:
            return hit[1]
        y = _to_dtype(x, want_half)
        cache[key] = (x, y)
        return y
    return _to_dtype(x, want_half)


def _map_structure(obj, fn):
    if isinstance(obj, (list, tuple)):
        return type(obj)(_map_structure(o, fn) for o in obj)
    if isinstance(obj, dict):
        return {k: _map_structure(v, fn) for k, v in obj.items()}
    return fn(obj)


def make_cast_wrapper(orig, want_half: bool, get_cache, is_active):
    """Wrap ``orig`` to cast all floating args to bf16 (half list) or
    fp32 (float list) while amp is active."""

    @functools.wraps(orig)
    def wrapper(*args, **kwargs):
        if not is_active():
            return orig(*args, **kwargs)
        cache = get_cache()
        cast = lambda x: cached_cast(x, want_half, cache)  # noqa: E731
        args = _map_structure(list(args), cast)
        kwargs = _map_structure(kwargs, cast)
        return orig(*args, **kwargs)

    wrapper._amp_original = orig
    return wrapper


def _widest_is_fp32(tensors) -> bool:
    return any(not _is_half(t) for t in tensors)


def make_promote_wrapper(orig, is_active):
    """Wrap a multi-arg op to promote every floating arg to the widest
    floating dtype among them (reference promote semantics: any fp32
    operand promotes the op to fp32)."""

    @functools.wraps(orig)
    def wrapper(*args, **kwargs):
        if not is_active():
            return orig(*args, **kwargs)
        fps = [a for a in args if _is_fp_tensor(a)]
        fps += [v for v in kwargs.values() if _is_fp_tensor(v)]
        if len(fps) < 2 or not _widest_is_fp32(fps):
            return orig(*args, **kwargs)
        cast = lambda x: cached_cast(x, False, None)  # noqa: E731
        args = _map_structure(list(args), cast)
        kwargs = _map_structure(kwargs, cast)
        return orig(*args, **kwargs)

    wrapper._amp_original = orig
    return wrapper


def make_inplace_promote_wrapper(orig, is_active):
    """Wrap an in-place tensor method (``__iadd__`` etc.).

    In-place ops mutate arg0's storage, so arg0's dtype wins: the OTHER
    floating args are cast to self's dtype and self is left untouched
    (reference: ``apex/amp/wrap.py :: promote_match_arg0`` semantics for
    in-place methods).  Promoting self instead would allocate a NEW
    tensor — ``x += y`` would rebind ``x`` and every other alias of the
    original storage (e.g. a module parameter) would silently stop
    seeing updates."""

    @functools.wraps(orig)
    def wrapper(self_, *args, **kwargs):
        if not is_active() or not _is_fp_tensor(self_):
            return orig(self_, *args, **kwargs)
        cast = lambda x: (_cast_like(x, self_)  # noqa: E731
                          if _is_fp_tensor(x) else x)
        args = _map_structure(list(args), cast)
        kwargs = _map_structure(kwargs, cast)
        return orig(self_, *args, **kwargs)

    wrapper._amp_original = orig
    return wrapper


def make_sequence_promote_wrapper(orig, is_active):
    """Wrap cat/stack-style ops: promote the tensors INSIDE the first
    (sequence) argument together."""

    @functools.wraps(orig)
    def wrapper(seq, *args, **kwargs):
        if not is_active():
            return orig(seq, *args, **kwargs)
        tensors = [t for t in seq if _is_fp_tensor(t)]
        if tensors and _widest_is_fp32(tensors):
            seq = type(seq)(
                cached_cast(t, False, None) if _is_fp_tensor(t) else t
                for t in seq)
        return orig(seq, *args, **kwargs)

    wrapper._amp_original = orig
    return wrapper
