"""apex_tpu.amp — automatic mixed precision (reference: ``apex/amp``).

Public surface parity: ``initialize``, ``scale_loss``, ``master_params``,
``state_dict``, ``load_state_dict`` plus the functional scaler API that the
TPU path uses inside jitted train steps (:mod:`apex_tpu.amp.scaler`).
"""
from apex_tpu.amp.frontend import (
    AmpOptimizer,
    Properties,
    initialize,
    load_state_dict,
    master_params,
    opt_levels,
    state_dict,
)
from apex_tpu.amp.amp import (
    float_function,
    half_function,
    init,
    promote_function,
    register_float_function,
    register_half_function,
    register_promote_function,
)
from apex_tpu.amp.handle import scale_loss
from apex_tpu.amp.scaler import (
    LossScaler,
    LossScaleState,
    init_loss_scale,
    scale_loss_value,
    unscale_grads,
    update_scale,
)

__all__ = [
    "AmpOptimizer", "Properties", "initialize", "load_state_dict",
    "master_params", "opt_levels", "state_dict", "scale_loss",
    "LossScaler", "LossScaleState", "init_loss_scale", "scale_loss_value",
    "unscale_grads", "update_scale",
    "init", "half_function", "float_function", "promote_function",
    "register_half_function", "register_float_function",
    "register_promote_function",
]
