"""O1 cast lists for the ``torch`` namespace (reference:
``apex/amp/lists/torch_overrides.py``)."""

# matmul/conv family -> 16-bit (MXU-shaped work)
FP16_FUNCS = [
    "conv1d", "conv2d", "conv3d",
    "conv_transpose1d", "conv_transpose2d", "conv_transpose3d",
    "conv_tbc",
    "matmul", "mm", "mv", "bmm",
    "addmm", "addmv", "addr", "addbmm", "baddbmm",
    "prelu",
]

# precision-sensitive -> fp32
FP32_FUNCS = [
    "acos", "asin", "cosh", "erfinv", "exp", "expm1",
    "log", "log10", "log1p", "log2", "reciprocal", "rsqrt",
    "sinh", "tan",
    "pow",
    "softmax", "log_softmax",
    "cumprod", "cumsum", "prod", "sum",
    "dist", "norm", "renorm",
    "cosine_similarity",
]

# multi-arg ops -> widest dtype among the args
CASTS = [
    "add", "addcdiv", "addcmul", "atan2", "bilinear", "cross", "div",
    "dot", "fmod", "mul", "sub",
    "eq", "equal", "ge", "gt", "le", "lt", "ne",
    "min", "max",
]

# first arg is a sequence of tensors, promoted together
SEQUENCE_CASTS = ["cat", "stack"]
