"""O1 cast lists for the ``torch`` namespace (reference:
``apex/amp/lists/torch_overrides.py``)."""

# matmul/conv family -> 16-bit (MXU-shaped work).  einsum's equation
# string is not a tensor, so the generic cast wrapper passes it through
# and half-casts only the operands (reference parity for the
# tensor-varargs einsum signature).
FP16_FUNCS = [
    "conv1d", "conv2d", "conv3d",
    "conv_transpose1d", "conv_transpose2d", "conv_transpose3d",
    "conv_tbc",
    "matmul", "mm", "mv", "bmm",
    "addmm", "addmv", "addr", "addbmm", "baddbmm",
    "prelu",
    "einsum",
]

# precision-sensitive -> fp32
FP32_FUNCS = [
    "acos", "asin", "cosh", "erfinv", "exp", "expm1",
    "log", "log10", "log1p", "log2", "reciprocal", "rsqrt",
    "sinh", "tan",
    "pow",
    "softmax", "log_softmax",
    "cumprod", "cumsum", "prod", "sum",
    "mean", "std", "var",
    "dist", "norm", "renorm",
    "cosine_similarity",
]

# RNN-family dispatch targets on ``torch.nn.modules.rnn._VF`` — the
# point every ``nn.{RNN,GRU,LSTM}`` forward and ``*Cell`` call funnels
# through in modern torch (reference: ``rnn_cast``/``new_rnn_cast`` on
# the legacy THNN backend).  Patched by
# ``rnn_compat.whitelist_rnn_cells``, not ``_apply_lists`` (the target
# module is resolved at init time, and the packed-sequence overloads
# share these names).
RNN_CAST_FUNCS = [
    "rnn_tanh", "rnn_relu", "lstm", "gru",
    "rnn_tanh_cell", "rnn_relu_cell", "lstm_cell", "gru_cell",
]

# multi-arg ops -> widest dtype among the args
CASTS = [
    "add", "addcdiv", "addcmul", "atan2", "bilinear", "cross", "div",
    "dot", "fmod", "mul", "sub",
    "eq", "equal", "ge", "gt", "le", "lt", "ne",
    "min", "max",
]

# first arg is a sequence of tensors, promoted together
SEQUENCE_CASTS = ["cat", "stack"]
