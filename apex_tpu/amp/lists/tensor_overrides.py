"""O1 cast lists for ``torch.Tensor`` methods (reference:
``apex/amp/lists/tensor_overrides.py``)."""

FP16_FUNCS = [
    "__matmul__",
    "matmul", "mm", "mv", "bmm",
    "addmm", "addmv", "addr", "addbmm", "baddbmm",
]

FP32_FUNCS = [
    "acos", "asin", "cosh", "erfinv", "exp", "expm1",
    "log", "log10", "log1p", "log2", "reciprocal", "rsqrt",
    "sinh", "tan",
    "pow", "__pow__", "__rpow__",
    "softmax", "log_softmax",
    "cumprod", "cumsum", "prod", "sum",
    "mean", "std", "var",
    "dist", "norm", "renorm",
]

CASTS = [
    "__add__", "__div__", "__eq__", "__ge__", "__gt__", "__le__",
    "__lt__", "__mul__", "__ne__", "__radd__", "__rdiv__", "__rmul__",
    "__rsub__", "__rtruediv__", "__sub__", "__truediv__",
    "add", "addcdiv", "addcmul", "atan2", "div", "dot", "fmod", "mul",
    "sub",
]

# In-place methods mutate arg0's storage: the other args are cast to
# arg0's dtype (promote_match_arg0), never arg0 itself — a widest-dtype
# promote would rebind instead of mutate and break parameter aliasing.
# The named ``*_`` forms are the reference's ``as_inplace`` expansion of
# the promote list.
INPLACE_CASTS = [
    "__iadd__", "__idiv__", "__imul__", "__isub__", "__itruediv__",
    "add_", "sub_", "mul_", "div_",
    "addcdiv_", "addcmul_", "atan2_", "fmod_",
]

SEQUENCE_CASTS = []
