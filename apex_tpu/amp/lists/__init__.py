"""Per-namespace O1 cast lists (reference: ``apex/amp/lists/``).

Three categories per namespace, mirroring the reference's registry:

* ``FP16_FUNCS`` — run in the 16-bit type (bf16 on TPU/CPU): the
  MXU-friendly matmul/conv family where reduced precision is free accuracy
  and maximal throughput.
* ``FP32_FUNCS`` — numerically sensitive ops (transcendentals, softmax,
  norms, losses, big reductions) always run fp32.
* ``CASTS`` — multi-arg ops promoted to the widest floating dtype among
  their args; ``SEQUENCE_CASTS`` take a sequence first-arg (cat/stack);
  ``INPLACE_CASTS`` mutate arg0's storage, so the OTHER args cast to
  arg0's dtype (the reference's ``promote_match_arg0`` semantics).

Names are strings resolved with ``hasattr`` at patch time so the lists
stay valid across torch versions.

Intentional deltas vs the reference tables (everything else is parity;
tests/L0/run_amp/test_patch_lists.py pins each category end to end):

* **Half type is bf16 by default**, fp16 via ``half_dtype`` — the
  reference is fp16-only.  Consequence: the reference's CUDA-9.1 gate
  that demotes ``bmm``/``addbmm``/``baddbmm`` to fp32 on old toolkits
  has no analog; the batched matmuls are unconditionally 16-bit here
  (every supported backend has fast bf16 matmul).
* **RNN-family casts patch ``torch.nn.modules.rnn._VF``** (the modern
  dispatch point ``nn.LSTM``/``GRU``/``RNN`` and the ``*Cell`` modules
  call) via ``rnn_compat.whitelist_rnn_cells``; the reference's legacy
  ``torch.nn.backends.thnn`` backend wrapping (``rnn_cast``) targets a
  torch that no longer exists and stays tombstoned.
* **No banned-function error wrappers**: the reference plants loud
  errors on in-place blacklist ops (``err_if_any_half``); here the
  in-place surface uses match-arg0 promotion instead — an in-place op
  never silently rebinds, so the failure mode those errors guarded
  against (alias divergence) cannot occur.
* ``einsum`` rides the plain half-cast wrapper (the equation string
  passes through the cast untouched); the reference routes it through a
  bespoke handler for torch versions whose einsum took a sequence arg.
"""
from apex_tpu.amp.lists import (  # noqa: F401
    functional_overrides,
    tensor_overrides,
    torch_overrides,
)
