"""Per-namespace O1 cast lists (reference: ``apex/amp/lists/``).

Three categories per namespace, mirroring the reference's registry:

* ``FP16_FUNCS`` — run in the 16-bit type (bf16 on TPU/CPU): the
  MXU-friendly matmul/conv family where reduced precision is free accuracy
  and maximal throughput.
* ``FP32_FUNCS`` — numerically sensitive ops (transcendentals, softmax,
  norms, losses, big reductions) always run fp32.
* ``CASTS`` — multi-arg ops promoted to the widest floating dtype among
  their args; ``SEQUENCE_CASTS`` take a sequence first-arg (cat/stack).

Names are strings resolved with ``hasattr`` at patch time so the lists
stay valid across torch versions.
"""
from apex_tpu.amp.lists import (  # noqa: F401
    functional_overrides,
    tensor_overrides,
    torch_overrides,
)
