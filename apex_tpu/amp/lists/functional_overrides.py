"""O1 cast lists for ``torch.nn.functional`` (reference:
``apex/amp/lists/functional_overrides.py``)."""

MODULE = "torch.nn.functional"

FP16_FUNCS = [
    "conv1d", "conv2d", "conv3d",
    "conv_transpose1d", "conv_transpose2d", "conv_transpose3d",
    "conv_tbc",
    "linear",
]

FP32_FUNCS = [
    "softmax", "log_softmax",
    "layer_norm", "group_norm", "local_response_norm", "normalize",
    "softplus", "softmin", "gelu", "tanh",
    "cosine_similarity",
    "poisson_nll_loss", "cosine_embedding_loss", "cross_entropy",
    "hinge_embedding_loss", "kl_div", "l1_loss", "mse_loss",
    "margin_ranking_loss", "multilabel_margin_loss", "multi_margin_loss",
    "nll_loss", "smooth_l1_loss", "soft_margin_loss",
    "triplet_margin_loss",
]

CASTS = []

SEQUENCE_CASTS = []
