"""RNN compatibility shims for O1 patching (reference:
``apex/amp/rnn_compat.py`` — wraps torch's RNN backend so
patched-function autocast reaches RNN cells).

The reference targets two backends: the legacy THNN factories
(``torch.nn.backends.thnn``, ``rnn_cast``) and the ``_rnn_impls`` /
``_VF`` dispatch table (``new_rnn_cast``).  The THNN surface this
rebuild's torch no longer ships stays tombstoned
(:func:`has_old_rnns` is always False, see ``apex_tpu/RNN``); the
modern equivalent — every ``nn.{RNN,GRU,LSTM}`` forward and ``*Cell``
call funnels through ``torch.nn.modules.rnn._VF`` — IS patched:
:func:`whitelist_rnn_cells` wraps the names in
``torch_overrides.RNN_CAST_FUNCS`` with the standard half-cast wrapper.
The flat weight lists are nested sequences of leaf parameters, which
the cast wrapper maps structurally and memoizes per-parameter in the
handle's cache — the reference's ``cached_cast``-inside-``rnn_cast``
behavior, for free.
"""
from __future__ import annotations

__all__ = ["has_old_rnns", "has_vf_rnns", "whitelist_rnn_cells"]


def has_old_rnns() -> bool:
    """The legacy torch THNN RNN backend the reference patches does not
    exist on this stack (reference probes ``torch.nn.backends.thnn``)."""
    return False


def _vf_module():
    try:
        import torch.nn.modules.rnn as rnn_mod
    except ImportError:  # pragma: no cover — torch absent
        return None
    return getattr(rnn_mod, "_VF", None)


def has_vf_rnns() -> bool:
    """True when the modern ``_VF`` RNN dispatch point is patchable."""
    vf = _vf_module()
    return vf is not None and hasattr(vf, "lstm")


def whitelist_rnn_cells(handle, verbose: bool = False) -> None:
    """Register half casts on the RNN-family ``_VF`` entry points
    (reference: ``new_rnn_cast``), through ``handle._patch`` so
    ``_deactivate`` restores the originals."""
    from apex_tpu.amp.amp import _get_cache, _is_active
    from apex_tpu.amp.lists.torch_overrides import RNN_CAST_FUNCS
    from apex_tpu.amp.wrap import make_cast_wrapper

    vf = _vf_module()
    if vf is None:
        if verbose:
            print("apex_tpu.amp.rnn_compat: no RNN backend to patch")
        return
    for name in RNN_CAST_FUNCS:
        if not hasattr(vf, name):
            continue
        handle._patch(vf, name, make_cast_wrapper(
            getattr(vf, name), True, _get_cache, _is_active))
        if verbose:
            print(f"apex_tpu.amp.rnn_compat: half-casting _VF.{name}")
