"""RNN compatibility shims for O1 patching (reference:
``apex/amp/rnn_compat.py`` — wraps torch's legacy RNN backend factories so
patched-function autocast reaches RNN cells).

The legacy fused-RNN surface this patched (``apex.RNN``) is deprecated in
the reference and tombstoned here (see ``apex_tpu/RNN``); modern recurrent
models run through scan + the patched functional ops, which O1 already
covers.  The module keeps the reference's probe helper so callers can
feature-test it.
"""
from __future__ import annotations

__all__ = ["has_old_rnns", "whitelist_rnn_cells"]


def has_old_rnns() -> bool:
    """The legacy torch RNN backend the reference patches does not exist
    on this stack (reference probes ``torch.nn.backends.thnn``)."""
    return False


def whitelist_rnn_cells(handle, verbose: bool = False) -> None:
    """No-op: RNN cells route through already-patched functional ops
    (reference registers fp16 casts on the legacy cell backends)."""
    if verbose:
        print("apex_tpu.amp.rnn_compat: no legacy RNN backend to patch")
