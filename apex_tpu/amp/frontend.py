"""amp frontend — opt-level Properties + initialize.

Reference: ``apex/amp/frontend.py :: initialize, Properties`` and the
O0..O3 opt-level classes.  The property matrix is kept verbatim; the only
TPU-native change is that "fp16" defaults to bfloat16 (the MXU-native 16-bit
type; fp16 is still selectable via ``cast_model_type=jnp.float16``).

Two entry paths:
* **JAX path** (the performance path): ``initialize(params, optimizer, ...)``
  with a params pytree and an ``apex_tpu.optimizers`` instance — returns
  (cast params, :class:`AmpOptimizer`) where the wrapper owns the loss scaler
  and plumbs overflow-skip into the fused update kernels.
* **torch path** (CPU parity for ``examples/imagenet/main_amp.py``): when
  given a ``torch.nn.Module`` the call dispatches to
  :mod:`apex_tpu.amp._torch_shim`.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp import _amp_state
from apex_tpu.amp.scaler import (
    LossScaler, init_loss_scale, scale_loss_value, unscale_grads,
    update_scale)

__all__ = ["Properties", "opt_levels", "initialize", "AmpOptimizer",
           "state_dict", "load_state_dict", "master_params"]


class Properties:
    """Mutable options bag (parity: ``apex/amp/frontend.py :: Properties``)."""

    def __init__(self):
        self.options = {
            "enabled": False,
            "opt_level": None,
            "cast_model_type": None,
            "patch_torch_functions": False,
            "keep_batchnorm_fp32": None,
            "master_weights": None,
            "loss_scale": 1.0,
        }

    def __getattr__(self, name):
        if "options" in self.__dict__ and name in self.__dict__["options"]:
            return self.__dict__["options"][name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if "options" in self.__dict__ and name in self.__dict__["options"]:
            self.__dict__["options"][name] = value
        else:
            super().__setattr__(name, value)

    def _update(self, **kw):
        for k, v in kw.items():
            if v is not None:
                self.options[k] = v
        return self


class O3:
    brief = "O3: pure 16-bit training."

    def __call__(self, properties: Properties) -> Properties:
        return properties._update(
            enabled=True, opt_level="O3", cast_model_type=jnp.bfloat16,
            patch_torch_functions=False, keep_batchnorm_fp32=False,
            master_weights=False, loss_scale=1.0)


class O2:
    brief = "O2: 16-bit model + fp32 master weights + dynamic loss scaling."

    def __call__(self, properties: Properties) -> Properties:
        return properties._update(
            enabled=True, opt_level="O2", cast_model_type=jnp.bfloat16,
            patch_torch_functions=False, keep_batchnorm_fp32=True,
            master_weights=True, loss_scale="dynamic")


class O1:
    brief = "O1: autocast around compute-bound ops + dynamic loss scaling."

    def __call__(self, properties: Properties) -> Properties:
        return properties._update(
            enabled=True, opt_level="O1", cast_model_type=None,
            patch_torch_functions=True, keep_batchnorm_fp32=None,
            master_weights=None, loss_scale="dynamic")


class O0:
    brief = "O0: pure fp32 training."

    def __call__(self, properties: Properties) -> Properties:
        return properties._update(
            enabled=True, opt_level="O0", cast_model_type=jnp.float32,
            patch_torch_functions=False, keep_batchnorm_fp32=None,
            master_weights=False, loss_scale=1.0)


opt_levels = {"O3": O3(), "O2": O2(), "O1": O1(), "O0": O0()}


def _is_torch_module(model) -> bool:
    try:
        import torch
        if isinstance(model, (list, tuple)) and model:
            return all(isinstance(m, torch.nn.Module) for m in model)
        return isinstance(model, torch.nn.Module)
    except ImportError:  # pragma: no cover
        return False


class AmpOptimizer:
    """Loss-scaling optimizer wrapper produced by :func:`initialize`.

    Composes an ``apex_tpu.optimizers`` instance with a
    :class:`~apex_tpu.amp.scaler.LossScaler`: ``step(scaled_grads)`` unscales
    with fused overflow detection, applies the update with the overflow
    ``noop_flag`` predicated into the kernel, then runs the dynamic-scale
    schedule — the whole reference ``scale_loss``-exit + patched
    ``optimizer.step`` flow (SURVEY §3.1) with no host sync.
    """

    def __init__(self, optimizer, properties: Properties, num_losses=1,
                 min_loss_scale=None, max_loss_scale=2.0 ** 24):
        self._optimizer = optimizer
        self._properties = properties
        # one scaler per loss (parity: amp's per-loss_id LossScalers)
        self.loss_scalers = [
            LossScaler(properties.loss_scale, min_loss_scale=min_loss_scale,
                       max_loss_scale=max_loss_scale)
            for _ in range(num_losses)]
        self._last_found_inf = None

    @property
    def inner(self):
        return self._optimizer

    @property
    def param_groups(self):
        return self._optimizer.param_groups

    @property
    def loss_scaler(self):
        return self.loss_scalers[0]

    def scale(self, loss, loss_id=0):
        return scale_loss_value(loss, self.loss_scalers[loss_id].state)

    def scale_value(self, loss_id=0) -> float:
        return self.loss_scalers[loss_id].loss_scale()

    def step(self, scaled_grads, loss_id=0, **kw):
        scaler = self.loss_scalers[loss_id]
        st = scaler.state
        grads, st = unscale_grads(scaled_grads, st)
        params = self._optimizer.step(grads, noop_flag=st.found_inf, **kw)
        # device array kept lazily; reading .last_step_skipped syncs, step()
        # itself never does (the no-host-sync contract).
        self._last_found_inf = st.found_inf
        scaler.state = update_scale(
            st, min_scale=scaler._min_scale, max_scale=scaler._max_scale)
        return params

    @property
    def _last_step_skipped(self) -> bool:
        if self._last_found_inf is None:
            return False
        return bool(self._last_found_inf > 0)

    last_step_skipped = _last_step_skipped

    def zero_grad(self, set_to_none: bool = True):
        self._optimizer.zero_grad(set_to_none)

    def state_dict(self):
        return {"optimizer": self._optimizer.state_dict(),
                "loss_scaler": self.loss_scalers[0].state_dict(),
                "loss_scalers": [s.state_dict() for s in self.loss_scalers]}

    def load_state_dict(self, sd):
        self._optimizer.load_state_dict(sd["optimizer"])
        if "loss_scalers" in sd:
            for s, ssd in zip(self.loss_scalers, sd["loss_scalers"]):
                s.load_state_dict(ssd)
        else:
            self.loss_scalers[0].load_state_dict(sd["loss_scaler"])


def _cast_params(params, dtype, keep_fp32_names=()):
    """Cast a params pytree to ``dtype``, keeping fp32 for matching names."""
    if dtype is None:
        return params

    def cast(path, x):
        name = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                        for p in path).lower()
        if any(k in name for k in keep_fp32_names):
            return x
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map_with_path(cast, params)


def initialize(models, optimizers=None, enabled=True, opt_level="O1",
               cast_model_type=None, patch_torch_functions=None,
               keep_batchnorm_fp32=None, master_weights=None,
               loss_scale=None, cast_model_outputs=None, num_losses=1,
               verbosity=1, min_loss_scale=None, max_loss_scale=2.0 ** 24):
    """Configure mixed precision (parity: ``apex.amp.initialize``).

    JAX path: ``models`` is a params pytree; returns ``(params, optimizer)``
    with params cast per the opt level and the optimizer wrapped in
    :class:`AmpOptimizer`.  torch path: ``models`` is a ``torch.nn.Module``
    (CPU parity shim).

    ``cast_model_outputs`` wraps each torch model's forward to cast
    floating outputs to the given dtype regardless of opt level
    (reference contract).  On the JAX path it has no effect: initialize
    only sees the params pytree, not the apply function — cast outputs
    at the loss boundary instead (the examples' ``.float()`` pattern).
    """
    if not enabled:
        return (models, optimizers) if optimizers is not None else models
    if opt_level not in opt_levels:
        raise RuntimeError(f"Unexpected optimization level {opt_level}")

    # argparse-style string bools (the reference maps these explicitly;
    # main_amp.py passes --keep-batchnorm-fp32 "False" as a string)
    def _to_bool(v, name):
        if isinstance(v, str):
            if v == "True":
                return True
            if v == "False":
                return False
            raise RuntimeError(f"{name} must be True/False or a bool, got "
                               f"{v!r}")
        return v

    keep_batchnorm_fp32 = _to_bool(keep_batchnorm_fp32,
                                   "keep_batchnorm_fp32")
    master_weights = _to_bool(master_weights, "master_weights")
    if isinstance(loss_scale, str) and loss_scale != "dynamic":
        loss_scale = float(loss_scale)

    props = opt_levels[opt_level](Properties())
    props._update(cast_model_type=cast_model_type,
                  patch_torch_functions=patch_torch_functions,
                  keep_batchnorm_fp32=keep_batchnorm_fp32,
                  master_weights=master_weights,
                  loss_scale=loss_scale)
    _amp_state.amp_state.opt_properties = props
    _amp_state.amp_state.verbosity = verbosity

    if _is_torch_module(models):
        from apex_tpu.amp import _torch_shim
        return _torch_shim.initialize_torch(
            models, optimizers, props, num_losses=num_losses,
            min_loss_scale=min_loss_scale, max_loss_scale=max_loss_scale,
            cast_model_outputs=cast_model_outputs)

    # JAX path: params pytree (+ apex_tpu optimizer)
    keep = ("batchnorm", "bn") if props.keep_batchnorm_fp32 else ()
    cast = None if props.opt_level == "O1" else props.cast_model_type
    params = _cast_params(models, cast, keep)
    if optimizers is None:
        return params
    wrapped = AmpOptimizer(optimizers, props, num_losses=num_losses,
                           min_loss_scale=min_loss_scale,
                           max_loss_scale=max_loss_scale)
    _amp_state.amp_state.loss_scalers = list(wrapped.loss_scalers)
    _amp_state.amp_state.optimizers = [wrapped]
    return params, wrapped


def master_params(optimizer):
    """Iterate per-parameter fp32 master arrays (parity:
    ``amp.master_params``, e.g. for ``clip_grad_norm_(amp.master_params(opt),
    ...)``).  Works for both the JAX optimizers and the torch shim."""
    inner = getattr(optimizer, "inner", optimizer)
    groups = getattr(inner, "param_groups", None)
    if groups and isinstance(groups[0], dict):  # torch optimizer
        for g in groups:
            yield from g["params"]
        return
    for group in groups:
        for off, size, shape in zip(group.offsets, group.sizes,
                                    group.shapes):
            yield jax.lax.dynamic_slice_in_dim(
                group.master, off, size).reshape(shape)


def state_dict(destination=None):
    """Persist loss-scaler state (parity: ``amp.state_dict``)."""
    d = destination if destination is not None else {}
    for i, s in enumerate(getattr(_amp_state.amp_state, "loss_scalers", [])):
        d[f"loss_scaler{i}"] = s.state_dict()
    return d


def load_state_dict(state):
    scalers = getattr(_amp_state.amp_state, "loss_scalers", [])
    for i, s in enumerate(scalers):
        key = f"loss_scaler{i}"
        if key in state:
            s.load_state_dict(state[key])
