"""Process-global amp state (parity: ``apex/amp/_amp_state.py``)."""
from __future__ import annotations


class AmpState:
    def __init__(self):
        self.hard_override = False
        self.allow_incoming_model_not_fp32 = False
        self.verbosity = 1
        self.opt_properties = None
        self.loss_scalers: list = []
        self.optimizers: list = []


amp_state = AmpState()


def maybe_print(msg: str, rank0: bool = False) -> None:
    if amp_state.verbosity > 0:
        print(msg)


def warn_or_err(msg: str) -> None:
    if amp_state.hard_override:
        print("Warning: " + msg)
    else:
        raise RuntimeError(msg + "  If you're sure you know what you're "
                           "doing, supply hard_override=True to "
                           "amp.initialize.")
