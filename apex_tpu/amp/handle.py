"""``amp.scale_loss`` context manager (parity: ``apex/amp/handle.py``).

torch path: yields ``loss * scale``; on ``__exit__`` unscales the grads
sitting on the optimizer's params, detects overflow, and arms the patched
``optimizer.step`` to skip (the reference flow).

JAX path: yields the scaled loss value.  Gradient unscaling happens inside
``AmpOptimizer.step`` (functional grads are explicit), so exit is a no-op —
the ctx manager exists for source-level API parity.
"""
from __future__ import annotations

import contextlib

from apex_tpu.amp import _amp_state

__all__ = ["scale_loss"]


@contextlib.contextmanager
def scale_loss(loss, optimizers, loss_id=0, model=None, delay_unscale=False,
               delay_overflow_check=False):
    try:
        import torch
        is_torch = isinstance(loss, torch.Tensor)
    except ImportError:  # pragma: no cover
        is_torch = False

    if is_torch:
        from apex_tpu.amp._torch_shim import torch_scale_loss
        with torch_scale_loss(loss, optimizers, loss_id=loss_id,
                              delay_unscale=delay_unscale) as scaled:
            yield scaled
        return

    # JAX path
    opt = optimizers[0] if isinstance(optimizers, (list, tuple)) \
        else optimizers
    if hasattr(opt, "scale"):
        yield opt.scale(loss, loss_id=loss_id)
    else:
        yield loss
