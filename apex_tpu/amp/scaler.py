"""Loss scaling — functional core + imperative parity wrapper.

Reference: ``apex/amp/scaler.py :: LossScaler`` with the classic dynamic
schedule — init scale 2**16, x2 growth every 2000 clean steps, x0.5 backoff
on overflow — and ``_has_inf_or_nan`` overflow detection.

TPU-native design: the scaler is a pytree (``LossScaleState``) carried
through the jitted train step; overflow detection is the fused non-finite
flag from :func:`apex_tpu.ops.fused_update.fused_scale` (no device→host
sync, the classic CUDA perf trap called out in SURVEY §3.1); skip-on-overflow
is the ``noop_flag`` predicate inside the fused optimizer kernel.
"""
from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp

from apex_tpu.ops.fused_update import fused_scale
from apex_tpu.utils import tree_ravel

__all__ = ["LossScaleState", "init_loss_scale", "scale_loss_value",
           "unscale_grads", "unscale_flat_grads",
           "nonfinite_leaf_counts", "update_scale", "LossScaler"]

# Reference constants (apex/amp/scaler.py)
DEFAULT_INIT_SCALE = 2.0 ** 16
DEFAULT_GROWTH_FACTOR = 2.0
DEFAULT_BACKOFF_FACTOR = 0.5
DEFAULT_GROWTH_INTERVAL = 2000
MAX_SCALE = 2.0 ** 24
MIN_SCALE = 1.0


@flax.struct.dataclass
class LossScaleState:
    """Jit-carried scaler state (pytree; ``dynamic`` is static aux data)."""
    loss_scale: jax.Array          # f32 scalar
    growth_tracker: jax.Array      # i32 scalar: clean steps since last growth
    found_inf: jax.Array           # f32 scalar: overflow flag of last unscale
    dynamic: bool = flax.struct.field(pytree_node=False, default=True)


def init_loss_scale(loss_scale="dynamic") -> LossScaleState:
    """Build scaler state.  ``loss_scale``: "dynamic" or a fixed float."""
    dynamic = loss_scale == "dynamic"
    scale = DEFAULT_INIT_SCALE if dynamic else float(loss_scale)
    return LossScaleState(
        loss_scale=jnp.asarray(scale, jnp.float32),
        growth_tracker=jnp.asarray(0, jnp.int32),
        found_inf=jnp.asarray(0.0, jnp.float32),
        dynamic=dynamic)


def scale_loss_value(loss, state: LossScaleState):
    """loss * scale (the body of the reference's ``scale_loss`` ctx mgr)."""
    return loss * state.loss_scale.astype(loss.dtype)


def unscale_grads(grads, state: LossScaleState):
    """Unscale a grad pytree by 1/scale with fused overflow detection.

    Returns (unscaled_grads, new_state with found_inf set).
    Parity: ``LossScaler.unscale_`` (amp_C.multi_tensor_scale path).
    """
    flat, unravel = tree_ravel(grads)
    out, flag = fused_scale(flat, 1.0 / state.loss_scale)
    return unravel(out), state.replace(found_inf=flag)


def unscale_flat_grads(flat_grads, state: LossScaleState, axis_name=None):
    """Flat-native :func:`unscale_grads`: same fused unscale + overflow
    detection, but over an already-flat grad buffer — the variant the
    flat-native train step uses, where autodiff produced flat grads and
    a tree round-trip would reintroduce the re-ravel concatenate.

    ``axis_name`` reduces the overflow flag across a mesh axis (pmax):
    under ZeRO each rank unscales only its own grad SHARD, but the
    skip decision must be replica-uniform — a rank whose shard happens
    to be finite must still skip when any peer overflowed, or the
    ranks' masters diverge silently.

    Returns (unscaled_flat_grads, new_state with found_inf set).
    """
    out, flag = fused_scale(flat_grads, 1.0 / state.loss_scale)
    if axis_name is not None:
        flag = jax.lax.pmax(flag, axis_name)
    return out, state.replace(found_inf=flag)


def nonfinite_leaf_counts(flat_grads, sizes, *, axis_name=None, dp=1,
                          shard_len=None, rank=None, spans=None):
    """Per-leaf counts of nonfinite (inf/nan) elements of a flat grad
    buffer — WHICH parameter overflowed, next to
    :func:`unscale_flat_grads`'s scalar ``found_inf`` that only says
    THAT one did.  This is the overflow autopsy's attribution signal
    (ISSUE 11): computed in-program as one more scalar-vector output of
    the donated step, resolved one step late by the telemetry, so the
    attribution costs no host sync and no recompile.

    Dense (``dp == 1``): ``flat_grads`` is the full flat buffer and
    ``sizes`` its per-leaf layout.  Under ZeRO pass the grad SHARD with
    the state's static layout (``dp``/``shard_len``/``spans``) and
    ``rank = lax.axis_index(axis_name)``; ``axis_name`` psums the
    partial counts replica-uniform — every rank reports the same
    autopsy, the same APX213 discipline as ``found_inf``'s pmax.

    Returns an ``[n_leaves]`` f32 count vector (0.0 everywhere on a
    clean step)."""
    from apex_tpu.optimizers.base import sharded_leaf_nonfinite_counts
    if axis_name is not None and int(dp) <= 1:
        # psum of per-rank counts is only correct over SHARDS; on
        # replicated grads every rank already holds the global counts
        # and the psum would overcount by the replica count (found_inf
        # sidesteps the same hazard with pmax)
        raise ValueError(
            "axis_name without a sharded layout (dp <= 1): replicated "
            "grads would psum to replica_count x the true counts — "
            "drop axis_name (every rank already holds the global "
            "counts) or pass the shard layout (dp/shard_len/rank)")
    sizes = tuple(int(s) for s in sizes)
    if shard_len is None:
        shard_len = int(flat_grads.shape[0])
    if rank is None:
        rank = jnp.int32(0)
    counts = sharded_leaf_nonfinite_counts(
        (flat_grads,), sizes, dp=int(dp), shard_len=int(shard_len),
        rank=rank, spans=spans)[0]
    if axis_name is not None:
        counts = jax.lax.psum(counts, axis_name)
    return counts


def update_scale(state: LossScaleState,
                 growth_factor=DEFAULT_GROWTH_FACTOR,
                 backoff_factor=DEFAULT_BACKOFF_FACTOR,
                 growth_interval=DEFAULT_GROWTH_INTERVAL,
                 min_scale=MIN_SCALE, max_scale=MAX_SCALE) -> LossScaleState:
    """Post-step scale update (parity: ``LossScaler.update_scale``)."""
    if not state.dynamic:
        return state.replace(found_inf=jnp.asarray(0.0, jnp.float32))
    overflow = state.found_inf > 0
    tracker = jnp.where(overflow, 0, state.growth_tracker + 1)
    grow = tracker >= growth_interval
    scale = jnp.where(
        overflow,
        jnp.maximum(state.loss_scale * backoff_factor, min_scale),
        jnp.where(grow,
                  jnp.minimum(state.loss_scale * growth_factor, max_scale),
                  state.loss_scale))
    tracker = jnp.where(grow, 0, tracker)
    return LossScaleState(scale.astype(jnp.float32),
                          tracker.astype(jnp.int32),
                          jnp.asarray(0.0, jnp.float32),
                          state.dynamic)


class LossScaler:
    """Imperative parity wrapper (reference: ``apex/amp/scaler.py``).

    Holds a :class:`LossScaleState` and mirrors the reference's method
    surface for eager-style training loops.  Inside fully-jitted steps use
    the functional API directly.
    """

    def __init__(self, loss_scale="dynamic", init_scale=None,
                 scale_factor=DEFAULT_GROWTH_FACTOR,
                 scale_window=DEFAULT_GROWTH_INTERVAL,
                 min_loss_scale=MIN_SCALE, max_loss_scale=MAX_SCALE):
        if init_scale is not None:
            loss_scale = "dynamic" if loss_scale == "dynamic" else init_scale
        self.state = init_loss_scale(loss_scale)
        if init_scale is not None and self.state.dynamic:
            self.state = self.state.replace(
                loss_scale=jnp.asarray(init_scale, jnp.float32))
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._min_scale = MIN_SCALE if min_loss_scale is None \
            else float(min_loss_scale)
        self._max_scale = MAX_SCALE if max_loss_scale is None \
            else float(max_loss_scale)

    def loss_scale(self) -> float:
        return float(self.state.loss_scale)

    def scale_loss(self, loss):
        return scale_loss_value(loss, self.state)

    def unscale_(self, grads):
        out, self.state = unscale_grads(grads, self.state)
        return out

    def update_scale(self):
        self.state = update_scale(
            self.state, growth_factor=self._scale_factor,
            growth_interval=self._scale_window,
            min_scale=self._min_scale, max_scale=self._max_scale)

    @property
    def found_inf(self):
        return self.state.found_inf

    # checkpoint parity: apex persists these via amp.state_dict()
    def state_dict(self) -> dict:
        return {"loss_scale": float(self.state.loss_scale),
                "unskipped": int(self.state.growth_tracker),
                "dynamic": self.state.dynamic}

    def load_state_dict(self, sd: dict) -> None:
        self.state = LossScaleState(
            loss_scale=jnp.asarray(sd["loss_scale"], jnp.float32),
            growth_tracker=jnp.asarray(sd.get("unskipped", 0), jnp.int32),
            found_inf=jnp.asarray(0.0, jnp.float32),
            dynamic=bool(sd.get("dynamic", True)))
