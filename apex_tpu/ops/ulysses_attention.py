"""Ulysses attention — all-to-all sequence parallelism for long sequences.

The reference has NO context parallelism (SURVEY.md §2.4); the task spec
makes long-context first-class and names BOTH strategies: ring attention
(``ops/ring_attention.py``) and all-to-all sequence parallelism
(DeepSpeed-Ulysses, Jacobs et al. 2023).  This is the latter, TPU-native:

* activations arrive sequence-sharded ``[b, h, s/cp, d]`` on the
  ``context`` mesh axis;
* one ``all_to_all`` reshards to head-sharded ``[b, h/cp, s, d]`` — each
  rank now holds the FULL sequence for its subset of heads;
* the local Pallas flash kernel runs unmodified (attention is
  embarrassingly parallel over heads — no cross-rank softmax algebra,
  unlike the ring's log-space merges);
* a second ``all_to_all`` reshards the output back to sequence shards.

Trade-off vs the ring: Ulysses moves activations twice through ICI
all-to-alls and needs ``heads % cp == 0``, but runs ONE kernel pass with
no per-step rotation (latency ~2 collectives instead of cp ppermute
steps); the ring keeps heads intact and overlaps compute with neighbor
traffic.  Both are exact; pick per topology.

``jax.lax.all_to_all`` is differentiable (its transpose is the inverse
resharding), so the backward needs no custom VJP.  cp=1 degrades to plain
flash attention.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import flash_attention
from apex_tpu.transformer.parallel_state import CONTEXT_AXIS

__all__ = ["ulysses_attention"]


def ulysses_attention(q, k, v, *, causal: bool = False,
                      sm_scale: Optional[float] = None,
                      axis_name: str = CONTEXT_AXIS,
                      block_q: Optional[int] = None,
                      block_k: Optional[int] = None,
                      dropout_rate: float = 0.0,
                      dropout_seed=None):
    """Exact attention over a context-sharded sequence via head/sequence
    all-to-all resharding.

    ``q, k, v``: ``[b, h, s_local, d]`` — this rank's sequence shard
    (rank i holds tokens ``[i*s_local, (i+1)*s_local)``; same contract as
    :func:`ring_attention`).  Must run inside ``shard_map`` binding
    ``axis_name``; requires ``h % cp == 0``.  Returns the local output
    shard ``[b, h, s_local, d]``.

    ``dropout_rate`` > 0 drops attention probabilities in-kernel.  The
    seed is folded with the rank index, so each rank's head subset draws
    an independent stream — a valid regularizer with fwd/bwd mask
    consistency, but NOT bit-matched to an unsharded run (the local
    head index enters the counter hash; use :func:`ring_attention` when
    sharded-vs-dense bit parity under dropout matters)."""
    if axis_name is None:
        cp = 1
    else:
        try:
            cp = jax.lax.axis_size(axis_name)
        except NameError:
            # Unbound axis: only safe to degrade when there IS no
            # context axis to speak of (host / single-device usage with
            # the canonical axis).  A custom/typo'd name inside an
            # actual mesh would silently attend within one shard.
            from apex_tpu.transformer import parallel_state
            if (axis_name == CONTEXT_AXIS
                    and (not parallel_state.model_parallel_is_initialized()
                         or parallel_state.get_context_parallel_world_size()
                         == 1)):
                cp = 1
            else:
                raise
    if cp == 1:
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                               block_q=block_q, block_k=block_k,
                               dropout_rate=dropout_rate,
                               dropout_seed=dropout_seed)
    if dropout_rate and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    b, h, s_local, d = q.shape
    if h % cp != 0:
        raise ValueError(
            f"ulysses_attention needs heads divisible by the context "
            f"axis size: {h} % {cp} != 0 (use ring_attention otherwise)")

    # ONE inbound all-to-all for the stacked q/k/v (3 launches would
    # triple the collective latency on the hot path), one outbound
    qkv = jnp.stack([q, k, v])           # [3, b, h, s/cp, d]
    qkv = jax.lax.all_to_all(qkv, axis_name, split_axis=2,
                             concat_axis=3, tiled=True)
    drop_kw = {}
    if dropout_rate:
        from apex_tpu.ops.attention import fold_rank_seed
        # rank-decorrelated stream (see docstring)
        drop_kw = dict(dropout_rate=dropout_rate,
                       dropout_seed=fold_rank_seed(dropout_seed, axis_name))
    o = flash_attention(qkv[0], qkv[1], qkv[2],
                        causal=causal, sm_scale=sm_scale,
                        block_q=block_q, block_k=block_k, **drop_kw)
    # [b, h/cp, s, d] -> [b, h, s/cp, d]
    return jax.lax.all_to_all(o, axis_name, split_axis=2,
                              concat_axis=1, tiled=True)
