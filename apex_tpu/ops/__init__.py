"""apex_tpu.ops — Pallas TPU kernels + pure-jnp oracle twins.

This is the rebuild of the reference's native kernel layer (``csrc/`` and
``apex/contrib/csrc/``).  Every fused kernel ships with a jnp reference
implementation (the "oracle"); tests assert kernel ≡ oracle, mirroring the
reference's fused-vs-eager test pattern.
"""
from .layer_norm import (
    layer_norm,
    rms_norm,
    layer_norm_reference,
    rms_norm_reference,
)
from .fused_update import (
    fused_scale,
    fused_axpby,
    fused_l2norm,
    fused_adam_flat,
    fused_adagrad_flat,
    fused_sgd_flat,
    fused_lamb_phase1_flat,
    adam_reference,
)
from .attention import decode_attention, flash_attention, mha_reference
from .paged_attention import paged_decode_attention
from .ring_attention import ring_attention, ring_attention_reference
from .ulysses_attention import ulysses_attention
from .xentropy import softmax_cross_entropy_loss, xentropy_reference
from .fused_lm_xent import (
    fused_lm_head_cross_entropy,
    fused_lm_head_vocab_parallel_cross_entropy,
    lm_head_xentropy_reference,
)

__all__ = [
    "ring_attention",
    "ring_attention_reference",
    "ulysses_attention",
    "layer_norm",
    "rms_norm",
    "layer_norm_reference",
    "rms_norm_reference",
    "fused_scale",
    "fused_axpby",
    "fused_l2norm",
    "fused_adam_flat",
    "fused_adagrad_flat",
    "fused_sgd_flat",
    "fused_lamb_phase1_flat",
    "adam_reference",
    "flash_attention",
    "decode_attention",
    "paged_decode_attention",
    "mha_reference",
    "softmax_cross_entropy_loss",
    "xentropy_reference",
    "fused_lm_head_cross_entropy",
    "fused_lm_head_vocab_parallel_cross_entropy",
    "lm_head_xentropy_reference",
]
