"""Fused (flash) attention — the TPU-native equivalent of the reference's
fused-attention extensions.

Reference surface being rebuilt (see SURVEY.md §2.3):

* ``apex/contrib/csrc/fmha/`` (``fmhalib``): fused MHA fwd+bwd, fp16,
  head_dim 64, seqlen ≤ 512 (FasterTransformer-derived fixed-shape kernels).
* ``apex/contrib/csrc/multihead_attn/`` (``fast_multihead_attn``): fused
  QKV GEMM → scaled masked softmax(+dropout) → AV → out-proj chains.
* ``csrc/megatron/scaled_upper_triang_masked_softmax*``: the causal
  softmax those attention stacks lean on.

On TPU one blockwise-streaming kernel family covers all of them with no
shape table: an online-softmax ("flash") attention in Pallas.  Scores for a
(q-block, k-block) tile live in VMEM, softmax statistics (running max m and
normalizer l) are carried across k-blocks in VMEM scratch, and the O(s²)
score matrix never touches HBM — which is exactly the memory-traffic
property the CUDA kernels buy, achieved compiler-portably.  Unlike
``fmhalib`` there is no 512-token ceiling: block streaming scales to the
16k+ sequences the reference's softmax kernels cap out at.

The backward follows the standard flash decomposition: save only
(out, logsumexp); recompute score tiles blockwise.  The default is a
FUSED one-pass backward (dq/dk/dv from a single k-major sweep with a
full-sequence dq accumulator in VMEM scratch — one exp+mask recompute
instead of two); shapes whose dq accumulator would not fit the scoped
VMEM budget fall back to the split q-major dq / k-major dkv kernels.

Attention-probability dropout runs IN-KERNEL, like the reference's
softmax+dropout fusion (``apex/contrib/csrc/multihead_attn/philox.h``:
the CUDA kernels drop softmax *probabilities* with a counter-based
philox stream so forward and backward regenerate identical masks from a
seed).  The TPU equivalent here is a keyed counter hash (murmur3
finalizer over the global ``(batch·head, row, col)`` coordinates): pure
int32 VPU ops, so the SAME bits come out of CPU interpret mode and
compiled TPU — the mask generation the tests cover is the mask
generation the chip runs, with no O(s²) mask array ever touching HBM.
Dropout applies to the normalized probabilities (softmax THEN dropout,
the reference's order): the l/lse statistics accumulate clean p, only
the p·V contraction sees the dropped+rescaled p̃.

Oracle: :func:`mha_reference` (pure jnp, materializes the score matrix);
tests assert kernel ≡ oracle, the reference's fused-vs-eager pattern.
Tolerance note: on-chip, fp32 operands still contract at JAX's default
matmul precision (bf16 on the MXU) in kernel and oracle alike, so
fp32 comparisons on real hardware see ~1e-3 blockwise noise; interpret
mode is exact and the fused-vs-split tests hold at 1e-5.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.utils import cdiv, interpret_mode

__all__ = ["flash_attention", "mha_reference", "decode_attention",
           "prefix_window_attention", "slab_decode_attention"]

#: pallas_audit registration (analysis hook only, no behavior change):
#: every attention kernel carries online-softmax (m/l/acc) or wgrad
#: accumulators whose scratch must be fp32 (APX302).
PALLAS_AUDIT = {
    "_fwd_kernel": {"reduction": True},
    "_dq_kernel": {"reduction": True},
    "_dkv_kernel": {"reduction": True},
    "_bwd_fused_kernel": {"reduction": True},
}

_NEG_INF = -1e30          # finite "masked" score: keeps exp()/where() NaN-free
# The kernels work in BASE-2 log domain: the dot's scalar scale absorbs
# log2(e), and every softmax exp is jnp.exp2.  The VPU lowers exp(x) as
# exp2(x * log2e) anyway, so folding the constant into the (free) score
# scale deletes one full [bq, bk] vector multiply per exp site — fwd p,
# rescale alpha, and the backward recompute — pure VPU savings exactly
# where PERF.md locates the d=64 attention floor.  lse is produced and
# consumed in base 2 strictly inside the kernels; the public API and the
# oracle stay in natural log.
_LOG2E = 1.4426950408889634
# a row whose max score is below this is FULLY masked (causal sq > sk,
# fully-masked varlen rows): it must emit 0 output and 0 grads.  One
# definition shared by the oracle, the forward kernel, and the backward
# recompute so the three can never disagree on which rows qualify.
_MASKED_ROW_THRESH = _NEG_INF * 0.5
_LANES = 128              # TPU lane width; m/l scratch is lane-replicated
# murmur3 fmix32 constants as signed int32 literals (int32 arithmetic
# wraps two's-complement in XLA, bit-identical to uint32 mod-2^32)
_H1 = 0x9E3779B9 - (1 << 32)
_H2 = 0x85EBCA6B - (1 << 32)
_H3 = 0xC2B2AE35 - (1 << 32)
# seed-fold multiplier for fold_rank_seed — murmur3's c1, deliberately
# distinct from the coordinate multipliers above so a rank fold can't
# alias a row/col shift in the pre-finalizer state
_HF = 0xCC9E2D51 - (1 << 32)
# lane width for the per-row softmax stats (lse, delta) at the kernel
# HBM boundary.  Full 128-lane replication cost real bandwidth: at
# [8,16,1024,64] the two broadcast stats were 134 MB of HBM traffic per
# backward — ~25% of its runtime — carrying 1 useful lane in 128.  Eight
# lanes keeps the arrays 2-D-tileable while cutting that 16x; kernels
# only ever read [:, :1].
_STAT_LANES = 8


def _rows_can_be_fully_masked(causal, off, masked, valid) -> bool:
    """Statically decide whether ANY query row could end up fully
    masked — only then do the kernels pay the [bq, bk] zero-forcing
    ``where`` on p (fwd) / the recompute (bwd).  Possible sources: an
    explicit mask, a validity window (padded rows), or causal with
    sq > sk (queries before the first key).  The flagship causal
    sq == sk unpadded path — the VPU-bound case PERF.md profiles —
    skips the select entirely."""
    return masked or (valid is not None) or (causal and off < 0)


def _keep_mask(seed, bi, qi, ki, bq, bk, rate, row_off=0, col_off=0):
    """Counter-based keep mask for one (qi, ki) block of batch·head bi.

    The philox-equivalent: bits are a pure function of
    ``(seed, bi, global row, global col)``, so the forward kernel and
    every backward recompute regenerate the identical mask regardless
    of grid order.  murmur3's 32-bit finalizer over the coordinates
    gives well-mixed bits in ~10 int32 VPU ops per element; the top 24
    bits form the uniform variate (2^-24 rate resolution).

    ``row_off``/``col_off`` translate LOCAL kernel coordinates to the
    GLOBAL sequence position — ring attention sets them per shard pair
    so a context-sharded run draws the exact mask the unsharded run
    would (the coordinates, not the blocking, define the stream)."""
    bi = jnp.asarray(bi, jnp.int32)   # python ints would overflow in *_H1
    rows = (row_off + qi * bq
            + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0))
    cols = (col_off + ki * bk
            + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1))
    h = seed ^ (bi * _H1) ^ (rows * _H2) ^ (cols * _H3)
    h = h ^ jax.lax.shift_right_logical(h, 16)
    h = h * _H2
    h = h ^ jax.lax.shift_right_logical(h, 13)
    h = h * _H3
    h = h ^ jax.lax.shift_right_logical(h, 16)
    u24 = jax.lax.shift_right_logical(h, 8)          # uniform in [0, 2^24)
    return u24 >= int(round(rate * (1 << 24)))


def _dropout_reference(p, *, rate, seed):
    """Oracle twin of the kernels' dropout on a full ``[b, h, sq, sk]``
    probability array.  Because the keep mask is a pure function of the
    GLOBAL (bh, row, col) coordinates, it is independent of the kernel's
    block decomposition — one full-matrix draw per bh predicts every
    flash_attention blocking (and the backward's recompute) bit-for-bit."""
    b, hh, sq, sk = p.shape
    seed = jnp.asarray(seed, jnp.int32)
    keep = jnp.stack([
        _keep_mask(seed, bi, 0, 0, sq, sk, rate)
        for bi in range(b * hh)]).reshape(b, hh, sq, sk)
    return jnp.where(keep, p, 0.0) * (1.0 / (1.0 - rate))


def mha_reference(q, k, v, *, causal: bool = False, mask=None,
                  sm_scale: Optional[float] = None,
                  dropout_rate: float = 0.0, dropout_seed=None):
    """Pure-jnp oracle: softmax(scale·QKᵀ + mask)·V, fp32 accumulation.

    ``mask`` is boolean, True = masked out (the reference's convention in
    ``scaled_masked_softmax``), broadcastable to ``[b, h, sq, sk]``.
    """
    *_, sq, d = q.shape
    sk = k.shape[-2]
    scale = (d ** -0.5) if sm_scale is None else sm_scale
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(cm, s, _NEG_INF)
    if mask is not None:
        s = jnp.where(mask, _NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    # fully-masked rows (e.g. causal sq > sk: queries before the first
    # key) emit 0, not softmax-of-constant's uniform artifact — the
    # FlashAttention convention the kernel implements
    p = jnp.where(jnp.max(s, axis=-1, keepdims=True) <= _MASKED_ROW_THRESH,
                  0.0, p)
    if dropout_rate:
        # softmax THEN dropout, drawing the kernel's exact
        # (block-independent) mask
        p = _dropout_reference(p, rate=dropout_rate, seed=dropout_seed)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
                      ).astype(q.dtype)


# --------------------------------------------------------------------------
# forward kernel: grid (bh, nq, nk), k innermost ("arbitrary"), online softmax
# --------------------------------------------------------------------------

def _valid_mask(s, valid, qi, ki, bq, bk):
    """Mask scores outside the (q_len, k_len) valid region to _NEG_INF —
    used when the sequence was padded up to a lane multiple."""
    if valid is None:
        return s
    rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where((rows < valid[0]) & (cols < valid[1]), s, _NEG_INF)


def _fwd_kernel(causal, off, scale, bq, bk, nk, masked, valid, rate,
                *refs):
    q_ref, k_ref, v_ref = refs[:3]
    i = 3
    mask_ref = refs[i] if masked else None
    i += 1 if masked else 0
    seed_ref = refs[i] if rate else None
    i += 1 if rate else 0
    o_ref, lse_ref, m_scr, l_scr, acc_scr = refs[i:i + 5]
    bi = pl.program_id(0)
    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: whole block above the diagonal contributes nothing — skip
    run = True if not causal else (ki * bk <= qi * bq + bq - 1 + off)

    @pl.when(run)
    def _body():
        # dots run on the INPUT dtype (bf16 in, fp32 MXU accumulate):
        # pre-casting operands to fp32 would force the MXU into its
        # several-times-slower fp32 mode.  The scale moves to the fp32
        # product (linear, identical math).
        q = q_ref[0]
        kb = k_ref[0]
        # base-2 log domain: log2e folded into the scalar scale (see
        # _LOG2E note at the top of the module)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * (
                                    scale * _LOG2E)
        if causal:
            rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows + off >= cols, s, _NEG_INF)
        if masked:
            s = jnp.where(mask_ref[0], _NEG_INF, s)
        s = _valid_mask(s, valid, qi, ki, bq, bk)
        m_prev = m_scr[...]                              # [bq, LANES]
        m_cur = jnp.max(s, axis=1, keepdims=True)        # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)               # lane-replicated
        alpha = jnp.exp2(m_prev[:, :1] - m_new[:, :1])   # [bq, 1]
        # _NEG_INF is finite, so a fully-masked row would get
        # exp2(s - m) = exp2(0) = 1 everywhere and emit mean(v) instead
        # of 0 (hit by causal sq > sk: queries before the first key);
        # force p = 0 there so l stays 0 and _finish emits 0.  Shapes
        # that can't produce such rows skip the [bq, bk] select.
        p = jnp.exp2(s - m_new[:, :1])                   # [bq, bk]
        if _rows_can_be_fully_masked(causal, off, masked, valid):
            p = jnp.where(m_new[:, :1] <= _MASKED_ROW_THRESH, 0.0, p)
        l_scr[...] = l_scr[...] * alpha + \
            jnp.sum(p, axis=1, keepdims=True)
        # prob dropout: the l/lse normalizer above accumulates CLEAN p
        # (softmax first); only the p·V feed sees the dropped+rescaled
        # probabilities — dividing by l in _finish then yields
        # dropout(softmax(s)) @ V exactly
        pv = p
        if rate:
            keep = _keep_mask(seed_ref[0], bi, qi, ki, bq, bk, rate,
                              seed_ref[1], seed_ref[2])
            pv = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - rate))
        # p rounds to the input dtype for the MXU pass (the standard
        # flash-on-TPU precision: probabilities in [0,1] lose ~3 decimal
        # digits in bf16, accumulation stays fp32 in scratch)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot(
            pv.astype(v_ref.dtype), v_ref[0],
            preferred_element_type=jnp.float32)
        m_scr[...] = m_new

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        # fully-masked rows (l == 0) emit 0, not NaN — matches the oracle's
        # softmax-of-all--inf convention closely enough for padding rows
        o_ref[0] = (acc_scr[...] / jnp.where(l == 0.0, 1.0, l)
                    ).astype(o_ref.dtype)
        # lse in BASE 2 (m is a base-2 log max): consumed only by
        # _recompute_p, which is in the same domain
        lse_ref[0] = (m_scr[...] + jnp.log2(jnp.where(l == 0.0, 1.0, l))
                      )[:, :_STAT_LANES]


def _fwd(q3, k3, v3, mask3, causal, scale, bq, bk, out_dtype=None,
         causal_off=None, valid=None, rate=0.0, seed3=None):
    bh, sq, d = q3.shape
    out_dtype = out_dtype or q3.dtype
    sk = k3.shape[1]
    off = (sk - sq) if causal_off is None else causal_off
    nq, nk = cdiv(sq, bq), cdiv(sk, bk)
    masked = mask3 is not None
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
    ]
    operands = [q3, k3, v3]
    if masked:
        nmask = mask3.shape[0]
        h_per = bh // nmask
        in_specs.append(pl.BlockSpec(
            (1, bq, bk), lambda b, i, j: (b // h_per, i, j)))
        operands.append(mask3)
    if rate:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.append(seed3)
    kernel = functools.partial(_fwd_kernel, causal, off, scale, bq, bk, nk,
                               masked, valid, rate)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, _STAT_LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), out_dtype),
            jax.ShapeDtypeStruct((bh, sq, _STAT_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(*operands)
    return out, lse[:, :, 0]


# --------------------------------------------------------------------------
# backward kernels (flash decomposition): recompute p blockwise from lse
# --------------------------------------------------------------------------

def _parse_bwd_refs(refs, masked, rate):
    """Common backward operand layout: [q, k, v, do, lse, delta]
    (+mask)(+seed), then the kernel-specific outs/scratch as the tail."""
    fixed = list(refs[:6])
    i = 6
    mask_ref = refs[i] if masked else None
    i += 1 if masked else 0
    seed_ref = refs[i] if rate else None
    i += 1 if rate else 0
    return fixed, mask_ref, seed_ref, refs[i:]


def _dropped_dp(rate, seed_ref, bi, qi, ki, bq, bk, p, dp):
    """(p̃ for the dv contraction, dL/dp for ds) under prob dropout.

    With out = (M ⊙ p / keep) @ V: dv sees the dropped p̃, and the
    softmax backward's upstream is dL/dp = M ⊙ dp / keep.  delta keeps
    its no-dropout definition (Σ do·out = Σ_j dL/dp_j · p_j still holds,
    so the saved-residual contract is unchanged)."""
    if not rate:
        return p, dp
    keep = _keep_mask(seed_ref[0], bi, qi, ki, bq, bk, rate,
                      seed_ref[1], seed_ref[2])
    inv = 1.0 / (1.0 - rate)
    return jnp.where(keep, p, 0.0) * inv, jnp.where(keep, dp * inv, 0.0)


def _dq_kernel(causal, off, scale, bq, bk, nk, masked, valid, rate,
               *refs):
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref), mask_ref, \
        seed_ref, (dq_ref, dq_scr) = _parse_bwd_refs(refs, masked, rate)
    bi = pl.program_id(0)
    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    run = True if not causal else (ki * bk <= qi * bq + bq - 1 + off)

    @pl.when(run)
    def _body():
        p = _recompute_p(causal, off, scale, bq, bk, masked, valid,
                         qi, ki, q_ref, k_ref, lse_ref, mask_ref)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0],
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        _, g = _dropped_dp(rate, seed_ref, bi, qi, ki, bq, bk, p, dp)
        ds = p * (g - delta_ref[0][:, :1])
        dq_scr[...] += scale * jax.lax.dot(
            ds.astype(k_ref.dtype), k_ref[0],
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(causal, off, scale, bq, bk, nq, masked, valid, rate,
                *refs):
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref), mask_ref, \
        seed_ref, (dk_ref, dv_ref, dk_scr, dv_scr) = \
        _parse_bwd_refs(refs, masked, rate)
    bi = pl.program_id(0)
    qi = pl.program_id(2)
    ki = pl.program_id(1)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    run = True if not causal else (ki * bk <= qi * bq + bq - 1 + off)

    @pl.when(run)
    def _body():
        p = _recompute_p(causal, off, scale, bq, bk, masked, valid,
                         qi, ki, q_ref, k_ref, lse_ref, mask_ref)
        do = do_ref[0]
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        pd, g = _dropped_dp(rate, seed_ref, bi, qi, ki, bq, bk, p, dp)
        dv_scr[...] += jax.lax.dot_general(
            pd.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # p̃ᵀ @ do
        ds = p * (g - delta_ref[0][:, :1])
        dk_scr[...] += scale * jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # dsᵀ @ q

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _recompute_p(causal, off, scale, bq, bk, masked, valid, qi, ki,
                 q_ref, k_ref, lse_ref, mask_ref):
    """Shared backward score recompute: p = exp2(s - lse) for one
    (qi, ki) block pair, with causal/mask/valid-window masking — base-2
    log domain throughout, matching the forward (lse is base 2).  One
    definition so the three backward kernels can never drift apart."""
    s = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * (scale * _LOG2E)
    if causal:
        rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(rows + off >= cols, s, _NEG_INF)
    if masked:
        s = jnp.where(mask_ref[0], _NEG_INF, s)
    s = _valid_mask(s, valid, qi, ki, bq, bk)
    # fully-masked rows carry lse = _NEG_INF (finite), so exp2(s - lse)
    # would be 1, not 0 — mirror the forward's guard (and its static
    # skip for shapes that can't produce such rows)
    p = jnp.exp2(s - lse_ref[0][:, :1])
    if _rows_can_be_fully_masked(causal, off, masked, valid):
        p = jnp.where(lse_ref[0][:, :1] <= _MASKED_ROW_THRESH, 0.0, p)
    return p


def _bwd_fused_kernel(causal, off, scale, bq, bk, nq, nk, masked, valid,
                      rate, *refs):
    """One-pass backward (FlashAttention-2 shape): dq, dk, dv from a
    single sweep over (ki, qi) blocks.

    The split dq/dkv kernels each recompute the scores and the exp — the
    dominant VPU cost at small head_dim — and each re-read q/k/v/do.
    Fusing them computes p/ds ONCE per block pair (5 MXU dots instead of
    7, 1 exp+mask pass instead of 2).  The price is a full-sequence
    ``[sq, d]`` fp32 dq accumulator in VMEM scratch (dq contributions
    arrive k-major, so no single output block is complete until the
    sweep ends) — affordable exactly when sq*d is moderate, which the
    caller gates on; and the ki grid dim turns sequential (the scratch
    carries across it), keeping only bh as the parallel dim.
    """
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref), mask_ref, \
        seed_ref, (dq_ref, dk_ref, dv_ref, dq_scr, dk_scr, dv_scr) = \
        _parse_bwd_refs(refs, masked, rate)
    bi = pl.program_id(0)
    qi = pl.program_id(2)
    ki = pl.program_id(1)

    @pl.when((ki == 0) & (qi == 0))
    def _init_dq():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @pl.when(qi == 0)
    def _init_dkv():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    run = True if not causal else (ki * bk <= qi * bq + bq - 1 + off)

    @pl.when(run)
    def _body():
        p = _recompute_p(causal, off, scale, bq, bk, masked, valid,
                         qi, ki, q_ref, k_ref, lse_ref, mask_ref)
        do = do_ref[0]
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        pd, g = _dropped_dp(rate, seed_ref, bi, qi, ki, bq, bk, p, dp)
        dv_scr[...] += jax.lax.dot_general(
            pd.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # p̃ᵀ @ do
        ds = p * (g - delta_ref[0][:, :1])
        dsl = ds.astype(q_ref.dtype)
        dk_scr[...] += scale * jax.lax.dot_general(
            dsl, q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # dsᵀ @ q
        dq_scr[pl.ds(qi * bq, bq), :] += scale * jax.lax.dot(
            dsl, k_ref[0], preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _fin_dkv():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)

    @pl.when((ki == nk - 1) & (qi == nq - 1))
    def _fin_dq():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


# fused-backward gate: the [sq, d] fp32 dq scratch (plus the same-sized
# output block) must stay a small slice of the ~16 MB scoped VMEM —
# 2 MB covers seq 8192 @ d 64 / seq 4096 @ d 128; beyond it the split
# two-kernel backward below takes over.  Module-level so tests can
# force either path.
_FUSED_BWD_MAX_BYTES = 2 * 1024 * 1024


def _bwd_impl(q3, k3, v3, mask3, o3, lse, do3, causal, scale, bq, bk,
              out_dtype=None, causal_off=None, valid=None, rate=0.0,
              seed3=None):
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    off = (sk - sq) if causal_off is None else causal_off
    nq, nk = cdiv(sq, bq), cdiv(sk, bk)
    masked = mask3 is not None
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)                               # [bh, sq]
    lse2 = jnp.broadcast_to(lse[..., None], (bh, sq, _STAT_LANES))
    delta2 = jnp.broadcast_to(delta[..., None], (bh, sq, _STAT_LANES))

    h_per = bh // mask3.shape[0] if masked else 1
    common = [q3, k3, v3, do3, lse2, delta2] + ([mask3] if masked else []) \
        + ([seed3] if rate else [])

    # k-major (grid (bh, ki, qi)) input specs — shared by the fused and
    # dkv kernels, which iterate the identical block layout
    kmajor_in_specs = [
        pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, bq, _STAT_LANES), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, bq, _STAT_LANES), lambda b, j, i: (b, i, 0)),
    ]
    if masked:
        kmajor_in_specs.append(pl.BlockSpec(
            (1, bq, bk), lambda b, j, i: (b // h_per, i, j)))
    if rate:
        kmajor_in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))

    if sq * d * 4 <= _FUSED_BWD_MAX_BYTES:
        kernel = functools.partial(
            _bwd_fused_kernel, causal, off, scale, bq, bk, nq, nk,
            masked, valid, rate)
        dq, dk, dv = pl.pallas_call(
            kernel,
            grid=(bh, nk, nq),
            in_specs=kmajor_in_specs,
            out_specs=[
                pl.BlockSpec((1, sq, d), lambda b, j, i: (b, 0, 0)),
                pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, sq, d), out_dtype or q3.dtype),
                jax.ShapeDtypeStruct((bh, sk, d), out_dtype or k3.dtype),
                jax.ShapeDtypeStruct((bh, sk, d), out_dtype or v3.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((sq, d), jnp.float32),
                pltpu.VMEM((bk, d), jnp.float32),
                pltpu.VMEM((bk, d), jnp.float32),
            ],
            # ki is sequential: the dq scratch carries across it
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary", "arbitrary")),
            interpret=interpret_mode(),
        )(*common)
        return dq, dk, dv

    dq_in_specs = [
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bq, _STAT_LANES), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bq, _STAT_LANES), lambda b, i, j: (b, i, 0)),
    ]
    if masked:
        dq_in_specs.append(pl.BlockSpec(
            (1, bq, bk), lambda b, i, j: (b // h_per, i, j)))
    if rate:
        dq_in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))

    dq_kernel = functools.partial(_dq_kernel, causal, off, scale, bq, bk,
                                  nk, masked, valid, rate)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, nq, nk),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), out_dtype or q3.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(*common)

    dkv_kernel = functools.partial(
        _dkv_kernel, causal, off, scale, bq, bk, nq, masked, valid, rate)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, nk, nq),
        in_specs=kmajor_in_specs,
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), out_dtype or k3.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), out_dtype or v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(*common)
    return dq, dk, dv


# --------------------------------------------------------------------------
# public entry: custom VJP over the kernel pair, oracle fallback for odd shapes
# --------------------------------------------------------------------------

def fold_rank_seed(seed, axis_name):
    """Derive a per-rank dropout seed from a replicated one (Megatron's
    per-tensor-rank rng stream): distinct ranks get well-separated
    streams; rank 0 keeps ``seed`` unchanged.  Must run inside
    ``shard_map`` binding ``axis_name``.  Do NOT fold the context axis —
    ring attention's sharded-equals-dense dropout needs a CP-uniform
    seed."""
    return (jnp.asarray(seed, jnp.int32)
            ^ (jax.lax.axis_index(axis_name) * jnp.int32(_HF)))


def _zero_cotangent(x):
    """Cotangent for a non-differentiable custom_vjp argument: None for
    an absent (None) operand, float0 zeros for integer/bool primals,
    ordinary zeros for inexact dtypes (a 0/1 float mask is accepted by
    the forward's ``where``, so its grad path must not type-error)."""
    if x is None:
        return None
    if jnp.issubdtype(x.dtype, jnp.inexact):
        return jnp.zeros(x.shape, x.dtype)
    return np.zeros(np.shape(x), jax.dtypes.float0)


def _seed_operand(seed, row_off=0, col_off=0):
    """SMEM dropout operand: [seed, global row offset, global col
    offset].  Offsets are 0 for unsharded attention; ring attention sets
    them per shard pair (see _keep_mask)."""
    return jnp.stack([jnp.asarray(seed, jnp.int32),
                      jnp.asarray(row_off, jnp.int32),
                      jnp.asarray(col_off, jnp.int32)])


def _fit_block(s: int, preferred: int):
    """Largest block <= preferred that divides s and is a lane multiple
    (or s itself when s < 128); None -> needs padding."""
    if s <= preferred:
        return s
    for cand in range(preferred, _LANES - 1, -_LANES):
        if s % cand == 0:
            return cand
    return None


def _plan_block(s: int, preferred: int):
    """(block, padded_len) — pad s up to the next lane multiple when no
    lane-multiple block divides it (e.g. s=1000 -> 1024, block 512)."""
    b = _fit_block(s, preferred)
    if b is not None:
        return b, s
    s_pad = cdiv(s, _LANES) * _LANES
    return _fit_block(s_pad, preferred), s_pad


#: measured kernel/XLA crossover on v5e (bench_captures/
#: r5_attn_crossover.py, fwd+bwd, h=16 d=64): at s=128 the Pallas grid
#: degenerates to b*h tiny programs and Mosaic dispatch dominates —
#: 828 µs vs 119 µs for plain XLA einsum attention; at s=256 it is
#: 707 vs 379; from s=512 the kernel wins (777 vs 2033, and 4.3x at
#: s=2048).  Auto-dispatch sends padded-seq <= 256 to the XLA path.
#: The 256 boundary itself is interpolated from those four points, not
#: measured densely — override per-run with the environment variable
#: ``APEX_TPU_ATTN_XLA_MAX_SEQ`` or per-call with the
#: ``flash_attention(..., xla_max_seq=)`` kwarg (0 disables the XLA
#: path entirely); bench attn captures stamp the effective value.
_XLA_PATH_MAX_SEQ = 256

_XLA_MAX_SEQ_ENV = "APEX_TPU_ATTN_XLA_MAX_SEQ"


def xla_path_max_seq(override=None) -> int:
    """The effective auto-dispatch crossover: explicit kwarg override >
    ``APEX_TPU_ATTN_XLA_MAX_SEQ`` env var > the measured default."""
    if override is not None:
        return int(override)
    env = os.environ.get(_XLA_MAX_SEQ_ENV)
    if env:
        try:
            return int(env)
        except ValueError as e:
            raise ValueError(
                f"{_XLA_MAX_SEQ_ENV} must be an int, got {env!r}") from e
    return _XLA_PATH_MAX_SEQ


def _xla_attention(q, k, v, *, causal, scale, mask, rate, seed):
    """Short-sequence attention as plain XLA ops — same semantics as the
    kernels (True-=-masked boolean mask, fully-masked rows emit zeros,
    the identical coordinate-hash probability dropout), but lowered to
    one batched einsum chain XLA fuses well at small ``s``.

    Numerics mirror the kernel: bf16 operands into the MXU with fp32
    accumulation (``preferred_element_type``), softmax in fp32, the
    probability matrix cast back to ``v.dtype`` for the PV dot."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    s = jax.lax.dot_general(
        q, k, (((3,), (3,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32) * scale
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(cols <= rows + (sk - sq), s, _NEG_INF)
    if mask is not None:
        s = jnp.where(mask, _NEG_INF, s)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    if causal or mask is not None:
        p = jnp.where(m <= _MASKED_ROW_THRESH, 0.0, p)
    if rate:
        keep = _keep_mask(jnp.asarray(seed, jnp.int32),
                          jnp.arange(b * h, dtype=jnp.int32)[:, None, None],
                          0, 0, sq, sk, rate).reshape(b, h, sq, sk)
        p = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - rate))
    out = jax.lax.dot_general(
        p.astype(v.dtype), v, (((3,), (2,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def flash_attention(q, k, v, *, causal: bool = False, mask=None,
                    sm_scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    dropout_rate: float = 0.0,
                    dropout_seed=None,
                    use_kernel: Optional[bool] = None,
                    xla_max_seq: Optional[int] = None):
    """Fused blockwise attention, ``[b, h, s, d]`` layout.

    Drop-in fused path for the reference's ``fmhalib`` /
    ``fast_multihead_attn`` forward+backward.  ``mask`` is boolean with
    True = masked (broadcastable ``[b|1, 1, sq, sk]``).  Sequences that
    don't tile to the 128-lane grid are padded up to the next lane
    multiple and masked inside the kernel — the kernel path is taken for
    EVERY shape (the reference kernels instead refuse such shapes; the
    old behavior here was a silent O(s²) oracle fallback).

    ``use_kernel=None`` auto-dispatches: on TPU backends, sequences at
    or under the crossover (``xla_max_seq`` kwarg >
    ``APEX_TPU_ATTN_XLA_MAX_SEQ`` env var > the measured default
    ``_XLA_PATH_MAX_SEQ`` — see its note; the guessed 256 boundary is
    tunable without a code edit) run as one fused XLA einsum chain
    instead of the Pallas kernels; identical semantics including the
    dropout mask stream.  Explicit ``block_q``/``block_k`` forces the
    kernel (the caller is tuning it), as does ``use_kernel=True``;
    non-TPU backends always take the kernel so interpret-mode tests
    exercise kernel code.

    ``dropout_rate`` > 0 drops attention *probabilities* in-kernel (the
    reference's philox softmax+dropout fusion; see the module
    docstring), rescaling survivors by ``1/(1-rate)``.  ``dropout_seed``
    (int32 scalar, traced OK — pass a fresh value per training step,
    e.g. drawn from the tensor-parallel RNG tracker) fully determines
    the mask; the backward regenerates it from the same seed, so
    activation-recompute training stays bit-identical.  ``rate`` itself
    is static: rate=0 compiles the exact pre-dropout kernels.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = (d ** -0.5) if sm_scale is None else sm_scale
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got "
                         f"{dropout_rate}")
    if dropout_rate and dropout_seed is None:
        raise ValueError(
            "dropout_rate > 0 requires dropout_seed (reusing an "
            "implicit constant seed would repeat the same mask "
            "every training step)")
    # validate the mask contract BEFORE the use_kernel dispatch so the
    # short-seq XLA path and the kernel path enforce the same shape —
    # a malformed mask must not silently broadcast on one side of the
    # auto-dispatch boundary and error on the other (ADVICE r5 #1)
    if mask is not None:
        shape_ok = (mask.ndim == 4
                    and mask.shape[0] in (1, b)
                    and mask.shape[1] in (1, h)
                    and mask.shape[2] in (1, sq)
                    and mask.shape[3] in (1, sk))
        if not shape_ok:
            raise ValueError(
                f"mask must be boolean [b|1, h|1, sq|1, sk|1] "
                f"(broadcastable to [{b}, {h}, {sq}, {sk}]); got "
                f"{tuple(mask.shape)}")
    if use_kernel is None:
        use_kernel = (block_q is not None or block_k is not None
                      or max(sq, sk) > xla_path_max_seq(xla_max_seq)
                      or jax.default_backend() not in ("tpu", "axon"))
    if not use_kernel:
        return _xla_attention(q, k, v, causal=causal, scale=scale,
                              mask=mask, rate=dropout_rate,
                              seed=dropout_seed)
    seed3 = None
    if dropout_rate:
        seed3 = _seed_operand(dropout_seed)
    # default 1024x1024 blocks: measured ~21% faster fwd+bwd than
    # 512x512 at [*, 16, 1024-2048, 64] on v5e (fewer online-softmax
    # rescale rounds, larger MXU feeds).  Verified to fit scoped VMEM
    # through head_dim 128 UNMASKED; outside that envelope (d > 128, or
    # a mask operand adding a [bq, bk] block per grid step) fall back
    # to the conservative 512 so previously-compiling calls keep
    # compiling.  _plan_block shrinks further for short sequences.
    default_block = 1024 if (d <= 128 and mask is None) else 512
    bq, sq_pad = _plan_block(sq, block_q or default_block)
    bk, sk_pad = _plan_block(sk, block_k or default_block)
    padded = (sq_pad != sq) or (sk_pad != sk)
    # real-length causal offset / validity window, pre-padding
    causal_off = sk - sq
    valid = (sq, sk) if padded else None

    q3 = q.reshape(b * h, sq, d)
    k3 = k.reshape(b * h, sk, d)
    v3 = v.reshape(b * h, sk, d)
    mask3 = None
    if mask is not None:
        # shape already validated ahead of the use_kernel dispatch
        mb, mh = mask.shape[0], mask.shape[1]
        if mh == 1:
            mask3 = jnp.broadcast_to(
                mask, (mb, 1, sq, sk)).reshape(mb, sq, sk)
        else:           # per-head mask: materialize the full [b*h, sq, sk]
            mask3 = jnp.broadcast_to(
                mask, (b, h, sq, sk)).reshape(b * h, sq, sk)
    if padded:
        q3 = jnp.pad(q3, ((0, 0), (0, sq_pad - sq), (0, 0)))
        k3 = jnp.pad(k3, ((0, 0), (0, sk_pad - sk), (0, 0)))
        v3 = jnp.pad(v3, ((0, 0), (0, sk_pad - sk), (0, 0)))
        if mask3 is not None:   # padding handled by the validity window
            mask3 = jnp.pad(
                mask3, ((0, 0), (0, sq_pad - sq), (0, sk_pad - sk)))

    # mask3/seed3 are custom_vjp ARGUMENTS, not closure captures: a
    # traced value closed over by a custom_vjp function leaks its trace
    # under nn.scan/lax.scan + grad (UnexpectedTracerError — hit by
    # scan_layers models with dropout).  None passes through as an
    # empty pytree; arrays get float0 cotangents (bool/int primals).
    @jax.custom_vjp
    def run(q3, k3, v3, mask3, seed3):
        out, _ = _fwd(q3, k3, v3, mask3, causal, scale, bq, bk,
                      causal_off=causal_off, valid=valid,
                      rate=dropout_rate, seed3=seed3)
        return out

    def run_fwd(q3, k3, v3, mask3, seed3):
        out, lse = _fwd(q3, k3, v3, mask3, causal, scale, bq, bk,
                        causal_off=causal_off, valid=valid,
                        rate=dropout_rate, seed3=seed3)
        return out, (q3, k3, v3, mask3, seed3, out, lse)

    def run_bwd(res, do3):
        q3, k3, v3, mask3, seed3, out, lse = res
        dq, dk, dv = _bwd_impl(q3, k3, v3, mask3, out, lse, do3,
                               causal, scale, bq, bk,
                               causal_off=causal_off, valid=valid,
                               rate=dropout_rate, seed3=seed3)
        return dq, dk, dv, _zero_cotangent(mask3), _zero_cotangent(seed3)

    run.defvjp(run_fwd, run_bwd)
    out = run(q3, k3, v3, mask3, seed3)
    if padded:
        out = out[:, :sq, :]
    return out.reshape(b, h, sq, d)


# --------------------------------------------------------------------------
# single-token decode attention against a KV cache
# --------------------------------------------------------------------------

#: decode (q_len = 1) kernel/XLA crossover.  A single query row feeds the
#: Pallas kernel a q block padded up to the 128-lane grid — 128x wasted
#: MXU rows — while the whole op is one bandwidth-bound matvec over the
#: cache that XLA lowers to clean VPU code.  The XLA path therefore wins
#: everywhere the O(b·h·S) score tensor stays small; the kernel only
#: pays off once the materialized scores outgrow VMEM-friendly sizes at
#: very long contexts.  4096 is a PROVISIONAL boundary (same status the
#: attention crossover had before the r5 sweep); override per-run with
#: ``APEX_TPU_DECODE_XLA_MAX_SEQ`` or per-call with ``xla_max_seq=``
#: (0 forces the kernel path), and bench infer captures stamp the
#: effective value so on-chip sweeps can refine it without a code edit.
_DECODE_XLA_MAX_SEQ = 4096

_DECODE_XLA_MAX_SEQ_ENV = "APEX_TPU_DECODE_XLA_MAX_SEQ"


def decode_xla_max_seq(override=None) -> int:
    """Effective decode crossover: explicit kwarg override >
    ``APEX_TPU_DECODE_XLA_MAX_SEQ`` env var > the provisional default."""
    if override is not None:
        return int(override)
    env = os.environ.get(_DECODE_XLA_MAX_SEQ_ENV)
    if env:
        try:
            return int(env)
        except ValueError as e:
            raise ValueError(
                f"{_DECODE_XLA_MAX_SEQ_ENV} must be an int, got "
                f"{env!r}") from e
    return _DECODE_XLA_MAX_SEQ


def decode_attention(q, k, v, lengths, *, sm_scale: Optional[float] = None,
                     use_kernel: Optional[bool] = None,
                     xla_max_seq: Optional[int] = None):
    """Single-token attention against a per-slot KV cache.

    The inference engine's decode core: one query per sequence slot
    scores the slot's whole (statically shaped) cache, masked to the
    slot's live length.

    * ``q``: ``[b, h, 1, d]`` (or ``[b, h, d]``) — the current token's
      query heads per slot.
    * ``k``/``v``: ``[b, kv_heads, S, d]`` — the cache, ``kv_heads``
      dividing ``h`` (GQA/MQA: each kv head serves ``h // kv_heads``
      query heads, so LLaMA's replicated-kv layout is scored straight
      from its once-per-kv-head cache with no broadcast materialized on
      the XLA path).
    * ``lengths``: ``[b]`` int32 — valid entries per slot; positions at
      or past a slot's length are masked out.  A slot with length 0
      emits zeros (the kernels' fully-masked-row convention).

    ``use_kernel=None`` auto-dispatches on the cache length: at or under
    the crossover (``xla_max_seq`` kwarg > ``APEX_TPU_DECODE_XLA_MAX_SEQ``
    env var > the provisional default ``_DECODE_XLA_MAX_SEQ``) the op is
    a fused XLA einsum chain — the VPU-friendly shape for a bandwidth
    -bound matvec; above it the flash kernel streams the cache blockwise
    (k/v broadcast to the query heads, the length mask as the kernel's
    boolean mask operand).  Numerics mirror the kernels: input-dtype
    operands into the MXU with fp32 accumulation, fp32 softmax.
    """
    squeezed = q.ndim == 3
    if squeezed:
        q = q[:, :, None, :]
    b, h, q_len, d = q.shape
    if q_len != 1:
        raise ValueError(
            f"decode_attention is the q_len == 1 path, got q_len {q_len}; "
            "use flash_attention for prefill")
    if k.shape != v.shape or k.ndim != 4 or k.shape[0] != b \
            or k.shape[3] != d:
        raise ValueError(
            f"k/v must be [b, kv_heads, S, d] = [{b}, *, *, {d}] and "
            f"equal-shaped; got k {tuple(k.shape)} v {tuple(v.shape)}")
    kvh, s_cache = k.shape[1], k.shape[2]
    if kvh == 0 or h % kvh:
        raise ValueError(
            f"kv_heads ({kvh}) must divide query heads ({h})")
    if lengths.shape != (b,):
        raise ValueError(
            f"lengths must be [{b}], got {tuple(lengths.shape)}")
    scale = (d ** -0.5) if sm_scale is None else sm_scale
    lengths = lengths.astype(jnp.int32)

    if use_kernel is None:
        use_kernel = s_cache > decode_xla_max_seq(xla_max_seq)

    if use_kernel:
        group = h // kvh
        if group > 1:
            kb, vb = (jnp.broadcast_to(
                t[:, :, None], (b, kvh, group, s_cache, d)
            ).reshape(b, h, s_cache, d) for t in (k, v))
        else:
            kb, vb = k, v
        mask = (jnp.arange(s_cache, dtype=jnp.int32)[None, None, None, :]
                >= lengths[:, None, None, None])
        out = flash_attention(q, kb, vb, mask=mask, sm_scale=scale,
                              use_kernel=True)
        return out[:, :, 0] if squeezed else out

    # XLA path: grouped-query einsum chain, no kv broadcast materialized
    group = h // kvh
    qg = q.reshape(b, kvh, group, d)
    s = jax.lax.dot_general(
        qg, k, (((3,), (3,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32) * scale     # [b, kvh, group, S]
    live = (jnp.arange(s_cache, dtype=jnp.int32)[None, None, None, :]
            < lengths[:, None, None, None])
    s = jnp.where(live, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    # length-0 slots: every score is _NEG_INF — emit 0, not uniform
    p = jnp.where(m <= _MASKED_ROW_THRESH, 0.0, p)
    out = jax.lax.dot_general(
        p.astype(v.dtype), v, (((3,), (2,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32)             # [b, kvh, group, d]
    out = out.reshape(b, h, 1, d).astype(q.dtype)
    return out[:, :, 0] if squeezed else out


def slab_decode_attention(q, win_k, win_v, lengths,
                          *, sm_scale: Optional[float] = None):
    """Verify-step attention (ISSUE 15): a small slab of ``S`` drafted
    tokens per slot scores the slot's cache window, causally within the
    slab.

    The q_len = S generalization of :func:`decode_attention`'s XLA
    grouped-einsum chain, shaped for speculative decoding: the slab's
    own k/v have ALREADY been appended to the cache at positions
    ``[lengths, lengths + S)``, so query row ``r`` (absolute position
    ``lengths + r``) attends to window columns ``j <= lengths + r`` —
    the cached context plus the draft prefix up to and including
    itself.  S = 1 degenerates to exactly ``decode_attention``'s
    masking (``j < lengths + 1``).

    * ``q``: ``[slots, h, S, d]`` — the drafted tokens' query heads.
    * ``win_k``/``win_v``: ``[slots, kv_heads, W, d]`` — the slot's
      full cache window (dense cache directly; paged via the page
      gather in :func:`~apex_tpu.ops.paged_attention.
      paged_slab_attention`).
    * ``lengths``: ``[slots]`` int32 — live tokens BEFORE the slab was
      appended.

    Rows whose absolute position falls outside the window (a slot at
    the end of its virtual window — its slab rows were dropped by the
    append) are fully masked and emit zeros, mirroring the kernels'
    fully-masked-row convention; their emitted tokens are garbage the
    caller retires as truncated.  Numerics mirror
    :func:`decode_attention`: input-dtype MXU operands with fp32
    accumulation, fp32 softmax, no kv broadcast materialized.
    """
    slots, h, sq, d = q.shape
    if win_k.shape != win_v.shape or win_k.ndim != 4 \
            or win_k.shape[0] != slots or win_k.shape[3] != d:
        raise ValueError(
            f"window k/v must be [{slots}, kv_heads, W, {d}] and "
            f"equal-shaped; got win_k {tuple(win_k.shape)} win_v "
            f"{tuple(win_v.shape)}")
    kvh, w = win_k.shape[1], win_k.shape[2]
    if kvh == 0 or h % kvh:
        raise ValueError(
            f"kv_heads ({kvh}) must divide query heads ({h})")
    if lengths.shape != (slots,):
        raise ValueError(
            f"lengths must be [{slots}], got {tuple(lengths.shape)}")
    scale = (d ** -0.5) if sm_scale is None else sm_scale
    lengths = lengths.astype(jnp.int32)
    group = h // kvh
    qg = q.reshape(slots, kvh, group, sq, d)
    s = jax.lax.dot_general(
        qg, win_k, (((4,), (3,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32) * scale  # [b, kvh, g, S, W]
    col = jnp.arange(w, dtype=jnp.int32)[None, None, :]       # [1, 1, W]
    row = jnp.arange(sq, dtype=jnp.int32)[None, :, None]      # [1, S, 1]
    pos = lengths[:, None, None] + row            # absolute row position
    # rows past the virtual window (their append was dropped) mask
    # FULLY: without the pos < w term they would attend to the whole
    # window minus themselves and emit plausible-looking garbage
    live = (col <= pos) & (pos < jnp.int32(w))                # [b, S, W]
    s = jnp.where(live[:, None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    # rows past the virtual window (dropped appends) are fully masked —
    # emit zeros, not softmax-of-constant's uniform artifact
    p = jnp.where(m <= _MASKED_ROW_THRESH, 0.0, p)
    out = jax.lax.dot_general(
        p.astype(win_v.dtype), win_v, (((4,), (2,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32)          # [b, kvh, g, S, d]
    return out.reshape(slots, h, sq, d).astype(q.dtype)


def prefix_window_attention(q, k, v, win_k, win_v, start,
                            *, sm_scale: Optional[float] = None):
    """Suffix-prefill attention: each query row attends to a cached
    prefix WINDOW plus causally to the suffix itself (ISSUE 12 — the
    math behind prefix-cache hits and chunked prefill).

    * ``q``: ``[b, h, s, d]`` — the suffix tokens' query heads; row
      ``i`` sits at absolute position ``start + i``.
    * ``k``/``v``: ``[b, kv_heads, s, d]`` — the suffix's own
      (pre-broadcast, GQA/MQA) keys/values.
    * ``win_k``/``win_v``: ``[b, kv_heads, W, d]`` — the cached prefix
      window gathered from the slot's KV pages; only columns
      ``j < start`` are live (rows past the prefix hold other pages'
      garbage — finite by construction — and are masked, so their
      values can never leak into the context).
    * ``start``: ``[]`` int32 (traced OK) — the prefix length, i.e.
      how many window columns are valid.

    One fused XLA chain mirroring :func:`decode_attention`'s grouped
    einsum path: bf16 operands into the MXU with fp32 accumulation,
    fp32 softmax over the concatenated ``[W + s]`` key axis.  Every
    real query row has at least itself to attend to (causal self), so
    no fully-masked-row zeroing is needed.
    """
    b, h, sq, d = q.shape
    if k.shape != v.shape or k.ndim != 4 or k.shape[0] != b \
            or k.shape[2] != sq or k.shape[3] != d:
        raise ValueError(
            f"suffix k/v must be [b, kv_heads, {sq}, {d}], got "
            f"k {tuple(k.shape)} v {tuple(v.shape)}")
    if win_k.shape != win_v.shape or win_k.ndim != 4 \
            or win_k.shape[:2] != k.shape[:2] or win_k.shape[3] != d:
        raise ValueError(
            f"window k/v must be [b, kv_heads, W, {d}], got "
            f"win_k {tuple(win_k.shape)} win_v {tuple(win_v.shape)}")
    kvh, w = win_k.shape[1], win_k.shape[2]
    if kvh == 0 or h % kvh:
        raise ValueError(
            f"kv_heads ({kvh}) must divide query heads ({h})")
    scale = (d ** -0.5) if sm_scale is None else sm_scale
    start = jnp.asarray(start, jnp.int32)
    group = h // kvh
    qg = q.reshape(b, kvh, group, sq, d)
    kk = jnp.concatenate([win_k, k], axis=2)            # [b, kvh, W+s, d]
    vv = jnp.concatenate([win_v, v], axis=2)
    s = jax.lax.dot_general(
        qg, kk, (((4,), (3,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32) * scale  # [b,kvh,g,s,W+s]
    col = jnp.arange(w + sq, dtype=jnp.int32)[None, :]
    row = jnp.arange(sq, dtype=jnp.int32)[:, None]
    valid = jnp.where(col < w, col < start, (col - w) <= row)
    s = jnp.where(valid[None, None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    out = jax.lax.dot_general(
        p.astype(vv.dtype), vv, (((4,), (2,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32)          # [b, kvh, g, s, d]
    return out.reshape(b, h, sq, d).astype(q.dtype)
