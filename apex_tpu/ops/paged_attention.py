"""Ragged paged decode attention — single-token attention against a
paged KV pool (PAPERS.md "Ragged Paged Attention", TPU-native).

The paged twin of :func:`apex_tpu.ops.attention.decode_attention`: one
query per sequence slot scores the slot's live tokens, but the tokens
live in fixed-size PAGES of a shared pool rather than a contiguous
per-slot ``max_seq`` window —

    k_pages, v_pages : [pages, kv_heads, page_size, head_dim]
    page_table       : [slots, max_pages_per_slot]  int32
    lengths          : [slots]                      int32

virtual position ``t`` of a slot resolves to physical page
``page_table[slot, t // page_size]``, row ``t % page_size``.

Two implementations behind one crossover knob, mirroring the dense
kernel/XLA machinery in ``attention.py``:

* **Pallas kernel** (long virtual windows): grid ``(slots, pages)``
  with the page table and lengths as SCALAR-PREFETCH operands — the
  k/v BlockSpec index map reads ``page_table[slot, page]`` so Pallas
  DMAs exactly that slot's live pages from HBM, page by page, with its
  standard double buffering; nothing resembling the gathered
  ``[slots, max_seq]`` window ever materializes.  Online softmax (fp32
  running max/normalizer/accumulator in VMEM scratch, base-2 log
  domain like the flash kernels) carries across the page loop; dead
  pages are skipped (``pl.when``) and their DMA is deduplicated by
  clamping the index map to the slot's last live page (Pallas skips
  refetching an unchanged block index).  Dead rows inside the last
  live page mask to ``_NEG_INF``.

* **XLA gather fallback** (short windows): gather the slot's pages
  into the dense ``[slots, kv_heads, max_seq, d]`` window and reuse
  ``decode_attention``'s grouped-query einsum chain — at small
  ``max_pages_per_slot`` the gather transient is cheap and XLA's fused
  matvec wins for the same reason the dense crossover exists.  The
  gathered window equals the dense cache's view position for position,
  so this path is numerically IDENTICAL to the dense XLA decode path.

GQA/MQA: ``kv_heads`` divides the query heads; the kernel loops kv
heads (static, small) scoring each head's ``group`` query rows against
the once-per-kv-head page — no broadcast materialized anywhere.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops.attention import (_LOG2E, _NEG_INF, decode_attention)
from apex_tpu.utils import interpret_mode

__all__ = ["paged_decode_attention", "paged_xla_max_pages"]

#: paged kernel/XLA crossover, in PAGES per slot (the paged analog of
#: ``_DECODE_XLA_MAX_SEQ``; ~4096 tokens at the default page size 64).
#: Below it the XLA gather fallback materializes the slot windows —
#: fine while they are small; above it the Pallas kernel streams pages
#: straight from the pool.  PROVISIONAL like the dense decode crossover
#: was at introduction: override per-run with the environment variable
#: ``APEX_TPU_PAGED_XLA_MAX_PAGES`` or per-call with ``xla_max_pages=``
#: (0 forces the kernel path); bench infer captures stamp the
#: effective value so on-chip sweeps can refine it without a code edit.
_PAGED_XLA_MAX_PAGES = 64

_PAGED_XLA_MAX_PAGES_ENV = "APEX_TPU_PAGED_XLA_MAX_PAGES"


def paged_xla_max_pages(override=None) -> int:
    """Effective paged-decode crossover: explicit kwarg override >
    ``APEX_TPU_PAGED_XLA_MAX_PAGES`` env var > the provisional
    default."""
    if override is not None:
        return int(override)
    env = os.environ.get(_PAGED_XLA_MAX_PAGES_ENV)
    if env:
        try:
            return int(env)
        except ValueError as e:
            raise ValueError(
                f"{_PAGED_XLA_MAX_PAGES_ENV} must be an int, got "
                f"{env!r}") from e
    return _PAGED_XLA_MAX_PAGES


# --------------------------------------------------------------------------
# Pallas kernel: grid (slots, pages), page table as scalar prefetch
# --------------------------------------------------------------------------

def _paged_kernel(scale, kvh, group, ps, mpps,
                  pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  s_scr, m_scr, l_scr, acc_scr):
    sid = pl.program_id(0)
    p = pl.program_id(1)
    h = kvh * group

    @pl.when(p == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = len_ref[sid]
    live_pages = (length + ps - 1) // ps

    @pl.when(p < live_pages)
    def _body():
        q = q_ref[0]                                     # [h, d]
        # per-kv-head scoring: each kv head's page block serves its
        # `group` query rows (GQA) — kvh is static and small, and the
        # disjoint row segments land in one [h, ps] score scratch
        for i in range(kvh):
            seg = slice(i * group, (i + 1) * group)
            s_scr[seg, :] = jax.lax.dot_general(
                q[seg], k_ref[0, i], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * (scale * _LOG2E)
        cols = p * ps + jax.lax.broadcasted_iota(jnp.int32, (h, ps), 1)
        s = jnp.where(cols < length, s_scr[...], _NEG_INF)
        # online softmax, base-2 log domain (scale absorbed log2e):
        # within a live page every row has >= 1 live column, so no
        # fully-masked-row guard is needed here (length-0 slots never
        # enter the body and finish at l == 0 -> zeros)
        m_prev = m_scr[...]                              # [h, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp2(m_prev - m_new)
        pmat = jnp.exp2(s - m_new)
        l_scr[...] = l_scr[...] * alpha + \
            jnp.sum(pmat, axis=1, keepdims=True)
        for i in range(kvh):
            seg = slice(i * group, (i + 1) * group)
            acc_scr[seg, :] = acc_scr[seg, :] * alpha[seg] + jax.lax.dot(
                pmat[seg, :].astype(v_ref.dtype), v_ref[0, i],
                preferred_element_type=jnp.float32)
        m_scr[...] = m_new

    @pl.when(p == mpps - 1)
    def _finish():
        l = l_scr[...]
        o_ref[0] = (acc_scr[...] / jnp.where(l == 0.0, 1.0, l)
                    ).astype(o_ref.dtype)


def _paged_kernel_call(q, k_pages, v_pages, page_table, lengths, scale):
    slots, h, d = q.shape
    _, kvh, ps, _ = k_pages.shape
    mpps = page_table.shape[1]
    group = h // kvh

    def page_index(s, p, pt, ln):
        # clamp dead trailing pages to the slot's last live page: an
        # unchanged block index lets Pallas skip the (useless) refetch,
        # and pl.when skips its compute entirely
        last = jnp.maximum((ln[s] + ps - 1) // ps - 1, 0)
        return (pt[s, jnp.minimum(p, last)], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, mpps),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda s, p, pt, ln: (s, 0, 0)),
            pl.BlockSpec((1, kvh, ps, d), page_index),
            pl.BlockSpec((1, kvh, ps, d), page_index),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda s, p, pt, ln: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, ps), jnp.float32),     # score block
            pltpu.VMEM((h, 1), jnp.float32),      # running max (base 2)
            pltpu.VMEM((h, 1), jnp.float32),      # running normalizer
            pltpu.VMEM((h, d), jnp.float32),      # fp32 output accum
        ],
    )
    kernel = functools.partial(_paged_kernel, scale, kvh, group, ps, mpps)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, h, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(page_table, lengths, q, k_pages, v_pages)


# --------------------------------------------------------------------------
# public entry
# --------------------------------------------------------------------------

def paged_decode_attention(q, k_pages, v_pages, page_table, lengths, *,
                           sm_scale: Optional[float] = None,
                           use_kernel: Optional[bool] = None,
                           xla_max_pages: Optional[int] = None):
    """Single-token attention against a paged KV pool.

    * ``q``: ``[slots, h, 1, d]`` (or ``[slots, h, d]``) — the current
      token's query heads per slot.
    * ``k_pages``/``v_pages``: ``[pages, kv_heads, page_size, d]`` —
      ONE layer's slice of the pool, ``kv_heads`` dividing ``h``.
    * ``page_table``: ``[slots, max_pages_per_slot]`` int32 — physical
      page backing each ``page_size`` stretch of the slot's virtual
      window; dead entries may hold any valid page index (they are
      masked by ``lengths``, and the pool's trash page is the
      conventional filler).
    * ``lengths``: ``[slots]`` int32 — live tokens per slot; a slot
      with length 0 emits zeros.

    ``use_kernel=None`` auto-dispatches on ``max_pages_per_slot``: at
    or under the crossover (``xla_max_pages`` kwarg >
    ``APEX_TPU_PAGED_XLA_MAX_PAGES`` env var > the provisional default
    ``_PAGED_XLA_MAX_PAGES``) the pages are gathered into dense slot
    windows and scored by ``decode_attention``'s XLA einsum chain
    (numerically identical to the dense cache's decode); above it the
    Pallas kernel streams the live pages via the page table with no
    materialized gather.
    """
    squeezed = q.ndim == 3
    if squeezed:
        q = q[:, :, None, :]
    slots, h, q_len, d = q.shape
    if q_len != 1:
        raise ValueError(
            f"paged_decode_attention is the q_len == 1 path, got q_len "
            f"{q_len}; use flash_attention for prefill")
    if k_pages.shape != v_pages.shape or k_pages.ndim != 4 \
            or k_pages.shape[3] != d:
        raise ValueError(
            f"k/v pages must be [pages, kv_heads, page_size, {d}] and "
            f"equal-shaped; got k {tuple(k_pages.shape)} v "
            f"{tuple(v_pages.shape)}")
    kvh = k_pages.shape[1]
    if kvh == 0 or h % kvh:
        raise ValueError(
            f"kv_heads ({kvh}) must divide query heads ({h})")
    if page_table.ndim != 2 or page_table.shape[0] != slots:
        raise ValueError(
            f"page_table must be [{slots}, max_pages_per_slot], got "
            f"{tuple(page_table.shape)}")
    if lengths.shape != (slots,):
        raise ValueError(
            f"lengths must be [{slots}], got {tuple(lengths.shape)}")
    mpps = page_table.shape[1]
    ps = k_pages.shape[2]
    scale = (d ** -0.5) if sm_scale is None else sm_scale
    page_table = page_table.astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)

    if use_kernel is None:
        use_kernel = mpps > paged_xla_max_pages(xla_max_pages)

    if not use_kernel:
        # gather the virtual windows and reuse the dense XLA chain —
        # [slots, mpps, kvh, ps, d] -> [slots, kvh, mpps*ps, d]
        def window(pages):
            g = jnp.take(pages, page_table, axis=0)
            return jnp.moveaxis(g, 2, 1).reshape(slots, kvh, mpps * ps, d)

        out = decode_attention(q, window(k_pages), window(v_pages),
                               lengths, sm_scale=scale, use_kernel=False)
        return out[:, :, 0] if squeezed else out

    out = _paged_kernel_call(q[:, :, 0, :], k_pages, v_pages, page_table,
                             lengths, scale)
    return out if squeezed else out[:, :, None, :]
