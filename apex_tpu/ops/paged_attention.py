"""Ragged paged decode attention — single-token attention against a
paged KV pool (PAPERS.md "Ragged Paged Attention", TPU-native).

The paged twin of :func:`apex_tpu.ops.attention.decode_attention`: one
query per sequence slot scores the slot's live tokens, but the tokens
live in fixed-size PAGES of a shared pool rather than a contiguous
per-slot ``max_seq`` window —

    k_pages, v_pages : [pages, kv_heads, page_size, head_dim]
    page_table       : [slots, max_pages_per_slot]  int32
    lengths          : [slots]                      int32

virtual position ``t`` of a slot resolves to physical page
``page_table[slot, t // page_size]``, row ``t % page_size``.

Two implementations behind one crossover knob, mirroring the dense
kernel/XLA machinery in ``attention.py``:

* **Pallas kernel** (long virtual windows): grid ``(slots, pages)``
  with the page table and lengths as SCALAR-PREFETCH operands — the
  k/v BlockSpec index map reads ``page_table[slot, page]`` so Pallas
  DMAs exactly that slot's live pages from HBM, page by page, with its
  standard double buffering; nothing resembling the gathered
  ``[slots, max_seq]`` window ever materializes.  Online softmax (fp32
  running max/normalizer/accumulator in VMEM scratch, base-2 log
  domain like the flash kernels) carries across the page loop; dead
  pages are skipped (``pl.when``) and their DMA is deduplicated by
  clamping the index map to the slot's last live page (Pallas skips
  refetching an unchanged block index).  Dead rows inside the last
  live page mask to ``_NEG_INF``.

* **XLA gather fallback** (short windows): gather the slot's pages
  into the dense ``[slots, kv_heads, max_seq, d]`` window and reuse
  ``decode_attention``'s grouped-query einsum chain — at small
  ``max_pages_per_slot`` the gather transient is cheap and XLA's fused
  matvec wins for the same reason the dense crossover exists.  The
  gathered window equals the dense cache's view position for position,
  so this path is numerically IDENTICAL to the dense XLA decode path.

GQA/MQA: ``kv_heads`` divides the query heads; the kernel loops kv
heads (static, small) scoring each head's ``group`` query rows against
the once-per-kv-head page — no broadcast materialized anywhere.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops.attention import (_LOG2E, _NEG_INF, decode_attention,
                                    slab_decode_attention)
from apex_tpu.utils import interpret_mode

__all__ = ["paged_decode_attention", "paged_xla_max_pages",
           "paged_slab_attention", "fused_block_decode", "decode_fusion",
           "fusion_min_pages", "resolve_decode_fusion"]

#: pallas_audit registration (analysis hook only, no behavior change):
#: both kernels run online-softmax in fp32 scratch (APX302) and mask
#: beyond-length pages in-kernel — the page grid intentionally covers
#: the slot's max_pages even when length doesn't fill the last page
#: (APX303 masked_tail).
PALLAS_AUDIT = {
    "_paged_kernel": {"reduction": True, "masked_tail": True},
    "_fused_block_kernel": {"reduction": True, "masked_tail": True},
}

#: paged kernel/XLA crossover, in PAGES per slot (the paged analog of
#: ``_DECODE_XLA_MAX_SEQ``; ~4096 tokens at the default page size 64).
#: Below it the XLA gather fallback materializes the slot windows —
#: fine while they are small; above it the Pallas kernel streams pages
#: straight from the pool.  PROVISIONAL like the dense decode crossover
#: was at introduction: override per-run with the environment variable
#: ``APEX_TPU_PAGED_XLA_MAX_PAGES`` or per-call with ``xla_max_pages=``
#: (0 forces the kernel path); bench infer captures stamp the
#: effective value so on-chip sweeps can refine it without a code edit.
_PAGED_XLA_MAX_PAGES = 64

_PAGED_XLA_MAX_PAGES_ENV = "APEX_TPU_PAGED_XLA_MAX_PAGES"


def paged_xla_max_pages(override=None) -> int:
    """Effective paged-decode crossover: explicit kwarg override >
    ``APEX_TPU_PAGED_XLA_MAX_PAGES`` env var > the provisional
    default."""
    if override is not None:
        return int(override)
    env = os.environ.get(_PAGED_XLA_MAX_PAGES_ENV)
    if env:
        try:
            return int(env)
        except ValueError as e:
            raise ValueError(
                f"{_PAGED_XLA_MAX_PAGES_ENV} must be an int, got "
                f"{env!r}") from e
    return _PAGED_XLA_MAX_PAGES


# --------------------------------------------------------------------------
# Pallas kernel: grid (slots, pages), page table as scalar prefetch
# --------------------------------------------------------------------------

def _paged_kernel(scale, kvh, group, ps, mpps,
                  pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  s_scr, m_scr, l_scr, acc_scr):
    sid = pl.program_id(0)
    p = pl.program_id(1)
    h = kvh * group

    @pl.when(p == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = len_ref[sid]
    live_pages = (length + ps - 1) // ps

    @pl.when(p < live_pages)
    def _body():
        q = q_ref[0]                                     # [h, d]
        # per-kv-head scoring: each kv head's page block serves its
        # `group` query rows (GQA) — kvh is static and small, and the
        # disjoint row segments land in one [h, ps] score scratch
        for i in range(kvh):
            seg = slice(i * group, (i + 1) * group)
            s_scr[seg, :] = jax.lax.dot_general(
                q[seg], k_ref[0, i], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * (scale * _LOG2E)
        cols = p * ps + jax.lax.broadcasted_iota(jnp.int32, (h, ps), 1)
        s = jnp.where(cols < length, s_scr[...], _NEG_INF)
        # online softmax, base-2 log domain (scale absorbed log2e):
        # within a live page every row has >= 1 live column, so no
        # fully-masked-row guard is needed here (length-0 slots never
        # enter the body and finish at l == 0 -> zeros)
        m_prev = m_scr[...]                              # [h, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp2(m_prev - m_new)
        pmat = jnp.exp2(s - m_new)
        l_scr[...] = l_scr[...] * alpha + \
            jnp.sum(pmat, axis=1, keepdims=True)
        for i in range(kvh):
            seg = slice(i * group, (i + 1) * group)
            acc_scr[seg, :] = acc_scr[seg, :] * alpha[seg] + jax.lax.dot(
                pmat[seg, :].astype(v_ref.dtype), v_ref[0, i],
                preferred_element_type=jnp.float32)
        m_scr[...] = m_new

    @pl.when(p == mpps - 1)
    def _finish():
        l = l_scr[...]
        o_ref[0] = (acc_scr[...] / jnp.where(l == 0.0, 1.0, l)
                    ).astype(o_ref.dtype)


def _paged_kernel_call(q, k_pages, v_pages, page_table, lengths, scale):
    slots, h, d = q.shape
    _, kvh, ps, _ = k_pages.shape
    mpps = page_table.shape[1]
    group = h // kvh

    def page_index(s, p, pt, ln):
        # clamp dead trailing pages to the slot's last live page: an
        # unchanged block index lets Pallas skip the (useless) refetch,
        # and pl.when skips its compute entirely
        last = jnp.maximum((ln[s] + ps - 1) // ps - 1, 0)
        return (pt[s, jnp.minimum(p, last)], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, mpps),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda s, p, pt, ln: (s, 0, 0)),
            pl.BlockSpec((1, kvh, ps, d), page_index),
            pl.BlockSpec((1, kvh, ps, d), page_index),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda s, p, pt, ln: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, ps), jnp.float32),     # score block
            pltpu.VMEM((h, 1), jnp.float32),      # running max (base 2)
            pltpu.VMEM((h, 1), jnp.float32),      # running normalizer
            pltpu.VMEM((h, d), jnp.float32),      # fp32 output accum
        ],
    )
    kernel = functools.partial(_paged_kernel, scale, kvh, group, ps, mpps)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, h, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(page_table, lengths, q, k_pages, v_pages)


# --------------------------------------------------------------------------
# public entry
# --------------------------------------------------------------------------

def paged_decode_attention(q, k_pages, v_pages, page_table, lengths, *,
                           sm_scale: Optional[float] = None,
                           use_kernel: Optional[bool] = None,
                           xla_max_pages: Optional[int] = None):
    """Single-token attention against a paged KV pool.

    * ``q``: ``[slots, h, 1, d]`` (or ``[slots, h, d]``) — the current
      token's query heads per slot.
    * ``k_pages``/``v_pages``: ``[pages, kv_heads, page_size, d]`` —
      ONE layer's slice of the pool, ``kv_heads`` dividing ``h``.
    * ``page_table``: ``[slots, max_pages_per_slot]`` int32 — physical
      page backing each ``page_size`` stretch of the slot's virtual
      window; dead entries may hold any valid page index (they are
      masked by ``lengths``, and the pool's trash page is the
      conventional filler).
    * ``lengths``: ``[slots]`` int32 — live tokens per slot; a slot
      with length 0 emits zeros.

    ``use_kernel=None`` auto-dispatches on ``max_pages_per_slot``: at
    or under the crossover (``xla_max_pages`` kwarg >
    ``APEX_TPU_PAGED_XLA_MAX_PAGES`` env var > the provisional default
    ``_PAGED_XLA_MAX_PAGES``) the pages are gathered into dense slot
    windows and scored by ``decode_attention``'s XLA einsum chain
    (numerically identical to the dense cache's decode); above it the
    Pallas kernel streams the live pages via the page table with no
    materialized gather.
    """
    squeezed = q.ndim == 3
    if squeezed:
        q = q[:, :, None, :]
    slots, h, q_len, d = q.shape
    if q_len != 1:
        raise ValueError(
            f"paged_decode_attention is the q_len == 1 path, got q_len "
            f"{q_len}; use flash_attention for prefill")
    if k_pages.shape != v_pages.shape or k_pages.ndim != 4 \
            or k_pages.shape[3] != d:
        raise ValueError(
            f"k/v pages must be [pages, kv_heads, page_size, {d}] and "
            f"equal-shaped; got k {tuple(k_pages.shape)} v "
            f"{tuple(v_pages.shape)}")
    kvh = k_pages.shape[1]
    if kvh == 0 or h % kvh:
        raise ValueError(
            f"kv_heads ({kvh}) must divide query heads ({h})")
    if page_table.ndim != 2 or page_table.shape[0] != slots:
        raise ValueError(
            f"page_table must be [{slots}, max_pages_per_slot], got "
            f"{tuple(page_table.shape)}")
    if lengths.shape != (slots,):
        raise ValueError(
            f"lengths must be [{slots}], got {tuple(lengths.shape)}")
    mpps = page_table.shape[1]
    ps = k_pages.shape[2]
    scale = (d ** -0.5) if sm_scale is None else sm_scale
    page_table = page_table.astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)

    if use_kernel is None:
        use_kernel = mpps > paged_xla_max_pages(xla_max_pages)

    if not use_kernel:
        # gather the virtual windows and reuse the dense XLA chain —
        # [slots, mpps, kvh, ps, d] -> [slots, kvh, mpps*ps, d]
        def window(pages):
            g = jnp.take(pages, page_table, axis=0)
            return jnp.moveaxis(g, 2, 1).reshape(slots, kvh, mpps * ps, d)

        out = decode_attention(q, window(k_pages), window(v_pages),
                               lengths, sm_scale=scale, use_kernel=False)
        return out[:, :, 0] if squeezed else out

    out = _paged_kernel_call(q[:, :, 0, :], k_pages, v_pages, page_table,
                             lengths, scale)
    return out if squeezed else out[:, :, None, :]


# --------------------------------------------------------------------------
# verify-slab attention (ISSUE 15): q_len = S against the paged pool
# --------------------------------------------------------------------------

def paged_slab_attention(q, k_pages, v_pages, page_table, lengths, *,
                         sm_scale: Optional[float] = None):
    """Speculative-verify attention against the paged pool: ``S``
    drafted tokens per slot (already appended to the slot's pages at
    positions ``[lengths, lengths + S)``) score the slot's virtual
    window, causally within the slab.

    The q_len = S sibling of :func:`paged_decode_attention`'s XLA
    gather path: the slot's pages gather into the dense
    ``[slots, kv_heads, max_seq, d]`` window (position for position the
    dense cache's view) and
    :func:`~apex_tpu.ops.attention.slab_decode_attention` scores it —
    numerically IDENTICAL to the dense cache's verify path, which is
    what keeps the speculative parity suite bitwise across cache
    layouts.  ``S`` is the engine's static ``spec_k + 1``, so one
    compiled verify step serves every wave.

    Scope note: unlike the q_len = 1 decode, the verify step has ONLY
    this gather lowering today — at very long virtual windows (where
    decode crosses to the Pallas streaming kernel) every verify round
    materializes the full window per layer, which erodes the
    speculation win.  The q_len = S streaming-kernel extension (the
    ``_paged_kernel`` grid with an S-row score block and causal
    masking on the final pages) is the PERF.md round-15 follow-up
    alongside the fused block's weight-tile streaming.
    """
    slots, h, sq, d = q.shape
    if k_pages.shape != v_pages.shape or k_pages.ndim != 4 \
            or k_pages.shape[3] != d:
        raise ValueError(
            f"k/v pages must be [pages, kv_heads, page_size, {d}] and "
            f"equal-shaped; got k {tuple(k_pages.shape)} v "
            f"{tuple(v_pages.shape)}")
    kvh = k_pages.shape[1]
    if kvh == 0 or h % kvh:
        raise ValueError(
            f"kv_heads ({kvh}) must divide query heads ({h})")
    if page_table.ndim != 2 or page_table.shape[0] != slots:
        raise ValueError(
            f"page_table must be [{slots}, max_pages_per_slot], got "
            f"{tuple(page_table.shape)}")
    mpps, ps = page_table.shape[1], k_pages.shape[2]
    page_table = page_table.astype(jnp.int32)

    def window(pages):
        g = jnp.take(pages, page_table, axis=0)
        return jnp.moveaxis(g, 2, 1).reshape(slots, kvh, mpps * ps, d)

    return slab_decode_attention(q, window(k_pages), window(v_pages),
                                 lengths, sm_scale=sm_scale)


# --------------------------------------------------------------------------
# fused transformer-block decode (ISSUE 15 tentpole)
# --------------------------------------------------------------------------
#
# One Pallas kernel per layer covering the decode hot path end to end:
#
#     norm1 -> qkv projection (+RoPE) -> paged attention over the
#     slot's live pages INCLUDING the current token -> output
#     projection -> residual -> [norm2 -> MLP -> residual]
#
# Grid (slots, pages), page table + lengths as scalar prefetch exactly
# like the attention-only kernel above.  The layer's weights ride in
# whole-array VMEM blocks with CONSTANT index maps, so Pallas DMAs each
# weight from HBM once and keeps it resident for every slot and page
# of the grid — the q_len = 1 activations (x, q, the fresh k/v, the
# online-softmax state) never leave VMEM between sublayers.  The
# unfused path round-trips five intermediates per layer through HBM
# (norm1 out, qkv, attention context, attn-out residual, norm2 out);
# here only the block output and the one token's k/v (for the pool
# append that follows) cross the HBM boundary.
#
# The current token's k/v are folded into the online softmax as one
# extra column FROM SCRATCH (the unfused path appends to the pool
# first and reads the row back); the caller appends them after the
# kernel, so the pool write stays the existing one-scatter-per-layer
# program and the kernel needs no aliased outputs.
#
# Numerics: fp32 norm statistics, bf16 operands into the MXU with fp32
# accumulation, fp32 online softmax in the base-2 log domain — the
# same discipline as the attention kernels.  The residual chain stays
# fp32 inside the kernel (the unfused path rounds to bf16 at each
# sublayer boundary), so fused vs unfused parity is tolerance, not
# bitwise; bitwise belongs to the XLA fallback (fusion off == the
# original per-op path, untouched).

_DECODE_FUSION_ENV = "APEX_TPU_DECODE_FUSION"

#: fused-block/unfused crossover in PAGES per slot, used when
#: ``APEX_TPU_DECODE_FUSION=auto``: short virtual windows are dominated
#: by the projections (XLA's fused matvecs are already good there);
#: long windows are where streaming pages through one kernel with the
#: weights resident wins.  PROVISIONAL like every crossover at
#: introduction — override with ``APEX_TPU_FUSION_MIN_PAGES``; bench
#: infer captures stamp the effective value.
_FUSION_MIN_PAGES = 8

_FUSION_MIN_PAGES_ENV = "APEX_TPU_FUSION_MIN_PAGES"


def decode_fusion(override=None) -> str:
    """Effective fused-block decode mode: explicit override >
    ``APEX_TPU_DECODE_FUSION`` env var > ``"0"`` (unfused default).
    ``"0"`` = the per-op XLA path, ``"1"`` = the fused-block kernel,
    ``"auto"`` = fuse when the engine's window is at least
    :func:`fusion_min_pages` pages."""
    val = override if override is not None \
        else (os.environ.get(_DECODE_FUSION_ENV) or "0")
    val = str(val).strip().lower() or "0"
    if val in ("0", "false", "off"):
        return "0"
    if val in ("1", "true", "on"):
        return "1"
    if val == "auto":
        return "auto"
    raise ValueError(
        f"{_DECODE_FUSION_ENV} must be 0, 1 or auto, got {val!r}")


def fusion_min_pages(override=None) -> int:
    """Effective auto-fusion crossover: explicit kwarg override >
    ``APEX_TPU_FUSION_MIN_PAGES`` env var > the provisional default."""
    if override is not None:
        return int(override)
    env = os.environ.get(_FUSION_MIN_PAGES_ENV)
    if env:
        try:
            return int(env)
        except ValueError as e:
            raise ValueError(
                f"{_FUSION_MIN_PAGES_ENV} must be an int, got "
                f"{env!r}") from e
    return _FUSION_MIN_PAGES


def resolve_decode_fusion(mode=None, *, paged: bool,
                          max_pages: Optional[int] = None,
                          min_pages: Optional[int] = None) -> bool:
    """Engine-side dispatch: does THIS engine run the fused-block
    decode kernel?  The fused kernel streams the slot's pages via the
    page table, so it rides the paged cache only — ``mode="1"`` on a
    dense engine is a configuration error, while ``"auto"`` quietly
    resolves to the (only available) unfused path."""
    mode = decode_fusion(mode)
    if mode == "0":
        return False
    if not paged:
        if mode == "1":
            raise ValueError(
                "fused-block decode streams the slot's KV pages via "
                "the page table (APEX_TPU_DECODE_FUSION=1 needs a "
                "paged engine); this engine runs the dense slot cache")
        return False
    if mode == "1":
        return True
    return int(max_pages or 0) >= fusion_min_pages(min_pages)


def _fused_block_kernel(kind, scale, kvh, group, ps, mpps, hidden, d,
                        eps, fuse_mlp, partial_out, *refs):
    gpt = kind == "gpt"
    h = kvh * group
    f32 = jnp.float32
    it = iter(refs)
    pt_ref, len_ref = next(it), next(it)
    x_ref = next(it)
    cos_ref = sin_ref = None
    if not gpt:
        cos_ref, sin_ref = next(it), next(it)
    ln1_w = next(it)
    ln1_b = next(it) if gpt else None
    wq = next(it)
    bq = next(it) if gpt else None
    wk = next(it)
    bk = next(it) if gpt else None
    wv = next(it)
    bv = next(it) if gpt else None
    k_ref, v_ref = next(it), next(it)
    wo = next(it)
    bo = next(it) if gpt and not partial_out else None
    ln2_w = ln2_b = wg = wu = bu = wd = bd = None
    if fuse_mlp:
        ln2_w = next(it)
        ln2_b = next(it) if gpt else None
        if not gpt:
            wg = next(it)
        wu = next(it)
        bu = next(it) if gpt else None
        wd = next(it)
        bd = next(it) if gpt else None
    o_ref, kt_ref, vt_ref = next(it), next(it), next(it)
    q_scr, kn_scr, vn_scr, s_scr, m_scr, l_scr, acc_scr = it

    sid = pl.program_id(0)
    p = pl.program_id(1)

    def norm(x, w_ref, b_ref):
        w = w_ref[...].astype(f32)
        if gpt:
            mu = jnp.mean(x, axis=1, keepdims=True)
            var = jnp.mean((x - mu) ** 2, axis=1, keepdims=True)
            return (x - mu) * jax.lax.rsqrt(var + eps) * w \
                + b_ref[...].astype(f32)
        ms = jnp.mean(x * x, axis=1, keepdims=True)
        return x * jax.lax.rsqrt(ms + eps) * w

    def matmul(x2d, w_ref, b_ref):
        y = jax.lax.dot(x2d.astype(w_ref.dtype), w_ref[...],
                        preferred_element_type=f32)
        if b_ref is not None:
            y = y + b_ref[...].astype(f32)
        return y

    @pl.when(p == 0)
    def _project():
        # norm1 + the three projections run ONCE per slot; everything
        # they produce stays in VMEM scratch across the page loop
        xv = x_ref[...].astype(f32)                      # [1, hidden]
        h1 = norm(xv, ln1_w, ln1_b)
        qh = matmul(h1, wq, bq).reshape(h, d)
        kh = matmul(h1, wk, bk).reshape(kvh, d)
        vh = matmul(h1, wv, bv).reshape(kvh, d)
        if not gpt:
            cos = cos_ref[...].astype(f32)               # [1, d]
            sin = sin_ref[...].astype(f32)

            def rot(t):
                t1, t2 = jnp.split(t, 2, axis=-1)
                return jnp.concatenate((-t2, t1), axis=-1)

            qh = qh * cos + rot(qh) * sin
            kh = kh * cos + rot(kh) * sin
        q_scr[...] = qh
        kn_scr[...] = kh
        vn_scr[...] = vh
        kt_ref[...] = kh.reshape(1, kvh * d).astype(kt_ref.dtype)
        vt_ref[...] = vh.reshape(1, kvh * d).astype(vt_ref.dtype)
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = len_ref[sid]
    live_pages = (length + ps - 1) // ps

    @pl.when(p < live_pages)
    def _pages():
        # the attention-only paged kernel's page loop, with q from the
        # in-VMEM projection instead of an HBM operand
        for i in range(kvh):
            seg = slice(i * group, (i + 1) * group)
            s_scr[seg, :] = jax.lax.dot_general(
                q_scr[seg, :].astype(k_ref.dtype), k_ref[0, i],
                (((1,), (1,)), ((), ())),
                preferred_element_type=f32) * (scale * _LOG2E)
        cols = p * ps + jax.lax.broadcasted_iota(jnp.int32, (h, ps), 1)
        s = jnp.where(cols < length, s_scr[...], _NEG_INF)
        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp2(m_prev - m_new)
        pmat = jnp.exp2(s - m_new)
        l_scr[...] = l_scr[...] * alpha + \
            jnp.sum(pmat, axis=1, keepdims=True)
        for i in range(kvh):
            seg = slice(i * group, (i + 1) * group)
            acc_scr[seg, :] = acc_scr[seg, :] * alpha[seg] + jax.lax.dot(
                pmat[seg, :].astype(v_ref.dtype), v_ref[0, i],
                preferred_element_type=f32)
        m_scr[...] = m_new

    @pl.when(p == mpps - 1)
    def _finish():
        # fold the CURRENT token as one extra online-softmax column
        # (the unfused path appends it to the pool first and reads the
        # row back; live = length + 1 either way), then run the whole
        # back half of the block on the VMEM-resident context
        q_ = q_scr[...]
        kn = kn_scr[...]
        s_new = jnp.sum(q_.reshape(kvh, group, d) * kn[:, None, :],
                        axis=-1).reshape(h, 1) * (scale * _LOG2E)
        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, s_new)
        alpha = jnp.exp2(m_prev - m_new)
        p_new = jnp.exp2(s_new - m_new)                  # [h, 1]
        l = l_scr[...] * alpha + p_new
        vb = jnp.broadcast_to(vn_scr[...][:, None, :],
                              (kvh, group, d)).reshape(h, d)
        acc = acc_scr[...] * alpha + p_new * vb
        ctx = acc / l            # the current token is always live: l > 0
        attn = matmul(ctx.reshape(1, h * d), wo, bo)
        if partial_out:
            # tensor-parallel shard (ISSUE 17): emit the RANK-PARTIAL
            # out-proj row product — no residual, no bias.  The caller
            # psums at the row boundary, adds ``bo`` once, and runs
            # norm2 + the col/row MLP outside the kernel.
            o_ref[...] = attn.astype(o_ref.dtype)
            return
        x2 = x_ref[...].astype(f32) + attn               # [1, hidden]
        if fuse_mlp:
            h2 = norm(x2, ln2_w, ln2_b)
            if gpt:
                u = jax.nn.gelu(matmul(h2, wu, bu))
                y = x2 + matmul(u, wd, bd)
            else:
                g = matmul(h2, wg, None)
                u = matmul(h2, wu, None)
                y = x2 + matmul(jax.nn.silu(g) * u, wd, None)
        else:
            y = x2
        o_ref[...] = y.astype(o_ref.dtype)


def fused_block_decode(x, blk, k_pages, v_pages, page_table, lengths, *,
                       kind: str, eps: float, cos=None, sin=None,
                       sm_scale: Optional[float] = None,
                       fuse_mlp: bool = True,
                       partial_out: bool = False):
    """One fused transformer-block decode step against the paged pool.

    * ``x``: ``[slots, hidden]`` — the block's input activations (the
      residual stream), one token per slot.
    * ``blk``: the layer's weights in the FUSED layout
      (:func:`apex_tpu.inference.models.fused_layer_params` builds it
      once at engine construction): matmul-ready ``[in, out]`` arrays
      ``wq [hidden, h*d]`` / ``wk``/``wv [hidden, kv_heads*d]`` /
      ``wo [h*d, hidden]`` (+ GPT biases ``bq/bk/bv/bo`` as ``[1, n]``
      rows and LayerNorm ``ln1_w/ln1_b``; LLaMA carries RMSNorm
      ``ln1_w`` only), plus — under ``fuse_mlp`` — the MLP half
      (``ln2_*``, GPT ``wu/bu/wd/bd``, LLaMA ``wg/wu/wd``).
    * ``k_pages``/``v_pages``: ONE layer's ``[pages, kv_heads,
      page_size, d]`` slice of the pool; ``page_table``/``lengths`` as
      in :func:`paged_decode_attention`.
    * ``cos``/``sin``: ``[slots, d]`` RoPE rows at each slot's current
      position (LLaMA only).

    Returns ``(y [slots, hidden], k_tok [slots, kv_heads, d], v_tok)``
    — the block output plus the current token's k/v for the caller's
    one-scatter-per-layer pool append (``kv_cache.append_layer``).
    Always the Pallas kernel (interpret mode off-TPU); the engine-level
    XLA fallback is the original unfused per-op path, selected by
    ``APEX_TPU_DECODE_FUSION`` / the ``auto`` crossover
    (:func:`resolve_decode_fusion`).

    ``partial_out`` (ISSUE 17, tensor-parallel serving): ``blk`` is a
    rank's 1/tp shard (heads/kvh column-split, ``wo`` row-split exactly
    as ``pallas_audit --mesh`` prices it) and ``y`` is the RANK-PARTIAL
    out-proj product — no residual, no out-proj bias.  The out-proj
    psum moves OUTSIDE the kernel: the caller reduces over the tensor
    axis, adds ``bo`` once, and finishes norm2 + the MLP with its own
    row-boundary psum.  Requires ``fuse_mlp=False`` (the MLP cannot
    fuse across the row reduction).
    """
    if kind not in ("gpt", "llama"):
        raise ValueError(f"unknown block kind {kind!r}")
    if partial_out and fuse_mlp:
        raise ValueError(
            "partial_out emits the pre-psum attention shard; the MLP "
            "runs after the row-boundary reduction (fuse_mlp=False)")
    gpt = kind == "gpt"
    slots, hidden = x.shape
    if k_pages.shape != v_pages.shape or k_pages.ndim != 4:
        raise ValueError(
            f"k/v pages must be [pages, kv_heads, page_size, d] and "
            f"equal-shaped; got k {tuple(k_pages.shape)} v "
            f"{tuple(v_pages.shape)}")
    _, kvh, ps, d = k_pages.shape
    hd = blk["wq"].shape[1]
    if hd % d:
        raise ValueError(
            f"wq width {hd} must be a multiple of head_dim {d}")
    h = hd // d
    if h % kvh:
        raise ValueError(
            f"kv_heads ({kvh}) must divide query heads ({h})")
    group = h // kvh
    mpps = page_table.shape[1]
    if page_table.shape[0] != slots or lengths.shape != (slots,):
        raise ValueError(
            f"page_table/lengths must cover {slots} slots; got "
            f"{tuple(page_table.shape)} / {tuple(lengths.shape)}")
    if (not gpt) and (cos is None or sin is None):
        raise ValueError("llama fused block needs cos/sin RoPE rows")
    scale = (d ** -0.5) if sm_scale is None else sm_scale
    page_table = page_table.astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)

    const = lambda s, p, pt, ln: (0, 0)                  # noqa: E731
    slot = lambda s, p, pt, ln: (s, 0)                   # noqa: E731

    def page_index(s, p, pt, ln):
        last = jnp.maximum((ln[s] + ps - 1) // ps - 1, 0)
        return (pt[s, jnp.minimum(p, last)], 0, 0, 0)

    def wspec(a):
        return pl.BlockSpec(a.shape, const)

    operands = [x]
    in_specs = [pl.BlockSpec((1, hidden), slot)]

    def add_w(*names):
        for n in names:
            operands.append(blk[n])
            in_specs.append(wspec(blk[n]))

    if not gpt:
        operands.extend([cos, sin])
        in_specs.extend([pl.BlockSpec((1, d), slot)] * 2)
        add_w("ln1_w", "wq", "wk", "wv")
    else:
        add_w("ln1_w", "ln1_b", "wq", "bq", "wk", "bk", "wv", "bv")
    operands.extend([k_pages, v_pages])
    in_specs.extend([pl.BlockSpec((1, kvh, ps, d), page_index)] * 2)
    add_w(*(("wo", "bo") if gpt and not partial_out else ("wo",)))
    if fuse_mlp:
        if gpt:
            add_w("ln2_w", "ln2_b", "wu", "bu", "wd", "bd")
        else:
            add_w("ln2_w", "wg", "wu", "wd")

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, mpps),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, hidden), slot),
            pl.BlockSpec((1, kvh * d), slot),
            pl.BlockSpec((1, kvh * d), slot),
        ],
        scratch_shapes=[
            pltpu.VMEM((h, d), jnp.float32),      # q (RoPE'd, unscaled)
            pltpu.VMEM((kvh, d), jnp.float32),    # fresh k
            pltpu.VMEM((kvh, d), jnp.float32),    # fresh v
            pltpu.VMEM((h, ps), jnp.float32),     # score block
            pltpu.VMEM((h, 1), jnp.float32),      # running max (base 2)
            pltpu.VMEM((h, 1), jnp.float32),      # running normalizer
            pltpu.VMEM((h, d), jnp.float32),      # fp32 output accum
        ],
    )
    kernel = functools.partial(_fused_block_kernel, kind, scale, kvh,
                               group, ps, mpps, hidden, d, eps, fuse_mlp,
                               partial_out)
    y, kt, vt = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((slots, hidden), x.dtype),
            jax.ShapeDtypeStruct((slots, kvh * d), x.dtype),
            jax.ShapeDtypeStruct((slots, kvh * d), x.dtype),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(page_table, lengths, *operands)
    return y, kt.reshape(slots, kvh, d), vt.reshape(slots, kvh, d)
