"""Fused softmax cross-entropy with label smoothing.

Reference: ``apex/contrib/csrc/xentropy/xentropy_kernel.cu`` (online
softmax + smoothing, in-place bwd option) surfaced as
``apex.contrib.xentropy.SoftmaxCrossEntropyLoss``.

TPU-native design: the forward is XLA's fused logsumexp + a target gather —
the [tokens, vocab] softmax is never materialized, which is the traffic win
the CUDA kernel buys.  On TPU, XLA's two-pass reduction measured 372 GB/s
vs 136 GB/s for a hand-written online-softmax Pallas loop (v5e, 8192x51200
bf16): the online max-rescale chain is VPU-ALU-bound, while XLA's separate
max and sum(exp) passes stream at HBM rate — so the idiomatic path IS the
fast path and no custom kernel is kept.  Reproduce the measurement with
``python bench.py --inner tpu --leg xent`` (the ``xentropy_gbps`` extra).  Residuals are just (logsumexp);
the backward is one fused elementwise pass ``(softmax - smoothed_onehot) *
dloss`` ("in-place" maps to XLA buffer donation).

Oracle: :func:`xentropy_reference`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["softmax_cross_entropy_loss", "xentropy_reference"]


def xentropy_reference(logits, labels, smoothing: float = 0.0):
    """Pure-jnp oracle (matches the CUDA kernel's definition):
    ``loss = lse - (1-s)*logit[y] - s * mean(logits)``."""
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(
        logits, labels[..., None], axis=-1)[..., 0]
    if smoothing == 0.0:
        return lse - picked
    mean_all = jnp.mean(logits, axis=-1)
    return lse - (1.0 - smoothing) * picked - smoothing * mean_all


def _fwd(logits2, labels, smoothing):
    x = logits2.astype(jnp.float32)
    v = x.shape[-1]
    lse = jax.scipy.special.logsumexp(x, axis=-1)
    pick = jnp.take_along_axis(x, labels[:, None], axis=1)[:, 0]
    loss = lse - pick
    if smoothing != 0.0:
        loss = loss + smoothing * (pick - jnp.sum(x, axis=-1) / v)
    return loss, lse


def softmax_cross_entropy_loss(logits, labels, smoothing: float = 0.0,
                               padding_idx: int = -100,
                               half_to_float: bool = False):
    """Fused CE loss per token (parity:
    ``apex.contrib.xentropy.SoftmaxCrossEntropyLoss.apply``); ``labels ==
    padding_idx`` rows yield 0 loss and 0 grad.

    ``half_to_float`` is accepted for parity (outputs are always fp32).
    """
    orig_shape = labels.shape
    v = logits.shape[-1]
    logits2 = logits.reshape(-1, v)
    labels1 = labels.reshape(-1)
    pad_mask = labels1 == padding_idx
    safe_labels = jnp.where(pad_mask, 0, labels1).astype(jnp.int32)

    @jax.custom_vjp
    def run(logits2):
        loss, _ = _fwd(logits2, safe_labels, smoothing)
        return loss

    def run_fwd(logits2):
        loss, lse = _fwd(logits2, safe_labels, smoothing)
        return loss, (logits2, lse)

    def run_bwd(res, dloss):
        logits2, lse = res
        x = logits2.astype(jnp.float32)
        p = jnp.exp(x - lse[:, None])
        # subtract-at-index instead of materializing a second fp32
        # [tokens, vocab] one_hot: the scatter-add of -(1-s) at the
        # label column is bitwise the onehot subtraction (a + (-b) is
        # IEEE a - b; untouched columns keep p exactly), at half the
        # backward's transient footprint
        grad = p.at[jnp.arange(p.shape[0]), safe_labels].add(
            -(1.0 - smoothing))
        if smoothing != 0.0:
            grad = grad - smoothing / v
        grad = grad * jnp.where(pad_mask, 0.0, dloss)[:, None]
        return (grad.astype(logits2.dtype),)

    run.defvjp(run_fwd, run_bwd)
    loss = jnp.where(pad_mask, 0.0, run(logits2))
    return loss.reshape(orig_shape)
