"""Chunked fused LM-head + softmax cross-entropy: the ``[tokens, vocab]``
logits tensor never touches HBM.

Reference technique: Liger Kernel's ``FusedLinearCrossEntropy`` (PAPERS.md
"Liger Kernel: Efficient Triton Kernels for LLM Training") — fuse the
output projection ``hidden @ head_w.T`` with the softmax-CE loss in
chunks, so the full-vocab logits (the single largest transient in a
decoder train step: CE forward + the half-residual backward) exist only
one chunk at a time.  This is the TPU/XLA port: instead of a Triton
kernel, a ``jax.custom_vjp`` whose forward ``lax.scan``\\ s over token
chunks — each chunk projects, reduces to per-token ``(loss, lse)``
scalars, and discards its logits slice — and whose backward re-projects
per chunk (recompute-over-residual, exactly Liger's trade: one extra
chunk GEMM instead of an O(tokens x vocab) residual) and accumulates
``dhead_w`` in place over the scan carry.  Peak-live holds
``O(token_chunk x vocab)`` (optionally ``O(token_chunk x vocab_chunk)``
with the online-logsumexp inner scan) instead of ``O(tokens x vocab)``.

Loss definition matches :func:`apex_tpu.ops.xentropy.softmax_cross_entropy_loss`
(``apex.contrib.xentropy`` parity)::

    loss = lse - (1-s) * logit[y] - s * sum(logits) / V

which is algebraically the Megatron smoothing
``(1-s) * nll + s * mean(-log_softmax)`` — the two spellings cancel to
the same value, so the fused op drops into both loss heads.

The vocab-parallel variant composes the same token-chunk scan with
:mod:`~apex_tpu.transformer.tensor_parallel.cross_entropy`'s pmax/psum
algebra, so tensor-parallel training drops the sharded
``[tokens, vocab/tp]`` logits transient too.

Machine-checked: the ``lm_xent_fused`` / ``lm_xent_unfused`` executable
twins in the SPMD auditor pin the APX215 peak-live drop in the committed
``.analysis_budget.json``; the jaxpr precision auditor traces the op
under the bf16 policy.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from apex_tpu.ops.xentropy import softmax_cross_entropy_loss

__all__ = ["fused_lm_head_cross_entropy",
           "fused_lm_head_vocab_parallel_cross_entropy",
           "lm_head_xentropy_reference",
           "xent_chunk_default", "xent_vocab_chunk_default"]


def xent_chunk_default() -> int:
    """Effective ``APEX_TPU_XENT_CHUNK``: the token-chunk size loss
    heads use when ``fused_head_xent=``/``token_chunk=`` is not passed
    (0 = unfused dense logits); stamped into xent_fused bench
    captures."""
    return int(os.environ.get("APEX_TPU_XENT_CHUNK", "0"))


def xent_vocab_chunk_default() -> int:
    """Effective ``APEX_TPU_XENT_VOCAB_CHUNK``: the vocab-chunk size of
    the fused head's inner online-logsumexp scan when ``vocab_chunk=``
    is not passed (0 = whole vocab per token chunk)."""
    return int(os.environ.get("APEX_TPU_XENT_VOCAB_CHUNK", "0"))


def lm_head_xentropy_reference(hidden, head_w, labels,
                               smoothing: float = 0.0,
                               padding_idx: int = -100):
    """Unfused oracle: materialize the full ``[tokens, vocab]`` logits,
    then the fused-logsumexp CE.  This IS the production ``chunk=0``
    path (and the ``lm_xent_unfused`` audited twin) — the A-leg every
    parity test and bench capture compares against."""
    logits = jnp.matmul(hidden, head_w.T)
    return softmax_cross_entropy_loss(logits, labels, smoothing=smoothing,
                                      padding_idx=padding_idx)


def _project_f32(hc, w):
    """One chunk's logits slice in fp32: the GEMM runs in the operands'
    promoted dtype (matching the unfused ``einsum`` + ``.astype(f32)``
    loss heads bit for bit per row), the fp32 view feeds the
    reductions."""
    dt = jnp.promote_types(hc.dtype, w.dtype)
    return jnp.matmul(hc.astype(dt), w.astype(dt).T).astype(jnp.float32)


def _chunk_loss_lse(hc, lc, w, smoothing, vocab_chunk):
    """Per-token ``(loss, lse)`` for one token chunk — the ONLY place a
    logits slice exists in the forward.  ``vocab_chunk > 0`` scans the
    vocab dimension too, carrying the online (max, sumexp) pair, so the
    transient shrinks to ``[token_chunk, vocab_chunk]``."""
    v = w.shape[0]
    if vocab_chunk and 0 < vocab_chunk < v:
        n_vc = v // vocab_chunk
        w3 = w.reshape(n_vc, vocab_chunk, w.shape[1])
        starts = jnp.arange(n_vc, dtype=jnp.int32) * vocab_chunk

        def vbody(carry, xs):
            m, s, pick, sumx = carry
            wj, start = xs
            x = _project_f32(hc, wj)                       # [C, Vc]
            mj = jnp.max(x, axis=-1)
            m_new = jnp.maximum(m, mj)
            # online rescale: dead cheap on [C] vectors
            s = s * jnp.exp(m - m_new) + jnp.sum(
                jnp.exp(x - m_new[:, None]), axis=-1)
            idx = lc - start
            inb = (idx >= 0) & (idx < vocab_chunk)
            safe = jnp.clip(idx, 0, vocab_chunk - 1)
            val = jnp.take_along_axis(x, safe[:, None], axis=1)[:, 0]
            pick = pick + jnp.where(inb, val, 0.0)
            sumx = sumx + jnp.sum(x, axis=-1)
            return (m_new, s, pick, sumx), None

        c = hc.shape[0]
        init = (jnp.full((c,), -jnp.inf, jnp.float32),
                jnp.zeros((c,), jnp.float32),
                jnp.zeros((c,), jnp.float32),
                jnp.zeros((c,), jnp.float32))
        (m, s, pick, sumx), _ = jax.lax.scan(vbody, init, (w3, starts))
        lse = m + jnp.log(s)
    else:
        x = _project_f32(hc, w)                            # [C, V]
        lse = jax.scipy.special.logsumexp(x, axis=-1)
        pick = jnp.take_along_axis(x, lc[:, None], axis=1)[:, 0]
        sumx = jnp.sum(x, axis=-1)
    loss = lse - pick
    if smoothing != 0.0:
        loss = loss + smoothing * (pick - sumx / v)
    return loss, lse


def _slice_grads(hc, lc, lse_c, d_c, wj, start, smoothing, v, dt):
    """CE grads of one ``[chunk, vocab-slice]`` re-projection — the ONE
    copy of the fused backward discipline, shared by the local (full
    and vocab-chunked) and vocab-parallel paths so they cannot drift:
    softmax from the saved per-token lse, subtract-at-index at labels
    landing in this slice (no one_hot buffer), smoothing over the FULL
    vocab ``v``, scale by the (pad-masked) loss cotangent, then the two
    GEMMs.  ``dwj`` comes back fp32 straight from the MXU accumulator
    (the ``_linear_wgrad_fp32`` discipline) so scan-carry accumulation
    never quantizes."""
    x = _project_f32(hc, wj)
    p = jnp.exp(x - lse_c[:, None])
    idx = lc - start
    inb = (idx >= 0) & (idx < wj.shape[0])
    safe = jnp.clip(idx, 0, wj.shape[0] - 1)
    g = p.at[jnp.arange(hc.shape[0]), safe].add(
        jnp.where(inb, -(1.0 - smoothing), 0.0))
    if smoothing != 0.0:
        g = g - smoothing / v
    g = (g * d_c[:, None]).astype(dt)
    dhc = jnp.matmul(g, wj.astype(dt))
    dwj = jax.lax.dot_general(g, hc.astype(dt), (((0,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    return dhc, dwj


def _chunk_grads(hc, lc, lse_c, d_c, w, smoothing, vocab_chunk):
    """Backward for one token chunk (local path): :func:`_slice_grads`
    over the whole vocab, or scanned over vocab slices.  Returns
    ``(dhidden_chunk, dhead_w_contribution)`` — the latter fp32 for the
    token-scan carry."""
    v, h = w.shape
    dt = jnp.promote_types(hc.dtype, w.dtype)
    c = hc.shape[0]

    if vocab_chunk and 0 < vocab_chunk < v:
        n_vc = v // vocab_chunk
        w3 = w.reshape(n_vc, vocab_chunk, h)
        starts = jnp.arange(n_vc, dtype=jnp.int32) * vocab_chunk

        def vbody(dhc, xs):
            wj, start = xs
            dhc_j, dwj = _slice_grads(hc, lc, lse_c, d_c, wj, start,
                                      smoothing, v, dt)
            return dhc + dhc_j.astype(jnp.float32), dwj

        dhc, dw3 = jax.lax.scan(
            vbody, jnp.zeros((c, h), jnp.float32), (w3, starts))
        return dhc.astype(dt), dw3.reshape(v, h)
    return _slice_grads(hc, lc, lse_c, d_c, w, jnp.int32(0),
                        smoothing, v, dt)


def fused_lm_head_cross_entropy(hidden, head_w, labels, *,
                                smoothing: float = 0.0,
                                padding_idx: int = -100,
                                token_chunk: int | None = None,
                                vocab_chunk: int | None = None):
    """Per-token CE loss of the LM head ``hidden @ head_w.T`` without
    materializing the ``[tokens, vocab]`` logits.

    ``hidden``: ``[..., hidden_size]`` (leading dims flatten to the
    token axis); ``head_w``: ``[vocab, hidden_size]`` (embedding-table
    layout — tied heads pass the table, untied heads their
    ColumnParallelLinear kernel); ``labels``: ``hidden.shape[:-1]``
    int ids, ``padding_idx`` rows yield 0 loss and 0 grad.

    ``token_chunk``: rows projected per scan step (``None`` reads
    ``APEX_TPU_XENT_CHUNK``; ``<= 0`` falls back to the unfused dense
    oracle — the production default).  Token counts that don't divide
    pad internally.  ``vocab_chunk`` additionally scans the vocab
    dimension with an online logsumexp (``None`` reads
    ``APEX_TPU_XENT_VOCAB_CHUNK``; must divide vocab when set).

    Differentiable in ``hidden`` and ``head_w``; grads match the
    unfused path to fp-reorder tolerance (the parity suite pins
    <= 2e-4, observed far tighter).
    """
    if token_chunk is None:
        token_chunk = xent_chunk_default()
    if vocab_chunk is None:
        vocab_chunk = xent_vocab_chunk_default()
    if token_chunk is None or token_chunk <= 0:
        return lm_head_xentropy_reference(hidden, head_w, labels,
                                          smoothing=smoothing,
                                          padding_idx=padding_idx)
    v, hdim = head_w.shape
    if vocab_chunk and vocab_chunk > 0 and v % vocab_chunk:
        raise ValueError(f"vocab_chunk {vocab_chunk} must divide "
                         f"vocab {v}")
    orig_shape = labels.shape
    h2 = hidden.reshape(-1, hdim)
    lab = labels.reshape(-1).astype(jnp.int32)
    n = h2.shape[0]
    c = min(int(token_chunk), n)
    pad_mask = lab == padding_idx
    safe_labels = jnp.where(pad_mask, 0, lab)
    n_pad = (-n) % c
    if n_pad:
        h2 = jnp.concatenate(
            [h2, jnp.zeros((n_pad, hdim), h2.dtype)])
        safe_labels = jnp.concatenate(
            [safe_labels, jnp.zeros((n_pad,), jnp.int32)])
    n_chunks = (n + n_pad) // c
    lab3 = safe_labels.reshape(n_chunks, c)
    smoothing = float(smoothing)

    @jax.custom_vjp
    def run(h2, head_w):
        loss, _ = _fwd(h2, head_w)
        return loss

    def _fwd(h2, head_w):
        h3 = h2.reshape(n_chunks, c, hdim)

        def body(_, xs):
            hc, lc = xs
            out = _chunk_loss_lse(hc, lc, head_w, smoothing, vocab_chunk)
            return None, out

        _, (loss3, lse3) = jax.lax.scan(body, None, (h3, lab3))
        return loss3.reshape(-1)[:n], lse3

    def run_fwd(h2, head_w):
        loss, lse3 = _fwd(h2, head_w)
        # residuals are the op's own INPUTS plus O(tokens) lse — no
        # [tokens, vocab] tensor is saved (the Liger trade)
        return loss, (h2, head_w, lse3)

    def run_bwd(res, dloss):
        h2, head_w, lse3 = res
        d = jnp.where(pad_mask, 0.0, dloss.astype(jnp.float32))
        if n_pad:
            d = jnp.concatenate([d, jnp.zeros((n_pad,), jnp.float32)])
        h3 = h2.reshape(n_chunks, c, hdim)
        d3 = d.reshape(n_chunks, c)

        def body(dw, xs):
            hc, lc, lse_c, d_c = xs
            dhc, dw_c = _chunk_grads(hc, lc, lse_c, d_c, head_w,
                                     smoothing, vocab_chunk)
            return dw + dw_c, dhc

        dw, dh3 = jax.lax.scan(
            body, jnp.zeros((v, hdim), jnp.float32),
            (h3, lab3, lse3, d3))
        # padded rows carry d == 0 so their dh rows are exact zeros;
        # the outer concatenate's vjp slices them back off
        dh2 = dh3.reshape(-1, hdim).astype(h2.dtype)
        return dh2, dw.astype(head_w.dtype)

    run.defvjp(run_fwd, run_bwd)
    loss = jnp.where(pad_mask, 0.0, run(h2, head_w))
    return loss.reshape(orig_shape)


def fused_lm_head_vocab_parallel_cross_entropy(
        hidden, head_w_shard, labels, *,
        smoothing: float = 0.0,
        padding_idx: int = -100,
        axis_name: str | None = None,
        token_chunk: int | None = None,
        grad_input_psum: bool = False):
    """Vocab-parallel twin: ``head_w_shard`` is this rank's
    ``[vocab/tp, hidden]`` rows; per token chunk the per-token max,
    sum-exp, target logit and (for smoothing) logit sum reduce over the
    tensor axis with exactly
    :func:`~apex_tpu.transformer.tensor_parallel.cross_entropy.vocab_parallel_cross_entropy`'s
    pmax/psum algebra — so TP trains drop the sharded
    ``[tokens, vocab/tp]`` logits transient too.  ``padding_idx`` rows
    yield 0 loss and 0 grad on every rank, matching the local op (the
    unfused ``vocab_parallel_cross_entropy`` has no padding support, so
    this is strictly more than drop-in there).  The backward is
    collective-free by default (softmax from the saved per-token lse;
    each rank owns its shard's ``dhead`` and its PARTIAL ``dhidden`` —
    the rank-partial contract of a raw-einsum tied head like the
    standalone GPT's; the backward map is linear, so downstream grad
    reductions reconcile identically).  ``grad_input_psum=True`` psums
    ``dhidden`` over the axis instead — the ``ColumnParallelLinear``/
    ``copy_to_tensor_model_parallel_region`` contract an untied head
    (standalone LLaMA) needs, at the same comm bytes the unfused
    column-parallel backward pays.

    Must run inside ``shard_map`` with ``axis_name`` bound (default:
    the tensor axis); with tp == 1 it degrades to the local fused op.
    """
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.parallel_state import TENSOR_AXIS

    if axis_name is None:
        axis_name = TENSOR_AXIS
    if (axis_name == TENSOR_AXIS
            and parallel_state.model_parallel_is_initialized()
            and parallel_state.get_tensor_model_parallel_world_size() == 1):
        return fused_lm_head_cross_entropy(
            hidden, head_w_shard, labels, smoothing=smoothing,
            padding_idx=padding_idx, token_chunk=token_chunk,
            vocab_chunk=0)
    if token_chunk is None:
        token_chunk = xent_chunk_default()
    vp, hdim = head_w_shard.shape
    tp = jax.lax.axis_size(axis_name)
    v = vp * tp
    rank = jax.lax.axis_index(axis_name)
    start = rank * vp
    orig_shape = labels.shape
    h2 = hidden.reshape(-1, hdim)
    lab = labels.reshape(-1).astype(jnp.int32)
    n = h2.shape[0]
    # padding_idx rows: 0 loss / 0 grad on EVERY tp, exactly the local
    # op's semantics (beyond vocab_parallel_cross_entropy, which has no
    # padding support — a -100 there silently clips into rank 0's
    # shard); the safe label 0 keeps the chunk math in-range and the
    # masks zero the row out
    pad_mask = lab == padding_idx
    lab = jnp.where(pad_mask, 0, lab)
    c = min(int(token_chunk), n) if token_chunk and token_chunk > 0 else n
    n_pad = (-n) % c
    if n_pad:
        h2 = jnp.concatenate([h2, jnp.zeros((n_pad, hdim), h2.dtype)])
        lab = jnp.concatenate([lab, jnp.zeros((n_pad,), jnp.int32)])
    n_chunks = (n + n_pad) // c
    lab3 = lab.reshape(n_chunks, c)
    smoothing = float(smoothing)

    @jax.custom_vjp
    def run(h2, w):
        return _fwd(h2, w)[0]

    def _fwd(h2, w):
        h3 = h2.reshape(n_chunks, c, hdim)

        def body(_, xs):
            hc, lc = xs
            x = _project_f32(hc, w)                        # [C, V/tp]
            m = jax.lax.pmax(jnp.max(x, axis=-1), axis_name)
            shifted = x - m[:, None]
            sum_exp = jax.lax.psum(
                jnp.sum(jnp.exp(shifted), axis=-1), axis_name)
            idx = lc - start
            mask = (idx < 0) | (idx >= vp)
            safe = jnp.clip(idx, 0, vp - 1)
            pred = jnp.take_along_axis(shifted, safe[:, None],
                                       axis=1)[:, 0]
            pred = jax.lax.psum(jnp.where(mask, 0.0, pred), axis_name)
            log_sum_exp = jnp.log(sum_exp)
            loss = log_sum_exp - pred
            if smoothing > 0.0:
                sum_log = jax.lax.psum(jnp.sum(shifted, axis=-1),
                                       axis_name) - v * log_sum_exp
                loss = ((1.0 - smoothing) * loss
                        + smoothing * (-sum_log / v))
            return None, (loss, m + log_sum_exp)

        _, (loss3, lse3) = jax.lax.scan(body, None, (h3, lab3))
        return loss3.reshape(-1)[:n], lse3

    def run_fwd(h2, w):
        loss, lse3 = _fwd(h2, w)
        return loss, (h2, w, lse3)

    def run_bwd(res, dloss):
        h2, w, lse3 = res
        d = jnp.where(pad_mask, 0.0, dloss.astype(jnp.float32))
        if n_pad:
            d = jnp.concatenate([d, jnp.zeros((n_pad,), jnp.float32)])
        h3 = h2.reshape(n_chunks, c, hdim)
        d3 = d.reshape(n_chunks, c)
        dt = jnp.promote_types(h2.dtype, w.dtype)

        def body(dw, xs):
            hc, lc, lse_c, d_c = xs
            dhc, dw_c = _slice_grads(hc, lc, lse_c, d_c, w, start,
                                     smoothing, v, dt)
            return dw + dw_c, dhc

        dw, dh3 = jax.lax.scan(
            body, jnp.zeros((vp, hdim), jnp.float32),
            (h3, lab3, lse3, d3))
        dh2 = dh3.reshape(-1, hdim)
        if grad_input_psum:
            dh2 = jax.lax.psum(dh2, axis_name)
        return dh2.astype(h2.dtype), dw.astype(head_w_shard.dtype)

    run.defvjp(run_fwd, run_bwd)
    loss = jnp.where(pad_mask, 0.0, run(h2, head_w_shard))
    return loss.reshape(orig_shape)
