"""Fused LayerNorm / RMSNorm — Pallas TPU kernels with pure-jnp oracles.

TPU-native rebuild of the reference's layer-norm CUDA extension
(``csrc/layer_norm_cuda.cpp`` dispatch + ``csrc/layer_norm_cuda_kernel.cu ::
cuApplyLayerNorm / cuComputeGradInput / cuComputePartGradGammaBeta`` and the
RMSNorm variants), surfaced in Python by
``apex/normalization/fused_layer_norm.py :: FusedLayerNormAffineFunction``.

Design notes (TPU-first, not a translation):

* Rows live in VMEM one block at a time; statistics are computed in fp32
  registers in a single pass over the block (the CUDA Welford machinery exists
  to cooperate across threads — unnecessary here, the VPU reduces a whole
  (block_rows, hidden) tile at once).
* The backward kernel *recomputes* mean/rstd from the saved input instead of
  saving them forward (the reference's ``memory_efficient=True`` mode) — on
  TPU this trades a tiny amount of VPU math for not writing two fp32 vectors
  per row to HBM, a win since LayerNorm is bandwidth-bound.
* dγ/dβ are accumulated across the sequential TPU grid into a single (1, H)
  fp32 output (the CUDA version needs a two-stage partial-sum reduction across
  thread blocks; the TPU grid is sequential so a running accumulate works).
* Hidden sizes that are not lane-aligned (H % 128 != 0) dispatch to the jnp
  reference path — mirroring the reference's CPU fallback behavior
  (``FusedLayerNorm`` falls back to ``F.layer_norm`` off-GPU).
"""
from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.utils import interpret_mode, pad_rows, round_up

__all__ = [
    "layer_norm",
    "rms_norm",
    "layer_norm_reference",
    "rms_norm_reference",
]

#: pallas_audit registration (analysis hook only, no behavior change):
#: both kernels reduce over the hidden dim — mean/var (fwd) and dw/db
#: partials (bwd) must accumulate in fp32 (APX302).
PALLAS_AUDIT = {
    "_ln_fwd_kernel": {"reduction": True},
    "_ln_bwd_kernel": {"reduction": True},
}

_MAX_BLOCK_ROWS = 512
_VMEM_BUDGET_BYTES = 3 * 1024 * 1024  # per fp32 operand tile


def _block_rows(hidden: int) -> int:
    br = _VMEM_BUDGET_BYTES // (hidden * 4)
    return min(_MAX_BLOCK_ROWS, (br // 8) * 8)


def _pallas_ok(hidden: int) -> bool:
    # Need at least one (8, hidden) fp32 tile inside the per-operand budget;
    # otherwise fall back to the jnp path rather than overflow VMEM.
    return hidden % 128 == 0 and _block_rows(hidden) >= 8


# ---------------------------------------------------------------------------
# jnp oracles (the "eager fallback" twins; also the test oracle)
# ---------------------------------------------------------------------------

def layer_norm_reference(x, weight=None, bias=None, eps: float = 1e-5):
    """Pure-jnp LayerNorm over the last axis (oracle for the Pallas kernel)."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    xc = x32 - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def rms_norm_reference(x, weight=None, eps: float = 1e-5):
    """Pure-jnp RMSNorm over the last axis (oracle for the Pallas kernel)."""
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(ms + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    return y.astype(x.dtype)


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------

def _ln_fwd_kernel(eps, rms, x_ref, w_ref, b_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)
    if rms:
        ms = jnp.mean(x * x, axis=-1, keepdims=True)
        xhat = x * jax.lax.rsqrt(ms + eps)
    else:
        mean = jnp.mean(x, axis=-1, keepdims=True)
        xc = x - mean
        var = jnp.mean(xc * xc, axis=-1, keepdims=True)
        xhat = xc * jax.lax.rsqrt(var + eps)
    y = xhat * w_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


def _ln_bwd_kernel(eps, rms, x_ref, w_ref, dy_ref, dx_ref, dw_ref, db_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    if rms:
        ms = jnp.mean(x * x, axis=-1, keepdims=True)
        rstd = jax.lax.rsqrt(ms + eps)
        xhat = x * rstd
        wdy = dy * w
        c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
        dx = (wdy - xhat * c2) * rstd
    else:
        mean = jnp.mean(x, axis=-1, keepdims=True)
        xc = x - mean
        var = jnp.mean(xc * xc, axis=-1, keepdims=True)
        rstd = jax.lax.rsqrt(var + eps)
        xhat = xc * rstd
        wdy = dy * w
        c1 = jnp.mean(wdy, axis=-1, keepdims=True)
        c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
        dx = (wdy - c1 - xhat * c2) * rstd
    dx_ref[...] = dx.astype(dx_ref.dtype)
    dw_ref[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_ref[...] += jnp.sum(dy, axis=0, keepdims=True)


def _fwd_2d(x2, w, b, eps, rms):
    rows, hidden = x2.shape
    br = _block_rows(hidden)
    x2p, orig = pad_rows(x2, br)
    grid = x2p.shape[0] // br
    w2 = w.reshape(1, hidden)
    b2 = b.reshape(1, hidden)
    out = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps, rms),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, hidden), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2p.shape, x2.dtype),
        interpret=interpret_mode(),
    )(x2p, w2, b2)
    return out[:orig]


def _bwd_2d(x2, w, dy2, eps, rms):
    rows, hidden = x2.shape
    br = _block_rows(hidden)
    x2p, orig = pad_rows(x2, br)
    dy2p, _ = pad_rows(dy2, br)
    grid = x2p.shape[0] // br
    w2 = w.reshape(1, hidden)
    dx, dw, db = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, eps, rms),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(x2p.shape, x2.dtype),
            jax.ShapeDtypeStruct((1, hidden), jnp.float32),
            jax.ShapeDtypeStruct((1, hidden), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(x2p, w2, dy2p)
    return dx[:orig], dw.reshape(hidden), db.reshape(hidden)


# ---------------------------------------------------------------------------
# custom_vjp public entry points
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _layer_norm_affine(x2, w, b, eps):
    if _pallas_ok(x2.shape[-1]):
        return _fwd_2d(x2, w, b, eps, rms=False)
    return layer_norm_reference(x2, w, b, eps)


def _layer_norm_affine_fwd(x2, w, b, eps):
    return _layer_norm_affine(x2, w, b, eps), (x2, w)


def _layer_norm_affine_bwd(eps, res, dy2):
    x2, w = res
    if _pallas_ok(x2.shape[-1]):
        dx, dw, db = _bwd_2d(x2, w, dy2, eps, rms=False)
    else:
        _, vjp = jax.vjp(lambda x, w_, b_: layer_norm_reference(x, w_, b_, eps),
                         x2, w, jnp.zeros_like(w))
        dx, dw, db = vjp(dy2)
    return dx, dw.astype(w.dtype), db.astype(w.dtype)


_layer_norm_affine.defvjp(_layer_norm_affine_fwd, _layer_norm_affine_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms_norm_affine(x2, w, eps):
    if _pallas_ok(x2.shape[-1]):
        zeros = jnp.zeros_like(w)
        return _fwd_2d(x2, w, zeros, eps, rms=True)
    return rms_norm_reference(x2, w, eps)


def _rms_norm_affine_fwd(x2, w, eps):
    return _rms_norm_affine(x2, w, eps), (x2, w)


def _rms_norm_affine_bwd(eps, res, dy2):
    x2, w = res
    if _pallas_ok(x2.shape[-1]):
        dx, dw, _ = _bwd_2d(x2, w, dy2, eps, rms=True)
    else:
        _, vjp = jax.vjp(lambda x, w_: rms_norm_reference(x, w_, eps), x2, w)
        dx, dw = vjp(dy2)
    return dx, dw.astype(w.dtype)


_rms_norm_affine.defvjp(_rms_norm_affine_fwd, _rms_norm_affine_bwd)


def _flatten_normalized(x, normalized_shape):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    normalized_shape = tuple(normalized_shape)
    n_norm = 1
    for d in normalized_shape:
        n_norm *= d
    if tuple(x.shape[-len(normalized_shape):]) != normalized_shape:
        raise ValueError(
            f"normalized_shape {normalized_shape} does not match input trailing "
            f"dims {x.shape}")
    lead = x.shape[: x.ndim - len(normalized_shape)]
    return x.reshape(-1, n_norm), lead, normalized_shape, n_norm


def layer_norm(x, weight=None, bias=None, *, normalized_shape=None,
               eps: float = 1e-5):
    """Fused LayerNorm over ``normalized_shape`` (defaults to the last axis).

    API parity: ``apex.normalization.fused_layer_norm :: fused_layer_norm`` /
    ``FusedLayerNormAffineFunction.apply``.  Differentiable (custom_vjp with a
    fused backward kernel).
    """
    if normalized_shape is None:
        normalized_shape = (x.shape[-1],)
    x2, lead, nshape, n = _flatten_normalized(x, normalized_shape)
    w = (weight.reshape(n) if weight is not None
         else jnp.ones((n,), jnp.float32))
    b = (bias.reshape(n) if bias is not None
         else jnp.zeros((n,), jnp.float32))
    out = _layer_norm_affine(x2, w, b, float(eps))
    return out.reshape(*lead, *nshape)


def rms_norm(x, weight=None, *, normalized_shape=None, eps: float = 1e-5):
    """Fused RMSNorm (parity: ``fused_rms_norm`` / ``FusedRMSNormAffineFunction``)."""
    if normalized_shape is None:
        normalized_shape = (x.shape[-1],)
    x2, lead, nshape, n = _flatten_normalized(x, normalized_shape)
    w = (weight.reshape(n) if weight is not None
         else jnp.ones((n,), jnp.float32))
    out = _rms_norm_affine(x2, w, float(eps))
    return out.reshape(*lead, *nshape)
