"""The multi-tensor engine — fused optimizer/scaling kernels over flat buffers.

TPU-native rebuild of the reference's ``amp_C`` extension
(``csrc/multi_tensor_apply.cuh`` chunked tensor-list launcher plus the functor
kernels ``multi_tensor_scale_kernel.cu``, ``multi_tensor_axpby_kernel.cu``,
``multi_tensor_l2norm_kernel.cu``, ``multi_tensor_adam.cu``,
``multi_tensor_adagrad.cu``, ``multi_tensor_sgd_kernel.cu``,
``multi_tensor_lamb.cu``), driven from Python by
``apex/multi_tensor_apply/multi_tensor_apply.py :: MultiTensorApply``.

Design (TPU-first, not a translation):

* The CUDA engine exists to amortize kernel-launch overhead across a *list* of
  small tensors by packing chunk metadata into kernel arguments.  On TPU the
  idiomatic equivalent is stronger: ravel the whole parameter pytree into ONE
  flat buffer (``jax.flatten_util.ravel_pytree``) and run ONE Pallas kernel
  over it per step.  Chunking becomes the Pallas grid; "tensor boundaries"
  only matter for per-tensor reductions (LAMB trust ratios), which are
  computed per-leaf by XLA and applied through a precomputed per-element
  segment-id gather.
* The reference's ``noop_flag`` (device-side overflow guard that turns the
  whole launch into a no-op) maps to a traced scalar in SMEM: the kernel
  computes the update and predicates the write with ``jnp.where`` — no host
  sync, jit-safe, exactly the semantics amp needs for skip-on-overflow.
* Hyperparameters (lr, betas, bias corrections, the noop flag) travel in a
  single small fp32 vector placed in SMEM, so changing the learning rate does
  NOT recompile the kernel.
* Every kernel has a pure-jnp oracle twin (``*_reference``) used as the test
  oracle and as the fallback for shapes the kernel does not accept.

Flat buffers are processed as 1-D arrays in blocks of ``_BLOCK`` elements;
Pallas masks the partial tail block, so buffers of ANY length run with zero
padding copies — the perf property of the reference's chunked launcher
(``multi_tensor_apply.cuh`` chunks at arbitrary offsets).  Empty (length-0)
buffers are handled at the wrapper level (the grid would be empty and the
SMEM flag/accumulator initializers would never run).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.utils import cdiv, interpret_mode

__all__ = [
    "fused_scale",
    "fused_axpby",
    "fused_l2norm",
    "fused_l2norm_scale",
    "fused_adam_flat",
    "fused_adagrad_flat",
    "fused_sgd_flat",
    "fused_lamb_phase1_flat",
    "adam_reference",
    "ADAM_MODE_L2",
    "ADAM_MODE_ADAMW",
]

_LANES = 128
_BLOCK = 512 * 128  # 1-D block: 256 KiB fp32 per operand tile

#: pallas_audit registration (analysis hook only, no behavior change):
#: flat arrays are padded up to the lane-aligned block, so the block
#: intentionally exceeds short operands — the tail is masked in-kernel
#: via the n scalar (APX303 masked_tail); _l2norm's sum-of-squares
#: accumulates in fp32 scratch (APX302).
PALLAS_AUDIT = {
    "_scale_kernel": {"masked_tail": True},
    "_axpby_kernel": {"masked_tail": True},
    "_l2norm_kernel": {"reduction": True, "masked_tail": True},
    "_l2norm_scale_kernel": {"reduction": True, "masked_tail": True},
    "_adam_kernel": {"masked_tail": True},
    "_adagrad_kernel": {"masked_tail": True},
    "_sgd_kernel": {"masked_tail": True},
    "_lamb1_kernel": {"masked_tail": True},
}

ADAM_MODE_L2 = 0  # classic Adam: weight decay folded into the gradient
ADAM_MODE_ADAMW = 1  # decoupled weight decay


def _grid(x: jax.Array) -> int:
    return cdiv(x.shape[0], _BLOCK)


def _vspec():
    return pl.BlockSpec((_BLOCK,), lambda i: (i,))


def _sspec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _tail_mask(i, n: int, x, fill):
    """Zero/neutralize out-of-bounds lanes of the final partial block.
    Elementwise kernels don't need this (OOB writes are dropped); reduction
    and flag kernels must not read OOB garbage."""
    if n % _BLOCK == 0:
        return x
    idx = i * _BLOCK + jax.lax.broadcasted_iota(jnp.int32, (_BLOCK,), 0)
    return jnp.where(idx < n, x, fill)


# every kernel grid is parallel (Megacore splits it freely): the flag /
# accumulator kernels write PER-BLOCK partials into a (grid,)-shaped SMEM
# output (each step owns its own slot) that the wrapper reduces with one
# tiny XLA max/sum — no SMEM state carried across grid steps, unlike the
# earlier serialized ("arbitrary") variant that pinned the whole unscale
# path to one core (parity: ``amp_C.multi_tensor_scale``'s chunked
# launcher is likewise grid-parallel with a global flag buffer)
_PAR = pltpu.CompilerParams(dimension_semantics=("parallel",))


def _bspec():
    """Per-grid-step (1,) SMEM output block: step i owns slot i.

    The blocked index map means only ONE element is staged in SMEM per
    grid step (the assembled ``(grid,)`` array lives in HBM), so SMEM
    pressure is O(1) in buffer size; SMEM is the right home for a scalar
    store (Mosaic vector stores want lane-shaped VMEM tiles)."""
    return pl.BlockSpec((1,), lambda i: (i,), memory_space=pltpu.SMEM)


# ---------------------------------------------------------------------------
# scale / axpby (the amp unscale path) with non-finite detection
# ---------------------------------------------------------------------------

def _scale_kernel(n, x_ref, hp_ref, o_ref, flag_ref):
    i = pl.program_id(0)
    x = x_ref[...].astype(jnp.float32)
    y = x * hp_ref[0]
    flag_ref[0] = jnp.any(~jnp.isfinite(_tail_mask(i, n, y, 0.0))
                          ).astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


def fused_scale(flat: jax.Array, scale, out_dtype=None):
    """``out = flat * scale`` with fused non-finite detection.

    Parity: ``amp_C.multi_tensor_scale`` (csrc/multi_tensor_scale_kernel.cu) —
    the overflow buffer becomes a returned fp32 flag (0.0 clean, 1.0 inf/nan).
    """
    out_dtype = out_dtype or flat.dtype
    n = flat.shape[0]
    if n == 0:   # empty grid would leave the SMEM flag uninitialized
        return flat.astype(out_dtype), jnp.float32(0.0)
    hp = jnp.asarray([scale], jnp.float32)
    out, flags = pl.pallas_call(
        functools.partial(_scale_kernel, n),
        grid=(_grid(flat),),
        in_specs=[_vspec(), _sspec()],
        out_specs=[_vspec(), _bspec()],
        out_shape=[
            jax.ShapeDtypeStruct(flat.shape, out_dtype),
            jax.ShapeDtypeStruct((_grid(flat),), jnp.float32),
        ],
        compiler_params=_PAR,
        interpret=interpret_mode(),
    )(flat, hp)
    return out, jnp.max(flags)


def _axpby_kernel(n, x_ref, y_ref, hp_ref, o_ref, flag_ref):
    i = pl.program_id(0)
    x = x_ref[...].astype(jnp.float32)
    y = y_ref[...].astype(jnp.float32)
    o = hp_ref[0] * x + hp_ref[1] * y
    flag_ref[0] = jnp.any(~jnp.isfinite(_tail_mask(i, n, o, 0.0))
                          ).astype(jnp.float32)
    o_ref[...] = o.astype(o_ref.dtype)


def fused_axpby(a, x: jax.Array, b, y: jax.Array, out_dtype=None):
    """``out = a*x + b*y`` with non-finite detection.

    Parity: ``amp_C.multi_tensor_axpby`` (csrc/multi_tensor_axpby_kernel.cu).
    """
    out_dtype = out_dtype or x.dtype
    n = x.shape[0]
    if n == 0:   # empty grid would leave the SMEM flag uninitialized
        return x.astype(out_dtype), jnp.float32(0.0)
    hp = jnp.asarray([a, b], jnp.float32)
    out, flags = pl.pallas_call(
        functools.partial(_axpby_kernel, n),
        grid=(_grid(x),),
        in_specs=[_vspec(), _vspec(), _sspec()],
        out_specs=[_vspec(), _bspec()],
        out_shape=[
            jax.ShapeDtypeStruct(x.shape, out_dtype),
            jax.ShapeDtypeStruct((_grid(x),), jnp.float32),
        ],
        compiler_params=_PAR,
        interpret=interpret_mode(),
    )(x, y, hp)
    return out, jnp.max(flags)


# ---------------------------------------------------------------------------
# L2 norm (grad clipping, LAMB global norm)
# ---------------------------------------------------------------------------

def _l2norm_kernel(n, x_ref, acc_ref):
    i = pl.program_id(0)
    x = _tail_mask(i, n, x_ref[...].astype(jnp.float32), 0.0)
    acc_ref[0] = jnp.sum(x * x)


def fused_l2norm(flat: jax.Array) -> jax.Array:
    """L2 norm of a flat buffer in one fused pass.

    Parity: ``amp_C.multi_tensor_l2norm`` (csrc/multi_tensor_l2norm_kernel.cu).
    """
    n = flat.shape[0]
    if n == 0:   # empty grid would leave the SMEM accumulator uninitialized
        return jnp.float32(0.0)
    acc = pl.pallas_call(
        functools.partial(_l2norm_kernel, n),
        grid=(_grid(flat),),
        in_specs=[_vspec()],
        out_specs=_bspec(),
        out_shape=jax.ShapeDtypeStruct((_grid(flat),), jnp.float32),
        compiler_params=_PAR,
        interpret=interpret_mode(),
    )(flat)
    return jnp.sqrt(jnp.sum(acc))


def _l2norm_scale_kernel(n, x_ref, hp_ref, o_ref, acc_ref, flag_ref):
    i = pl.program_id(0)
    x = x_ref[...].astype(jnp.float32) * hp_ref[0]
    xm = _tail_mask(i, n, x, 0.0)
    acc_ref[0] = jnp.sum(xm * xm)
    flag_ref[0] = jnp.any(~jnp.isfinite(xm)).astype(jnp.float32)
    o_ref[...] = x.astype(o_ref.dtype)


def fused_l2norm_scale(flat: jax.Array, scale, out_dtype=None):
    """``out = flat * scale`` AND the L2 norm of the scaled buffer, in one
    pass (parity: ``amp_C.multi_tensor_l2norm_scale`` — the reference
    fuses gradient unscaling with the norm the clipper needs, halving
    the HBM traffic of scale-then-norm).  Returns ``(out, norm,
    found_inf)`` — the non-finite flag keeps the unscale path's
    skip-on-overflow contract (same as :func:`fused_scale`).
    """
    out_dtype = out_dtype or flat.dtype
    n = flat.shape[0]
    if n == 0:
        return flat.astype(out_dtype), jnp.float32(0.0), jnp.float32(0.0)
    hp = jnp.asarray([scale], jnp.float32)
    out, acc, flags = pl.pallas_call(
        functools.partial(_l2norm_scale_kernel, n),
        grid=(_grid(flat),),
        in_specs=[_vspec(), _sspec()],
        out_specs=[_vspec(), _bspec(), _bspec()],
        out_shape=[
            jax.ShapeDtypeStruct(flat.shape, out_dtype),
            jax.ShapeDtypeStruct((_grid(flat),), jnp.float32),
            jax.ShapeDtypeStruct((_grid(flat),), jnp.float32),
        ],
        compiler_params=_PAR,
        interpret=interpret_mode(),
    )(flat, hp)
    return out, jnp.sqrt(jnp.sum(acc)), jnp.max(flags)


# ---------------------------------------------------------------------------
# Adam / AdamW
# ---------------------------------------------------------------------------

def _adam_kernel(adam_w, p_ref, g_ref, m_ref, v_ref, hp_ref,
                 po_ref, mo_ref, vo_ref):
    lr, b1, b2, eps, wd = (hp_ref[0], hp_ref[1], hp_ref[2], hp_ref[3],
                           hp_ref[4])
    inv_bc1, inv_sqrt_bc2, noop, gscale = (hp_ref[5], hp_ref[6], hp_ref[7],
                                           hp_ref[8])
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32) * gscale
    m = m_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)

    if not adam_w:
        g = g + wd * p
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * g * g
    denom = jnp.sqrt(v_new) * inv_sqrt_bc2 + eps
    update = (m_new * inv_bc1) / denom
    if adam_w:
        update = update + wd * p
    p_new = p - lr * update

    skip = noop > 0.0
    po_ref[...] = jnp.where(skip, p, p_new).astype(po_ref.dtype)
    mo_ref[...] = jnp.where(skip, m, m_new).astype(mo_ref.dtype)
    vo_ref[...] = jnp.where(skip, v, v_new).astype(vo_ref.dtype)


def fused_adam_flat(p, g, m, v, *, lr, beta1, beta2, eps, weight_decay,
                    step, adam_w_mode=True, bias_correction=True,
                    noop_flag=0.0, grad_scale=1.0):
    """One fused Adam(W) step over flat fp32 state.

    Parity: ``amp_C.multi_tensor_adam`` (csrc/multi_tensor_adam.cu ::
    AdamFunctor) as driven by ``apex/optimizers/fused_adam.py :: FusedAdam``.
    ``noop_flag`` > 0 turns the whole step into a no-op (overflow skip);
    ``grad_scale`` folds gradient unscaling into the same kernel.
    Returns (p, m, v) updated.
    """
    if bias_correction:
        t = jnp.asarray(step, jnp.float32)
        inv_bc1 = 1.0 / (1.0 - jnp.power(jnp.float32(beta1), t))
        inv_sqrt_bc2 = jax.lax.rsqrt(1.0 - jnp.power(jnp.float32(beta2), t))
    else:
        inv_bc1 = jnp.float32(1.0)
        inv_sqrt_bc2 = jnp.float32(1.0)
    hp = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(inv_bc1, jnp.float32),
        jnp.asarray(inv_sqrt_bc2, jnp.float32),
        jnp.asarray(noop_flag, jnp.float32),
        jnp.asarray(grad_scale, jnp.float32),
    ])
    p2, n = p, p.shape[0]
    g2 = g
    m2 = m
    v2 = v
    po, mo, vo = pl.pallas_call(
        functools.partial(_adam_kernel, bool(adam_w_mode)),
        grid=(_grid(p2),),
        in_specs=[_vspec(), _vspec(), _vspec(), _vspec(), _sspec()],
        out_specs=[_vspec(), _vspec(), _vspec()],
        out_shape=[
            jax.ShapeDtypeStruct(p2.shape, p2.dtype),
            jax.ShapeDtypeStruct(m2.shape, m2.dtype),
            jax.ShapeDtypeStruct(v2.shape, v2.dtype),
        ],
        input_output_aliases={0: 0, 2: 1, 3: 2},
        compiler_params=_PAR,
        interpret=interpret_mode(),
    )(p2, g2, m2, v2, hp)
    return (po, mo, vo)


def adam_reference(p, g, m, v, *, lr, beta1, beta2, eps, weight_decay, step,
                   adam_w_mode=True, bias_correction=True, grad_scale=1.0):
    """Pure-jnp oracle for :func:`fused_adam_flat` (mirrors torch.optim.AdamW)."""
    p = p.astype(jnp.float32)
    g = g.astype(jnp.float32) * grad_scale
    if not adam_w_mode:
        g = g + weight_decay * p
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * g * g
    if bias_correction:
        bc1 = 1 - beta1 ** step
        bc2 = 1 - beta2 ** step
    else:
        bc1 = bc2 = 1.0
    update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w_mode:
        update = update + weight_decay * p
    return p - lr * update, m, v


# ---------------------------------------------------------------------------
# Adagrad
# ---------------------------------------------------------------------------

def _adagrad_kernel(w_mode, p_ref, g_ref, h_ref, hp_ref, po_ref, ho_ref):
    lr, eps, wd, noop, gscale = (hp_ref[0], hp_ref[1], hp_ref[2], hp_ref[3],
                                 hp_ref[4])
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32) * gscale
    h = h_ref[...].astype(jnp.float32)
    if not w_mode:
        g = g + wd * p
    h_new = h + g * g
    update = g / (jnp.sqrt(h_new) + eps)
    if w_mode:
        update = update + wd * p
    p_new = p - lr * update
    skip = noop > 0.0
    po_ref[...] = jnp.where(skip, p, p_new).astype(po_ref.dtype)
    ho_ref[...] = jnp.where(skip, h, h_new).astype(ho_ref.dtype)


def fused_adagrad_flat(p, g, h, *, lr, eps, weight_decay, w_mode=False,
                       noop_flag=0.0, grad_scale=1.0):
    """Fused Adagrad step (parity: ``amp_C.multi_tensor_adagrad``; ``w_mode``
    is the reference's ADAGRAD_MODE for decoupled weight decay)."""
    hp = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(noop_flag, jnp.float32),
        jnp.asarray(grad_scale, jnp.float32),
    ])
    p2, n = p, p.shape[0]
    g2 = g
    h2 = h
    po, ho = pl.pallas_call(
        functools.partial(_adagrad_kernel, bool(w_mode)),
        grid=(_grid(p2),),
        in_specs=[_vspec(), _vspec(), _vspec(), _sspec()],
        out_specs=[_vspec(), _vspec()],
        out_shape=[
            jax.ShapeDtypeStruct(p2.shape, p2.dtype),
            jax.ShapeDtypeStruct(h2.shape, h2.dtype),
        ],
        input_output_aliases={0: 0, 2: 1},
        compiler_params=_PAR,
        interpret=interpret_mode(),
    )(p2, g2, h2, hp)
    return po, ho


# ---------------------------------------------------------------------------
# SGD (momentum, nesterov)
# ---------------------------------------------------------------------------

def _sgd_kernel(nesterov, wd_after, p_ref, g_ref, b_ref, hp_ref, po_ref,
                bo_ref):
    lr, mom, damp, wd = hp_ref[0], hp_ref[1], hp_ref[2], hp_ref[3]
    first, noop, gscale = hp_ref[4], hp_ref[5], hp_ref[6]
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32) * gscale
    buf = b_ref[...].astype(jnp.float32)
    # wd_after_momentum (reference multi_tensor_sgd flag): decay joins
    # AFTER the momentum update instead of inside the momentum input
    d = g if wd_after else g + wd * p
    buf_new = jnp.where(first > 0.0, d, mom * buf + (1.0 - damp) * d)
    if nesterov:
        step_dir = d + mom * buf_new
    else:
        step_dir = buf_new
    step_dir = jnp.where(mom == 0.0, d, step_dir)
    if wd_after:
        step_dir = step_dir + wd * p
    p_new = p - lr * step_dir
    skip = noop > 0.0
    po_ref[...] = jnp.where(skip, p, p_new).astype(po_ref.dtype)
    bo_ref[...] = jnp.where(skip, buf, buf_new).astype(bo_ref.dtype)


def fused_sgd_flat(p, g, buf, *, lr, momentum, dampening, weight_decay,
                   nesterov=False, wd_after_momentum=False,
                   first_run=False, noop_flag=0.0, grad_scale=1.0):
    """Fused SGD step, torch-SGD semantics.

    Parity: ``amp_C.multi_tensor_sgd`` (csrc/multi_tensor_sgd_kernel.cu) as
    driven by ``apex/optimizers/fused_sgd.py :: FusedSGD``, including the
    ``wd_after_momentum`` decay-placement flag.
    """
    hp = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(momentum, jnp.float32),
        jnp.asarray(dampening, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(1.0 if first_run else 0.0, jnp.float32)
        if isinstance(first_run, bool)
        else jnp.asarray(first_run, jnp.float32),
        jnp.asarray(noop_flag, jnp.float32),
        jnp.asarray(grad_scale, jnp.float32),
    ])
    p2, n = p, p.shape[0]
    g2 = g
    b2 = buf
    po, bo = pl.pallas_call(
        functools.partial(_sgd_kernel, bool(nesterov),
                          bool(wd_after_momentum)),
        grid=(_grid(p2),),
        in_specs=[_vspec(), _vspec(), _vspec(), _sspec()],
        out_specs=[_vspec(), _vspec()],
        out_shape=[
            jax.ShapeDtypeStruct(p2.shape, p2.dtype),
            jax.ShapeDtypeStruct(b2.shape, b2.dtype),
        ],
        input_output_aliases={0: 0, 2: 1},
        compiler_params=_PAR,
        interpret=interpret_mode(),
    )(p2, g2, b2, hp)
    return po, bo


# ---------------------------------------------------------------------------
# LAMB phase 1 (elementwise Adam-style direction; trust ratio applied later)
# ---------------------------------------------------------------------------

def _lamb1_kernel(p_ref, g_ref, m_ref, v_ref, hp_ref, mo_ref, vo_ref, u_ref):
    b1, b2, eps, wd = hp_ref[0], hp_ref[1], hp_ref[2], hp_ref[3]
    inv_bc1, inv_sqrt_bc2, gscale = hp_ref[4], hp_ref[5], hp_ref[6]
    beta3 = hp_ref[7]      # 1-b1 normally; 1.0 when grad_averaging=False
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32) * gscale
    m = m_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    m_new = b1 * m + beta3 * g
    v_new = b2 * v + (1.0 - b2) * g * g
    u = (m_new * inv_bc1) / (jnp.sqrt(v_new) * inv_sqrt_bc2 + eps) + wd * p
    mo_ref[...] = m_new.astype(mo_ref.dtype)
    vo_ref[...] = v_new.astype(vo_ref.dtype)
    u_ref[...] = u.astype(u_ref.dtype)


def fused_lamb_phase1_flat(p, g, m, v, *, beta1, beta2, eps, weight_decay,
                           step, bias_correction=True, grad_scale=1.0,
                           grad_averaging=True):
    """LAMB stage 1: moments + raw update direction ``u``.

    Parity: ``amp_C.multi_tensor_lamb_stage_1`` / the fused
    ``multi_tensor_lamb.cu``; stage 2 (per-tensor trust ratio apply) happens
    at the optimizer level where tensor boundaries are known.
    Returns (m, v, u).
    """
    if bias_correction:
        t = jnp.asarray(step, jnp.float32)
        inv_bc1 = 1.0 / (1.0 - jnp.power(jnp.float32(beta1), t))
        inv_sqrt_bc2 = jax.lax.rsqrt(1.0 - jnp.power(jnp.float32(beta2), t))
    else:
        inv_bc1 = jnp.float32(1.0)
        inv_sqrt_bc2 = jnp.float32(1.0)
    hp = jnp.stack([
        jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(inv_bc1, jnp.float32),
        jnp.asarray(inv_sqrt_bc2, jnp.float32),
        jnp.asarray(grad_scale, jnp.float32),
        jnp.asarray((1.0 - beta1) if grad_averaging else 1.0, jnp.float32),
    ])
    p2, n = p, p.shape[0]
    g2 = g
    m2 = m
    v2 = v
    mo, vo, u = pl.pallas_call(
        _lamb1_kernel,
        grid=(_grid(p2),),
        in_specs=[_vspec(), _vspec(), _vspec(), _vspec(), _sspec()],
        out_specs=[_vspec(), _vspec(), _vspec()],
        out_shape=[
            jax.ShapeDtypeStruct(m2.shape, m2.dtype),
            jax.ShapeDtypeStruct(v2.shape, v2.dtype),
            jax.ShapeDtypeStruct(p2.shape, jnp.float32),
        ],
        input_output_aliases={2: 0, 3: 1},
        compiler_params=_PAR,
        interpret=interpret_mode(),
    )(p2, g2, m2, v2, hp)
    return (mo, vo, u)
