"""Ring attention — context-parallel exact attention for long sequences.

The reference has NO context parallelism (SURVEY.md §2.4: its longest-
sequence tool is Megatron sequence parallelism; fused softmax caps at 16k,
fmha at 512).  The task spec makes long-context first-class, so this is the
designed-for-TPU extension: shard the sequence over the ``context`` mesh
axis and keep attention EXACT by rotating K/V shards around the ring with
``jax.lax.ppermute`` (ICI neighbor traffic), combining per-shard partial
attention with the same online-softmax algebra the flash kernel uses
(RingAttention, Liu et al. 2023; the blockwise-parallel formulation).

Each of the cp steps runs the local Pallas flash kernel (which returns
(out, lse)); partials merge in log-space:

    m   = max(lse_a, lse_b)
    out = (out_a·e^{lse_a−m} + out_b·e^{lse_b−m}) / (e^{lse_a−m}+e^{lse_b−m})

Causal masking across shards: with sequence shard i holding tokens
[i·S, (i+1)·S), a K/V shard j is fully visible when j < i, invisible when
j > i, and diagonal (locally causal) when j == i — handled per step with a
static switch on the rotation index (the ring order is known at trace
time), so no cross-shard index arithmetic reaches the kernel.

Composes under ``shard_map`` with the ``context`` axis of
``parallel_state``'s mesh; cp=1 degrades to plain flash attention.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import (_bwd_impl, _fwd, _fit_block,
                                    _seed_operand, _zero_cotangent,
                                    mha_reference)
from apex_tpu.transformer.parallel_state import CONTEXT_AXIS

__all__ = ["ring_attention", "ring_attention_reference"]


def ring_attention_reference(q, k, v, *, causal=False,
                             sm_scale: Optional[float] = None,
                             dropout_rate: float = 0.0,
                             dropout_seed=None):
    """Oracle: plain attention on the FULL (already gathered) sequence.

    With dropout, this draws the same global-coordinate mask the
    sharded ring draws — sharded-vs-dense stays an exact comparison."""
    return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale,
                         dropout_rate=dropout_rate,
                         dropout_seed=dropout_seed)


def _local_flash(q3, k3, v3, causal, scale, bq, bk, rate=0.0, seed3=None):
    """One shard-pair partial: (out [bh,s,d] fp32, lse [bh,s]) — partials
    stay fp32 so the cp-step ring accumulation doesn't round through the
    input dtype at every merge."""
    return _fwd(q3, k3, v3, None, causal, scale, bq, bk,
                out_dtype=jnp.float32, rate=rate, seed3=seed3)


def _merge(out_a, lse_a, out_b, lse_b):
    """Log-space combine of two attention partials over the same queries.

    The flash kernel's lse is BASE 2 (log2e folded into its score scale),
    so the merge runs in base 2 too — the algebra is base-invariant."""
    m = jnp.maximum(lse_a, lse_b)
    wa = jnp.exp2(lse_a - m)[..., None]
    wb = jnp.exp2(lse_b - m)[..., None]
    out = (out_a * wa + out_b * wb) / (wa + wb)
    return out, m + jnp.log2(wa[..., 0] + wb[..., 0])


def ring_attention(q, k, v, *, causal: bool = False,
                   sm_scale: Optional[float] = None,
                   axis_name: str = CONTEXT_AXIS,
                   block_q: Optional[int] = None,
                   block_k: Optional[int] = None,
                   dropout_rate: float = 0.0,
                   dropout_seed=None):
    """Exact attention over a context-sharded sequence.

    ``q, k, v``: ``[b, h, s_local, d]`` — this rank's sequence shard (rank
    i holds tokens ``[i*s_local, (i+1)*s_local)``).  Must run inside
    ``shard_map`` binding ``axis_name``; returns the local output shard.

    ``dropout_rate`` > 0 drops attention probabilities in-kernel at
    GLOBAL sequence coordinates (each shard pair offsets the counter
    hash by its global row/col position), so the context-sharded result
    equals the unsharded ``flash_attention`` / ``mha_reference`` run
    with the same seed — exactness survives dropout.  The merge algebra
    still holds because the l/lse statistics accumulate clean p; only
    the p·V feeds see the dropped probabilities.  ``dropout_seed`` must
    be IDENTICAL on every cp rank (one global mask, not per-rank
    streams)."""
    b, h, s_local, d = q.shape
    scale = (d ** -0.5) if sm_scale is None else sm_scale
    cp = jax.lax.axis_size(axis_name) if axis_name else 1
    if cp == 1:
        from apex_tpu.ops.attention import flash_attention
        return flash_attention(q, k, v, causal=causal, sm_scale=scale,
                               block_q=block_q, block_k=block_k,
                               dropout_rate=dropout_rate,
                               dropout_seed=dropout_seed)
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(
            f"dropout_rate must be in [0, 1), got {dropout_rate}")
    if dropout_rate and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")

    # None inherits flash_attention's tuned default (1024 inside its
    # verified VMEM envelope, 512 beyond it)
    default_block = 1024 if d <= 128 else 512
    bq = _fit_block(s_local, block_q or default_block)
    bk = _fit_block(s_local, block_k or default_block)
    if bq is None or bk is None:
        raise ValueError(
            f"ring_attention local shard length {s_local} must tile into "
            f"lane-multiple blocks")

    q3 = q.reshape(b * h, s_local, d)
    k3in = k.reshape(b * h, s_local, d)
    v3in = v.reshape(b * h, s_local, d)
    # rotation: at step t this rank holds K/V shard (my - t) mod cp.
    # Causal visibility is static-per-step: shard src = (my-t) mod cp is
    # src <= my  ⟺  my >= t, and the diagonal (src == my) ⟺ t == 0 — so
    # step 0 runs the locally-causal kernel, later steps run the full
    # kernel with validity masked by the traced (my >= t).
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def rot(x):
        return jax.lax.ppermute(x, axis_name, perm)

    # seed is a custom_vjp ARGUMENT (None when dropout is off): closing
    # over a traced seed leaks its trace under scan + grad — see the
    # matching note in flash_attention
    @jax.custom_vjp
    def run(q3, k3in, v3in, seed):
        out, _ = _ring_fwd(q3, k3in, v3in, seed)
        return out

    def _drop_seed3(seed, my, t):
        """Dropout operand for the step-t pair: global row offset is this
        rank's query origin; global col offset is the HELD shard's origin
        (source rank (my - t) mod cp)."""
        if not dropout_rate:
            return None
        src = jax.lax.rem(my - t + cp, cp)
        return _seed_operand(seed, my * s_local, src * s_local)

    def _ring_fwd(q3, k3in, v3in, seed):
        my = jax.lax.axis_index(axis_name)
        out = jnp.zeros((b * h, s_local, d), jnp.float32)
        lse = jnp.full((b * h, s_local), -1e30, jnp.float32)
        kv = (k3in, v3in)
        for t in range(cp):
            k3, v3 = kv
            s3 = _drop_seed3(seed, my, t)
            if causal and t > 0:
                # invisible shards: skip the kernel entirely (lax.cond on
                # the traced rank): no wasted FLOPs, and no exp(s - lse)
                # overflow from scores the global lse never bounded
                o_t, l_t = jax.lax.cond(
                    my >= t,
                    lambda k3=k3, v3=v3, s3=s3: _local_flash(
                        q3, k3, v3, False, scale, bq, bk,
                        dropout_rate, s3),
                    lambda: (jnp.zeros((b * h, s_local, d), jnp.float32),
                             jnp.full((b * h, s_local), -1e30,
                                      jnp.float32)))
            else:
                o_t, l_t = _local_flash(q3, k3, v3, causal and t == 0,
                                        scale, bq, bk, dropout_rate, s3)
            out, lse = _merge(out, lse, o_t, l_t)
            if t < cp - 1:
                kv = jax.tree.map(rot, kv)
        return out.astype(q3.dtype), lse

    def run_fwd(q3, k3in, v3in, seed):
        out, lse = _ring_fwd(q3, k3in, v3in, seed)
        return out, (q3, k3in, v3in, seed, out, lse)

    def run_bwd(res, do3):
        # flash decomposition per shard pair with the GLOBAL lse: p =
        # exp(s - lse) is the true global softmax for that pair, so each
        # pair contributes its exact dq/dk/dv.  dk/dv accumulators travel
        # WITH their K/V shard; after the final step one more rotation
        # brings every shard (and its grads) home.
        q3, k3in, v3in, seed, out, lse = res
        my = jax.lax.axis_index(axis_name)
        dq = jnp.zeros_like(q3, dtype=jnp.float32)
        kv_dkv = (k3in, v3in,
                  jnp.zeros_like(k3in, dtype=jnp.float32),
                  jnp.zeros_like(v3in, dtype=jnp.float32))
        zeros3 = lambda: (jnp.zeros_like(q3, dtype=jnp.float32),
                          jnp.zeros_like(k3in, dtype=jnp.float32),
                          jnp.zeros_like(v3in, dtype=jnp.float32))
        for t in range(cp):
            k3, v3, dk_acc, dv_acc = kv_dkv
            s3 = _drop_seed3(seed, my, t)
            if causal and t > 0:
                # skip invisible pairs (see forward): avoids inf partials
                # from exp(s - lse) on unbounded scores AND the FLOPs
                dq_t, dk_t, dv_t = jax.lax.cond(
                    my >= t,
                    lambda k3=k3, v3=v3, s3=s3: _bwd_impl(
                        q3, k3, v3, None, out, lse, do3, False, scale,
                        bq, bk, out_dtype=jnp.float32,
                        rate=dropout_rate, seed3=s3),
                    zeros3)
            else:
                dq_t, dk_t, dv_t = _bwd_impl(
                    q3, k3, v3, None, out, lse, do3,
                    causal and t == 0, scale, bq, bk,
                    out_dtype=jnp.float32, rate=dropout_rate, seed3=s3)
            dq = dq + dq_t
            kv_dkv = (k3, v3, dk_acc + dk_t, dv_acc + dv_t)
            kv_dkv = jax.tree.map(rot, kv_dkv)   # cp rotations total
        _, _, dk, dv = kv_dkv
        return (dq.astype(q3.dtype), dk.astype(k3in.dtype),
                dv.astype(v3in.dtype), _zero_cotangent(seed))

    run.defvjp(run_fwd, run_bwd)
    seed_arr = (None if not dropout_rate
                else jnp.asarray(dropout_seed, jnp.int32))
    return run(q3, k3in, v3in, seed_arr).reshape(b, h, s_local, d)
