"""KV caches: statically shaped, donated pure updates.

Two cache layouts share one mutation API (``insert*`` / ``append_layer``
/ ``advance`` / ``evict``), both the serving-side analog of the flat
optimizer master (ISSUE 2/3) — allocated once at engine construction,
carried through the jitted prefill/decode executables, donated every
step:

* :class:`KVCache` — the dense slot cache (ISSUE 4)::

      k, v : [slots, layers, kv_heads, max_seq, head_dim]

  One contiguous ``max_seq`` window per slot: simple, but a single
  128K-context straggler pins ``max_seq`` worth of HBM for EVERY slot.

* :class:`PagedKVCache` — the ragged paged pool (ISSUE 6, after
  PAPERS.md "Ragged Paged Attention")::

      k, v       : [pages, layers, kv_heads, page_size, head_dim]
      page_table : [slots, max_pages_per_slot]  int32
      lengths    : [slots]  int32   live tokens per slot
      capacity   : [slots]  int32   page_size * pages owned by the slot

  A slot's tokens live in whichever fixed-size pages the host-side
  :class:`PageAllocator` handed it; the page table (a small int32
  array, a *traced operand* like the lengths) maps virtual position
  ``t`` to physical page ``page_table[slot, t // page_size]``.  HBM is
  bounded by the POOL, not by ``slots * max_seq`` — concurrency scales
  with the mean sequence, not the straggler.

Shared design positions:

* **Slots, not sequences.**  A slot is a fixed request lane; the
  host-side scheduler maps live requests onto slots (and, paged, onto
  pages) between device steps, so admitting/retiring requests never
  changes a device shape — the decode executable compiles once.
* **GQA/MQA-aware.**  Both caches store ``kv_heads`` (not query
  heads): k/v are cached pre-broadcast, the group broadcast happens
  inside the grouped attention ops.
* **Pure donated updates.**  Every mutation is a
  ``lax.dynamic_update_slice`` returning ``cache.replace(...)`` —
  donation-safe and scan-carryable exactly like ``FlatState``.  Page
  indices come from the traced page table, so one compiled
  insert/append serves every page assignment.
* **Eviction is metadata.**  Retiring a request zeroes the slot's
  length (and, paged, its capacity); the stale k/v rows are dead
  weight masked out by the length.  No data movement on the retire
  path — the host allocator reclaims the page IDs.
* **The trash page.**  The paged pool carries ONE sacrificial page at
  index ``pages - 1`` that the allocator never hands out; page-table
  entries beyond a slot's reservation point there, so the statically
  shaped prefill/append writes that overrun a reservation land
  harmlessly instead of corrupting another slot's pages.
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.transformer.parallel_state import TENSOR_AXIS

__all__ = ["KVCache", "init_cache", "PagedKVCache", "init_paged_cache",
           "PageAllocator", "HostPageStore", "default_page_size",
           "default_swap_batch_pages", "insert_tokens", "cow_page",
           "extract_pages", "restore_pages", "append_slab",
           "advance_by", "set_lengths", "paged_cache_partition_specs"]

_PAGE_SIZE_ENV = "APEX_TPU_PAGE_SIZE"
_DEFAULT_PAGE_SIZE = 64
_SWAP_BATCH_ENV = "APEX_TPU_SWAP_BATCH_PAGES"
_DEFAULT_SWAP_BATCH = 8


def default_page_size() -> int:
    """Engine-default KV page size: ``APEX_TPU_PAGE_SIZE`` env var >
    the built-in 64 (a power of two <= the smallest prefill bucket, so
    buckets always tile exactly into pages)."""
    env = os.environ.get(_PAGE_SIZE_ENV)
    if env:
        try:
            val = int(env)
        except ValueError as e:
            raise ValueError(
                f"{_PAGE_SIZE_ENV} must be an int, got {env!r}") from e
        if val < 1 or (val & (val - 1)):
            raise ValueError(
                f"{_PAGE_SIZE_ENV} must be a positive power of two, "
                f"got {val}")
        return val
    return _DEFAULT_PAGE_SIZE


def default_swap_batch_pages() -> int:
    """Pages moved per host-tier swap dispatch (ISSUE 18):
    ``APEX_TPU_SWAP_BATCH_PAGES`` env var > the built-in 8.  The batch
    width is a STATIC operand dimension of the two swap copy programs
    (:func:`extract_pages` / :func:`restore_pages`): page-ID vectors
    are padded host-side to this width, so one compiled program per
    direction serves every page count — the zero-recompile guarantee
    every other serving-path program already gives."""
    env = os.environ.get(_SWAP_BATCH_ENV)
    if env:
        try:
            val = int(env)
        except ValueError as e:
            raise ValueError(
                f"{_SWAP_BATCH_ENV} must be an int, got {env!r}") from e
        if val < 1:
            raise ValueError(
                f"{_SWAP_BATCH_ENV} must be >= 1, got {val}")
        return val
    return _DEFAULT_SWAP_BATCH


@flax.struct.dataclass
class KVCache:
    """Static-shape slot cache (see the module docstring for layout)."""
    k: jax.Array          # [slots, layers, kv_heads, max_seq, head_dim]
    v: jax.Array          # same shape/dtype as k
    lengths: jax.Array    # [slots] int32: live tokens per slot

    @property
    def slots(self) -> int:
        return self.k.shape[0]

    @property
    def layers(self) -> int:
        return self.k.shape[1]

    @property
    def kv_heads(self) -> int:
        return self.k.shape[2]

    @property
    def max_seq(self) -> int:
        return self.k.shape[3]

    @property
    def head_dim(self) -> int:
        return self.k.shape[4]


def init_cache(slots: int, layers: int, kv_heads: int, max_seq: int,
               head_dim: int, dtype=jnp.bfloat16) -> KVCache:
    """Allocate an empty cache (every slot free, length 0)."""
    shape = (slots, layers, kv_heads, max_seq, head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   lengths=jnp.zeros((slots,), jnp.int32))


def insert(cache: KVCache, slot, k, v, length) -> KVCache:
    """Prefill write: park a prompt's k/v into one slot.

    ``k``/``v``: ``[layers, kv_heads, s, head_dim]`` with ``s`` the
    (possibly bucket-padded) prompt length, ``s <= max_seq``; ``length``
    is the number of REAL tokens (padding rows beyond it are stored but
    masked by the length everywhere they could be read).  ``slot`` and
    ``length`` may be traced — one compiled insert serves every slot.
    """
    s = k.shape[2]
    if k.shape != v.shape or k.shape[:2] != (cache.layers, cache.kv_heads) \
            or k.shape[3] != cache.head_dim:
        raise ValueError(
            f"prefill k/v must be [layers={cache.layers}, "
            f"kv_heads={cache.kv_heads}, s, head_dim={cache.head_dim}], "
            f"got k {tuple(k.shape)} v {tuple(v.shape)}")
    if s > cache.max_seq:
        raise ValueError(
            f"prompt length {s} exceeds cache max_seq {cache.max_seq}")
    slot = jnp.asarray(slot, jnp.int32)
    zero = jnp.int32(0)
    start = (slot, zero, zero, zero, zero)
    new_k = jax.lax.dynamic_update_slice(
        cache.k, k[None].astype(cache.k.dtype), start)
    new_v = jax.lax.dynamic_update_slice(
        cache.v, v[None].astype(cache.v.dtype), start)
    new_len = jax.lax.dynamic_update_slice(
        cache.lengths, jnp.asarray(length, jnp.int32)[None], (slot,))
    return cache.replace(k=new_k, v=new_v, lengths=new_len)


def append_layer(cache, layer: int, k_tok, v_tok):
    """Decode write for ONE layer: each slot's token row lands at that
    slot's current length.

    ``k_tok``/``v_tok``: ``[slots, kv_heads, head_dim]`` — the new
    token's k/v per slot.  ``layer`` is static (the decode forward is an
    unrolled python loop over layers).  Lengths do NOT advance here —
    call :func:`advance` once after the last layer so every layer of a
    decode step writes to the same position.  Dispatches on the cache
    layout: dense slot cache or paged pool.
    """
    if k_tok.shape != (cache.slots, cache.kv_heads, cache.head_dim):
        raise ValueError(
            f"token k/v must be [slots={cache.slots}, "
            f"kv_heads={cache.kv_heads}, head_dim={cache.head_dim}], "
            f"got {tuple(k_tok.shape)}")
    if isinstance(cache, PagedKVCache):
        return _append_layer_paged(cache, layer, k_tok, v_tok)

    def write(buf, tok, pos):
        # buf [kv_heads, max_seq, d], tok [kv_heads, d]: one token row
        # at this slot's own position
        return jax.lax.dynamic_update_slice(
            buf, tok[:, None, :].astype(buf.dtype),
            (jnp.int32(0), pos, jnp.int32(0)))

    upd = jax.vmap(write)
    new_k = cache.k.at[:, layer].set(
        upd(cache.k[:, layer], k_tok, cache.lengths))
    new_v = cache.v.at[:, layer].set(
        upd(cache.v[:, layer], v_tok, cache.lengths))
    return cache.replace(k=new_k, v=new_v)


def append_slab(cache, layer: int, k_slab, v_slab):
    """Speculative-verify write for ONE layer (ISSUE 15): each slot's
    ``S`` drafted-token rows land at that slot's positions
    ``[lengths, lengths + S)``.

    ``k_slab``/``v_slab``: ``[slots, kv_heads, S, head_dim]`` — the
    whole verify slab's k/v per slot.  ``S = 1`` is exactly
    :func:`append_layer`'s write.  Lengths do NOT advance here — the
    verify step advances by the ACCEPTED count once after the last
    layer (:func:`advance_by`), which is what makes rejection a length
    rollback: rows past the accepted length are dead-by-mask and the
    next append overwrites them.  Rows past a slot's virtual window
    are DROPPED (paged: an out-of-bounds page sentinel; dense: an
    out-of-bounds position), never clamped onto live rows — the same
    bounded-damage discipline as :func:`insert_tokens`.
    """
    slots, kvh, s, d = k_slab.shape
    if k_slab.shape != v_slab.shape or slots != cache.slots \
            or kvh != cache.kv_heads or d != cache.head_dim:
        raise ValueError(
            f"slab k/v must be [slots={cache.slots}, "
            f"kv_heads={cache.kv_heads}, S, head_dim={cache.head_dim}] "
            f"and equal-shaped; got k {tuple(k_slab.shape)} v "
            f"{tuple(v_slab.shape)}")
    pos = cache.lengths[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
    # [slots, S, kv_heads, d]: advanced indices lead, interior follow
    rows_k = jnp.moveaxis(k_slab, 2, 1).astype(cache.k.dtype)
    rows_v = jnp.moveaxis(v_slab, 2, 1).astype(cache.v.dtype)
    if isinstance(cache, PagedKVCache):
        ps, mpps = cache.page_size, cache.max_pages_per_slot
        ordinal = jnp.minimum(pos // ps, jnp.int32(mpps - 1))
        pages = jnp.take_along_axis(cache.page_table, ordinal, axis=1)
        # past the virtual window: OOB page sentinel -> mode="drop"
        # discards the row (clamping would clobber the last live token)
        pages = jnp.where(pos < jnp.int32(mpps * ps), pages,
                          jnp.int32(cache.pages))
        offs = jnp.minimum(pos - ordinal * ps, jnp.int32(ps - 1))
        new_k = cache.k.at[pages, layer, :, offs, :].set(rows_k,
                                                         mode="drop")
        new_v = cache.v.at[pages, layer, :, offs, :].set(rows_v,
                                                         mode="drop")
        return cache.replace(k=new_k, v=new_v)
    sid = jnp.arange(slots, dtype=jnp.int32)[:, None]
    # past max_seq: OOB position -> dropped (dynamic_update_slice would
    # clamp the whole slab backwards over live rows instead)
    posd = jnp.where(pos < jnp.int32(cache.max_seq), pos,
                     jnp.int32(cache.max_seq))
    new_k = cache.k.at[sid, layer, :, posd, :].set(rows_k, mode="drop")
    new_v = cache.v.at[sid, layer, :, posd, :].set(rows_v, mode="drop")
    return cache.replace(k=new_k, v=new_v)


def advance_by(cache, active, delta):
    """Advance the active slots' lengths by a PER-SLOT count — the
    speculative verify step's accept/rollback in one move (ISSUE 15):
    ``delta[slot]`` is the number of tokens the slot confirmed
    (accepted drafts + the bonus token), so rows appended beyond
    ``lengths + delta`` — the rejected tail of the slab — fall back to
    dead-by-mask without any data movement.  Returns
    ``(cache, truncated)`` with the same clamp/flag semantics as
    :func:`advance` (``delta = 1`` everywhere is exactly ``advance``):
    lengths clamp at capacity and ``truncated`` flags active slots
    whose confirmed tokens could not all be appended."""
    act = jnp.asarray(active)
    delta = jnp.asarray(delta, jnp.int32)
    cap = (cache.capacity if isinstance(cache, PagedKVCache)
           else jnp.int32(cache.max_seq))
    want = cache.lengths + act.astype(jnp.int32) * delta
    truncated = act.astype(bool) & (want > cap) & (cap > 0)
    return cache.replace(lengths=jnp.minimum(want, cap)), truncated


def set_lengths(cache, new_lengths):
    """Directly set every slot's length (clamped to capacity) — the
    host-driven rollback primitive a DRAFT engine needs (ISSUE 15):
    after the target verifies, the drafter rolls its own cache back to
    the pre-draft lengths so only CONFIRMED tokens ever stay resident.
    Rows beyond the restored length are dead-by-mask, exactly like a
    retired slot's rows."""
    new_lengths = jnp.asarray(new_lengths, jnp.int32)
    cap = (cache.capacity if isinstance(cache, PagedKVCache)
           else jnp.int32(cache.max_seq))
    return cache.replace(lengths=jnp.clip(new_lengths, 0, cap))


def advance(cache, active):
    """Advance the active slots' lengths by the one token the decode
    step just appended; inactive slots stay put (their garbage write at
    position ``length`` stays dead).  Returns ``(cache, truncated)``.

    Lengths clamp at capacity (``max_seq`` dense, the slot's owned
    pages paged): a slot decoded past capacity stops growing instead of
    walking its length off the buffer.  ``truncated`` is a ``[slots]``
    bool vector — True where an active slot was ALREADY at capacity, so
    the token this step emitted for it could not be appended and its
    stream is no longer extendable.  The silent clamp was ISSUE 6's
    surfaced bug: callers (the scheduler) must retire truncated slots
    and record why instead of dropping tokens on the floor."""
    act = jnp.asarray(active)
    cap = (cache.capacity if isinstance(cache, PagedKVCache)
           else jnp.int32(cache.max_seq))
    # cap > 0 gates the flag: a never-admitted paged slot (capacity 0)
    # marked active is empty, not a truncated stream
    truncated = act.astype(bool) & (cache.lengths >= cap) & (cap > 0)
    new_len = jnp.minimum(cache.lengths + act.astype(jnp.int32), cap)
    return cache.replace(lengths=new_len), truncated


def evict(cache, slot):
    """Retire a slot: zero its length (and, paged, its capacity, with
    the page-table row re-parked on the trash page).  Metadata-only —
    the k/v rows/pages are left in place; a paged slot's page IDs are
    reclaimed host-side by the :class:`PageAllocator`.

    Paged eviction MUST run before the slot's pages are reassigned:
    unlike the dense cache's slot-private rows, a stale page-table row
    would keep routing the slot's (masked, garbage) decode appends into
    pages that now belong to another request.  Resetting the row to the
    trash page makes the idle slot's writes land where the pool absorbs
    them by design."""
    slot = jnp.asarray(slot, jnp.int32)
    zero = jnp.zeros((1,), jnp.int32)
    new_len = jax.lax.dynamic_update_slice(cache.lengths, zero, (slot,))
    if isinstance(cache, PagedKVCache):
        null_row = jnp.full((1, cache.max_pages_per_slot),
                            cache.null_page, jnp.int32)
        return cache.replace(
            lengths=new_len,
            capacity=jax.lax.dynamic_update_slice(
                cache.capacity, zero, (slot,)),
            page_table=jax.lax.dynamic_update_slice(
                cache.page_table, null_row, (slot, jnp.int32(0))))
    return cache.replace(lengths=new_len)


# --------------------------------------------------------------------------
# ragged paged pool (ISSUE 6)
# --------------------------------------------------------------------------

@flax.struct.dataclass
class PagedKVCache:
    """Fixed-size page pool + per-slot page table (module docstring).

    ``k``/``v`` hold ``pages`` physical pages of ``page_size`` token
    rows each; the LAST page (``null_page == pages - 1``) is the trash
    page the allocator never hands out.  ``page_table[slot, j]`` names
    the physical page backing virtual positions ``[j*page_size,
    (j+1)*page_size)`` of the slot; entries beyond the slot's
    reservation hold ``null_page``.  ``capacity[slot]`` is
    ``page_size *`` the slot's owned pages — the clamp bound
    :func:`advance` enforces (the dense cache's ``max_seq``, made
    per-slot).

    ``attn_max_pages`` is STATIC aux data (not a leaf): the engine's
    kernel/XLA crossover override for
    :func:`~apex_tpu.ops.paged_attention.paged_decode_attention`
    (None = the env/default dispatch).

    Tensor-parallel serving (ISSUE 17) shards ONLY the ``k``/``v``
    pool, over the kv-head dim (``kv_heads/tp`` heads per rank — see
    :func:`paged_cache_partition_specs`); the page table, lengths and
    capacity stay REPLICATED, so admission, prefix sharing, COW and
    eviction run unchanged on the host-side allocator.  Inside the
    engine's ``shard_map`` every mutator here sees the per-rank shard
    as an ordinary pool — the shape checks validate against the
    LOCAL ``kv_heads`` and all page/length arithmetic is rank-
    invariant.
    """
    k: jax.Array           # [pages, layers, kv_heads, page_size, head_dim]
    v: jax.Array           # same shape/dtype as k
    page_table: jax.Array  # [slots, max_pages_per_slot] int32
    lengths: jax.Array     # [slots] int32: live tokens per slot
    capacity: jax.Array    # [slots] int32: page_size * owned pages
    attn_max_pages: Optional[int] = flax.struct.field(
        pytree_node=False, default=None)

    @property
    def pages(self) -> int:
        """Total physical pages INCLUDING the trash page."""
        return self.k.shape[0]

    @property
    def null_page(self) -> int:
        return self.k.shape[0] - 1

    @property
    def alloc_pages(self) -> int:
        """Pages the allocator may hand out (pool minus the trash page)."""
        return self.k.shape[0] - 1

    @property
    def layers(self) -> int:
        return self.k.shape[1]

    @property
    def kv_heads(self) -> int:
        return self.k.shape[2]

    @property
    def page_size(self) -> int:
        return self.k.shape[3]

    @property
    def head_dim(self) -> int:
        return self.k.shape[4]

    @property
    def slots(self) -> int:
        return self.page_table.shape[0]

    @property
    def max_pages_per_slot(self) -> int:
        return self.page_table.shape[1]

    @property
    def max_seq(self) -> int:
        """The virtual per-slot window: ``max_pages_per_slot *
        page_size`` (what the dense cache calls ``max_seq``)."""
        return self.page_table.shape[1] * self.k.shape[3]


def init_paged_cache(pages: int, layers: int, kv_heads: int,
                     page_size: int, head_dim: int, *, slots: int,
                     max_pages_per_slot: int, dtype=jnp.bfloat16,
                     attn_max_pages: Optional[int] = None) -> PagedKVCache:
    """Allocate an empty pool: ``pages`` allocatable pages (+1 trash
    page appended), every page-table entry pointing at the trash page,
    every slot empty."""
    if pages < 1 or page_size < 1 or max_pages_per_slot < 1:
        raise ValueError(
            f"pages ({pages}), page_size ({page_size}) and "
            f"max_pages_per_slot ({max_pages_per_slot}) must be >= 1")
    shape = (pages + 1, layers, kv_heads, page_size, head_dim)
    return PagedKVCache(
        k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
        page_table=jnp.full((slots, max_pages_per_slot), pages,
                            jnp.int32),
        lengths=jnp.zeros((slots,), jnp.int32),
        capacity=jnp.zeros((slots,), jnp.int32),
        attn_max_pages=attn_max_pages)


def paged_cache_partition_specs(attn_max_pages: Optional[int] = None,
                                axis: str = TENSOR_AXIS) -> PagedKVCache:
    """The pool's ``PartitionSpec`` tree for tensor-parallel serving:
    ``k``/``v`` ``[pages+1, layers, kv_heads/tp, page_size, head_dim]``
    sharded over the kv-head dim, page table / lengths / capacity
    replicated — each rank's pages are a contiguous slab (the ragged-
    paged-attention layout argument), and page IDs mean the same thing
    on every rank.  Doubles as the engine's ``shard_map`` in/out spec
    for the cache operand and as the ``NamedSharding`` source for the
    one-time ``device_put``; ``attn_max_pages`` must match the cache it
    will describe (aux data participates in pytree equality)."""
    from jax.sharding import PartitionSpec as P
    kv = P(None, None, axis, None, None)
    return PagedKVCache(k=kv, v=kv, page_table=P(), lengths=P(),
                        capacity=P(), attn_max_pages=attn_max_pages)


def page_row(page_ids: Sequence[int], max_pages_per_slot: int,
             null_page: int) -> np.ndarray:
    """Host helper: pad an allocator's page-ID list to a full
    ``[max_pages_per_slot]`` int32 page-table row (dead entries point
    at the trash page)."""
    ids = list(page_ids)
    if len(ids) > max_pages_per_slot:
        raise ValueError(
            f"{len(ids)} pages exceed max_pages_per_slot "
            f"{max_pages_per_slot}")
    return np.asarray(ids + [null_page] * (max_pages_per_slot - len(ids)),
                      np.int32)


def insert_pages(cache: PagedKVCache, slot, k, v, length,
                 row) -> PagedKVCache:
    """Prefill write: park a prompt's k/v into the slot's pages.

    ``k``/``v``: ``[layers, kv_heads, s, head_dim]`` with ``s`` the
    bucket-padded prompt length — ``s`` must tile into whole pages (the
    engine guarantees it: buckets and page sizes are both powers of
    two, ``page_size <= bucket``).  ``row`` is the slot's FULL page-
    table row (``[max_pages_per_slot]`` int32, traced OK — see
    :func:`page_row`); the first ``s // page_size`` entries receive the
    prompt's pages, later owned entries are decode headroom, trash-page
    entries absorb any static overhang harmlessly.  The slot's capacity
    is derived in-program from the row (owned pages x page_size), so
    one compiled insert serves every page assignment.
    """
    ps, s = cache.page_size, k.shape[2]
    if k.shape != v.shape or k.shape[0] != cache.layers \
            or k.shape[1] != cache.kv_heads \
            or k.shape[3] != cache.head_dim:
        raise ValueError(
            f"prefill k/v must be [layers={cache.layers}, "
            f"kv_heads={cache.kv_heads}, s, head_dim={cache.head_dim}], "
            f"got k {tuple(k.shape)} v {tuple(v.shape)}")
    if s % ps or s > cache.max_seq:
        raise ValueError(
            f"prompt slab length {s} must be a multiple of page_size "
            f"{ps} and <= max_seq {cache.max_seq}")
    row = jnp.asarray(row, jnp.int32)
    if row.shape != (cache.max_pages_per_slot,):
        raise ValueError(
            f"page row must be [{cache.max_pages_per_slot}], got "
            f"{tuple(row.shape)}")
    slot = jnp.asarray(slot, jnp.int32)
    zero = jnp.int32(0)
    n = s // ps

    def paged_slab(x):
        # [layers, kvh, s, d] -> [n, layers, kvh, ps, d]: one entry per
        # bucket page, scattered to its physical page in ONE op (bucket
        # overhang beyond the reservation targets the trash page; the
        # trash page appearing more than once just stacks garbage)
        return jnp.moveaxis(
            x.reshape(x.shape[0], x.shape[1], n, ps, x.shape[3]), 2, 0)

    new_k = cache.k.at[row[:n]].set(paged_slab(k).astype(cache.k.dtype),
                                    mode="drop")
    new_v = cache.v.at[row[:n]].set(paged_slab(v).astype(cache.v.dtype),
                                    mode="drop")
    owned = jnp.sum((row != cache.null_page).astype(jnp.int32))
    return cache.replace(
        k=new_k, v=new_v,
        page_table=jax.lax.dynamic_update_slice(
            cache.page_table, row[None], (slot, zero)),
        lengths=jax.lax.dynamic_update_slice(
            cache.lengths, jnp.asarray(length, jnp.int32)[None], (slot,)),
        capacity=jax.lax.dynamic_update_slice(
            cache.capacity, (owned * ps)[None], (slot,)))


def insert_tokens(cache: PagedKVCache, slot, k, v, length, row,
                  start) -> PagedKVCache:
    """Suffix prefill write (ISSUE 12): scatter a bucket-padded slab of
    ``s`` token rows into the slot's pages at positions ``[start,
    start + s)`` — ANY alignment, so a prefix-cache hit can resume
    mid-page after its boundary COW.

    ``k``/``v``: ``[layers, kv_heads, s, head_dim]``; ``start`` (traced
    OK) is the first virtual position the slab covers — ``0`` for a
    cold prefill, the shared-prefix coverage for a hit, a chunk
    boundary for chunked prefill.  ``length`` is the slot's TOTAL live
    length after this write (prefix + real suffix tokens).  Unlike
    :func:`insert_pages`' page-granular slab scatter, every token row
    targets ``(row[pos // page_size], pos % page_size)`` individually
    (the :func:`_append_layer_paged` addressing, vectorized over the
    slab) — positions past the reservation clamp into the trash page
    exactly like the slab insert's bucket overhang, and rows mapping
    into SHARED prefix pages never occur by contract (the scheduler
    COWs the boundary page before admitting a mid-page suffix).

    The page-table row, lengths, and capacity update exactly as in
    :func:`insert_pages` (capacity derived in-program from the owned
    entries), so one compiled insert serves every page assignment and
    every ``start``.
    """
    ps, mpps, s = cache.page_size, cache.max_pages_per_slot, k.shape[2]
    if k.shape != v.shape or k.shape[0] != cache.layers \
            or k.shape[1] != cache.kv_heads \
            or k.shape[3] != cache.head_dim:
        raise ValueError(
            f"prefill k/v must be [layers={cache.layers}, "
            f"kv_heads={cache.kv_heads}, s, head_dim={cache.head_dim}], "
            f"got k {tuple(k.shape)} v {tuple(v.shape)}")
    if s < 1 or s > cache.max_seq:
        raise ValueError(
            f"suffix slab length {s} must be in [1, max_seq "
            f"{cache.max_seq}]")
    row = jnp.asarray(row, jnp.int32)
    if row.shape != (cache.max_pages_per_slot,):
        raise ValueError(
            f"page row must be [{cache.max_pages_per_slot}], got "
            f"{tuple(row.shape)}")
    slot = jnp.asarray(slot, jnp.int32)
    start = jnp.asarray(start, jnp.int32)
    pos = start + jnp.arange(s, dtype=jnp.int32)            # [s]
    ordinal = jnp.minimum(pos // ps, jnp.int32(mpps - 1))
    pages = jnp.take(row, ordinal)                          # [s]
    # rows past the virtual window get an OUT-OF-BOUNDS page index so
    # mode="drop" discards them — clamping them onto the last owned
    # position would collide with (and clobber) the real last token
    # whenever the prompt fills the whole window
    pages = jnp.where(pos < jnp.int32(mpps * ps), pages,
                      jnp.int32(cache.pages))
    offs = jnp.minimum(pos - ordinal * ps, jnp.int32(ps - 1))
    # [layers, kvh, s, d] -> [s, layers, kvh, d]: the advanced indices
    # (pages, offs) lead, interior layer/head slices follow — one
    # vectorized scatter per buffer, donation-safe like every .at[].set
    rows_k = jnp.moveaxis(k, 2, 0).astype(cache.k.dtype)
    rows_v = jnp.moveaxis(v, 2, 0).astype(cache.v.dtype)
    new_k = cache.k.at[pages, :, :, offs, :].set(rows_k, mode="drop")
    new_v = cache.v.at[pages, :, :, offs, :].set(rows_v, mode="drop")
    owned = jnp.sum((row != cache.null_page).astype(jnp.int32))
    zero = jnp.int32(0)
    return cache.replace(
        k=new_k, v=new_v,
        page_table=jax.lax.dynamic_update_slice(
            cache.page_table, row[None], (slot, zero)),
        lengths=jax.lax.dynamic_update_slice(
            cache.lengths, jnp.asarray(length, jnp.int32)[None], (slot,)),
        capacity=jax.lax.dynamic_update_slice(
            cache.capacity, (owned * ps)[None], (slot,)))


def cow_page(cache: PagedKVCache, src, dst) -> PagedKVCache:
    """Copy-on-write page duplication: copy physical page ``src``'s k/v
    rows into page ``dst`` (both traced int32 — ONE compiled copy
    serves every page pair).

    The sharing contract's write barrier: a slot about to write into a
    page whose refcount is above one (a prefix-cache boundary page
    shared mid-fill, or any future fork) first duplicates it into a
    freshly acquired page and points its table row at the copy, so the
    other owners' reads stay bitwise untouched.  The table-row swap is
    NOT performed here — the suffix prefill that follows writes the
    slot's full row (with ``dst`` at the boundary ordinal) through
    :func:`insert_tokens`, so the copy plus the row write stay two
    dispatches of already-compiled programs.  Pure donated update like
    every other cache mutation.
    """
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    zero = jnp.int32(0)
    page_k = jax.lax.dynamic_slice(
        cache.k, (src, zero, zero, zero, zero),
        (1,) + cache.k.shape[1:])
    page_v = jax.lax.dynamic_slice(
        cache.v, (src, zero, zero, zero, zero),
        (1,) + cache.v.shape[1:])
    new_k = jax.lax.dynamic_update_slice(
        cache.k, page_k, (dst, zero, zero, zero, zero))
    new_v = jax.lax.dynamic_update_slice(
        cache.v, page_v, (dst, zero, zero, zero, zero))
    return cache.replace(k=new_k, v=new_v)


def extract_pages(cache: PagedKVCache, page_ids):
    """Swap-out gather (ISSUE 18 host page tier): read physical pages
    ``page_ids``' k/v rows into contiguous slabs —
    ``[n, layers, kv_heads, page_size, head_dim]`` per buffer, the
    :func:`insert_pages` slab layout — that the engine then
    ``device_get``\\ s into the host store.

    ``page_ids`` is a ``[n]`` int32 vector with STATIC ``n`` (the swap
    batch width): the engine pads short batches with the trash page —
    an in-bounds gather whose garbage rows the host slices off — so one
    compiled extract serves every page set.  Pure read: the cache
    operand is NOT donated (the pool stays live; eviction returns the
    page IDs to the free list host-side, no device-side erase needed).
    Under tensor parallelism each rank gathers its own ``kv_heads/tp``
    shard of the requested pages; the host-side ``device_get``
    assembles the global slab."""
    page_ids = jnp.asarray(page_ids, jnp.int32)
    if page_ids.ndim != 1:
        raise ValueError(
            f"page_ids must be a rank-1 int32 vector, got shape "
            f"{tuple(page_ids.shape)}")
    k_slab = jnp.take(cache.k, page_ids, axis=0, mode="clip")
    v_slab = jnp.take(cache.v, page_ids, axis=0, mode="clip")
    return k_slab, v_slab


def restore_pages(cache: PagedKVCache, page_ids, k_slab,
                  v_slab) -> PagedKVCache:
    """Swap-in scatter (ISSUE 18 host page tier): write host-tier page
    slabs back into freshly acquired physical pages ``page_ids`` — the
    :func:`insert_pages` slab scatter aimed by an explicit page-ID
    vector instead of a table row.

    ``page_ids`` is ``[n]`` int32 with STATIC ``n`` (the swap batch
    width); ``k_slab``/``v_slab`` are ``[n, layers, kv_heads,
    page_size, head_dim]``.  The engine pads short batches with an
    OUT-OF-BOUNDS page index (``cache.pages``) and zero slabs, so
    ``mode="drop"`` discards the padding rows — one compiled restore
    serves every page set.  Pure donated update like every other cache
    mutation.  Under tensor parallelism each rank scatters its own
    ``kv_heads/tp`` shard of the (globally sharded) slab operand."""
    page_ids = jnp.asarray(page_ids, jnp.int32)
    if page_ids.ndim != 1:
        raise ValueError(
            f"page_ids must be a rank-1 int32 vector, got shape "
            f"{tuple(page_ids.shape)}")
    n = page_ids.shape[0]
    want = (n, cache.layers, cache.kv_heads, cache.page_size,
            cache.head_dim)
    if tuple(k_slab.shape) != want or tuple(v_slab.shape) != want:
        raise ValueError(
            f"swap-in slabs must be {want}, got k "
            f"{tuple(k_slab.shape)} v {tuple(v_slab.shape)}")
    new_k = cache.k.at[page_ids].set(k_slab.astype(cache.k.dtype),
                                     mode="drop")
    new_v = cache.v.at[page_ids].set(v_slab.astype(cache.v.dtype),
                                     mode="drop")
    return cache.replace(k=new_k, v=new_v)


def _append_layer_paged(cache: PagedKVCache, layer: int, k_tok,
                        v_tok) -> PagedKVCache:
    """Paged decode write for ONE layer: slot ``i``'s token row lands in
    page ``page_table[i, lengths[i] // page_size]`` at row
    ``lengths[i] % page_size``.  One vectorized scatter per buffer
    (every slot's ``(page, row)`` target derives from the traced
    lengths/page table up front) — the paged analog of the dense
    append's vmap, donation-safe like every ``.at[].set`` on a donated
    operand.  At capacity the write clamps into the trash page / last
    row — the same bounded-damage semantics as the dense clamp, with
    the damage redirected off the live data entirely (slots at
    capacity may alias the trash page; they hold garbage by contract,
    so scatter order between them is irrelevant)."""
    ps, mpps = cache.page_size, cache.max_pages_per_slot
    pos = cache.lengths                                     # [slots]
    ordinal = jnp.minimum(pos // ps, jnp.int32(mpps - 1))
    pages = jnp.take_along_axis(cache.page_table, ordinal[:, None],
                                axis=1)[:, 0]               # [slots]
    offs = jnp.minimum(pos - ordinal * ps, jnp.int32(ps - 1))
    # advanced indices (pages, offs) with interior slices: the
    # broadcast slot dim leads, giving [slots, kv_heads, head_dim] —
    # exactly the token layout
    new_k = cache.k.at[pages, layer, :, offs, :].set(
        k_tok.astype(cache.k.dtype), mode="drop")
    new_v = cache.v.at[pages, layer, :, offs, :].set(
        v_tok.astype(cache.v.dtype), mode="drop")
    return cache.replace(k=new_k, v=new_v)


class PageAllocator:
    """Host-side reference-counted free-list allocator over the pool's
    allocatable pages (ISSUE 12: refcounts make shared-prefix page
    sharing and copy-on-write a bookkeeping operation).

    The scheduler's admission-control arm: a request is admitted only
    if :meth:`acquire` can hand it every PRIVATE page it may need
    (suffix + token budget, rounded up to whole pages) — out-of-pages
    is BACKPRESSURE (the request waits), never a mid-decode failure,
    because reservations are made in full before prefill.  A request
    extending a cached prefix does not copy the prefix's pages: it
    :meth:`share`\\ s them (refcount + 1 per co-owner), so N
    concurrent requests over a P-page prefix pin P physical pages,
    not N·P.  :meth:`release` is the ONLY way out: the page returns
    to the LIFO free list exactly when its LAST owner releases it.
    LIFO reuse keeps recently-touched pages hot.  Double-release and
    foreign-page releases raise — a leaked page is a capacity leak
    forever and a premature free corrupts another request's stream,
    so the bookkeeping is strict.

    Conservation invariant (the allocator sweep test walks it every
    step): ``free_pages + live_pages == num_pages`` with
    ``live_pages`` counting DISTINCT outstanding pages, while
    ``weighted_live()`` (the refcount-weighted view) equals the sum of
    every holder's page list — shared pages counted once per owner.
    """

    def __init__(self, num_pages: int, page_size: int,
                 max_pages_per_slot: int):
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_pages_per_slot = int(max_pages_per_slot)
        self._free: List[int] = list(range(self.num_pages))
        self._refs: dict = {}          # page id -> outstanding refcount

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def live_pages(self) -> int:
        """Distinct pages with at least one outstanding reference."""
        return len(self._refs)

    def weighted_live(self) -> int:
        """Sum of refcounts over live pages — what N sharers of one
        page would have paid WITHOUT sharing."""
        return sum(self._refs.values())

    def shared_pages(self) -> int:
        """Pages currently held by more than one owner."""
        return sum(1 for c in self._refs.values() if c > 1)

    def refcount(self, pid: int) -> int:
        return self._refs.get(int(pid), 0)

    def pages_needed(self, tokens: int) -> int:
        """Whole pages covering ``tokens``, clamped to the per-slot
        table size (a request past the virtual window truncates at
        capacity — the scheduler records why)."""
        need = -(-int(tokens) // self.page_size)
        return max(1, min(need, self.max_pages_per_slot))

    def acquire(self, n: int) -> Optional[List[int]]:
        """``n`` fresh page IDs at refcount 1 each, or None
        (backpressure) if the free list can't cover the reservation."""
        if n > len(self._free):
            return None
        ids = [self._free.pop() for _ in range(n)]
        for pid in ids:
            self._refs[pid] = 1
        return ids

    def share(self, ids: Sequence[int]) -> None:
        """Take one additional reference on each (already outstanding)
        page — the sharing half of copy-on-write.  Sharing a page with
        no live owner raises: a freed page may already back another
        request, so silent resurrection is the corruption this
        allocator exists to prevent."""
        ids = [int(p) for p in ids]
        for pid in ids:
            if pid not in self._refs:
                raise ValueError(
                    f"page {pid} is not outstanding (cannot share a "
                    f"freed page, or a page this allocator never "
                    f"issued)")
        for pid in ids:
            self._refs[pid] += 1

    def release(self, ids: Sequence[int]) -> None:
        """Drop one reference per page; a page whose LAST owner
        releases it returns to the LIFO free list.  Strict: releasing
        a page with no outstanding reference (double release, or a
        page this allocator never issued) raises."""
        for pid in ids:
            pid = int(pid)
            if pid not in self._refs:
                raise ValueError(
                    f"page {pid} is not outstanding (double release, "
                    f"or a page this allocator never issued)")
            self._refs[pid] -= 1
            if self._refs[pid] == 0:
                del self._refs[pid]
                self._free.append(pid)

    def snapshot(self) -> dict:
        """Read-only copy of the books, the SANCTIONED way to observe
        allocator internals from outside this package (the APX112 lint
        rule bans underscore-attribute mutation from anywhere else;
        the protocol auditor canonicalizes states through this).
        ``free`` preserves LIFO order — it determines which page the
        next acquire hands out, so two states whose free lists differ
        only in order are NOT equivalent."""
        return {"free": tuple(self._free),
                "refs": dict(self._refs)}


class _DeferredSlab:
    """Placeholder for one page whose device→host drain has been
    DISPATCHED but not yet fetched (ISSUE 19): ``pending.resolve()``
    returns the batch's stacked ``(k, v)`` slabs and ``index`` selects
    this page's row.  Bytes are booked the moment the placeholder is
    parked — the drain WILL land — so the budget stays as strict as an
    eager put."""
    __slots__ = ("pending", "index")

    def __init__(self, pending, index: int):
        self.pending = pending
        self.index = index

    def materialize(self):
        k, v = self.pending.resolve()
        return k[self.index].copy(), v[self.index].copy()


class HostPageStore:
    """Host-DRAM page tier under the HBM pool (ISSUE 18): a
    byte-budgeted dict of per-page k/v slabs, keyed by opaque integer
    handles the prefix cache's ``host``-state edges carry.

    The store is deliberately dumb: which entries exist and WHEN they
    are dropped is the prefix cache's per-tier LRU policy — this class
    only owns the byte ledger.  Entries are the GLOBAL page geometry
    (``[layers, kv_heads, page_size, head_dim]`` per buffer) even under
    tensor parallelism: the engine's swap-out assembles the full
    kv-head dim via ``device_get`` and the swap-in re-shards, so the
    host books stay replicated exactly like the page table.

    Conservation mirror (the churn sweep walks it every step):
    ``pages == `` the prefix cache's count of host-state edges, and
    ``bytes_used == pages * page_bytes <= capacity_bytes``.
    """

    def __init__(self, capacity_bytes: int, page_bytes: int):
        capacity_bytes = int(capacity_bytes)
        page_bytes = int(page_bytes)
        if capacity_bytes < 0 or page_bytes < 1:
            raise ValueError(
                f"capacity_bytes ({capacity_bytes}) must be >= 0 and "
                f"page_bytes ({page_bytes}) >= 1")
        self.capacity_bytes = capacity_bytes
        self.page_bytes = page_bytes
        self._slabs: dict = {}      # handle -> (k_np, v_np)
        self._next_handle = 0

    @property
    def pages(self) -> int:
        return len(self._slabs)

    @property
    def bytes_used(self) -> int:
        return len(self._slabs) * self.page_bytes

    def fits(self, n: int = 1) -> bool:
        """Would ``n`` more pages stay inside the byte budget?"""
        return self.bytes_used + int(n) * self.page_bytes \
            <= self.capacity_bytes

    def put(self, k_np, v_np) -> int:
        """Park one page's k/v slabs; returns the handle.  Strict on
        the budget: the caller (the prefix cache's offload path) makes
        room FIRST — an over-budget put is a bookkeeping bug."""
        if not self.fits(1):
            raise ValueError(
                f"host tier over budget: {self.bytes_used} + "
                f"{self.page_bytes} > {self.capacity_bytes}")
        handle = self._next_handle
        self._next_handle += 1
        self._slabs[handle] = (k_np, v_np)
        return handle

    def put_deferred(self, n: int, pending) -> list:
        """Park ``n`` pages whose device→host drain is in flight
        (ISSUE 19): ``pending.resolve()`` must return the batch's
        stacked ``(k, v)`` slabs ``[n, ...]``.  Same strict budget as
        :meth:`put` — bytes are booked eagerly for all ``n`` pages.
        Returns one handle per page.  A :meth:`get`/:meth:`pop` before
        the owner drains ``pending`` forces resolution (a prefix hit
        racing its own eviction is correct, just no longer deferred)."""
        n = int(n)
        if not self.fits(n):
            raise ValueError(
                f"host tier over budget: {self.bytes_used} + "
                f"{n * self.page_bytes} > {self.capacity_bytes}")
        handles = []
        for i in range(n):
            handle = self._next_handle
            self._next_handle += 1
            self._slabs[handle] = _DeferredSlab(pending, i)
            handles.append(handle)
        return handles

    def get(self, handle: int):
        """The ``(k, v)`` slabs behind ``handle`` (KeyError if the
        host-tier LRU already dropped it)."""
        handle = int(handle)
        entry = self._slabs[handle]
        if isinstance(entry, _DeferredSlab):
            entry = entry.materialize()
            self._slabs[handle] = entry
        return entry

    def pop(self, handle: int):
        """Drop an entry, returning its slabs (None if already gone —
        a swapped-in entry may race a host-tier eviction)."""
        entry = self._slabs.pop(int(handle), None)
        if isinstance(entry, _DeferredSlab):
            entry = entry.materialize()
        return entry

    def snapshot(self) -> dict:
        """Read-only view of the ledger, the sanctioned external
        observation surface (APX112): handle -> ``"resident"`` or
        ``"deferred"``.  Purely observational — an in-flight deferred
        entry is NOT materialized (that would force its pending drain
        and mutate the state being observed); a deferred entry whose
        pending already resolved counts as resident."""
        return {int(h): ("resident" if not isinstance(e, _DeferredSlab)
                         or getattr(e.pending, "done", False)
                         else "deferred")
                for h, e in self._slabs.items()}

    def peek_resident(self, handle: int):
        """The ``(k, v)`` slabs behind ``handle`` if resident (eager,
        or deferred with its drain already resolved), else None —
        unlike :meth:`get` this never forces an in-flight drain, so
        invariant checkers can inspect content without mutating the
        observable state."""
        entry = self._slabs.get(int(handle))
        if entry is None:
            return None
        if isinstance(entry, _DeferredSlab):
            if not getattr(entry.pending, "done", False):
                return None
            entry = entry.materialize()
            self._slabs[int(handle)] = entry
        return entry
