"""Slot-based KV cache: statically shaped, donated pure updates.

The serving-side analog of the flat optimizer master (ISSUE 2/3): ONE
statically shaped buffer pair

    k, v : [slots, layers, kv_heads, max_seq, head_dim]

plus a ``[slots]`` length vector, carried through the jitted
prefill/decode executables and donated every step — the cache is
allocated once at engine construction and never reallocated, the same
way the train step's FlatState master is.

Design positions:

* **Slots, not sequences.**  A slot is a fixed-capacity cache line; the
  host-side scheduler (``inference/scheduler.py``) maps live requests
  onto slots between device steps, so admitting/retiring requests never
  changes a device shape — the decode executable compiles once.
* **GQA/MQA-aware.**  The cache stores ``kv_heads`` (the model's
  ``cfg.kv_heads``), not query heads: k/v are cached at their
  pre-broadcast width, so LLaMA's grouped/replicated-kv layout is
  cached once per kv head and the group broadcast happens (implicitly)
  inside :func:`apex_tpu.ops.attention.decode_attention`'s grouped
  einsum — ``h // kv_heads``× less cache HBM, the whole point of GQA at
  serving time.
* **Pure donated updates.**  Every mutation is a
  ``lax.dynamic_update_slice`` (prefill insert: one static-shape slab;
  decode append: a vmap over slots, each writing one token row at its
  own length) returning ``cache.replace(...)`` — donation-safe and
  scan-carryable exactly like ``FlatState``.
* **Eviction is metadata.**  Retiring a request zeroes the slot's
  length; the stale k/v rows are dead weight masked out by the length
  and overwritten by the next insert.  No data movement on the retire
  path.
"""
from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp

__all__ = ["KVCache", "init_cache"]


@flax.struct.dataclass
class KVCache:
    """Static-shape slot cache (see the module docstring for layout)."""
    k: jax.Array          # [slots, layers, kv_heads, max_seq, head_dim]
    v: jax.Array          # same shape/dtype as k
    lengths: jax.Array    # [slots] int32: live tokens per slot

    @property
    def slots(self) -> int:
        return self.k.shape[0]

    @property
    def layers(self) -> int:
        return self.k.shape[1]

    @property
    def kv_heads(self) -> int:
        return self.k.shape[2]

    @property
    def max_seq(self) -> int:
        return self.k.shape[3]

    @property
    def head_dim(self) -> int:
        return self.k.shape[4]


def init_cache(slots: int, layers: int, kv_heads: int, max_seq: int,
               head_dim: int, dtype=jnp.bfloat16) -> KVCache:
    """Allocate an empty cache (every slot free, length 0)."""
    shape = (slots, layers, kv_heads, max_seq, head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   lengths=jnp.zeros((slots,), jnp.int32))


def insert(cache: KVCache, slot, k, v, length) -> KVCache:
    """Prefill write: park a prompt's k/v into one slot.

    ``k``/``v``: ``[layers, kv_heads, s, head_dim]`` with ``s`` the
    (possibly bucket-padded) prompt length, ``s <= max_seq``; ``length``
    is the number of REAL tokens (padding rows beyond it are stored but
    masked by the length everywhere they could be read).  ``slot`` and
    ``length`` may be traced — one compiled insert serves every slot.
    """
    s = k.shape[2]
    if k.shape != v.shape or k.shape[:2] != (cache.layers, cache.kv_heads) \
            or k.shape[3] != cache.head_dim:
        raise ValueError(
            f"prefill k/v must be [layers={cache.layers}, "
            f"kv_heads={cache.kv_heads}, s, head_dim={cache.head_dim}], "
            f"got k {tuple(k.shape)} v {tuple(v.shape)}")
    if s > cache.max_seq:
        raise ValueError(
            f"prompt length {s} exceeds cache max_seq {cache.max_seq}")
    slot = jnp.asarray(slot, jnp.int32)
    zero = jnp.int32(0)
    start = (slot, zero, zero, zero, zero)
    new_k = jax.lax.dynamic_update_slice(
        cache.k, k[None].astype(cache.k.dtype), start)
    new_v = jax.lax.dynamic_update_slice(
        cache.v, v[None].astype(cache.v.dtype), start)
    new_len = jax.lax.dynamic_update_slice(
        cache.lengths, jnp.asarray(length, jnp.int32)[None], (slot,))
    return cache.replace(k=new_k, v=new_v, lengths=new_len)


def append_layer(cache: KVCache, layer: int, k_tok, v_tok) -> KVCache:
    """Decode write for ONE layer: each slot's token row lands at that
    slot's current length.

    ``k_tok``/``v_tok``: ``[slots, kv_heads, head_dim]`` — the new
    token's k/v per slot.  ``layer`` is static (the decode forward is an
    unrolled python loop over layers).  Lengths do NOT advance here —
    call :func:`advance` once after the last layer so every layer of a
    decode step writes to the same position.
    """
    if k_tok.shape != (cache.slots, cache.kv_heads, cache.head_dim):
        raise ValueError(
            f"token k/v must be [slots={cache.slots}, "
            f"kv_heads={cache.kv_heads}, head_dim={cache.head_dim}], "
            f"got {tuple(k_tok.shape)}")

    def write(buf, tok, pos):
        # buf [kv_heads, max_seq, d], tok [kv_heads, d]: one token row
        # at this slot's own position
        return jax.lax.dynamic_update_slice(
            buf, tok[:, None, :].astype(buf.dtype),
            (jnp.int32(0), pos, jnp.int32(0)))

    upd = jax.vmap(write)
    new_k = cache.k.at[:, layer].set(
        upd(cache.k[:, layer], k_tok, cache.lengths))
    new_v = cache.v.at[:, layer].set(
        upd(cache.v[:, layer], v_tok, cache.lengths))
    return cache.replace(k=new_k, v=new_v)


def advance(cache: KVCache, active) -> KVCache:
    """Advance the active slots' lengths by the one token the decode
    step just appended; inactive slots stay put (their garbage write at
    position ``length`` stays dead).

    Lengths clamp at ``max_seq``: a slot decoded past capacity stops
    growing instead of walking its length off the buffer (the append's
    clamped write would otherwise keep overwriting the last row while
    the mask treats ever more rows as live).  Retiring full slots is
    the scheduler's job — the clamp just bounds the damage of a missing
    guard to the final cache row."""
    return cache.replace(
        lengths=jnp.minimum(
            cache.lengths + jnp.asarray(active, jnp.int32),
            jnp.int32(cache.max_seq)))


def evict(cache: KVCache, slot) -> KVCache:
    """Retire a slot: zero its length.  Metadata-only — the k/v rows are
    left in place, masked by the length, and overwritten by the next
    insert into this slot."""
    slot = jnp.asarray(slot, jnp.int32)
    return cache.replace(
        lengths=jax.lax.dynamic_update_slice(
            cache.lengths, jnp.zeros((1,), jnp.int32), (slot,)))
