"""apex_tpu.inference — TPU-native serving over the standalone models.

The inference workload as a first-class subsystem (ISSUE 4): a
prefill/decode engine whose decode step is ONE donated XLA executable
over a statically shaped KV cache, fed by a host-side
continuous-batching scheduler.  Two cache layouts (ISSUE 6):

    dense  [slots, layers, kv_heads, max_seq, d] — one contiguous
           window per slot; HBM scales with the WORST-case sequence
    paged  [pages, layers, kv_heads, page_size, d] + a [slots,
           max_pages_per_slot] page table — HBM bounded by the pool;
           the scheduler admits by free PAGES, so concurrency scales
           with the mean sequence, not the straggler

    engine        prefill/decode (+COW copy) executables, weight export
    kv_cache      donated slot cache + paged pool / refcounted host
                  PageAllocator (acquire / share / release)
    models        pure cache-aware forwards over the flax param trees
    sampling      greedy / temperature / top-k with explicit keys
    scheduler     SLO-aware continuous batching: shared-prefix
                  admission, chunked prefill, tenant fairness
    prefix_cache  host radix tree token ids -> KV page lists (ISSUE 12)
    speculative   drafters for speculative decoding (ISSUE 15):
                  prompt-lookup self-drafting, scripted replay, and a
                  small draft model beside the target

Quick start (see README "Inference")::

    from apex_tpu.inference import InferenceEngine
    engine = InferenceEngine("gpt", cfg, params, slots=8)
    # paged: bound KV HBM by a page pool instead of slots * max_seq
    engine = InferenceEngine("gpt", cfg, params, slots=32,
                             page_size=64, num_pages=256)
    outputs = engine.generate(prompts, max_new_tokens=32)
"""
from apex_tpu.inference.engine import (
    InferenceEngine,
    make_decode_fn,
    make_prefill_fn,
    make_verify_fn,
    prefill_bucket,
)
from apex_tpu.inference.kv_cache import (
    KVCache,
    PageAllocator,
    PagedKVCache,
    default_page_size,
    init_cache,
    init_paged_cache,
)
from apex_tpu.inference.prefix_cache import PrefixCache
from apex_tpu.inference.sampling import SamplingConfig, greedy, sample_token
from apex_tpu.inference.scheduler import Request, SlotScheduler, generate
from apex_tpu.inference.speculative import (
    Drafter,
    EngineDrafter,
    NGramDrafter,
    ReplayDrafter,
)

__all__ = [
    "Drafter",
    "EngineDrafter",
    "NGramDrafter",
    "ReplayDrafter",
    "InferenceEngine",
    "KVCache",
    "init_cache",
    "PagedKVCache",
    "init_paged_cache",
    "PageAllocator",
    "PrefixCache",
    "default_page_size",
    "SamplingConfig",
    "greedy",
    "sample_token",
    "Request",
    "SlotScheduler",
    "generate",
    "make_prefill_fn",
    "make_decode_fn",
    "make_verify_fn",
    "prefill_bucket",
]
