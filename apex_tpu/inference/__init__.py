"""apex_tpu.inference — TPU-native serving over the standalone models.

The inference workload as a first-class subsystem (ISSUE 4): a
prefill/decode engine whose decode step is ONE donated XLA executable
over a statically shaped slot KV cache, fed by a host-side
continuous-batching scheduler.

    engine     prefill/decode executables, weight export boundaries
    kv_cache   [slots, layers, kv_heads, max_seq, d] donated cache
    models     pure cache-aware forwards over the flax param trees
    sampling   greedy / temperature / top-k with explicit key threading
    scheduler  static-bucket continuous batching (host-side slots)

Quick start (see README "Inference")::

    from apex_tpu.inference import InferenceEngine
    engine = InferenceEngine("gpt", cfg, params, slots=8)
    outputs = engine.generate(prompts, max_new_tokens=32)
"""
from apex_tpu.inference.engine import (
    InferenceEngine,
    make_decode_fn,
    make_prefill_fn,
    prefill_bucket,
)
from apex_tpu.inference.kv_cache import KVCache, init_cache
from apex_tpu.inference.sampling import SamplingConfig, greedy, sample_token
from apex_tpu.inference.scheduler import Request, SlotScheduler, generate

__all__ = [
    "InferenceEngine",
    "KVCache",
    "init_cache",
    "SamplingConfig",
    "greedy",
    "sample_token",
    "Request",
    "SlotScheduler",
    "generate",
    "make_prefill_fn",
    "make_decode_fn",
    "prefill_bucket",
]
