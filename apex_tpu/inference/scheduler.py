"""SLO-aware continuous batching: a host-side slot (+page) allocator
with shared-prefix admission, chunked prefill, and tenant fairness.

The Megatron/vLLM-style serving loop reduced to its TPU-native core: the
DEVICE programs never change shape — decode is always ``[slots]``-wide,
prefill pads to one of O(log max_seq) buckets — and the HOST admits and
retires requests between device steps:

    admit:   free slot + queued request -> prefill into the slot
             (one donated executable; first token sampled in-program).
             PAGED engines additionally need the request's page
             reservation from the pool — but a request whose prompt
             extends a CACHED PREFIX (ISSUE 12) reserves only its
             uncached SUFFIX pages: the shared prefix pages are
             written into the slot's page-table row at one extra
             reference each (:class:`~apex_tpu.inference.prefix_cache.
             PrefixCache` + the refcounted allocator), and only the
             tail is prefilled (``prefill_from``).  Short of pages the
             scheduler first EVICTS cold cache entries (LRU), then
             WAITS (backpressure).  Admission order is SLO-aware:
             highest effective priority first (request priority + the
             ``APEX_TPU_TENANT_PRIORITY`` override), ties broken by
             least-recently-admitted tenant (per-tenant fairness under
             overload), then FIFO.
    chunk:   a long prompt's prefill is split into fixed-token chunks
             (``APEX_TPU_PREFILL_CHUNK``) interleaved with decode
             steps, so a long-prompt burst cannot stall every
             in-flight decode token for a whole monolithic prefill —
             at most ``max_chunks_per_pass`` chunks run between
             consecutive decode steps.
    step:    one decode executable over every slot (inactive slots
             compute garbage that is masked and never advances)
    retire:  EOS, the token budget, or slot capacity frees the slot;
             a retired slot only RELEASES its page references — a page
             another request (or the prefix cache) still maps goes
             back to the free list only when its LAST owner lets go.
             Every finished request records WHY in ``finish_reasons``.

Copy-on-write: a slot about to write into a page it still shares (the
partial boundary page of an unaligned prefix hit — e.g. a prompt that
EXACTLY matches a cached prefix re-prefills only its last token)
first privatizes it: one fresh page, one compiled copy dispatch
(:meth:`~apex_tpu.inference.engine.InferenceEngine.cow_page`), and the
row points at the copy — the other owners' reads stay bitwise
untouched.

A wave of requests therefore flows through a FIXED set of compiled
programs — the continuous-batching property — and N requests sharing a
P-page prefix pin P physical prefix pages, not N·P.

Telemetry (ISSUE 8/12): every scheduler carries a
:class:`~apex_tpu.observability.serve.ServeTelemetry` observing the
lifecycle at host points the loop ALREADY occupies — zero device reads,
zero recompiles — now including prefix-cache hit rate, shared-page and
cache-pinned-page gauges, COW copies, prefill chunks, and per-tenant
admitted/rejected counters.

SLO awareness (ISSUE 13): the same boundaries feed the request tracer
(``APEX_TPU_TRACE`` — per-request ``trace_span`` waterfalls) and an
:class:`~apex_tpu.observability.slo.SLOTracker` — one load observation
per loop pass through the overload detector, one burn-rate/error-budget
accounting window per ``run()`` wave (``APEX_TPU_SLO_TTFT_US`` /
``APEX_TPU_SLO_DECODE_US``).  Behind ``shed_on_overload=True`` the
priority admission consumes the advisory: while overload holds, the
LOWEST effective-priority queued request is rejected
(``finish_reasons[uid] == "shed"``, a ``rejected`` terminal span, the
rejected side of the conservation law) so high-priority tenants keep
their SLOs through the storm.
"""
from __future__ import annotations

import collections
import dataclasses
import os
from typing import Dict, Optional

import numpy as np

from apex_tpu.inference import kv_cache
from apex_tpu.inference.prefix_cache import PrefixCache, prefix_cache_enabled
from apex_tpu.inference.speculative import Drafter, NGramDrafter
from apex_tpu.observability import ServeTelemetry
from apex_tpu.observability.slo import SLOTracker

__all__ = ["Request", "SlotScheduler", "generate",
           "default_prefill_chunk", "tenant_priority_overrides"]

#: finish_reasons codes
REASON_EOS = "eos"                    # the request's eos_id was sampled
REASON_LENGTH = "length"              # max_new_tokens budget exhausted
REASON_TRUNCATED = "truncated"        # slot capacity (max_seq or page
#                                       reservation) cut the stream
REASON_SHED = "shed"                  # rejected while queued by the
#                                       overload shedding advisory

#: Admission-cost weight of one HOST-tier-covered token (ISSUE 19):
#: a swap-in upload per page instead of a full prefill recompute —
#: much cheaper than cold (1.0) but never free like an HBM hit (0.0).
#: The exact value only needs to preserve that ordering; 0.25 tracks
#: the dryrun's upload-vs-prefill ratio at the flagship page size.
HOST_HIT_TOKEN_COST = 0.25

_PREFILL_CHUNK_ENV = "APEX_TPU_PREFILL_CHUNK"
_TENANT_PRIORITY_ENV = "APEX_TPU_TENANT_PRIORITY"


def default_prefill_chunk() -> int:
    """``APEX_TPU_PREFILL_CHUNK``: chunked-prefill chunk size in tokens
    (``0`` = monolithic prefill).  Prompts longer than this prefill in
    chunks interleaved with decode steps, bounding decode-token p99
    during long-prompt bursts."""
    env = os.environ.get(_PREFILL_CHUNK_ENV)
    if not env:
        return 0
    try:
        val = int(env)
    except ValueError as e:
        raise ValueError(
            f"{_PREFILL_CHUNK_ENV} must be an int, got {env!r}") from e
    if val < 0:
        raise ValueError(
            f"{_PREFILL_CHUNK_ENV} must be >= 0, got {val}")
    return val


def tenant_priority_overrides() -> Dict[str, int]:
    """``APEX_TPU_TENANT_PRIORITY``: per-tenant admission-priority
    boosts, ``"tenantA=10,tenantB=-1"`` (empty/``0`` = none).  Added to
    each request's own ``priority`` when the scheduler picks the next
    admission."""
    env = os.environ.get(_TENANT_PRIORITY_ENV)
    if not env or env.strip() == "0":
        return {}
    out: Dict[str, int] = {}
    for item in env.split(","):
        item = item.strip()
        if not item:
            continue
        if "=" not in item:
            raise ValueError(
                f"{_TENANT_PRIORITY_ENV} entries must be "
                f"tenant=priority, got {item!r}")
        name, _, val = item.partition("=")
        try:
            out[name.strip()] = int(val)
        except ValueError as e:
            raise ValueError(
                f"{_TENANT_PRIORITY_ENV}: priority for {name!r} must "
                f"be an int, got {val!r}") from e
    return out


@dataclasses.dataclass
class Request:
    uid: int
    prompt: list
    max_new_tokens: int = 16
    eos_id: Optional[int] = None
    tenant: str = "default"
    priority: int = 0


@dataclasses.dataclass
class _SlotState:
    """Host bookkeeping for one occupied slot."""
    uid: int
    generated: list
    max_new_tokens: int
    eos_id: Optional[int]
    prompt_len: int = 0
    capacity: int = 0              # cache positions this slot owns
    pages: Optional[list] = None   # page refs held (shared + private)
    tenant: str = "default"
    prompt: Optional[list] = None  # full prompt (chunked prefill)
    prefilled: int = 0             # prompt tokens already in the cache
    chunked: bool = False          # prefill split into >1 chunk

    def prefilling(self) -> bool:
        """Still inserting prompt tokens — not decoding yet."""
        return self.prefilled < self.prompt_len

    def done(self) -> bool:
        if self.eos_id is not None and self.generated \
                and self.generated[-1] == self.eos_id:
            return True
        return len(self.generated) >= self.max_new_tokens

    def cache_len(self) -> int:
        """The slot's device cache length, derived host-side: the
        prompt plus one append per decode step taken (the first
        generated token comes from prefill and is written by the NEXT
        decode) — so the capacity guard never reads the device."""
        return self.prompt_len + len(self.generated) - 1


class SlotScheduler:
    """Maps a request queue onto the engine's fixed slots (and, paged,
    onto its page pool, sharing cached prefix pages across requests).

    ``finish_reasons[uid]`` records why each request stopped:
    ``"eos"``, ``"length"`` (token budget), or ``"truncated"`` (slot
    capacity — ``max_seq``, or the page reservation when prompt +
    budget exceeded the virtual window).  ``peak_active`` tracks the
    maximum concurrently-decoding requests the run reached — the
    admission-capacity observable prefix sharing exists to raise.

    ``prefill_chunk``/``tenant_priority`` default from their env knobs
    (``APEX_TPU_PREFILL_CHUNK`` / ``APEX_TPU_TENANT_PRIORITY``);
    ``prefix_cache=False`` disables prefix sharing for this scheduler
    regardless of ``APEX_TPU_PREFIX_CACHE``.
    """

    def __init__(self, engine, telemetry: Optional[ServeTelemetry] = None,
                 *, prefix_cache: Optional[bool] = None,
                 prefill_chunk: Optional[int] = None,
                 tenant_priority: Optional[Dict[str, int]] = None,
                 max_chunks_per_pass: int = 1,
                 slo: Optional[SLOTracker] = None,
                 shed_on_overload: bool = False,
                 drafter: Optional[Drafter] = None,
                 replica_id: Optional[int] = None):
        self.engine = engine
        # fleet plumb-through (ISSUE 19): the router stamps each
        # replica's ordinal here so per-replica metric labels and
        # route_decision events can name the scheduler they hit;
        # standalone schedulers stay unlabeled (None).
        self.replica_id = replica_id
        self.queue: collections.deque = collections.deque()
        self._next_uid = 0
        self.alloc = engine.new_allocator() if engine.paged else None
        self.finish_reasons: dict = {}
        self.peak_active = 0
        # default: the global registry (env-selected sinks attach there);
        # tests pass a ServeTelemetry over a fresh registry for isolation
        self.telemetry = (telemetry if telemetry is not None
                          else ServeTelemetry())
        use_prefix = (prefix_cache if prefix_cache is not None
                      else prefix_cache_enabled())
        # host-DRAM page tier (ISSUE 18): armed when the engine carries
        # a byte budget AND prefix caching is on — the tier is the
        # prefix cache's second level, nothing else swaps.  The store
        # and the offload closure (a batched engine extract over the
        # scheduler's live cache) are both owned here; the prefix cache
        # only does bookkeeping.
        self.host_store = None
        self._pending_swaps: list = []   # deferred D2H drains (ISSUE 19)
        if engine.paged and use_prefix \
                and getattr(engine, "host_tier_bytes", 0):
            self.host_store = kv_cache.HostPageStore(
                engine.host_tier_bytes, engine.page_host_bytes())
            self.prefix = PrefixCache(self.alloc,
                                      host_store=self.host_store,
                                      offload=self._offload_pages)
        elif engine.paged and use_prefix:
            self.prefix = PrefixCache(self.alloc)
        else:
            self.prefix = None
        self.prefill_chunk = (default_prefill_chunk()
                              if prefill_chunk is None
                              else int(prefill_chunk))
        if self.prefill_chunk and not engine.paged:
            raise ValueError(
                "chunked prefill rides the paged cache's prefill_from "
                "path; this engine runs the dense slot cache")
        if self.prefill_chunk and engine.paged \
                and self.prefill_chunk % engine.page_size:
            raise ValueError(
                f"prefill chunk ({self.prefill_chunk}) must be a "
                f"multiple of page_size ({engine.page_size}) so chunk "
                f"boundaries stay page-aligned")
        self.tenant_priority = (tenant_priority_overrides()
                                if tenant_priority is None
                                else dict(tenant_priority))
        self.max_chunks_per_pass = max(1, int(max_chunks_per_pass))
        # SLO accounting (ISSUE 13): the tracker shares the telemetry's
        # registry so its burn-rate math reads the SAME histograms the
        # lifecycle methods feed; specs default from the
        # APEX_TPU_SLO_*_US knobs (none armed = the tracker only runs
        # the overload detector).  shed_on_overload lets the priority
        # admission consume the advisory: while it holds, the LOWEST
        # effective-priority queued request is rejected (reason "shed")
        # once per pass instead of starving every tenant equally.
        self.slo = (slo if slo is not None
                    else SLOTracker(self.telemetry.registry))
        self.shed_on_overload = bool(shed_on_overload)
        # speculative decoding (ISSUE 15): engines built with
        # spec_k > 0 serve their decode tokens through the batched
        # verify step; the drafter proposes, the target disposes.
        # Default drafter = prompt-lookup self-drafting (zero device
        # work); pass drafter= for a scripted/model drafter.
        self.drafter: Optional[Drafter] = drafter
        if getattr(engine, "spec_k", 0) and self.drafter is None:
            self.drafter = NGramDrafter()
        self._admit_clock = 0
        self._tenant_last_admit: Dict[str, int] = {}
        # the scheduler OWNS one cache for its lifetime (lazily built):
        # the prefix cache indexes physical pages of THIS cache, so a
        # fresh pool per run() would turn every cached prefix into a
        # dangling pointer at zeroed pages.  One allocator, one prefix
        # cache, one device cache — one lifetime.
        self.cache = None
        # per-wave slot books (begin_run .. finish_run); empty between
        # waves so run_pending() is False outside one
        self._wave_open = False
        self._run_slots: list = []
        self._run_free: list = []
        self._run_last = np.zeros((engine.slots,), np.int32)
        self._run_results: dict = {}
        if self.alloc is not None:
            self.telemetry.pool(self.alloc.free_pages,
                                self.engine.num_pages)

    def submit(self, prompt, max_new_tokens: int = 16,
               eos_id: Optional[int] = None, tenant: str = "default",
               priority: int = 0) -> int:
        """Queue one request; returns its uid (results key)."""
        tel = self.telemetry
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            tel.request_rejected("empty_prompt", tenant=tenant)
            raise ValueError("empty prompt")
        if len(prompt) > self.engine.max_seq:
            tel.request_rejected("prompt_over_max_seq", tenant=tenant)
            raise ValueError(
                f"prompt length {len(prompt)} exceeds engine max_seq "
                f"{self.engine.max_seq}")
        if self.alloc is not None:
            # fail fast: a request no empty pool could ever cover would
            # otherwise stall the queue mid-run after earlier requests
            # already finished (and their results were built).  The
            # check is conservative — cold-path pages — because hits
            # cannot be known before the prefix cache is populated.
            need = self.alloc.pages_needed(len(prompt)
                                           + int(max_new_tokens))
            if need > self.engine.num_pages:
                tel.request_rejected("request_over_pool", tenant=tenant)
                raise ValueError(
                    f"request needs {need} pages of "
                    f"{self.engine.page_size} (prompt {len(prompt)} + "
                    f"budget {int(max_new_tokens)} tokens) but the "
                    f"pool has only {self.engine.num_pages}; grow "
                    f"num_pages or shrink the request")
        uid = self._next_uid
        self._next_uid += 1
        self.queue.append(Request(uid, prompt, int(max_new_tokens),
                                  eos_id, str(tenant), int(priority)))
        tel.request_submitted(uid, len(prompt), int(max_new_tokens),
                              queue_depth=len(self.queue))
        return uid

    def _offload_pages(self, page_ids):
        """Eviction-side device→host copy for the prefix cache's host
        tier (ISSUE 18): one batched extract over the scheduler's live
        cache, one store entry per page, handles back to the cache so
        its edges can transition to their ``host`` state.  Returns
        None before the first wave materializes a cache (nothing to
        copy — the eviction then discards, as without the tier).

        The drain is DEFERRED (ISSUE 19): the gather dispatches queue
        now, but the blocking ``device_get``\\ s run at the next wave
        boundary (or on the first hit against one of these handles,
        whichever comes first) — eviction inside the admission path no
        longer stalls on PCIe."""
        if self.cache is None or self.host_store is None:
            return None
        pending = self.engine.swap_out_pages(self.cache, page_ids,
                                             defer=True)
        handles = self.host_store.put_deferred(len(page_ids), pending)
        self._pending_swaps.append(pending)
        self.telemetry.page_swapped("out", len(page_ids))
        return handles

    def drain_pending_swaps(self) -> int:
        """Resolve every deferred device→host page drain (ISSUE 19):
        returns how many batches were forced.  Called at the wave
        boundary; hits against still-pending handles resolve lazily
        through the host store, so this only catches stragglers."""
        n = len(self._pending_swaps)
        for p in self._pending_swaps:
            p.resolve()
        self._pending_swaps.clear()
        return n

    def admission_cost(self, prompt) -> float:
        """Estimated admission cost in PREFILL-TOKEN EQUIVALENTS for a
        prompt, resolved against the prefix cache WITHOUT disturbing
        its LRU (a pure :meth:`PrefixCache.peek_match` probe).

        Cold tokens cost 1.0 each.  HBM-covered tokens cost 0 — the
        pages are already resident.  HOST-tier-covered tokens cost
        ``HOST_HIT_TOKEN_COST`` each (ISSUE 19 satellite): the swap-in
        upload is far cheaper than recomputing the prefix but it is
        NOT a free HBM hit — each such page still buys a fresh HBM
        page and a PCIe upload before the tail can prefill.  Pinned by
        a unit test: full-HBM hit < host hit < cold, always."""
        toks = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if self.prefix is None:
            return float(len(toks))
        covered, _hbm, host = self.prefix.peek_match(toks)
        host_tokens = min(host * self.engine.page_size, covered)
        return (float(len(toks) - covered)
                + HOST_HIT_TOKEN_COST * host_tokens)

    def shed_worst(self) -> Optional[int]:
        """Public shed hook for the fleet router (ISSUE 19): reject
        the worst-ranked QUEUED request (lowest effective priority,
        most recently admitted tenant, newest) and return its uid, or
        None when nothing is queued.  Same conservation-preserving
        path as the in-loop overload shed."""
        if not self.queue:
            return None
        return self._shed_one()

    # -- admission ----------------------------------------------------------
    def _pick_index(self, worst: bool = False) -> int:
        """Queue index of the next request to admit: highest effective
        priority (request priority + tenant override); ties go to the
        LEAST recently admitted tenant (round-robin fairness under
        overload), then FIFO.  ``worst=True`` inverts the ordering —
        the shed victim: LOWEST effective priority, most recently
        admitted tenant, newest submission."""
        best_key, best_i = None, 0
        for i, req in enumerate(self.queue):
            pr = req.priority + self.tenant_priority.get(req.tenant, 0)
            key = (-pr, self._tenant_last_admit.get(req.tenant, -1), i)
            better = (best_key is None
                      or (key > best_key if worst else key < best_key))
            if better:
                best_key, best_i = key, i
        return best_i

    def _shed_one(self) -> int:
        """Reject the worst-ranked queued request under the overload
        advisory (ISSUE 13): it leaves the queue with
        ``finish_reasons[uid] == "shed"`` (no results entry), its trace
        closes with a ``rejected`` terminal span, and the shed/rejected
        counters keep the conservation law intact."""
        i = self._pick_index(worst=True)
        req = self.queue[i]
        del self.queue[i]
        self.finish_reasons[req.uid] = REASON_SHED
        self.telemetry.request_shed(req.uid, tenant=req.tenant,
                                    queue_depth=len(self.queue))
        return req.uid

    def _reservation(self, req: Request):
        """Page plan for one request, or None (backpressure).

        Paged: match the prompt against BOTH tiers of the prefix
        cache, take one shared reference per HBM-covered page, and
        ACQUIRE the private pages (uncached suffix + decode headroom
        + one fresh page per HOST-covered ordinal — swapped-out
        content needs an HBM page to land in).  Coverage is clamped
        to ``len(prompt) - 1`` — the last prompt token is always
        prefilled so its logits seed the first sampled token — which
        is exactly what makes a fully-cached prompt's boundary page a
        COW candidate.  A HOST-resident boundary page needs no COW:
        its swapped-in copy is already private to the request.  Short
        of private pages the prefix cache evicts LRU entries first
        (offloading them to the host tier when armed); only then does
        the request wait.  Returns ``(row_ids, capacity, covered,
        cow_src, swap_plan)``: ``row_ids`` the slot's full ordered
        page list, ``covered`` the shared token coverage, ``cow_src``
        the shared page to privatize before the suffix prefill writes
        mid-page (or None), ``swap_plan`` the
        ``(page_ids, k_slabs, v_slabs)`` upload the admission must
        dispatch before the tail's first prefill chunk (or None).
        Dense: ``(None, max_seq, 0, None, None)``."""
        eng = self.engine
        if not eng.paged:
            return None, eng.max_seq, 0, None, None
        ps = eng.page_size
        need_total = self.alloc.pages_needed(
            len(req.prompt) + req.max_new_tokens)
        covered, mpages, host = 0, [], []
        if self.prefix is not None:
            covered, mpages, host = self.prefix.match_tiered(req.prompt)
            covered = min(covered, len(req.prompt) - 1)
            if covered < self.prefix.min_hit_tokens:
                covered, mpages, host = 0, [], []
            else:
                n_cov = -(-covered // ps)
                mpages = mpages[:n_cov]
                host = [(j, h) for j, h in host if j < n_cov]
        full = covered // ps
        partial = covered % ps
        host_map = dict(host)
        shared = [mpages[j] for j in range(full) if j not in host_map]
        boundary_host = bool(partial) and (full in host_map)
        cow_src = (mpages[full] if partial and full not in host_map
                   else None)
        # grab the host slabs NOW (numpy refs stay valid even if the
        # host-tier LRU drops these entries while evict_lru below
        # makes room for NEW offloads)
        swap_ordinals = sorted(host_map)
        swap_slabs = [self.host_store.get(host_map[j])
                      for j in swap_ordinals]
        # pin the matched HBM pages BEFORE eviction/acquire: evict_lru
        # may release the cache's (sole) reference on exactly these
        # pages, and the LIFO acquire would then re-issue one of them
        # as a private suffix page — the same physical page mapped
        # twice into one row.  The request's own references block that.
        pinned = shared + ([cow_src] if cow_src is not None else [])
        self.alloc.share(pinned)
        need_priv = need_total - len(shared)
        if need_priv > self.alloc.free_pages and self.prefix is not None:
            freed = self.prefix.evict_lru(
                need_priv - self.alloc.free_pages)
            if freed:
                self.telemetry.prefix_evicted(self.prefix.evictions)
        priv = self.alloc.acquire(need_priv)
        if priv is None:
            if pinned:
                self.alloc.release(pinned)
            return None, 0, covered, None, None
        # assemble the row POSITIONALLY: ordinal j's page backs tokens
        # [j*ps, (j+1)*ps) — HBM ordinals reuse the shared page, host
        # ordinals take a fresh private page the swap-in fills
        priv_q = list(priv)
        row_ids, swap_ids = [], []
        for j in range(full):
            if j in host_map:
                pid = priv_q.pop(0)
                row_ids.append(pid)
                swap_ids.append(pid)
            else:
                row_ids.append(mpages[j])
        if boundary_host:
            pid = priv_q.pop(0)
            row_ids.append(pid)
            swap_ids.append(pid)
        row_ids += priv_q
        swap_plan = None
        if swap_ids:
            swap_plan = (swap_ids,
                         np.stack([s[0] for s in swap_slabs]),
                         np.stack([s[1] for s in swap_slabs]))
        return row_ids, min(len(row_ids) * ps, eng.max_seq), covered, \
            cow_src, swap_plan

    # -- the wave loop, stepwise --------------------------------------------
    # run() is begin_run() + run_pass() until run_pending() clears +
    # finish_run().  The split exists so the protocol auditor
    # (``apex_tpu/analysis/protocol_audit.py``) can drive the SAME
    # admission/prefill/decode/retire code as discrete model-checking
    # actions interleaved with submits, evictions and handoffs — the
    # code being explored is the code that serves.

    def begin_run(self, cache=None) -> None:
        """Open one wave: telemetry wave marker, cache adoption, fresh
        per-wave slot books.  ``run()`` calls this once per wave; close
        with :meth:`finish_run`."""
        if self._wave_open:
            raise RuntimeError(
                "begin_run inside an open wave: finish_run() first")
        eng = self.engine
        self.telemetry.begin_wave()
        if cache is None:
            if self.cache is None:
                self.cache = eng.init_cache()
        elif cache is not self.cache:
            # the allocator and prefix cache index PHYSICAL page ids of
            # the cache this scheduler has been serving — swapping in a
            # foreign cache would turn every cached prefix into a
            # dangling pointer at zeroed pages.  A fresh cache is only
            # adoptable while no page state references the old one.
            if self.alloc is not None and (
                    self.alloc.live_pages > 0
                    or (self.prefix is not None
                        and self.prefix.pinned_pages > 0)):
                raise ValueError(
                    "a paged SlotScheduler owns its cache for its "
                    "lifetime (the prefix cache/allocator index this "
                    "cache's physical pages); cannot substitute a "
                    "different cache while pages are live — build a "
                    "new scheduler instead")
            self.cache = cache
        self._run_slots = [None] * eng.slots
        self._run_free = list(range(eng.slots))
        self._run_last = np.zeros((eng.slots,), np.int32)
        self._run_results = {}
        self._wave_open = True

    def run_pending(self) -> bool:
        """True while the open wave still has queued or in-flight
        requests — i.e. another :meth:`run_pass` would do work."""
        return bool(self.queue
                    or any(s is not None for s in self._run_slots))

    @property
    def wave_open(self) -> bool:
        """True between :meth:`begin_run` and :meth:`finish_run`."""
        return self._wave_open

    @property
    def pending_swaps(self) -> int:
        """Deferred device→host drain batches not yet resolved — 0
        outside a wave (the boundary drains them)."""
        return len(self._pending_swaps)

    def slot_states(self) -> list:
        """Read-only view of the open wave's slot books: one
        ``_SlotState`` (or None) per slot — the protocol auditor's
        observation surface for per-row page holdings."""
        return list(self._run_slots)

    def finish_run(self) -> dict:
        """Close the wave: force any deferred eviction drains to land
        (ISSUE 19 — the dispatches have been pipelining behind the
        wave's real work; the gets happen here, out of line), close one
        SLO accounting window (burn rate / budget gauges +
        slo_violation events off the histogram deltas this wave
        contributed), then flush snapshot sinks (the Prometheus file is
        only written on export).  Returns ``{uid: generated tokens}``
        for the wave."""
        if not self._wave_open:
            raise RuntimeError("finish_run without an open wave")
        self.drain_pending_swaps()
        self.slo.observe_window()
        self.telemetry.registry.export()
        self._wave_open = False
        results, self._run_results = self._run_results, {}
        return results

    def _pool_gauges(self) -> None:
        tel = self.telemetry
        tel.pool(self.alloc.free_pages, self.engine.num_pages)
        tel.prefix_pages(
            self.alloc.shared_pages(),
            self.prefix.pinned_pages if self.prefix is not None
            else 0)
        if self.host_store is not None:
            tel.host_tier(self.host_store.pages,
                          self.host_store.bytes_used)
            tel.host_tier_evicted(self.prefix.host_evictions)

    def _retire(self, slot: int, reason: str) -> None:
        st = self._run_slots[slot]
        # token budget may have been crossed by an EOS cut
        gen = st.generated[:st.max_new_tokens]
        if st.eos_id is not None and st.eos_id in gen:
            gen = gen[:gen.index(st.eos_id) + 1]
            reason = REASON_EOS
        self._run_results[st.uid] = gen
        self.finish_reasons[st.uid] = reason
        if st.pages is not None:
            # device-side metadata evict BEFORE any page could be
            # reassigned: it re-parks the slot's page-table row on
            # the trash page, so the idle slot's masked decode
            # appends can never land in another request's pages.
            # Host-side the slot then only RELEASES its references
            # — a page the prefix cache or a prefix-sharing
            # neighbour still maps stays live until its LAST owner
            # lets go (the ISSUE 12 silent-overwrite fix).
            self.cache = self.engine.evict_slot(self.cache, slot)
            self.alloc.release(st.pages)
            self._pool_gauges()
        self._run_slots[slot] = None
        self._run_free.append(slot)    # eviction = metadata; insert
        # on re-admit overwrites the stale cache rows
        if self.drafter is not None:
            self.drafter.retire(slot)
        self.telemetry.request_finished(st.uid, reason, len(gen))

    def _prefill_piece(self, slot: int) -> None:
        """Advance one slot's prefill by one chunk (or the whole
        uncached tail when chunking is off / the tail fits)."""
        eng, tel = self.engine, self.telemetry
        st = self._run_slots[slot]
        total = st.prompt_len
        start = st.prefilled
        end = (total if not self.prefill_chunk
               else min(total, start + self.prefill_chunk))
        with tel.prefill_step(
                prompt_len=end - start,
                bucket_len=eng.bucket_for(end - start),
                uid=st.uid, start_tok=start):
            self.cache, tok, _ = eng.prefill(
                self.cache, st.prompt[:end], slot, pages=st.pages,
                prefill_from=start)
            tok = int(np.asarray(tok))
        st.prefilled = end
        if st.chunked:
            tel.prefill_chunked(st.uid, start, end - start)
        if end < total:
            return                     # more chunks to go
        # final piece: the sampled token is the request's first
        tel.first_token(st.uid)
        st.generated.append(tok)
        self._run_last[slot] = tok
        if self.drafter is not None and eng.spec_k:
            self.drafter.begin(slot, st.prompt, tok)
        if self.prefix is not None:
            ps = eng.page_size
            new = self.prefix.insert(
                st.prompt, st.pages[:-(-total // ps)])
            if new:
                self._pool_gauges()
        if st.done():
            self._retire(slot, REASON_LENGTH)

    def _admit_one(self) -> bool:
        eng, tel = self.engine, self.telemetry
        i = self._pick_index()
        row_ids, capacity, covered, cow_src, swap_plan = \
            self._reservation(self.queue[i])
        if eng.paged and row_ids is None:
            tel.backpressured()
            return False               # out of pages: wait for a retire
        req = self.queue[i]
        del self.queue[i]
        slot = self._run_free.pop()
        self._admit_clock += 1
        self._tenant_last_admit[req.tenant] = self._admit_clock
        if self.prefix is not None:
            tel.prefix_lookup(covered > 0, covered)
        tel.request_admitted(
            req.uid, slot, queue_depth=len(self.queue),
            pages=len(row_ids) if row_ids is not None else None,
            tenant=req.tenant, prefix_tokens=covered)
        if row_ids is not None:
            self._pool_gauges()
        if cow_src is not None:
            # privatize the partially-shared boundary page before
            # the suffix prefill writes into it mid-page: the copy
            # lands in the first private page of the reservation.
            # The source was pinned by _reservation only for the
            # copy window — the slot's row maps the copy, not it.
            dst = row_ids[covered // eng.page_size]
            self.cache = eng.cow_page(self.cache, cow_src, dst)
            self.alloc.release([cow_src])
            tel.cow_copied(req.uid, slot, cow_src, dst)
        if swap_plan is not None:
            # host-tier hit (ISSUE 18): upload the swapped-out
            # prefix pages into their freshly acquired rows BEFORE
            # the tail's first prefill chunk — the batched uploads
            # queue ahead of the tail's compute and the prefill
            # attends across the partially-materialized prefix via
            # prefill_from.  The prefix edges resurrect to HBM at
            # this request's insert() (the swap-in commit and the
            # cold-dedup path are the same move).
            ids, kss, vss = swap_plan
            self.cache = eng.swap_in_pages(self.cache, ids, kss, vss)
            tel.page_swapped("in", len(ids), uid=req.uid)
            tel.prefix_host_hit()
            self._pool_gauges()
        n_chunks = (1 if not self.prefill_chunk else
                    -(-(len(req.prompt) - covered)
                      // self.prefill_chunk))
        self._run_slots[slot] = _SlotState(
            req.uid, [], req.max_new_tokens, req.eos_id,
            prompt_len=len(req.prompt), capacity=capacity,
            pages=row_ids, tenant=req.tenant, prompt=req.prompt,
            prefilled=covered, chunked=n_chunks > 1)
        return True

    def run_pass(self) -> None:
        """One pass of the wave loop: admit what fits (slots, pages —
        priority/fairness ordered), advance at most
        ``max_chunks_per_pass`` prefill chunks, then ONE batched
        decode (or verify) step over the decoding slots.  The device
        sees only the fixed-shape prefill/decode (+COW copy)
        executables; everything else here is host-side bookkeeping on
        ints."""
        eng, tel = self.engine, self.telemetry
        slots = self._run_slots
        # SLO load observation (ISSUE 13): one host-side sample per
        # pass through the overload detector; while the advisory
        # holds and shedding is armed, the worst-ranked queued
        # request is rejected (at most one per pass — shedding
        # relieves pressure, it does not empty the queue)
        advisory = self.slo.observe_load(
            queue_depth=len(self.queue),
            backpressure_total=tel.backpressure_waits.total(),
            free_pages=(self.alloc.free_pages
                        if self.alloc is not None else None))
        if advisory and self.shed_on_overload and self.queue:
            self._shed_one()
        # admit: fill free slots from the queue (priority/fairness
        # ordered — a picked request the pool can't cover yet
        # blocks this pass rather than being starved)
        blocked = False
        while self.queue and self._run_free:
            if not self._admit_one():
                blocked = True
                break
        # advance prefills.  Chunking off: every pending admission
        # prefills now (the classic loop).  Chunking on: at most
        # max_chunks_per_pass chunks run BETWEEN decode steps, so a
        # long-prompt burst cannot starve in-flight decodes.
        budget = (self.max_chunks_per_pass if self.prefill_chunk
                  else eng.slots)
        chunks = 0
        for slot in range(eng.slots):
            st = slots[slot]
            if st is None or not st.prefilling():
                continue
            self._prefill_piece(slot)
            chunks += 1
            if chunks >= budget:
                break
        active = np.array(
            [s is not None and not s.prefilling()
             and bool(s.generated) for s in slots], bool)
        if not active.any():
            if any(s is not None for s in slots):
                return                 # still prefilling: next pass
            if self.queue:
                if not blocked:
                    # slots opened up mid-pass (a request finished
                    # at its prefill): admit on the next pass
                    return
                # nothing running and the picked request still
                # can't be admitted: the POOL itself is too small
                # (prefix-cache eviction already ran)
                req = self.queue[self._pick_index()]
                raise RuntimeError(
                    f"request {req.uid} needs more pages than the "
                    f"pool frees up (prompt {len(req.prompt)} + "
                    f"budget {req.max_new_tokens} tokens vs "
                    f"{self.alloc.free_pages} free pages of "
                    f"{self.alloc.page_size}); grow num_pages or "
                    f"shrink the request")
            return
        # guard: a slot at its capacity cannot take another token.
        # Lengths are derived host-side (_SlotState.cache_len) — no
        # device readback in the control loop beyond the sampled
        # tokens themselves.  The decode step's `truncated` output
        # is the device-side belt to this suspender.
        for slot, st in enumerate(slots):
            if st is not None and active[slot] \
                    and st.cache_len() >= st.capacity:
                self._retire(slot, REASON_TRUNCATED)
                active[slot] = False
        if not active.any():
            return
        # counted AFTER the capacity guard: peak_active measures
        # requests that actually decode concurrently this step
        n_active = int(active.sum())
        self.peak_active = max(self.peak_active, n_active)
        if getattr(eng, "spec_k", 0):
            # speculative wave (ISSUE 15): drafts in, the verify
            # step scores one (k+1)-slab per slot, accepted drafts
            # + bonus come out.  The emitted stream is ALWAYS the
            # target's own greedy stream; rejection already rolled
            # the device lengths back in-program, and pages were
            # reserved at admission so nothing is released here.
            k = eng.spec_k
            slab = np.zeros((eng.slots, k + 1), np.int32)
            slab[:, 0] = self._run_last
            slab[:, 1:] = self.drafter.draft_batch(active, k)
            with tel.verify_step(n_active,
                                 capacity=eng.slots) as vstep:
                self.cache, toks, n_emit, truncated = eng.verify(
                    self.cache, slab, active)
                toks = np.asarray(toks)
                n_emit = np.asarray(n_emit)
                truncated = np.asarray(truncated)
                # per-token latency back-channel: the bracket's
                # histogram sample divides by mean emitted/slot.
                # Clamped the way the consumption loop below will
                # clamp (capacity AND token budget) so a final
                # short round cannot under-report per-token
                # latency; only an eos landing mid-slab (terminal
                # for the stream) escapes the host-side mirror.
                vstep["tokens"] = float(sum(
                    min(int(n_emit[s]),
                        slots[s].capacity - slots[s].cache_len(),
                        slots[s].max_new_tokens
                        - len(slots[s].generated))
                    for s in range(eng.slots)
                    if slots[s] is not None and active[s]))
            for slot, st in enumerate(slots):
                if st is None or not active[slot]:
                    continue
                # the host capacity mirror clamps exactly like the
                # device's advance_by did (same inputs, same min)
                remaining = st.capacity - st.cache_len()
                usable = int(min(int(n_emit[slot]), remaining))
                emitted = []
                reason = None
                for t in toks[slot, :usable]:
                    st.generated.append(int(t))
                    emitted.append(int(t))
                    if st.done():
                        reason = REASON_LENGTH
                        break
                # emitted counts tokens that actually reached the
                # request (capacity- AND budget-clamped), so
                # spec_emitted == tokens_generated minus the
                # prefill-sampled firsts — conservation-testable
                tel.speculation(k, int(n_emit[slot]) - 1,
                                len(emitted))
                if emitted:
                    self._run_last[slot] = emitted[-1]
                    self.drafter.observe(slot, emitted)
                if reason is not None:
                    self._retire(slot, reason)
                elif usable < int(n_emit[slot]) or truncated[slot]:
                    # capacity cut the emitted stream short
                    self._retire(slot, REASON_TRUNCATED)
            return
        # the decode bracket closes after the token host-read the
        # loop performs anyway, so the histogram sample is the true
        # per-token latency (dispatch + sync), and its recompile
        # flag feeds serve_recompiles_total (pinned 0 by tests)
        with tel.decode_step(n_active, capacity=eng.slots):
            self.cache, toks, _, truncated = eng.decode(
                self.cache, self._run_last, active)
            toks = np.asarray(toks)
            truncated = np.asarray(truncated)
        for slot, st in enumerate(slots):
            if st is None or not active[slot]:
                continue
            if truncated[slot]:
                # the host guard above should have retired this
                # slot first; trust the device flag regardless
                self._retire(slot, REASON_TRUNCATED)
                continue
            st.generated.append(int(toks[slot]))
            self._run_last[slot] = toks[slot]
            if st.done():
                self._retire(slot, REASON_LENGTH)

    def run(self, cache=None) -> dict:
        """Drain the queue; returns ``{uid: generated token list}``.

        One :meth:`begin_run`, :meth:`run_pass` until the queue and
        slots drain, one :meth:`finish_run` — the wave boundary.  The
        (donation-threaded) cache carries into the next wave, so
        cached prefix pages stay valid across ``run()`` calls.
        """
        self.begin_run(cache)
        while self.run_pending():
            self.run_pass()
        return self.finish_run()


def generate(engine, prompts, max_new_tokens: int = 16,
             eos_id: Optional[int] = None):
    """One-shot continuous-batching run: list of prompts in, list of
    generated token lists out (submission order)."""
    sched = SlotScheduler(engine)
    uids = [sched.submit(p, max_new_tokens=max_new_tokens, eos_id=eos_id)
            for p in prompts]
    out = sched.run()
    return [out[u] for u in uids]
