"""Static-bucket continuous batching: a host-side slot (+page)
allocator.

The Megatron/vLLM-style serving loop reduced to its TPU-native core: the
DEVICE programs never change shape — decode is always ``[slots]``-wide,
prefill pads to one of O(log max_seq) buckets — and the HOST admits and
retires requests between device steps:

    admit:   free slot + queued request -> prefill into the slot
             (one donated executable; first token sampled in-program).
             PAGED engines additionally need the request's page
             reservation (prompt + token budget, whole pages) from the
             pool — short of pages the request WAITS (backpressure)
             until a retire reclaims some, so admission is bounded by
             free HBM pages, not by worst-case slots.
    step:    one decode executable over every slot (inactive slots
             compute garbage that is masked and never advances)
    retire:  EOS, the token budget, or slot capacity frees the slot
             (and returns its pages to the pool); eviction is pure
             metadata, so retiring moves zero bytes on device.  Every
             finished request records WHY in ``finish_reasons`` —
             capacity truncation is surfaced, never silent (ISSUE 6).

A wave of requests therefore flows through a FIXED set of compiled
programs — the continuous-batching property: a finished sequence's slot
is refilled on the next loop iteration while the other slots keep
decoding, with no recompile and no cache reallocation anywhere.

Telemetry (ISSUE 8): every scheduler carries a
:class:`~apex_tpu.observability.serve.ServeTelemetry` observing the
lifecycle at the host points the loop ALREADY occupies (it reads
sampled tokens between steps by construction, so instrumentation adds
zero device reads and zero recompiles): submit/admit/first-token/finish
events, TTFT + per-token decode-latency histograms, queue depth,
backpressure + per-``finish_reasons`` counters, and the page-pool
free/occupancy gauges.  ``peak_active``/``finish_reasons`` stay as
attributes for existing callers, mirrored into the registry.
"""
from __future__ import annotations

import collections
import dataclasses
from typing import Optional

import numpy as np

from apex_tpu.inference import kv_cache
from apex_tpu.observability import ServeTelemetry

__all__ = ["Request", "SlotScheduler", "generate"]

#: finish_reasons codes
REASON_EOS = "eos"                    # the request's eos_id was sampled
REASON_LENGTH = "length"              # max_new_tokens budget exhausted
REASON_TRUNCATED = "truncated"        # slot capacity (max_seq or page
#                                       reservation) cut the stream


@dataclasses.dataclass
class Request:
    uid: int
    prompt: list
    max_new_tokens: int = 16
    eos_id: Optional[int] = None


@dataclasses.dataclass
class _SlotState:
    """Host bookkeeping for one occupied slot."""
    uid: int
    generated: list
    max_new_tokens: int
    eos_id: Optional[int]
    prompt_len: int = 0
    capacity: int = 0              # cache positions this slot owns
    pages: Optional[list] = None   # reserved page IDs (paged engines)

    def done(self) -> bool:
        if self.eos_id is not None and self.generated \
                and self.generated[-1] == self.eos_id:
            return True
        return len(self.generated) >= self.max_new_tokens

    def cache_len(self) -> int:
        """The slot's device cache length, derived host-side: the
        prompt plus one append per decode step taken (the first
        generated token comes from prefill and is written by the NEXT
        decode) — so the capacity guard never reads the device."""
        return self.prompt_len + len(self.generated) - 1


class SlotScheduler:
    """Maps a request queue onto the engine's fixed slots (and, paged,
    onto its page pool).

    ``finish_reasons[uid]`` records why each request stopped:
    ``"eos"``, ``"length"`` (token budget), or ``"truncated"`` (slot
    capacity — ``max_seq``, or the page reservation when prompt +
    budget exceeded the virtual window).  ``peak_active`` tracks the
    maximum concurrently-decoding requests the run reached — the
    admission-capacity observable the paged cache exists to raise.
    """

    def __init__(self, engine, telemetry: Optional[ServeTelemetry] = None):
        self.engine = engine
        self.queue: collections.deque = collections.deque()
        self._next_uid = 0
        self.alloc = engine.new_allocator() if engine.paged else None
        self.finish_reasons: dict = {}
        self.peak_active = 0
        # default: the global registry (env-selected sinks attach there);
        # tests pass a ServeTelemetry over a fresh registry for isolation
        self.telemetry = (telemetry if telemetry is not None
                          else ServeTelemetry())
        if self.alloc is not None:
            self.telemetry.pool(self.alloc.free_pages,
                                self.engine.num_pages)

    def submit(self, prompt, max_new_tokens: int = 16,
               eos_id: Optional[int] = None) -> int:
        """Queue one request; returns its uid (results key)."""
        tel = self.telemetry
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            tel.request_rejected("empty_prompt")
            raise ValueError("empty prompt")
        if len(prompt) > self.engine.max_seq:
            tel.request_rejected("prompt_over_max_seq")
            raise ValueError(
                f"prompt length {len(prompt)} exceeds engine max_seq "
                f"{self.engine.max_seq}")
        if self.alloc is not None:
            # fail fast: a request no empty pool could ever cover would
            # otherwise stall the FIFO mid-run after earlier requests
            # already finished (and their results were built)
            need = self.alloc.pages_needed(len(prompt)
                                           + int(max_new_tokens))
            if need > self.engine.num_pages:
                tel.request_rejected("request_over_pool")
                raise ValueError(
                    f"request needs {need} pages of "
                    f"{self.engine.page_size} (prompt {len(prompt)} + "
                    f"budget {int(max_new_tokens)} tokens) but the "
                    f"pool has only {self.engine.num_pages}; grow "
                    f"num_pages or shrink the request")
        uid = self._next_uid
        self._next_uid += 1
        self.queue.append(Request(uid, prompt, int(max_new_tokens),
                                  eos_id))
        tel.request_submitted(uid, len(prompt), int(max_new_tokens),
                              queue_depth=len(self.queue))
        return uid

    # -- admission ----------------------------------------------------------
    def _reservation(self, req: Request):
        """(pages or None, capacity) for one request.  Paged: whole
        pages covering prompt + token budget — the static prefill
        bucket may be LARGER, but bucket pages past the reservation
        hold only dead padding rows (masked by the length) and spill
        into the pool's trash page by construction, so they cost
        nothing.  ``None`` pages means the pool can't cover the
        request right now (backpressure).  Dense: capacity is the
        shared ``max_seq``."""
        eng = self.engine
        if not eng.paged:
            return None, eng.max_seq
        need = self.alloc.pages_needed(
            len(req.prompt) + req.max_new_tokens)
        pages = self.alloc.alloc(need)
        if pages is None:
            return None, 0
        return pages, min(need * eng.page_size, eng.max_seq)

    def run(self, cache=None) -> dict:
        """Drain the queue; returns ``{uid: generated token list}``.

        One pass of the loop = admit every free slot (and, paged, every
        page reservation) it can, then one batched decode step.  The
        device sees only the fixed-shape prefill/decode executables;
        everything else here is host-side bookkeeping on ints.
        """
        eng = self.engine
        tel = self.telemetry
        if cache is None:
            cache = eng.init_cache()
        slots: list = [None] * eng.slots
        free = list(range(eng.slots))
        last = np.zeros((eng.slots,), np.int32)
        results: dict = {}

        def retire(slot, reason):
            nonlocal cache
            st = slots[slot]
            # token budget may have been crossed by an EOS cut
            gen = st.generated[:st.max_new_tokens]
            if st.eos_id is not None and st.eos_id in gen:
                gen = gen[:gen.index(st.eos_id) + 1]
                reason = REASON_EOS
            results[st.uid] = gen
            self.finish_reasons[st.uid] = reason
            if st.pages is not None:
                # device-side metadata evict BEFORE the pages can be
                # reassigned: it re-parks the slot's page-table row on
                # the trash page, so the idle slot's masked decode
                # appends can never land in another request's pages
                # (dense slots skip this — their rows are slot-private)
                cache = kv_cache.evict(cache, slot)
                self.alloc.free(st.pages)      # pages back to the pool
                tel.pool(self.alloc.free_pages, eng.num_pages)
            slots[slot] = None
            free.append(slot)          # eviction = metadata; insert
            # on re-admit overwrites the stale cache rows
            tel.request_finished(st.uid, reason, len(gen))

        while self.queue or any(s is not None for s in slots):
            # admit: fill free slots from the queue (FIFO — a request
            # the pool can't cover yet blocks later ones rather than
            # being starved by them)
            while self.queue and free:
                pages, capacity = self._reservation(self.queue[0])
                if eng.paged and pages is None:
                    tel.backpressured()
                    break              # out of pages: wait for a retire
                req = self.queue.popleft()
                slot = free.pop()
                tel.request_admitted(
                    req.uid, slot, queue_depth=len(self.queue),
                    pages=len(pages) if pages is not None else None)
                if pages is not None:
                    tel.pool(self.alloc.free_pages, eng.num_pages)
                with tel.prefill_step(
                        prompt_len=len(req.prompt),
                        bucket_len=eng.bucket_for(len(req.prompt))):
                    cache, tok, _ = eng.prefill(cache, req.prompt, slot,
                                                pages=pages)
                    tok = int(np.asarray(tok))
                tel.first_token(req.uid)
                slots[slot] = _SlotState(req.uid, [tok],
                                         req.max_new_tokens, req.eos_id,
                                         prompt_len=len(req.prompt),
                                         capacity=capacity, pages=pages)
                last[slot] = tok
                if slots[slot].done():
                    retire(slot, REASON_LENGTH)
            active = np.array([s is not None for s in slots], bool)
            if not active.any():
                if self.queue:
                    # nothing running and the head request still can't
                    # be admitted: the POOL itself is too small for it
                    req = self.queue[0]
                    raise RuntimeError(
                        f"request {req.uid} needs more pages than the "
                        f"pool frees up (prompt {len(req.prompt)} + "
                        f"budget {req.max_new_tokens} tokens vs "
                        f"{self.alloc.free_pages} free pages of "
                        f"{self.alloc.page_size}); grow num_pages or "
                        f"shrink the request")
                continue
            # guard: a slot at its capacity cannot take another token.
            # Lengths are derived host-side (_SlotState.cache_len) — no
            # device readback in the control loop beyond the sampled
            # tokens themselves.  The decode step's `truncated` output
            # is the device-side belt to this suspender.
            for slot, st in enumerate(slots):
                if st is not None and st.cache_len() >= st.capacity:
                    retire(slot, REASON_TRUNCATED)
                    active[slot] = False
            if not active.any():
                continue
            # counted AFTER the capacity guard: peak_active measures
            # requests that actually decode concurrently this step
            n_active = int(active.sum())
            self.peak_active = max(self.peak_active, n_active)
            # the decode bracket closes after the token host-read the
            # loop performs anyway, so the histogram sample is the true
            # per-token latency (dispatch + sync), and its recompile
            # flag feeds serve_recompiles_total (pinned 0 by tests)
            with tel.decode_step(n_active, capacity=eng.slots):
                cache, toks, _, truncated = eng.decode(cache, last,
                                                       active)
                toks = np.asarray(toks)
                truncated = np.asarray(truncated)
            for slot, st in enumerate(slots):
                if st is None or not active[slot]:
                    continue
                if truncated[slot]:
                    # the host guard above should have retired this
                    # slot first; trust the device flag regardless
                    retire(slot, REASON_TRUNCATED)
                    continue
                st.generated.append(int(toks[slot]))
                last[slot] = toks[slot]
                if st.done():
                    retire(slot, REASON_LENGTH)
        # wave boundary: flush snapshot sinks (the Prometheus file is
        # only written on export — without this, APEX_TPU_TELEMETRY
        # would produce the JSONL stream but never metrics.prom)
        tel.registry.export()
        return results


def generate(engine, prompts, max_new_tokens: int = 16,
             eos_id: Optional[int] = None):
    """One-shot continuous-batching run: list of prompts in, list of
    generated token lists out (submission order)."""
    sched = SlotScheduler(engine)
    uids = [sched.submit(p, max_new_tokens=max_new_tokens, eos_id=eos_id)
            for p in prompts]
    out = sched.run()
    return [out[u] for u in uids]
