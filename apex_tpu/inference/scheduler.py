"""Static-bucket continuous batching: a host-side slot allocator.

The Megatron/vLLM-style serving loop reduced to its TPU-native core: the
DEVICE programs never change shape — decode is always ``[slots]``-wide,
prefill pads to one of O(log max_seq) buckets — and the HOST admits and
retires requests between device steps:

    admit:   free slot + queued request -> prefill into the slot
             (one donated executable; first token sampled in-program)
    step:    one decode executable over every slot (inactive slots
             compute garbage that is masked and never advances)
    retire:  EOS or the token budget frees the slot; eviction is pure
             metadata (the next insert overwrites), so retiring moves
             zero bytes on device

A wave of requests therefore flows through a FIXED set of compiled
programs — the continuous-batching property: a finished sequence's slot
is refilled on the next loop iteration while the other slots keep
decoding, with no recompile and no cache reallocation anywhere.
"""
from __future__ import annotations

import collections
import dataclasses
from typing import Optional

import numpy as np

__all__ = ["Request", "SlotScheduler", "generate"]


@dataclasses.dataclass
class Request:
    uid: int
    prompt: list
    max_new_tokens: int = 16
    eos_id: Optional[int] = None


@dataclasses.dataclass
class _SlotState:
    """Host bookkeeping for one occupied slot."""
    uid: int
    generated: list
    max_new_tokens: int
    eos_id: Optional[int]
    prompt_len: int = 0

    def done(self) -> bool:
        if self.eos_id is not None and self.generated \
                and self.generated[-1] == self.eos_id:
            return True
        return len(self.generated) >= self.max_new_tokens

    def cache_len(self) -> int:
        """The slot's device cache length, derived host-side: the
        prompt plus one append per decode step taken (the first
        generated token comes from prefill and is written by the NEXT
        decode) — so the capacity guard never reads the device."""
        return self.prompt_len + len(self.generated) - 1


class SlotScheduler:
    """Maps a request queue onto the engine's fixed slots."""

    def __init__(self, engine):
        self.engine = engine
        self.queue: collections.deque = collections.deque()
        self._next_uid = 0

    def submit(self, prompt, max_new_tokens: int = 16,
               eos_id: Optional[int] = None) -> int:
        """Queue one request; returns its uid (results key)."""
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self.engine.max_seq:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds engine max_seq "
                f"{self.engine.max_seq}")
        uid = self._next_uid
        self._next_uid += 1
        self.queue.append(Request(uid, prompt, int(max_new_tokens),
                                  eos_id))
        return uid

    def run(self, cache=None) -> dict:
        """Drain the queue; returns ``{uid: generated token list}``.

        One pass of the loop = admit every free slot it can, then one
        batched decode step.  The device sees only the fixed-shape
        prefill/decode executables; everything else here is host-side
        bookkeeping on ints.
        """
        eng = self.engine
        if cache is None:
            cache = eng.init_cache()
        slots: list = [None] * eng.slots
        free = list(range(eng.slots))
        last = np.zeros((eng.slots,), np.int32)
        results: dict = {}

        def retire(slot):
            st = slots[slot]
            # token budget may have been crossed by an EOS cut
            gen = st.generated[:st.max_new_tokens]
            if st.eos_id is not None and st.eos_id in gen:
                gen = gen[:gen.index(st.eos_id) + 1]
            results[st.uid] = gen
            slots[slot] = None
            free.append(slot)          # eviction = metadata; insert
            # on re-admit overwrites the stale cache rows

        while self.queue or any(s is not None for s in slots):
            # admit: fill every free slot from the queue
            while self.queue and free:
                req = self.queue.popleft()
                slot = free.pop()
                cache, tok, _ = eng.prefill(cache, req.prompt, slot)
                tok = int(np.asarray(tok))
                slots[slot] = _SlotState(req.uid, [tok],
                                         req.max_new_tokens, req.eos_id,
                                         prompt_len=len(req.prompt))
                last[slot] = tok
                if slots[slot].done():
                    retire(slot)
            active = np.array([s is not None for s in slots], bool)
            if not active.any():
                continue
            # guard: a slot at cache capacity cannot take another token.
            # Lengths are derived host-side (_SlotState.cache_len) — no
            # device readback in the control loop beyond the sampled
            # tokens themselves.
            for slot, st in enumerate(slots):
                if st is not None and st.cache_len() >= eng.max_seq:
                    retire(slot)
                    active[slot] = False
            if not active.any():
                continue
            cache, toks, _ = eng.decode(cache, last, active)
            toks = np.asarray(toks)
            for slot, st in enumerate(slots):
                if st is None or not active[slot]:
                    continue
                st.generated.append(int(toks[slot]))
                last[slot] = toks[slot]
                if st.done():
                    retire(slot)
        return results


def generate(engine, prompts, max_new_tokens: int = 16,
             eos_id: Optional[int] = None):
    """One-shot continuous-batching run: list of prompts in, list of
    generated token lists out (submission order)."""
    sched = SlotScheduler(engine)
    uids = [sched.submit(p, max_new_tokens=max_new_tokens, eos_id=eos_id)
            for p in prompts]
    out = sched.run()
    return [out[u] for u in uids]
