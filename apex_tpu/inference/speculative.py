"""Speculative decoding drafters (ISSUE 15): propose ``k`` tokens per
slot for the engine's batched verify step.

The division of labor: a :class:`Drafter` is pure HOST bookkeeping —
it sees each request's confirmed token stream (prompt at ``begin``,
every emitted token at ``observe``) and proposes up to ``k``
continuation tokens per decode round.  The DEVICE side never changes
with the drafter: the target engine scores whatever was proposed in
its one compiled verify executable
(:func:`~apex_tpu.inference.engine.make_verify_fn`), accepts the
longest matching prefix, and emits the bonus token — so a weak draft
can only cost speculation upside, never correctness (the emitted
stream is the target's own greedy stream, always).

Drafters shipped:

* :class:`NGramDrafter` — prompt-lookup ("self-drafting") after
  PAPERS.md's repeated-structure observation: the longest recent
  n-gram is matched against the request's OWN earlier tokens (prompt +
  generated) and the continuation that followed last time is proposed.
  Zero device work, zero extra compiles; acceptance tracks how
  self-similar the stream is (templated/structured output: high).
* :class:`ReplayDrafter` — drafts from a scripted continuation per
  prompt.  The measurement harness: a script recorded from a base
  (non-speculative) run gives acceptance ~1.0 — the machinery ceiling
  any model-based drafter is bounded by — and a poisoned script
  deterministically exercises the reject/rollback path in tests.
* :class:`EngineDrafter` — a SMALL draft model restored beside the
  target: a second (dense-cache) :class:`~apex_tpu.inference.engine.
  InferenceEngine` drafts ``k`` tokens with ``k`` batched greedy
  decode steps, then rolls its own cache back to the pre-draft
  lengths (:func:`~apex_tpu.inference.kv_cache.set_lengths`) so only
  CONFIRMED tokens ever stay resident — the draft-side mirror of the
  target's page-table rollback.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["default_spec_k", "Drafter", "NGramDrafter", "ReplayDrafter",
           "EngineDrafter"]

_SPEC_K_ENV = "APEX_TPU_SPEC_K"


def default_spec_k() -> int:
    """``APEX_TPU_SPEC_K``: drafted tokens per decode round (0 =
    speculation off, the default).  The engine compiles ONE verify
    executable per value (slab width ``k + 1`` is static)."""
    env = os.environ.get(_SPEC_K_ENV)
    if not env:
        return 0
    try:
        val = int(env)
    except ValueError as e:
        raise ValueError(
            f"{_SPEC_K_ENV} must be an int, got {env!r}") from e
    if val < 0:
        raise ValueError(f"{_SPEC_K_ENV} must be >= 0, got {val}")
    return val


class Drafter:
    """Base drafter: the host-side lifecycle the scheduler drives.

    ``begin(slot, prompt, first_token)`` opens a slot's stream (the
    prompt plus the target's prefill-sampled first token);
    ``observe(slot, tokens)`` appends every CONFIRMED emitted token
    (accepted drafts + bonus — the target's stream, never the
    drafts); ``draft(slot, k)`` proposes up to ``k`` continuation
    tokens (fewer or none is fine — the scheduler pads, and padding
    merely rejects); ``retire(slot)`` closes the stream.  The base
    class never drafts (every round emits exactly the bonus token =
    plain decode correctness at verify-step cost)."""

    def begin(self, slot: int, prompt: Sequence[int],
              first_token: int) -> None:
        pass

    def observe(self, slot: int, tokens: Sequence[int]) -> None:
        pass

    def retire(self, slot: int) -> None:
        pass

    def draft(self, slot: int, k: int) -> List[int]:
        return []

    def draft_batch(self, active, k) -> np.ndarray:
        """``[slots, k]`` int32 draft matrix for one verify round:
        per-slot :meth:`draft` results, zero-padded (a padding draft
        just rejects — correctness never depends on the drafter)."""
        active = np.asarray(active, bool)
        out = np.zeros((active.shape[0], k), np.int32)
        for s in range(active.shape[0]):
            if active[s]:
                d = list(self.draft(s, k))[:k]
                out[s, :len(d)] = d
        return out


class NGramDrafter(Drafter):
    """Prompt-lookup drafting: match the stream's recent suffix against
    its own history, propose what followed the last occurrence.

    ``max_ngram`` bounds the match length tried (longest first — a
    longer matched context predicts better); ``min_ngram`` refuses
    single-token coincidences when > 1.  Pure python over per-slot int
    lists: O(history · ngram) per draft, trivial at serving scale next
    to a device step."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"{min_ngram}/{max_ngram}")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        self._hist: Dict[int, List[int]] = {}

    def begin(self, slot, prompt, first_token):
        self._hist[slot] = [int(t) for t in prompt] + [int(first_token)]

    def observe(self, slot, tokens):
        if slot in self._hist:
            self._hist[slot].extend(int(t) for t in tokens)

    def retire(self, slot):
        self._hist.pop(slot, None)

    def draft(self, slot, k):
        hist = self._hist.get(slot)
        if not hist or k < 1:
            return []
        n = len(hist)
        for m in range(min(self.max_ngram, n - 1), self.min_ngram - 1,
                       -1):
            pat = hist[-m:]
            # latest earlier occurrence wins (recency: loops repeat
            # their most recent period)
            for i in range(n - m - 1, -1, -1):
                if hist[i:i + m] == pat:
                    out = hist[i + m:i + m + k]
                    if out:
                        return out
        return []


class ReplayDrafter(Drafter):
    """Drafts from a scripted continuation per prompt: ``script`` maps
    ``tuple(prompt)`` to the expected generated-token list (first
    token included).  A script recorded from a base greedy run yields
    acceptance ~1.0 (the harness ceiling); a deliberately wrong
    script exercises rejection deterministically."""

    def __init__(self, script: Dict[tuple, Sequence[int]]):
        self.script = {tuple(int(t) for t in k): [int(t) for t in v]
                       for k, v in script.items()}
        self._seq: Dict[int, List[int]] = {}
        self._pos: Dict[int, int] = {}

    def begin(self, slot, prompt, first_token):
        self._seq[slot] = self.script.get(
            tuple(int(t) for t in prompt), [])
        self._pos[slot] = 1            # first_token is generated[0]

    def observe(self, slot, tokens):
        if slot in self._pos:
            self._pos[slot] += len(tokens)

    def retire(self, slot):
        self._seq.pop(slot, None)
        self._pos.pop(slot, None)

    def draft(self, slot, k):
        seq = self._seq.get(slot)
        if not seq:
            return []
        pos = self._pos[slot]
        return seq[pos:pos + k]


class EngineDrafter(Drafter):
    """A small draft model beside the target: batched greedy decode
    steps on a second (dense-cache) engine propose ``k`` tokens, then
    the draft cache rolls back to the pre-draft lengths so only
    confirmed tokens stay resident.

    The draft engine must share the target's tokenizer/vocab, run the
    DENSE cache (its rollback is a pure length reset — no page
    bookkeeping to mirror), greedy sampling, and at least the target's
    slot count.  Confirmed tokens the target emits land in a pending
    queue and are fed through catch-up decode steps before the next
    draft round (a reference implementation: it re-decodes accepted
    tokens on the draft side rather than trusting draft-side rows
    that may diverge from the confirmed stream)."""

    def __init__(self, engine):
        import jax

        from apex_tpu.inference import kv_cache
        if engine.kind == "bert":
            raise ValueError("the draft engine must be generative")
        if engine.paged:
            raise ValueError(
                "EngineDrafter drafts on the DENSE slot cache (its "
                "rollback is a pure length reset); build the draft "
                "engine without paged kwargs")
        if not engine.sampling.is_greedy:
            raise ValueError("the draft engine must sample greedily")
        self.engine = engine
        self.cache = engine.init_cache()
        self._rollback = jax.jit(kv_cache.set_lengths,
                                 donate_argnums=(0,))
        self._len = np.zeros((engine.slots,), np.int32)
        self._pending: Dict[int, List[int]] = {}

    def begin(self, slot, prompt, first_token):
        self.cache, _, _ = self.engine.prefill(
            self.cache, list(prompt), slot)
        self._len[slot] = len(prompt)
        self._pending[slot] = [int(first_token)]

    def observe(self, slot, tokens):
        if slot in self._pending:
            self._pending[slot].extend(int(t) for t in tokens)

    def retire(self, slot):
        self._pending.pop(slot, None)
        self._len[slot] = 0

    def _catch_up(self):
        """Feed confirmed-but-unfed tokens (all but each slot's last)
        through batched decode steps; outputs are discarded."""
        slots = self.engine.slots
        while True:
            feed = np.zeros((slots,), np.int32)
            act = np.zeros((slots,), bool)
            for s, pend in self._pending.items():
                if len(pend) > 1:
                    feed[s] = pend.pop(0)
                    act[s] = True
            if not act.any():
                return
            self.cache, _, _, _ = self.engine.decode(self.cache, feed,
                                                     act)
            self._len[act] += 1

    def draft(self, slot, k):           # pragma: no cover - use batch
        out = self.draft_batch(
            np.eye(self.engine.slots, dtype=bool)[slot], k)
        return [int(t) for t in out[slot]]

    def draft_batch(self, active, k) -> np.ndarray:
        """``k`` greedy draft tokens for every active slot in ``k``
        batched decode steps, cache rolled back afterwards."""
        slots = self.engine.slots
        act = np.zeros((slots,), bool)
        feed = np.zeros((slots,), np.int32)
        for s, pend in self._pending.items():
            if active[s] and pend:
                act[s] = True
                feed[s] = pend[-1]
        drafts = np.zeros((slots, k), np.int32)
        if not act.any() or k < 1:
            return drafts
        self._catch_up()
        for s, pend in self._pending.items():   # refresh post-catch-up
            if act[s]:
                feed[s] = pend[-1]
        for j in range(k):
            self.cache, toks, _, _ = self.engine.decode(self.cache,
                                                        feed, act)
            toks = np.asarray(toks)
            drafts[:, j] = np.where(act, toks, 0)
            feed = np.where(act, toks, feed).astype(np.int32)
        # the rollback: drafted rows go dead-by-mask, pending stays
        # intact (its last token is still the next confirmed input)
        self.cache = self._rollback(self.cache, self._len.copy())
        return drafts
