"""Pure prefill/decode forwards over the standalone model param trees.

The training models (``transformer/testing/standalone_{gpt,llama}``) are
flax modules built for the training shapes; inference needs the same
math split into a *prefill* (full prompt, causal flash attention,
emitting every layer's k/v for the cache) and a *decode* (one token per
slot against the cache).  These functions consume the EXACT param pytree
``model.init`` produces — no re-keying, no conversion step — and mirror
the modules' op sequence call for call (same fused LayerNorm/RMSNorm
kernels, same flash attention, same RoPE convention, same qkv
reshape/split layout), so prefill logits reproduce ``model.apply``
bit-for-bit on the same weights and the parity tests in
``tests/L0/run_inference`` can pin decode against the full forward.

Single-chip serving (tp = 1): the TP layers all collapse to plain
matmuls at world size 1, which is what these forwards implement.
Unsupported training-only configs (scan_layers, MoE FFN, sequence/
context parallelism) fail loudly at engine construction.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.inference import kv_cache
from apex_tpu.ops import layer_norm, rms_norm
from apex_tpu.ops.attention import (
    decode_attention,
    flash_attention,
    prefix_window_attention,
    slab_decode_attention,
)
from apex_tpu.ops.paged_attention import (
    fused_block_decode,
    paged_decode_attention,
    paged_slab_attention,
)
from apex_tpu.transformer.functional.fused_rope import (
    fused_apply_rotary_pos_emb_cached,
)
from apex_tpu.transformer.testing.standalone_llama import _rope_cos_sin

__all__ = ["model_dims", "check_supported", "prefill_forward",
           "decode_forward", "verify_forward", "fused_layer_params"]


def model_dims(kind: str, cfg) -> dict:
    """Static cache geometry for a model config: layers / kv_heads /
    head_dim (+ query heads)."""
    head_dim = cfg.hidden_size // cfg.num_attention_heads
    kv_heads = (cfg.kv_heads if kind == "llama"
                else cfg.num_attention_heads)
    return {"layers": cfg.num_layers, "heads": cfg.num_attention_heads,
            "kv_heads": kv_heads, "head_dim": head_dim}


def check_supported(kind: str, cfg) -> None:
    if kind not in ("gpt", "llama"):
        raise ValueError(f"unknown generative model kind {kind!r} "
                         "(expected 'gpt' or 'llama')")
    for flag in ("sequence_parallel", "context_parallel", "scan_layers"):
        if getattr(cfg, flag, False):
            raise ValueError(
                f"inference forwards run tp=1 unrolled; cfg.{flag} is a "
                "training-topology knob — export the weights into a "
                "plain config instead")
    if getattr(cfg, "num_moe_experts", None):
        raise ValueError("MoE FFN decode is not implemented yet")


def _params_subtree(params):
    """Accept ``model.init``'s ``{"params": ...}`` or the bare tree."""
    return params["params"] if "params" in params and isinstance(
        params["params"], dict) else params


def _linear(p, x):
    """Column/RowParallelLinear at tp=1: ``x @ W.T (+ b)`` with the
    layers' ``[out, in]`` weight layout."""
    y = jnp.matmul(x, p["weight"].T)
    if "bias" in p:
        y = y + p["bias"]
    return y


def _suffix_attend(cache, layer: int, row, q, k, v, start):
    """Prefill attention for a (possibly mid-prompt) token slab: cold
    (``start == 0``) it is EXACTLY the causal flash path the original
    prefill ran — bitwise, so cold prefills and the dense-parity tests
    are untouched; warm (``start > 0``, a prefix-cache hit or a later
    chunk of a chunked prefill) each row additionally attends to the
    already-cached prefix, gathered from the slot's KV pages through
    ``row`` (:func:`~apex_tpu.ops.attention.prefix_window_attention`).

    ``q``: ``[b, h, s, d]``; ``k``/``v``: pre-broadcast
    ``[b, kv_heads, s, d]``.  One ``lax.cond`` keeps both paths inside
    the ONE compiled prefill executable per bucket — the runtime
    executes only the taken branch, so cold prefills never pay the
    window gather."""
    b, h, s, d = q.shape
    kvh = k.shape[1]
    group = h // kvh

    def cold(q, k, v, pk, pv):
        if group > 1:                   # GQA: share kv across the group
            k, v = (jnp.broadcast_to(
                t[:, :, None], (b, kvh, group, s, d)
            ).reshape(b, h, s, d) for t in (k, v))
        return flash_attention(q, k, v, causal=True)

    def warm(q, k, v, pk, pv):
        # pk/pv [pages, kvh, ps, d] -> the slot's virtual window
        # [b, kvh, max_seq, d] in row order; unowned ordinals gather the
        # trash page — finite garbage masked by start
        def window(p):
            w = jnp.take(p, row, axis=0)          # [mpps, kvh, ps, d]
            return w.transpose(1, 0, 2, 3).reshape(
                1, kvh, -1, d).astype(q.dtype)
        return prefix_window_attention(q, k, v, window(pk), window(pv),
                                       start)

    return jax.lax.cond(start > 0, warm, cold, q, k, v,
                        cache.k[:, layer], cache.v[:, layer])


def _slab_attend(cache, layer: int, q, lengths):
    """Verify-slab attention against ONE layer of whichever cache
    layout the engine runs: the dense slot window scored directly
    (:func:`~apex_tpu.ops.attention.slab_decode_attention`) or the
    paged pool gathered through the slot page table
    (:func:`~apex_tpu.ops.paged_attention.paged_slab_attention`).
    ``lengths`` is the live count BEFORE the slab was appended (the
    causal offset)."""
    if isinstance(cache, kv_cache.PagedKVCache):
        return paged_slab_attention(q, cache.k[:, layer],
                                    cache.v[:, layer], cache.page_table,
                                    lengths)
    return slab_decode_attention(q, cache.k[:, layer], cache.v[:, layer],
                                 lengths)


def _cache_attend(cache, layer: int, q, live):
    """Single-token attention against ONE layer of whichever cache
    layout the engine runs: the dense slot window
    (:func:`~apex_tpu.ops.attention.decode_attention`) or the paged
    pool threaded through the slot page table
    (:func:`~apex_tpu.ops.paged_attention.paged_decode_attention`).
    Both score the pre-broadcast per-kv-head cache (GQA/MQA grouped)."""
    if isinstance(cache, kv_cache.PagedKVCache):
        return paged_decode_attention(
            q, cache.k[:, layer], cache.v[:, layer], cache.page_table,
            live, xla_max_pages=cache.attn_max_pages)
    return decode_attention(q, cache.k[:, layer], cache.v[:, layer], live)


def _fused_bias(p, width):
    """A linear's bias as the fused layout's ``[1, width]`` row (zeros
    when the layer was built bias-free)."""
    if "bias" in p:
        return p["bias"].reshape(1, width)
    return jnp.zeros((1, width), p["weight"].dtype)


def fused_layer_params(kind: str, cfg, params):
    """The per-layer weights re-laid-out for the fused-block decode
    kernel (ISSUE 15): matmul-ready ``[in, out]`` arrays with q/k/v
    split into head-major planes, built ONCE at engine construction so
    no transpose/gather ever runs inside the decode step.

    GPT's interleaved ``query_key_value`` columns (per head:
    ``[q(d), k(d), v(d)]``) deinterleave into ``wq``/``wk``/``wv``;
    LLaMA's packed ``kv_proj`` splits the same way.  The layout is a
    one-time device-side copy of the layer weights — the engine then
    holds BOTH layouts (prefill keeps the original tree), a deliberate
    HBM-for-latency trade the README documents next to the knob.
    """
    p = _params_subtree(params)
    dims = model_dims(kind, cfg)
    heads, kvh, d = dims["heads"], dims["kv_heads"], dims["head_dim"]
    hidden = cfg.hidden_size
    out = []
    for i in range(cfg.num_layers):
        lp = p[f"layer_{i}"]
        if kind == "gpt":
            att = lp["self_attention"]
            w = jnp.transpose(att["query_key_value"]["weight"])
            w = w.reshape(hidden, heads, 3, d)
            b = _fused_bias(att["query_key_value"],
                            3 * heads * d).reshape(heads, 3, d)
            blk = {
                "ln1_w": lp["input_layernorm"]["weight"].reshape(
                    1, hidden),
                "ln1_b": lp["input_layernorm"]["bias"].reshape(1, hidden),
                "wq": w[:, :, 0, :].reshape(hidden, heads * d),
                "bq": b[:, 0, :].reshape(1, heads * d),
                "wk": w[:, :, 1, :].reshape(hidden, heads * d),
                "bk": b[:, 1, :].reshape(1, heads * d),
                "wv": w[:, :, 2, :].reshape(hidden, heads * d),
                "bv": b[:, 2, :].reshape(1, heads * d),
                "wo": jnp.transpose(att["dense"]["weight"]),
                "bo": _fused_bias(att["dense"], hidden),
                "ln2_w": lp["post_attention_layernorm"][
                    "weight"].reshape(1, hidden),
                "ln2_b": lp["post_attention_layernorm"][
                    "bias"].reshape(1, hidden),
                "wu": jnp.transpose(lp["mlp"]["dense_h_to_4h"]["weight"]),
                "bu": _fused_bias(lp["mlp"]["dense_h_to_4h"], cfg.ffn),
                "wd": jnp.transpose(lp["mlp"]["dense_4h_to_h"]["weight"]),
                "bd": _fused_bias(lp["mlp"]["dense_4h_to_h"], hidden),
            }
        else:
            att = lp["attention"]
            kvw = jnp.transpose(att["kv_proj"]["weight"]).reshape(
                hidden, kvh, 2, d)
            blk = {
                "ln1_w": lp["input_norm"]["weight"].reshape(1, hidden),
                "wq": jnp.transpose(att["q_proj"]["weight"]),
                "wk": kvw[:, :, 0, :].reshape(hidden, kvh * d),
                "wv": kvw[:, :, 1, :].reshape(hidden, kvh * d),
                "wo": jnp.transpose(att["o_proj"]["weight"]),
                "ln2_w": lp["post_attention_norm"]["weight"].reshape(
                    1, hidden),
                "wg": jnp.transpose(lp["mlp"]["gate_proj"]["weight"]),
                "wu": jnp.transpose(lp["mlp"]["up_proj"]["weight"]),
                "wd": jnp.transpose(lp["mlp"]["down_proj"]["weight"]),
            }
        out.append(blk)
    return out


# --------------------------------------------------------------------------
# GPT (standalone_gpt mirror)
# --------------------------------------------------------------------------

def _gpt_attn_proj(lp, h, heads, head_dim):
    """qkv projection + the model's reshape/split layout: returns
    q/k/v with a trailing ``[..., heads, head_dim]``."""
    qkv = _linear(lp["self_attention"]["query_key_value"], h)
    qkv = qkv.reshape(*h.shape[:-1], heads, 3 * head_dim)
    return jnp.split(qkv, 3, axis=-1)


def _gpt_mlp(lp, h):
    return _linear(lp["mlp"]["dense_4h_to_h"],
                   jax.nn.gelu(_linear(lp["mlp"]["dense_h_to_4h"], h)))


def _last_row(h, length):
    """Hidden state at the last REAL position (``length - 1``) of a
    bucket-padded ``[s, b, hid]`` activation — sliced BEFORE the lm
    head, so the O(s·vocab·hidden) projection runs on one row instead
    of every dead padding position (~1/3 of prefill FLOPs at the
    flagship shape)."""
    return jax.lax.dynamic_index_in_dim(h, length - 1, axis=0,
                                        keepdims=False)       # [b, hid]


def _gpt_prefill(cfg, params, tokens, length=None, cache=None, row=None,
                 start=None):
    p = _params_subtree(params)
    b, s = tokens.shape
    dims = model_dims("gpt", cfg)
    heads, head_dim = dims["heads"], dims["head_dim"]
    suffix = cache is not None          # static: suffix-prefill variant

    emb_w = p["embedding"]["word_embeddings"]["weight"]
    h = jnp.take(emb_w, tokens, axis=0)                     # [b, s, h]
    pos_tab = p["embedding"]["position_embeddings"]
    if suffix:
        # rows sit at absolute positions start + i (clamped: dead
        # bucket-padding rows past the table stay in range)
        positions = jnp.minimum(
            jnp.asarray(start, jnp.int32)
            + jnp.arange(s, dtype=jnp.int32),
            jnp.int32(pos_tab.shape[0] - 1))
        h = h + jnp.take(pos_tab, positions, axis=0)[None]
    else:
        h = h + pos_tab[None, :s, :]
    h = h.transpose(1, 0, 2)                                # [s, b, h]

    ks, vs = [], []
    for i in range(cfg.num_layers):
        lp = p[f"layer_{i}"]
        x = h
        h1 = layer_norm(x, lp["input_layernorm"]["weight"],
                        lp["input_layernorm"]["bias"])
        q, k, v = _gpt_attn_proj(lp, h1, heads, head_dim)   # [s, b, n, d]
        q, k, v = (t.transpose(1, 2, 0, 3) for t in (q, k, v))
        ks.append(k[0])                                     # [n, s, d]
        vs.append(v[0])
        if suffix:
            ctx = _suffix_attend(cache, i, row, q, k, v, start)
        else:
            ctx = flash_attention(q, k, v, causal=True)
        ctx = ctx.transpose(2, 0, 1, 3).reshape(s, b, -1)
        x = x + _linear(lp["self_attention"]["dense"], ctx)
        h2 = layer_norm(x, lp["post_attention_layernorm"]["weight"],
                        lp["post_attention_layernorm"]["bias"])
        h = x + _gpt_mlp(lp, h2)

    h = layer_norm(h, p["final_layernorm"]["weight"],
                   p["final_layernorm"]["bias"])
    if length is not None:
        last = length - start if suffix else length   # local slab index
        logits = jnp.einsum("bh,vh->bv", _last_row(h, last), emb_w)
    else:
        logits = jnp.einsum("sbh,vh->sbv", h, emb_w)        # tied head
    return logits, jnp.stack(ks), jnp.stack(vs)


def _gpt_decode(cfg, params, cache, tokens, fused=None):
    p = _params_subtree(params)
    dims = model_dims("gpt", cfg)
    heads, head_dim = dims["heads"], dims["head_dim"]
    positions = cache.lengths                               # [slots]

    emb_w = p["embedding"]["word_embeddings"]["weight"]
    h = jnp.take(emb_w, tokens, axis=0)                     # [slots, h]
    h = h + jnp.take(p["embedding"]["position_embeddings"],
                     positions, axis=0)

    live = positions + 1                    # incl. the token written now
    for i in range(cfg.num_layers):
        if fused is not None:
            # ISSUE 15: the whole block in ONE kernel (norm1 -> qkv ->
            # paged attention incl. this token -> out proj -> norm2 ->
            # MLP); only the pool append leaves the per-op path
            h, k_tok, v_tok = fused_block_decode(
                h, fused[i], cache.k[:, i], cache.v[:, i],
                cache.page_table, positions, kind="gpt", eps=1e-5)
            cache = kv_cache.append_layer(cache, i, k_tok, v_tok)
            continue
        lp = p[f"layer_{i}"]
        x = h
        h1 = layer_norm(x, lp["input_layernorm"]["weight"],
                        lp["input_layernorm"]["bias"])
        q, k_tok, v_tok = _gpt_attn_proj(lp, h1, heads, head_dim)
        cache = kv_cache.append_layer(cache, i, k_tok, v_tok)
        ctx = _cache_attend(cache, i, q, live)
        x = x + _linear(lp["self_attention"]["dense"],
                        ctx.reshape(ctx.shape[0], -1))
        h2 = layer_norm(x, lp["post_attention_layernorm"]["weight"],
                        lp["post_attention_layernorm"]["bias"])
        h = x + _gpt_mlp(lp, h2)

    h = layer_norm(h, p["final_layernorm"]["weight"],
                   p["final_layernorm"]["bias"])
    logits = jnp.einsum("bh,vh->bv", h, emb_w)
    return logits, cache


def _gpt_verify(cfg, params, cache, tokens):
    """Speculative verify (ISSUE 15): score an ``S``-token drafted slab
    per slot in ONE batched step — logits at EVERY slab position, the
    slab's k/v appended at ``[lengths, lengths + S)``.  Lengths do not
    advance here; the verify step advances by the accepted count
    (:func:`kv_cache.advance_by`) so rejection is a pure length
    rollback."""
    p = _params_subtree(params)
    dims = model_dims("gpt", cfg)
    heads, head_dim = dims["heads"], dims["head_dim"]
    slots, s = tokens.shape
    base = cache.lengths                                    # [slots]
    pos = base[:, None] + jnp.arange(s, dtype=jnp.int32)[None]

    emb_w = p["embedding"]["word_embeddings"]["weight"]
    pos_tab = p["embedding"]["position_embeddings"]
    h = jnp.take(emb_w, tokens, axis=0)                     # [b, S, hid]
    h = h + jnp.take(pos_tab,
                     jnp.minimum(pos, jnp.int32(pos_tab.shape[0] - 1)),
                     axis=0)

    for i in range(cfg.num_layers):
        lp = p[f"layer_{i}"]
        x = h
        h1 = layer_norm(x, lp["input_layernorm"]["weight"],
                        lp["input_layernorm"]["bias"])
        q, k, v = _gpt_attn_proj(lp, h1, heads, head_dim)   # [b,S,n,d]
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        cache = kv_cache.append_slab(cache, i, k, v)
        ctx = _slab_attend(cache, i, q, base)               # [b,h,S,d]
        ctx = ctx.transpose(0, 2, 1, 3).reshape(slots, s, -1)
        x = x + _linear(lp["self_attention"]["dense"], ctx)
        h2 = layer_norm(x, lp["post_attention_layernorm"]["weight"],
                        lp["post_attention_layernorm"]["bias"])
        h = x + _gpt_mlp(lp, h2)

    h = layer_norm(h, p["final_layernorm"]["weight"],
                   p["final_layernorm"]["bias"])
    logits = jnp.einsum("bsh,vh->bsv", h, emb_w)
    return logits, cache


# --------------------------------------------------------------------------
# LLaMA (standalone_llama mirror; GQA/MQA cached once per kv head)
# --------------------------------------------------------------------------

def _llama_rope_table(cfg, head_dim, max_seq):
    """Flat ``[max_seq, head_dim]`` cos/sin tables (the model's
    ``_rope_cos_sin`` values, position-indexable for decode)."""
    cos, sin = _rope_cos_sin(max_seq, head_dim, cfg.rope_theta)
    return cos.reshape(max_seq, head_dim), sin.reshape(max_seq, head_dim)


def _llama_proj(lp, h, cfg, heads, kv_heads, head_dim):
    q = _linear(lp["attention"]["q_proj"], h)
    kv = _linear(lp["attention"]["kv_proj"], h)
    q = q.reshape(*h.shape[:-1], heads, head_dim)
    k, v = jnp.split(kv.reshape(*h.shape[:-1], kv_heads, 2 * head_dim),
                     2, axis=-1)
    return q, k, v


def _llama_mlp(lp, h):
    gate = _linear(lp["mlp"]["gate_proj"], h)
    up = _linear(lp["mlp"]["up_proj"], h)
    return _linear(lp["mlp"]["down_proj"], jax.nn.silu(gate) * up)


def _llama_prefill(cfg, params, tokens, length=None, cache=None,
                   row=None, start=None):
    p = _params_subtree(params)
    b, s = tokens.shape
    dims = model_dims("llama", cfg)
    heads, kv_heads = dims["heads"], dims["kv_heads"]
    head_dim, group = dims["head_dim"], heads // kv_heads
    suffix = cache is not None          # static: suffix-prefill variant

    h = jnp.take(p["embed_tokens"]["weight"], tokens, axis=0)
    h = h.transpose(1, 0, 2)                                # [s, b, h]
    if suffix:
        # RoPE at the slab's absolute positions start + i (clamped for
        # dead bucket-padding rows), indexed from the full-window table
        cos_t, sin_t = _rope_cos_sin(cache.max_seq, head_dim,
                                     cfg.rope_theta)  # [max_seq, 1, 1, d]
        positions = jnp.minimum(
            jnp.asarray(start, jnp.int32)
            + jnp.arange(s, dtype=jnp.int32),
            jnp.int32(cache.max_seq - 1))
        cos = jnp.take(cos_t, positions, axis=0)            # [s, 1, 1, d]
        sin = jnp.take(sin_t, positions, axis=0)
    else:
        cos, sin = _rope_cos_sin(s, head_dim, cfg.rope_theta)

    ks, vs = [], []
    for i in range(cfg.num_layers):
        lp = p[f"layer_{i}"]
        x = h
        h1 = rms_norm(x, lp["input_norm"]["weight"], eps=cfg.rms_eps)
        q, k, v = _llama_proj(lp, h1, cfg, heads, kv_heads, head_dim)
        q = fused_apply_rotary_pos_emb_cached(q, cos, sin)
        k = fused_apply_rotary_pos_emb_cached(k, cos, sin)
        # cache the PRE-broadcast kv (once per kv head)
        ks.append(k.transpose(1, 2, 0, 3)[0])               # [kv, s, d]
        vs.append(v.transpose(1, 2, 0, 3)[0])
        if suffix:
            qb, kb, vb = (t.transpose(1, 2, 0, 3) for t in (q, k, v))
            ctx = _suffix_attend(cache, i, row, qb, kb, vb, start)
        else:
            if group > 1:               # GQA: share kv across the group
                k, v = (jnp.broadcast_to(
                    t[:, :, :, None, :],
                    (s, b, kv_heads, group, head_dim)
                ).reshape(s, b, heads, head_dim) for t in (k, v))
            q, k, v = (t.transpose(1, 2, 0, 3) for t in (q, k, v))
            ctx = flash_attention(q, k, v, causal=True)
        ctx = ctx.transpose(2, 0, 1, 3).reshape(s, b, -1)
        x = x + _linear(lp["attention"]["o_proj"], ctx)
        h1 = rms_norm(x, lp["post_attention_norm"]["weight"],
                      eps=cfg.rms_eps)
        h = x + _llama_mlp(lp, h1)

    h = rms_norm(h, p["final_norm"]["weight"], eps=cfg.rms_eps)
    if length is not None:
        last = length - start if suffix else length   # local slab index
        logits = _linear(p["lm_head"], _last_row(h, last))    # [b, v]
    else:
        logits = _linear(p["lm_head"], h)                     # [s, b, v]
    return logits, jnp.stack(ks), jnp.stack(vs)


def _llama_decode(cfg, params, cache, tokens, fused=None):
    p = _params_subtree(params)
    dims = model_dims("llama", cfg)
    heads, kv_heads = dims["heads"], dims["kv_heads"]
    head_dim = dims["head_dim"]
    positions = cache.lengths

    h = jnp.take(p["embed_tokens"]["weight"], tokens, axis=0)
    cos_t, sin_t = _llama_rope_table(cfg, head_dim, cache.max_seq)
    cos2 = jnp.take(cos_t, positions, axis=0)               # [slots, d]
    sin2 = jnp.take(sin_t, positions, axis=0)
    cos, sin = cos2[:, None, :], sin2[:, None, :]           # [slots, 1, d]

    live = positions + 1
    for i in range(cfg.num_layers):
        if fused is not None:
            h, k_tok, v_tok = fused_block_decode(
                h, fused[i], cache.k[:, i], cache.v[:, i],
                cache.page_table, positions, kind="llama",
                eps=cfg.rms_eps, cos=cos2, sin=sin2)
            cache = kv_cache.append_layer(cache, i, k_tok, v_tok)
            continue
        lp = p[f"layer_{i}"]
        x = h
        h1 = rms_norm(x, lp["input_norm"]["weight"], eps=cfg.rms_eps)
        q, k_tok, v_tok = _llama_proj(lp, h1, cfg, heads, kv_heads,
                                      head_dim)
        q = fused_apply_rotary_pos_emb_cached(q, cos, sin)
        k_tok = fused_apply_rotary_pos_emb_cached(k_tok, cos, sin)
        cache = kv_cache.append_layer(cache, i, k_tok, v_tok)
        # grouped-query scoring straight off the per-kv-head cache/pool
        ctx = _cache_attend(cache, i, q, live)
        x = x + _linear(lp["attention"]["o_proj"],
                        ctx.reshape(ctx.shape[0], -1))
        h1 = rms_norm(x, lp["post_attention_norm"]["weight"],
                      eps=cfg.rms_eps)
        h = x + _llama_mlp(lp, h1)

    h = rms_norm(h, p["final_norm"]["weight"], eps=cfg.rms_eps)
    logits = _linear(p["lm_head"], h)                       # [slots, v]
    return logits, cache


def _llama_verify(cfg, params, cache, tokens):
    """LLaMA twin of :func:`_gpt_verify`: RoPE at each slab row's
    absolute position, GQA/MQA slab scoring straight off the
    per-kv-head cache/pool."""
    p = _params_subtree(params)
    dims = model_dims("llama", cfg)
    heads, kv_heads = dims["heads"], dims["kv_heads"]
    head_dim = dims["head_dim"]
    slots, s = tokens.shape
    base = cache.lengths
    pos = base[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
    pos = jnp.minimum(pos, jnp.int32(cache.max_seq - 1))

    h = jnp.take(p["embed_tokens"]["weight"], tokens, axis=0)
    cos_t, sin_t = _llama_rope_table(cfg, head_dim, cache.max_seq)
    cos = jnp.take(cos_t, pos, axis=0)[:, :, None, :]     # [b, S, 1, d]
    sin = jnp.take(sin_t, pos, axis=0)[:, :, None, :]

    for i in range(cfg.num_layers):
        lp = p[f"layer_{i}"]
        x = h
        h1 = rms_norm(x, lp["input_norm"]["weight"], eps=cfg.rms_eps)
        q, k, v = _llama_proj(lp, h1, cfg, heads, kv_heads, head_dim)
        q = fused_apply_rotary_pos_emb_cached(q, cos, sin)
        k = fused_apply_rotary_pos_emb_cached(k, cos, sin)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        cache = kv_cache.append_slab(cache, i, k, v)
        ctx = _slab_attend(cache, i, q, base)               # [b,h,S,d]
        ctx = ctx.transpose(0, 2, 1, 3).reshape(slots, s, -1)
        x = x + _linear(lp["attention"]["o_proj"], ctx)
        h1 = rms_norm(x, lp["post_attention_norm"]["weight"],
                      eps=cfg.rms_eps)
        h = x + _llama_mlp(lp, h1)

    h = rms_norm(h, p["final_norm"]["weight"], eps=cfg.rms_eps)
    logits = _linear(p["lm_head"], h)                     # [b, S, v]
    return logits, cache


# --------------------------------------------------------------------------
# dispatch
# --------------------------------------------------------------------------

def prefill_forward(kind: str, cfg, params, tokens, length=None, *,
                    cache=None, row=None, prefill_from=None):
    """Full-prompt forward: ``tokens [1, s]`` -> ``(logits, k_stack,
    v_stack)`` with k/v ``[layers, kv_heads, s, head_dim]`` ready for
    :func:`kv_cache.insert`.

    With ``length`` (the real prompt length inside a bucket-padded
    ``s``, traced OK) the lm head runs on ONLY the last real position —
    ``logits [1, v]``; without it every position is projected
    (``logits [s, 1, v]``, the full-forward shape parity tests pin).

    Suffix mode (ISSUE 12 — paged engines only): with ``cache`` (the
    :class:`~apex_tpu.inference.kv_cache.PagedKVCache`), ``row`` (the
    slot's full page-table row) and ``prefill_from`` (how many prompt
    tokens are already cached, traced OK), ``tokens`` is the
    bucket-padded UNCACHED TAIL: rows sit at absolute positions
    ``prefill_from + i``, attend to the cached prefix through the page
    window (:func:`_suffix_attend`) and causally to the slab itself,
    and ``length`` is the TOTAL live length (prefix + real suffix).
    ``prefill_from == 0`` reproduces the cold path bitwise — one
    compiled executable per bucket serves cold prefills, prefix-cache
    hits, and chunked-prefill continuation chunks alike."""
    if tokens.ndim != 2 or tokens.shape[0] != 1:
        raise ValueError(
            f"prefill takes one prompt [1, s], got {tuple(tokens.shape)}")
    fn = _gpt_prefill if kind == "gpt" else _llama_prefill
    if cache is None:
        return fn(cfg, params, tokens, length)
    if row is None or prefill_from is None or length is None:
        raise ValueError(
            "suffix prefill needs cache, row, prefill_from AND length")
    return fn(cfg, params, tokens, length, cache=cache, row=row,
              start=prefill_from)


def decode_forward(kind: str, cfg, params, cache, tokens, fused=None):
    """One-token step for every slot: ``tokens [slots]`` ->
    ``(logits [slots, v], cache)`` with the new k/v appended at each
    slot's position.  Lengths do not advance here (the engine advances
    active slots once per step).

    ``fused`` (ISSUE 15) is the per-layer fused weight layout from
    :func:`fused_layer_params`: when present (paged engines under
    ``APEX_TPU_DECODE_FUSION``), every transformer block runs as ONE
    Pallas kernel (:func:`~apex_tpu.ops.paged_attention.
    fused_block_decode`) instead of the per-op XLA sequence — same
    embed/head, same pool append, same signature, tolerance-level
    numerics (the in-kernel residual chain stays fp32 where the
    unfused path rounds to bf16 at each sublayer)."""
    fn = _gpt_decode if kind == "gpt" else _llama_decode
    return fn(cfg, params, cache, tokens, fused=fused)


def verify_forward(kind: str, cfg, params, cache, tokens):
    """Speculative-verify step (ISSUE 15): ``tokens [slots, S]`` (the
    last confirmed token followed by ``S - 1`` drafts, per slot) ->
    ``(logits [slots, S, v], cache)`` with the slab's k/v appended at
    positions ``[lengths, lengths + S)``.  Lengths do NOT advance —
    the verify fn advances by the accepted count, which IS the
    page-table/length rollback (rejected rows go dead-by-mask; pages
    were already reserved, so rejection releases nothing)."""
    if tokens.ndim != 2:
        raise ValueError(
            f"verify takes a [slots, S] slab, got {tuple(tokens.shape)}")
    fn = _gpt_verify if kind == "gpt" else _llama_verify
    return fn(cfg, params, cache, tokens)
