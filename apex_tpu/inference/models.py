"""Pure prefill/decode forwards over the standalone model param trees.

The training models (``transformer/testing/standalone_{gpt,llama}``) are
flax modules built for the training shapes; inference needs the same
math split into a *prefill* (full prompt, causal flash attention,
emitting every layer's k/v for the cache) and a *decode* (one token per
slot against the cache).  These functions consume the EXACT param pytree
``model.init`` produces — no re-keying, no conversion step — and mirror
the modules' op sequence call for call (same fused LayerNorm/RMSNorm
kernels, same flash attention, same RoPE convention, same qkv
reshape/split layout), so prefill logits reproduce ``model.apply``
bit-for-bit on the same weights and the parity tests in
``tests/L0/run_inference`` can pin decode against the full forward.

Single-chip serving (tp = 1): the TP layers all collapse to plain
matmuls at world size 1, which is what these forwards implement.
Unsupported training-only configs (scan_layers, MoE FFN, sequence/
context parallelism) fail loudly at engine construction.

Multi-chip serving (ISSUE 17): every forward takes a static ``tp`` and,
at ``tp > 1``, runs as the per-rank body of a ``shard_map`` over the
``parallel_state`` tensor axis — the same column/row partitioning the
training ``transformer/tensor_parallel`` layers implement.  qkv / gate /
up projections are column-sharded over heads/ffn (no comm), out-proj and
down-proj are row-sharded with ONE psum each at the row boundary
(:func:`_row_linear` — the ``RowParallelLinear`` reduce, bias added
once AFTER the reduction), and the embedding / LM head are
vocab-sharded: the lookup is the ``VocabParallelEmbedding``
mask-clip-take-zero-psum (the PR 9 vocab-parallel xent target-pick
algebra), the head a local vocab-shard matmul whose tiled ``all_gather``
reassembles the full logits rank-major — original vocab order — so
sampling stays replica-uniform off one folded key.  GQA/MQA kv heads
replicate below tp (:func:`expand_kv_for_tp`): each kv head's packed
columns repeat ``tp/kvh`` times head-major, so the plain column shard
hands every rank exactly the kv head its query group reads.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.inference import kv_cache
from apex_tpu.ops import layer_norm, rms_norm
from apex_tpu.ops.attention import (
    decode_attention,
    flash_attention,
    prefix_window_attention,
    slab_decode_attention,
)
from apex_tpu.ops.paged_attention import (
    fused_block_decode,
    paged_decode_attention,
    paged_slab_attention,
)
from apex_tpu.transformer.functional.fused_rope import (
    fused_apply_rotary_pos_emb_cached,
)
from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.transformer.testing.standalone_llama import _rope_cos_sin

__all__ = ["model_dims", "tp_dims", "check_supported", "prefill_forward",
           "decode_forward", "verify_forward", "fused_layer_params",
           "expand_kv_for_tp", "param_partition_specs",
           "fused_partition_specs"]


def model_dims(kind: str, cfg) -> dict:
    """Static cache geometry for a model config: layers / kv_heads /
    head_dim (+ query heads)."""
    head_dim = cfg.hidden_size // cfg.num_attention_heads
    kv_heads = (cfg.kv_heads if kind == "llama"
                else cfg.num_attention_heads)
    return {"layers": cfg.num_layers, "heads": cfg.num_attention_heads,
            "kv_heads": kv_heads, "head_dim": head_dim}


def tp_dims(kind: str, cfg, tp: int) -> dict:
    """Per-rank geometry under tensor-parallel serving, validated.

    ``heads_local`` / ``kv_heads_local`` are what each rank's forwards
    compute with; ``kv_heads_pool`` is the GLOBAL kv-head count of the
    sharded paged pool (``kvh * rep`` — GQA/MQA heads replicate below
    tp, each kv head repeated ``rep = tp/kvh`` times head-major so the
    plain shard over the pool's kv-head dim hands every rank the kv
    head its query group reads)."""
    d = model_dims(kind, cfg)
    heads, kvh = d["heads"], d["kv_heads"]
    if tp <= 1:
        return dict(d, heads_local=heads, kv_heads_local=kvh,
                    kv_heads_pool=kvh, rep=1)
    if heads % tp:
        raise ValueError(
            f"tp={tp} does not divide num_attention_heads={heads}")
    if kvh % tp == 0:
        rep = 1
    elif tp % kvh == 0:
        rep = tp // kvh
    else:
        raise ValueError(
            f"tp={tp} vs kv_heads={kvh}: need tp | kv_heads (shard) or "
            f"kv_heads | tp (replicate below tp)")
    return dict(d, heads_local=heads // tp,
                kv_heads_local=max(kvh // tp, 1),
                kv_heads_pool=kvh * rep, rep=rep)


def check_supported(kind: str, cfg) -> None:
    if kind not in ("gpt", "llama"):
        raise ValueError(f"unknown generative model kind {kind!r} "
                         "(expected 'gpt' or 'llama')")
    for flag in ("sequence_parallel", "context_parallel", "scan_layers"):
        if getattr(cfg, flag, False):
            raise ValueError(
                f"inference forwards run tp=1 unrolled; cfg.{flag} is a "
                "training-topology knob — export the weights into a "
                "plain config instead")
    if getattr(cfg, "num_moe_experts", None):
        raise ValueError("MoE FFN decode is not implemented yet")


def _params_subtree(params):
    """Accept ``model.init``'s ``{"params": ...}`` or the bare tree."""
    return params["params"] if "params" in params and isinstance(
        params["params"], dict) else params


def _linear(p, x):
    """Column/RowParallelLinear at tp=1: ``x @ W.T (+ b)`` with the
    layers' ``[out, in]`` weight layout."""
    y = jnp.matmul(x, p["weight"].T)
    if "bias" in p:
        y = y + p["bias"]
    return y


def _row_linear(p, x, tp):
    """RowParallelLinear forward: the local in-shard matmul, ONE psum
    at the row boundary, bias added once AFTER the reduction (the
    training layers' ``reduce_from_tensor_model_parallel_region``
    discipline — a per-rank bias would add ``tp`` copies).  At tp=1
    this is :func:`_linear` op for op."""
    y = jnp.matmul(x, p["weight"].T)
    if tp > 1:
        y = jax.lax.psum(y, TENSOR_AXIS)
    if "bias" in p:
        y = y + p["bias"]
    return y


def _vocab_embed(emb_w, tokens, tp):
    """Vocab-parallel embedding lookup (the ``VocabParallelEmbedding``
    mask-clip-take-zero-psum, shared with the PR 9 vocab-parallel xent
    target pick): each rank holds rows ``[rank*vp, (rank+1)*vp)`` of
    the table, out-of-shard tokens gather row 0 and are zeroed, and the
    psum reassembles the full embedding replica-uniform."""
    if tp <= 1:
        return jnp.take(emb_w, tokens, axis=0)
    vp = emb_w.shape[0]
    start = jax.lax.axis_index(TENSOR_AXIS) * vp
    mask = (tokens < start) | (tokens >= start + vp)
    local = jnp.clip(tokens - start, 0, vp - 1)
    e = jnp.take(emb_w, local, axis=0)
    e = jnp.where(mask[..., None], jnp.zeros((), e.dtype), e)
    return jax.lax.psum(e, TENSOR_AXIS)


def _gather_logits(local, tp):
    """Reassemble vocab-sharded logits: a tiled ``all_gather`` over the
    tensor axis concatenates the rank shards along the vocab dim in
    rank-major order — which IS the original vocab order (shard ``r``
    holds rows ``[r*vp, (r+1)*vp)``), so greedy/sampled tokens off the
    gathered logits are replica-uniform with one folded key."""
    if tp <= 1:
        return local
    return jax.lax.all_gather(local, TENSOR_AXIS,
                              axis=local.ndim - 1, tiled=True)


def _suffix_attend(cache, layer: int, row, q, k, v, start):
    """Prefill attention for a (possibly mid-prompt) token slab: cold
    (``start == 0``) it is EXACTLY the causal flash path the original
    prefill ran — bitwise, so cold prefills and the dense-parity tests
    are untouched; warm (``start > 0``, a prefix-cache hit or a later
    chunk of a chunked prefill) each row additionally attends to the
    already-cached prefix, gathered from the slot's KV pages through
    ``row`` (:func:`~apex_tpu.ops.attention.prefix_window_attention`).

    ``q``: ``[b, h, s, d]``; ``k``/``v``: pre-broadcast
    ``[b, kv_heads, s, d]``.  One ``lax.cond`` keeps both paths inside
    the ONE compiled prefill executable per bucket — the runtime
    executes only the taken branch, so cold prefills never pay the
    window gather."""
    b, h, s, d = q.shape
    kvh = k.shape[1]
    group = h // kvh

    def cold(q, k, v, pk, pv):
        if group > 1:                   # GQA: share kv across the group
            k, v = (jnp.broadcast_to(
                t[:, :, None], (b, kvh, group, s, d)
            ).reshape(b, h, s, d) for t in (k, v))
        return flash_attention(q, k, v, causal=True)

    def warm(q, k, v, pk, pv):
        # pk/pv [pages, kvh, ps, d] -> the slot's virtual window
        # [b, kvh, max_seq, d] in row order; unowned ordinals gather the
        # trash page — finite garbage masked by start
        def window(p):
            w = jnp.take(p, row, axis=0)          # [mpps, kvh, ps, d]
            return w.transpose(1, 0, 2, 3).reshape(
                1, kvh, -1, d).astype(q.dtype)
        return prefix_window_attention(q, k, v, window(pk), window(pv),
                                       start)

    return jax.lax.cond(start > 0, warm, cold, q, k, v,
                        cache.k[:, layer], cache.v[:, layer])


def _slab_attend(cache, layer: int, q, lengths):
    """Verify-slab attention against ONE layer of whichever cache
    layout the engine runs: the dense slot window scored directly
    (:func:`~apex_tpu.ops.attention.slab_decode_attention`) or the
    paged pool gathered through the slot page table
    (:func:`~apex_tpu.ops.paged_attention.paged_slab_attention`).
    ``lengths`` is the live count BEFORE the slab was appended (the
    causal offset)."""
    if isinstance(cache, kv_cache.PagedKVCache):
        return paged_slab_attention(q, cache.k[:, layer],
                                    cache.v[:, layer], cache.page_table,
                                    lengths)
    return slab_decode_attention(q, cache.k[:, layer], cache.v[:, layer],
                                 lengths)


def _cache_attend(cache, layer: int, q, live):
    """Single-token attention against ONE layer of whichever cache
    layout the engine runs: the dense slot window
    (:func:`~apex_tpu.ops.attention.decode_attention`) or the paged
    pool threaded through the slot page table
    (:func:`~apex_tpu.ops.paged_attention.paged_decode_attention`).
    Both score the pre-broadcast per-kv-head cache (GQA/MQA grouped)."""
    if isinstance(cache, kv_cache.PagedKVCache):
        return paged_decode_attention(
            q, cache.k[:, layer], cache.v[:, layer], cache.page_table,
            live, xla_max_pages=cache.attn_max_pages)
    return decode_attention(q, cache.k[:, layer], cache.v[:, layer], live)


def _fused_bias(p, width):
    """A linear's bias as the fused layout's ``[1, width]`` row (zeros
    when the layer was built bias-free)."""
    if "bias" in p:
        return p["bias"].reshape(1, width)
    return jnp.zeros((1, width), p["weight"].dtype)


def fused_layer_params(kind: str, cfg, params):
    """The per-layer weights re-laid-out for the fused-block decode
    kernel (ISSUE 15): matmul-ready ``[in, out]`` arrays with q/k/v
    split into head-major planes, built ONCE at engine construction so
    no transpose/gather ever runs inside the decode step.

    GPT's interleaved ``query_key_value`` columns (per head:
    ``[q(d), k(d), v(d)]``) deinterleave into ``wq``/``wk``/``wv``;
    LLaMA's packed ``kv_proj`` splits the same way.  The layout is a
    one-time device-side copy of the layer weights — the engine then
    holds BOTH layouts (prefill keeps the original tree), a deliberate
    HBM-for-latency trade the README documents next to the knob.
    """
    p = _params_subtree(params)
    dims = model_dims(kind, cfg)
    heads, kvh, d = dims["heads"], dims["kv_heads"], dims["head_dim"]
    hidden = cfg.hidden_size
    out = []
    for i in range(cfg.num_layers):
        lp = p[f"layer_{i}"]
        if kind == "gpt":
            att = lp["self_attention"]
            w = jnp.transpose(att["query_key_value"]["weight"])
            w = w.reshape(hidden, heads, 3, d)
            b = _fused_bias(att["query_key_value"],
                            3 * heads * d).reshape(heads, 3, d)
            blk = {
                "ln1_w": lp["input_layernorm"]["weight"].reshape(
                    1, hidden),
                "ln1_b": lp["input_layernorm"]["bias"].reshape(1, hidden),
                "wq": w[:, :, 0, :].reshape(hidden, heads * d),
                "bq": b[:, 0, :].reshape(1, heads * d),
                "wk": w[:, :, 1, :].reshape(hidden, heads * d),
                "bk": b[:, 1, :].reshape(1, heads * d),
                "wv": w[:, :, 2, :].reshape(hidden, heads * d),
                "bv": b[:, 2, :].reshape(1, heads * d),
                "wo": jnp.transpose(att["dense"]["weight"]),
                "bo": _fused_bias(att["dense"], hidden),
                "ln2_w": lp["post_attention_layernorm"][
                    "weight"].reshape(1, hidden),
                "ln2_b": lp["post_attention_layernorm"][
                    "bias"].reshape(1, hidden),
                "wu": jnp.transpose(lp["mlp"]["dense_h_to_4h"]["weight"]),
                "bu": _fused_bias(lp["mlp"]["dense_h_to_4h"], cfg.ffn),
                "wd": jnp.transpose(lp["mlp"]["dense_4h_to_h"]["weight"]),
                "bd": _fused_bias(lp["mlp"]["dense_4h_to_h"], hidden),
            }
        else:
            att = lp["attention"]
            kvw = jnp.transpose(att["kv_proj"]["weight"])
            # kv-head count from the WEIGHT, not the config: a
            # kv-expanded tree (expand_kv_for_tp) carries kvh*rep heads
            kvh_w = kvw.shape[1] // (2 * d)
            kvw = kvw.reshape(hidden, kvh_w, 2, d)
            blk = {
                "ln1_w": lp["input_norm"]["weight"].reshape(1, hidden),
                "wq": jnp.transpose(att["q_proj"]["weight"]),
                "wk": kvw[:, :, 0, :].reshape(hidden, kvh_w * d),
                "wv": kvw[:, :, 1, :].reshape(hidden, kvh_w * d),
                "wo": jnp.transpose(att["o_proj"]["weight"]),
                "ln2_w": lp["post_attention_norm"]["weight"].reshape(
                    1, hidden),
                "wg": jnp.transpose(lp["mlp"]["gate_proj"]["weight"]),
                "wu": jnp.transpose(lp["mlp"]["up_proj"]["weight"]),
                "wd": jnp.transpose(lp["mlp"]["down_proj"]["weight"]),
            }
        out.append(blk)
    return out


# --------------------------------------------------------------------------
# tensor-parallel param mirrors (ISSUE 17)
# --------------------------------------------------------------------------

#: parent module names whose ``weight`` is column-partitioned ([out, in]
#: layout, out dim sharded — heads/ffn/vocab-major, so whole heads land
#: per rank) and whose ``bias`` shards with the out dim
_COL_PARENTS = frozenset({
    "query_key_value", "dense_h_to_4h",            # gpt
    "q_proj", "kv_proj", "gate_proj", "up_proj",   # llama
    "lm_head", "word_embeddings", "embed_tokens",  # vocab-sharded
})

#: parent module names whose ``weight`` is row-partitioned (in dim
#: sharded); their bias stays replicated — added once post-psum
_ROW_PARENTS = frozenset({
    "dense", "dense_4h_to_h",                      # gpt
    "o_proj", "down_proj",                         # llama
})


def expand_kv_for_tp(kind: str, cfg, params, tp: int):
    """Replicate GQA/MQA kv heads below tp (``rep = tp/kvh > 1``): each
    kv head's packed ``[2*head_dim]`` output columns of ``kv_proj``
    repeat ``rep`` times head-major, so the plain column shard over the
    expanded out dim hands every rank exactly the kv head its query
    group reads — the training layers' "replicate below tp" for
    serving mirrors.  Identity when ``rep == 1`` (tp=1, MHA, or
    tp-divisible GQA)."""
    td = tp_dims(kind, cfg, tp)
    rep, kvh, d = td["rep"], td["kv_heads"], td["head_dim"]
    if rep == 1:
        return params
    sub = _params_subtree(params)
    fixed = dict(sub)
    for name, lp in sub.items():
        if not name.startswith("layer_"):
            continue
        kvp = dict(lp["attention"]["kv_proj"])
        w = kvp["weight"]                          # [kvh*2d, hidden]
        kvp["weight"] = jnp.repeat(
            w.reshape(kvh, 2 * d, w.shape[1]), rep, axis=0
        ).reshape(kvh * rep * 2 * d, w.shape[1])
        if "bias" in kvp:
            kvp["bias"] = jnp.repeat(
                kvp["bias"].reshape(kvh, 2 * d), rep, axis=0).reshape(-1)
        att = dict(lp["attention"])
        att["kv_proj"] = kvp
        fixed[name] = dict(lp)
        fixed[name]["attention"] = att
    if sub is not params:
        return {**params, "params": fixed}
    return fixed


def param_partition_specs(kind: str, cfg, params, tp: int):
    """``PartitionSpec`` tree for the (kv-expanded) param tree: qkv /
    gate / up column-sharded over heads/ffn, out-proj / down
    row-sharded, embed + LM head vocab-sharded, norms / position table
    replicated.  Validates divisibility leaf by leaf so a bad geometry
    names the offending module."""
    from jax.sharding import PartitionSpec as P

    def spec(path, leaf):
        if tp <= 1:
            return P()
        keys = [getattr(k, "key", getattr(k, "name", str(k)))
                for k in path]
        name = keys[-1] if keys else ""
        parent = keys[-2] if len(keys) >= 2 else ""
        if parent in _COL_PARENTS:
            if leaf.shape[0] % tp:
                raise ValueError(
                    f"tp={tp} does not divide {parent}.{name} out dim "
                    f"{leaf.shape[0]}")
            return (P(TENSOR_AXIS, None) if name == "weight"
                    else P(TENSOR_AXIS))
        if parent in _ROW_PARENTS:
            if name == "weight":
                if leaf.shape[1] % tp:
                    raise ValueError(
                        f"tp={tp} does not divide {parent}.weight in "
                        f"dim {leaf.shape[1]}")
                return P(None, TENSOR_AXIS)
            return P()                  # row bias: replicated, post-psum
        return P()
    return jax.tree_util.tree_map_with_path(spec, params)


def fused_partition_specs(fused_layers, tp: int):
    """``PartitionSpec`` list matching :func:`fused_layer_params`'s
    ``[in, out]`` layout: q/k/v/gate/up planes column-sharded on the
    out dim, out-proj/down row-sharded on the in dim, norms and the
    post-psum biases (``bo``/``bd``) replicated."""
    from jax.sharding import PartitionSpec as P
    col = {"wq", "bq", "wk", "bk", "wv", "bv", "wg", "wu", "bu"}
    row = {"wo", "wd"}

    def one(blk):
        out = {}
        for k in blk:
            if tp > 1 and k in col:
                out[k] = P(None, TENSOR_AXIS)
            elif tp > 1 and k in row:
                out[k] = P(TENSOR_AXIS, None)
            else:
                out[k] = P()
        return out
    return [one(b) for b in fused_layers]


def _fused_block_tail_tp(kind: str, blk, x, part, eps):
    """Finish one fused block OUTSIDE the kernel under tp: psum the
    rank-partial attention output at the row boundary (the out-proj
    psum the ISSUE moves out of the kernel), add the out-proj bias
    once, then norm2 + the column/row-parallel MLP with its own
    row-boundary psum — the same two-psums-per-layer the unfused
    sharded path pays."""
    attn = jax.lax.psum(part, TENSOR_AXIS)
    if kind == "gpt":
        x2 = x + attn + blk["bo"]
        h2 = layer_norm(x2, blk["ln2_w"].reshape(-1),
                        blk["ln2_b"].reshape(-1))
        u = jax.nn.gelu(jnp.matmul(h2, blk["wu"]) + blk["bu"])
        y = jax.lax.psum(jnp.matmul(u, blk["wd"]), TENSOR_AXIS)
        y = y + blk["bd"]
    else:
        x2 = x + attn
        h2 = rms_norm(x2, blk["ln2_w"].reshape(-1), eps=eps)
        u = jax.nn.silu(jnp.matmul(h2, blk["wg"])) * jnp.matmul(
            h2, blk["wu"])
        y = jax.lax.psum(jnp.matmul(u, blk["wd"]), TENSOR_AXIS)
    return x2 + y


# --------------------------------------------------------------------------
# GPT (standalone_gpt mirror)
# --------------------------------------------------------------------------

def _gpt_attn_proj(lp, h, heads, head_dim):
    """qkv projection + the model's reshape/split layout: returns
    q/k/v with a trailing ``[..., heads, head_dim]``."""
    qkv = _linear(lp["self_attention"]["query_key_value"], h)
    qkv = qkv.reshape(*h.shape[:-1], heads, 3 * head_dim)
    return jnp.split(qkv, 3, axis=-1)


def _gpt_mlp(lp, h, tp=1):
    return _row_linear(lp["mlp"]["dense_4h_to_h"],
                       jax.nn.gelu(_linear(lp["mlp"]["dense_h_to_4h"],
                                           h)), tp)


def _last_row(h, length):
    """Hidden state at the last REAL position (``length - 1``) of a
    bucket-padded ``[s, b, hid]`` activation — sliced BEFORE the lm
    head, so the O(s·vocab·hidden) projection runs on one row instead
    of every dead padding position (~1/3 of prefill FLOPs at the
    flagship shape)."""
    return jax.lax.dynamic_index_in_dim(h, length - 1, axis=0,
                                        keepdims=False)       # [b, hid]


def _gpt_prefill(cfg, params, tokens, length=None, cache=None, row=None,
                 start=None, tp=1):
    p = _params_subtree(params)
    b, s = tokens.shape
    dims = model_dims("gpt", cfg)
    heads, head_dim = dims["heads"] // tp, dims["head_dim"]
    suffix = cache is not None          # static: suffix-prefill variant

    emb_w = p["embedding"]["word_embeddings"]["weight"]
    h = _vocab_embed(emb_w, tokens, tp)                     # [b, s, h]
    pos_tab = p["embedding"]["position_embeddings"]
    if suffix:
        # rows sit at absolute positions start + i (clamped: dead
        # bucket-padding rows past the table stay in range)
        positions = jnp.minimum(
            jnp.asarray(start, jnp.int32)
            + jnp.arange(s, dtype=jnp.int32),
            jnp.int32(pos_tab.shape[0] - 1))
        h = h + jnp.take(pos_tab, positions, axis=0)[None]
    else:
        h = h + pos_tab[None, :s, :]
    h = h.transpose(1, 0, 2)                                # [s, b, h]

    ks, vs = [], []
    for i in range(cfg.num_layers):
        lp = p[f"layer_{i}"]
        x = h
        h1 = layer_norm(x, lp["input_layernorm"]["weight"],
                        lp["input_layernorm"]["bias"])
        q, k, v = _gpt_attn_proj(lp, h1, heads, head_dim)   # [s, b, n, d]
        q, k, v = (t.transpose(1, 2, 0, 3) for t in (q, k, v))
        ks.append(k[0])                                     # [n, s, d]
        vs.append(v[0])
        if suffix:
            ctx = _suffix_attend(cache, i, row, q, k, v, start)
        else:
            ctx = flash_attention(q, k, v, causal=True)
        ctx = ctx.transpose(2, 0, 1, 3).reshape(s, b, -1)
        x = x + _row_linear(lp["self_attention"]["dense"], ctx, tp)
        h2 = layer_norm(x, lp["post_attention_layernorm"]["weight"],
                        lp["post_attention_layernorm"]["bias"])
        h = x + _gpt_mlp(lp, h2, tp)

    h = layer_norm(h, p["final_layernorm"]["weight"],
                   p["final_layernorm"]["bias"])
    if length is not None:
        last = length - start if suffix else length   # local slab index
        logits = jnp.einsum("bh,vh->bv", _last_row(h, last), emb_w)
    else:
        logits = jnp.einsum("sbh,vh->sbv", h, emb_w)        # tied head
    return _gather_logits(logits, tp), jnp.stack(ks), jnp.stack(vs)


def _gpt_decode(cfg, params, cache, tokens, fused=None, tp=1):
    p = _params_subtree(params)
    dims = model_dims("gpt", cfg)
    heads, head_dim = dims["heads"] // tp, dims["head_dim"]
    positions = cache.lengths                               # [slots]

    emb_w = p["embedding"]["word_embeddings"]["weight"]
    h = _vocab_embed(emb_w, tokens, tp)                     # [slots, h]
    h = h + jnp.take(p["embedding"]["position_embeddings"],
                     positions, axis=0)

    live = positions + 1                    # incl. the token written now
    for i in range(cfg.num_layers):
        if fused is not None:
            if tp > 1:
                # sharded fused block (ISSUE 17): the kernel runs on
                # the 1/tp weight shard and emits the RANK-PARTIAL
                # out-proj product (no residual, no bias) — the row
                # psum + bias + norm2 + col/row MLP finish outside
                part, k_tok, v_tok = fused_block_decode(
                    h, fused[i], cache.k[:, i], cache.v[:, i],
                    cache.page_table, positions, kind="gpt", eps=1e-5,
                    fuse_mlp=False, partial_out=True)
                cache = kv_cache.append_layer(cache, i, k_tok, v_tok)
                h = _fused_block_tail_tp("gpt", fused[i], h, part, 1e-5)
                continue
            # ISSUE 15: the whole block in ONE kernel (norm1 -> qkv ->
            # paged attention incl. this token -> out proj -> norm2 ->
            # MLP); only the pool append leaves the per-op path
            h, k_tok, v_tok = fused_block_decode(
                h, fused[i], cache.k[:, i], cache.v[:, i],
                cache.page_table, positions, kind="gpt", eps=1e-5)
            cache = kv_cache.append_layer(cache, i, k_tok, v_tok)
            continue
        lp = p[f"layer_{i}"]
        x = h
        h1 = layer_norm(x, lp["input_layernorm"]["weight"],
                        lp["input_layernorm"]["bias"])
        q, k_tok, v_tok = _gpt_attn_proj(lp, h1, heads, head_dim)
        cache = kv_cache.append_layer(cache, i, k_tok, v_tok)
        ctx = _cache_attend(cache, i, q, live)
        x = x + _row_linear(lp["self_attention"]["dense"],
                            ctx.reshape(ctx.shape[0], -1), tp)
        h2 = layer_norm(x, lp["post_attention_layernorm"]["weight"],
                        lp["post_attention_layernorm"]["bias"])
        h = x + _gpt_mlp(lp, h2, tp)

    h = layer_norm(h, p["final_layernorm"]["weight"],
                   p["final_layernorm"]["bias"])
    logits = jnp.einsum("bh,vh->bv", h, emb_w)
    return _gather_logits(logits, tp), cache


def _gpt_verify(cfg, params, cache, tokens, tp=1):
    """Speculative verify (ISSUE 15): score an ``S``-token drafted slab
    per slot in ONE batched step — logits at EVERY slab position, the
    slab's k/v appended at ``[lengths, lengths + S)``.  Lengths do not
    advance here; the verify step advances by the accepted count
    (:func:`kv_cache.advance_by`) so rejection is a pure length
    rollback."""
    p = _params_subtree(params)
    dims = model_dims("gpt", cfg)
    heads, head_dim = dims["heads"] // tp, dims["head_dim"]
    slots, s = tokens.shape
    base = cache.lengths                                    # [slots]
    pos = base[:, None] + jnp.arange(s, dtype=jnp.int32)[None]

    emb_w = p["embedding"]["word_embeddings"]["weight"]
    pos_tab = p["embedding"]["position_embeddings"]
    h = _vocab_embed(emb_w, tokens, tp)                     # [b, S, hid]
    h = h + jnp.take(pos_tab,
                     jnp.minimum(pos, jnp.int32(pos_tab.shape[0] - 1)),
                     axis=0)

    for i in range(cfg.num_layers):
        lp = p[f"layer_{i}"]
        x = h
        h1 = layer_norm(x, lp["input_layernorm"]["weight"],
                        lp["input_layernorm"]["bias"])
        q, k, v = _gpt_attn_proj(lp, h1, heads, head_dim)   # [b,S,n,d]
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        cache = kv_cache.append_slab(cache, i, k, v)
        ctx = _slab_attend(cache, i, q, base)               # [b,h,S,d]
        ctx = ctx.transpose(0, 2, 1, 3).reshape(slots, s, -1)
        x = x + _row_linear(lp["self_attention"]["dense"], ctx, tp)
        h2 = layer_norm(x, lp["post_attention_layernorm"]["weight"],
                        lp["post_attention_layernorm"]["bias"])
        h = x + _gpt_mlp(lp, h2, tp)

    h = layer_norm(h, p["final_layernorm"]["weight"],
                   p["final_layernorm"]["bias"])
    logits = jnp.einsum("bsh,vh->bsv", h, emb_w)
    return _gather_logits(logits, tp), cache


# --------------------------------------------------------------------------
# LLaMA (standalone_llama mirror; GQA/MQA cached once per kv head)
# --------------------------------------------------------------------------

def _llama_rope_table(cfg, head_dim, max_seq):
    """Flat ``[max_seq, head_dim]`` cos/sin tables (the model's
    ``_rope_cos_sin`` values, position-indexable for decode)."""
    cos, sin = _rope_cos_sin(max_seq, head_dim, cfg.rope_theta)
    return cos.reshape(max_seq, head_dim), sin.reshape(max_seq, head_dim)


def _llama_proj(lp, h, cfg, heads, kv_heads, head_dim):
    q = _linear(lp["attention"]["q_proj"], h)
    kv = _linear(lp["attention"]["kv_proj"], h)
    q = q.reshape(*h.shape[:-1], heads, head_dim)
    k, v = jnp.split(kv.reshape(*h.shape[:-1], kv_heads, 2 * head_dim),
                     2, axis=-1)
    return q, k, v


def _llama_mlp(lp, h, tp=1):
    gate = _linear(lp["mlp"]["gate_proj"], h)
    up = _linear(lp["mlp"]["up_proj"], h)
    return _row_linear(lp["mlp"]["down_proj"],
                       jax.nn.silu(gate) * up, tp)


def _llama_prefill(cfg, params, tokens, length=None, cache=None,
                   row=None, start=None, tp=1):
    p = _params_subtree(params)
    b, s = tokens.shape
    dims = tp_dims("llama", cfg, tp)
    heads, kv_heads = dims["heads_local"], dims["kv_heads_local"]
    head_dim, group = dims["head_dim"], (dims["heads_local"]
                                         // dims["kv_heads_local"])
    suffix = cache is not None          # static: suffix-prefill variant

    h = _vocab_embed(p["embed_tokens"]["weight"], tokens, tp)
    h = h.transpose(1, 0, 2)                                # [s, b, h]
    if suffix:
        # RoPE at the slab's absolute positions start + i (clamped for
        # dead bucket-padding rows), indexed from the full-window table
        cos_t, sin_t = _rope_cos_sin(cache.max_seq, head_dim,
                                     cfg.rope_theta)  # [max_seq, 1, 1, d]
        positions = jnp.minimum(
            jnp.asarray(start, jnp.int32)
            + jnp.arange(s, dtype=jnp.int32),
            jnp.int32(cache.max_seq - 1))
        cos = jnp.take(cos_t, positions, axis=0)            # [s, 1, 1, d]
        sin = jnp.take(sin_t, positions, axis=0)
    else:
        cos, sin = _rope_cos_sin(s, head_dim, cfg.rope_theta)

    ks, vs = [], []
    for i in range(cfg.num_layers):
        lp = p[f"layer_{i}"]
        x = h
        h1 = rms_norm(x, lp["input_norm"]["weight"], eps=cfg.rms_eps)
        q, k, v = _llama_proj(lp, h1, cfg, heads, kv_heads, head_dim)
        q = fused_apply_rotary_pos_emb_cached(q, cos, sin)
        k = fused_apply_rotary_pos_emb_cached(k, cos, sin)
        # cache the PRE-broadcast kv (once per kv head)
        ks.append(k.transpose(1, 2, 0, 3)[0])               # [kv, s, d]
        vs.append(v.transpose(1, 2, 0, 3)[0])
        if suffix:
            qb, kb, vb = (t.transpose(1, 2, 0, 3) for t in (q, k, v))
            ctx = _suffix_attend(cache, i, row, qb, kb, vb, start)
        else:
            if group > 1:               # GQA: share kv across the group
                k, v = (jnp.broadcast_to(
                    t[:, :, :, None, :],
                    (s, b, kv_heads, group, head_dim)
                ).reshape(s, b, heads, head_dim) for t in (k, v))
            q, k, v = (t.transpose(1, 2, 0, 3) for t in (q, k, v))
            ctx = flash_attention(q, k, v, causal=True)
        ctx = ctx.transpose(2, 0, 1, 3).reshape(s, b, -1)
        x = x + _row_linear(lp["attention"]["o_proj"], ctx, tp)
        h1 = rms_norm(x, lp["post_attention_norm"]["weight"],
                      eps=cfg.rms_eps)
        h = x + _llama_mlp(lp, h1, tp)

    h = rms_norm(h, p["final_norm"]["weight"], eps=cfg.rms_eps)
    if length is not None:
        last = length - start if suffix else length   # local slab index
        logits = _linear(p["lm_head"], _last_row(h, last))    # [b, v]
    else:
        logits = _linear(p["lm_head"], h)                     # [s, b, v]
    return _gather_logits(logits, tp), jnp.stack(ks), jnp.stack(vs)


def _llama_decode(cfg, params, cache, tokens, fused=None, tp=1):
    p = _params_subtree(params)
    dims = tp_dims("llama", cfg, tp)
    heads, kv_heads = dims["heads_local"], dims["kv_heads_local"]
    head_dim = dims["head_dim"]
    positions = cache.lengths

    h = _vocab_embed(p["embed_tokens"]["weight"], tokens, tp)
    cos_t, sin_t = _llama_rope_table(cfg, head_dim, cache.max_seq)
    cos2 = jnp.take(cos_t, positions, axis=0)               # [slots, d]
    sin2 = jnp.take(sin_t, positions, axis=0)
    cos, sin = cos2[:, None, :], sin2[:, None, :]           # [slots, 1, d]

    live = positions + 1
    for i in range(cfg.num_layers):
        if fused is not None:
            if tp > 1:
                part, k_tok, v_tok = fused_block_decode(
                    h, fused[i], cache.k[:, i], cache.v[:, i],
                    cache.page_table, positions, kind="llama",
                    eps=cfg.rms_eps, cos=cos2, sin=sin2,
                    fuse_mlp=False, partial_out=True)
                cache = kv_cache.append_layer(cache, i, k_tok, v_tok)
                h = _fused_block_tail_tp("llama", fused[i], h, part,
                                         cfg.rms_eps)
                continue
            h, k_tok, v_tok = fused_block_decode(
                h, fused[i], cache.k[:, i], cache.v[:, i],
                cache.page_table, positions, kind="llama",
                eps=cfg.rms_eps, cos=cos2, sin=sin2)
            cache = kv_cache.append_layer(cache, i, k_tok, v_tok)
            continue
        lp = p[f"layer_{i}"]
        x = h
        h1 = rms_norm(x, lp["input_norm"]["weight"], eps=cfg.rms_eps)
        q, k_tok, v_tok = _llama_proj(lp, h1, cfg, heads, kv_heads,
                                      head_dim)
        q = fused_apply_rotary_pos_emb_cached(q, cos, sin)
        k_tok = fused_apply_rotary_pos_emb_cached(k_tok, cos, sin)
        cache = kv_cache.append_layer(cache, i, k_tok, v_tok)
        # grouped-query scoring straight off the per-kv-head cache/pool
        ctx = _cache_attend(cache, i, q, live)
        x = x + _row_linear(lp["attention"]["o_proj"],
                            ctx.reshape(ctx.shape[0], -1), tp)
        h1 = rms_norm(x, lp["post_attention_norm"]["weight"],
                      eps=cfg.rms_eps)
        h = x + _llama_mlp(lp, h1, tp)

    h = rms_norm(h, p["final_norm"]["weight"], eps=cfg.rms_eps)
    logits = _linear(p["lm_head"], h)                       # [slots, v]
    return _gather_logits(logits, tp), cache


def _llama_verify(cfg, params, cache, tokens, tp=1):
    """LLaMA twin of :func:`_gpt_verify`: RoPE at each slab row's
    absolute position, GQA/MQA slab scoring straight off the
    per-kv-head cache/pool."""
    p = _params_subtree(params)
    dims = tp_dims("llama", cfg, tp)
    heads, kv_heads = dims["heads_local"], dims["kv_heads_local"]
    head_dim = dims["head_dim"]
    slots, s = tokens.shape
    base = cache.lengths
    pos = base[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
    pos = jnp.minimum(pos, jnp.int32(cache.max_seq - 1))

    h = _vocab_embed(p["embed_tokens"]["weight"], tokens, tp)
    cos_t, sin_t = _llama_rope_table(cfg, head_dim, cache.max_seq)
    cos = jnp.take(cos_t, pos, axis=0)[:, :, None, :]     # [b, S, 1, d]
    sin = jnp.take(sin_t, pos, axis=0)[:, :, None, :]

    for i in range(cfg.num_layers):
        lp = p[f"layer_{i}"]
        x = h
        h1 = rms_norm(x, lp["input_norm"]["weight"], eps=cfg.rms_eps)
        q, k, v = _llama_proj(lp, h1, cfg, heads, kv_heads, head_dim)
        q = fused_apply_rotary_pos_emb_cached(q, cos, sin)
        k = fused_apply_rotary_pos_emb_cached(k, cos, sin)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        cache = kv_cache.append_slab(cache, i, k, v)
        ctx = _slab_attend(cache, i, q, base)               # [b,h,S,d]
        ctx = ctx.transpose(0, 2, 1, 3).reshape(slots, s, -1)
        x = x + _row_linear(lp["attention"]["o_proj"], ctx, tp)
        h1 = rms_norm(x, lp["post_attention_norm"]["weight"],
                      eps=cfg.rms_eps)
        h = x + _llama_mlp(lp, h1, tp)

    h = rms_norm(h, p["final_norm"]["weight"], eps=cfg.rms_eps)
    logits = _linear(p["lm_head"], h)                     # [b, S, v]
    return _gather_logits(logits, tp), cache


# --------------------------------------------------------------------------
# dispatch
# --------------------------------------------------------------------------

def prefill_forward(kind: str, cfg, params, tokens, length=None, *,
                    cache=None, row=None, prefill_from=None, tp=1):
    """Full-prompt forward: ``tokens [1, s]`` -> ``(logits, k_stack,
    v_stack)`` with k/v ``[layers, kv_heads, s, head_dim]`` ready for
    :func:`kv_cache.insert`.

    With ``length`` (the real prompt length inside a bucket-padded
    ``s``, traced OK) the lm head runs on ONLY the last real position —
    ``logits [1, v]``; without it every position is projected
    (``logits [s, 1, v]``, the full-forward shape parity tests pin).

    Suffix mode (ISSUE 12 — paged engines only): with ``cache`` (the
    :class:`~apex_tpu.inference.kv_cache.PagedKVCache`), ``row`` (the
    slot's full page-table row) and ``prefill_from`` (how many prompt
    tokens are already cached, traced OK), ``tokens`` is the
    bucket-padded UNCACHED TAIL: rows sit at absolute positions
    ``prefill_from + i``, attend to the cached prefix through the page
    window (:func:`_suffix_attend`) and causally to the slab itself,
    and ``length`` is the TOTAL live length (prefix + real suffix).
    ``prefill_from == 0`` reproduces the cold path bitwise — one
    compiled executable per bucket serves cold prefills, prefix-cache
    hits, and chunked-prefill continuation chunks alike."""
    if tokens.ndim != 2 or tokens.shape[0] != 1:
        raise ValueError(
            f"prefill takes one prompt [1, s], got {tuple(tokens.shape)}")
    fn = _gpt_prefill if kind == "gpt" else _llama_prefill
    if cache is None:
        return fn(cfg, params, tokens, length, tp=tp)
    if row is None or prefill_from is None or length is None:
        raise ValueError(
            "suffix prefill needs cache, row, prefill_from AND length")
    return fn(cfg, params, tokens, length, cache=cache, row=row,
              start=prefill_from, tp=tp)


def decode_forward(kind: str, cfg, params, cache, tokens, fused=None,
                   tp=1):
    """One-token step for every slot: ``tokens [slots]`` ->
    ``(logits [slots, v], cache)`` with the new k/v appended at each
    slot's position.  Lengths do not advance here (the engine advances
    active slots once per step).

    ``fused`` (ISSUE 15) is the per-layer fused weight layout from
    :func:`fused_layer_params`: when present (paged engines under
    ``APEX_TPU_DECODE_FUSION``), every transformer block runs as ONE
    Pallas kernel (:func:`~apex_tpu.ops.paged_attention.
    fused_block_decode`) instead of the per-op XLA sequence — same
    embed/head, same pool append, same signature, tolerance-level
    numerics (the in-kernel residual chain stays fp32 where the
    unfused path rounds to bf16 at each sublayer)."""
    fn = _gpt_decode if kind == "gpt" else _llama_decode
    return fn(cfg, params, cache, tokens, fused=fused, tp=tp)


def verify_forward(kind: str, cfg, params, cache, tokens, tp=1):
    """Speculative-verify step (ISSUE 15): ``tokens [slots, S]`` (the
    last confirmed token followed by ``S - 1`` drafts, per slot) ->
    ``(logits [slots, S, v], cache)`` with the slab's k/v appended at
    positions ``[lengths, lengths + S)``.  Lengths do NOT advance —
    the verify fn advances by the accepted count, which IS the
    page-table/length rollback (rejected rows go dead-by-mask; pages
    were already reserved, so rejection releases nothing)."""
    if tokens.ndim != 2:
        raise ValueError(
            f"verify takes a [slots, S] slab, got {tuple(tokens.shape)}")
    fn = _gpt_verify if kind == "gpt" else _llama_verify
    return fn(cfg, params, cache, tokens, tp=tp)
