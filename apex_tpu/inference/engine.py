"""Prefill/decode inference engine: each phase is ONE donated XLA
executable.

Workload split (the flash-attention/Megatron serving shape):

* **Prefill** — the whole prompt in one causal forward through the flash
  kernels, k/v for every layer parked into one cache slot
  (``kv_cache.insert``), the first token sampled from the last real
  position's logits.  Compiled once per prompt *bucket* (prompts pad up
  to a power-of-two length) with the cache donated.
* **Decode** — one token for EVERY slot per step: embed, per-layer
  qkv + cache append + ``decode_attention`` over the slot's live
  length, lm head, sampling, length advance — all in one jitted program
  with the cache donated, so the executable's cache output aliases its
  input and no per-step reallocation exists.  The step's PRNG key is
  derived in-program (``fold_in(key, step)``), so sampled decoding adds
  no second executable.

Cache layouts (ISSUE 6): the dense slot cache provisions ``max_seq``
per slot; ``page_size=``/``num_pages=`` switch to the ragged paged
pool — k/v in fixed-size pages threaded through a traced per-slot page
table (``paged_decode_attention`` per layer), the host-side
``PageAllocator`` handing out reservations.  Same two executables,
same donation discipline; only the memory model (and the scheduler's
admission unit — pages, not slots) changes.

No host transfer appears anywhere in either jaxpr (audited by
``analysis/jaxpr_audit.py`` — the inference entries trace these exact
step builders); the only device<->host traffic is the scheduler reading
sampled tokens *between* steps, which is the continuous-batching control
loop by construction.

Weights: any checkpoint that can produce the flat fp32 master restores
straight into the engine — :meth:`InferenceEngine.from_train_state`
exports bf16 params from ``FlatState.params(dtype=...)`` (gathering
shards if the state is ZeRO-sharded), and
:meth:`InferenceEngine.from_state_dict` consumes the contrib
``DistributedFused*`` shard-aware ``state_dict`` written at ANY dp.

BERT rides along as the encode-only path (``kind="bert"``): one jitted
bidirectional forward, no cache — prefill and decode degenerate to the
same executable-shape discipline with nothing to split.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from apex_tpu import observability as obs
from apex_tpu.inference import kv_cache, models
from apex_tpu.inference.sampling import SamplingConfig, greedy, sample_token
from apex_tpu.inference.speculative import default_spec_k
from apex_tpu.ops.paged_attention import (decode_fusion as
                                          resolve_fusion_mode,
                                          resolve_decode_fusion)
from apex_tpu.transformer.parallel_state import serving_mesh

__all__ = ["InferenceEngine", "make_prefill_fn", "make_decode_fn",
           "make_verify_fn", "prefill_bucket", "serve_tp",
           "host_kv_tier_bytes"]

_HOST_TIER_ENV = "APEX_TPU_HOST_KV_TIER_BYTES"


def serve_tp() -> int:
    """Effective serving tensor-parallel width from ``APEX_TPU_SERVE_TP``
    (registered in ``analysis/env_registry.py``): unset/``0`` means
    single-chip; an explicit ``InferenceEngine(tp=)`` always wins."""
    raw = os.environ.get("APEX_TPU_SERVE_TP", "0").strip() or "0"
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"APEX_TPU_SERVE_TP must be an integer, got {raw!r}")
    if v < 0:
        raise ValueError(f"APEX_TPU_SERVE_TP must be >= 0, got {v}")
    return v or 1


def host_kv_tier_bytes() -> int:
    """Host-DRAM KV page tier byte budget from
    ``APEX_TPU_HOST_KV_TIER_BYTES`` (registered in
    ``analysis/env_registry.py``): unset/``0`` disables the tier (LRU
    eviction discards, the pre-ISSUE-18 behavior); an explicit
    ``InferenceEngine(host_tier_bytes=)`` always wins."""
    raw = os.environ.get(_HOST_TIER_ENV, "0").strip() or "0"
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"{_HOST_TIER_ENV} must be an integer, got {raw!r}")
    if v < 0:
        raise ValueError(f"{_HOST_TIER_ENV} must be >= 0, got {v}")
    return v


def make_prefill_fn(kind: str, cfg, sampling: SamplingConfig,
                    paged: bool = False, tp: int = 1):
    """Pure prefill step.  Dense: ``(cache, params, tokens [s], slot,
    length, key, step) -> (cache, next_token, last_logits)``; paged
    takes extra ``row`` (the slot's ``[max_pages_per_slot]`` page-table
    row) and ``prefill_from`` operands after ``length``.

    ``prefill_from`` (ISSUE 12) is the number of prompt tokens already
    sitting in the slot's pages: ``tokens`` is then the bucket-padded
    UNCACHED TAIL, the forward attends to the cached prefix through the
    page window, and the insert scatters only the tail's rows —
    ``prefill_from == 0`` is the cold path (bitwise the original math).
    ``length`` is the slot's TOTAL live length after this step (real
    prefix + real tail inside the padded bucket).  Both operands are
    traced, so ONE compiled executable per bucket serves cold
    prefills, prefix-cache hits, and chunked-prefill chunks alike —
    sharing changes page-table rows, never device programs."""

    def prefill_fn(cache, params, tokens, slot, length, key, step):
        # named_scope = metadata-only xprof regions (no prims added, so
        # the jaxpr/SPMD audits of these exact builders are unchanged)
        with obs.named_scope("apex_prefill_forward"):
            # length threads into the forward so the lm head projects
            # ONLY the last real position, not every bucket-padded row
            logits, ks, vs = models.prefill_forward(kind, cfg, params,
                                                    tokens[None], length,
                                                    tp=tp)
        with obs.named_scope("apex_prefill_cache_insert"):
            cache = kv_cache.insert(cache, slot, ks, vs, length)
        with obs.named_scope("apex_prefill_sample"):
            last = logits[0].astype(jnp.float32)            # [vocab]
            tok = sample_token(last, jax.random.fold_in(key, step),
                               sampling)
        return cache, tok, last

    def prefill_paged_fn(cache, params, tokens, slot, length, row,
                         prefill_from, key, step):
        with obs.named_scope("apex_prefill_forward"):
            logits, ks, vs = models.prefill_forward(
                kind, cfg, params, tokens[None], length, cache=cache,
                row=row, prefill_from=prefill_from, tp=tp)
        with obs.named_scope("apex_prefill_cache_insert"):
            cache = kv_cache.insert_tokens(cache, slot, ks, vs, length,
                                           row, prefill_from)
        with obs.named_scope("apex_prefill_sample"):
            last = logits[0].astype(jnp.float32)            # [vocab]
            tok = sample_token(last, jax.random.fold_in(key, step),
                               sampling)
        return cache, tok, last

    return prefill_paged_fn if paged else prefill_fn


def make_decode_fn(kind: str, cfg, sampling: SamplingConfig,
                   fused: bool = False, tp: int = 1):
    """Pure decode step: ``(cache, params, tokens [slots], active
    [slots], key, step) -> (cache, next_tokens, logits, truncated)``.
    Every slot computes (static shape); only active slots advance their
    length, and ``truncated`` flags active slots already at capacity
    whose emitted token could NOT be appended (the caller must retire
    them — nothing is clamped silently).  Serves both cache layouts:
    the paged pool threads its page table through the same signature.

    ``fused`` (ISSUE 15, paged engines): the ``params`` operand becomes
    the pair ``(tree, fused_layers)`` and every transformer block runs
    as ONE Pallas kernel (``fused_block_decode``) — still ONE donated
    executable with the same outputs, selected statically at engine
    construction by ``APEX_TPU_DECODE_FUSION``; fusion off keeps the
    original per-op lowering bitwise."""

    def decode_fn(cache, params, tokens, active, key, step):
        tree, fused_layers = params if fused else (params, None)
        with obs.named_scope("apex_decode_forward"):
            logits, cache = models.decode_forward(kind, cfg, tree,
                                                  cache, tokens,
                                                  fused=fused_layers,
                                                  tp=tp)
        with obs.named_scope("apex_decode_sample"):
            logits = logits.astype(jnp.float32)
            toks = sample_token(logits, jax.random.fold_in(key, step),
                                sampling)
        with obs.named_scope("apex_decode_advance"):
            cache, truncated = kv_cache.advance(cache, active)
        return cache, toks, logits, truncated

    return decode_fn


def make_verify_fn(kind: str, cfg, sampling: SamplingConfig, k: int,
                   tp: int = 1):
    """Pure speculative-verify step (ISSUE 15): ``(cache, params, slab
    [slots, k+1], active [slots], key, step) -> (cache, tokens
    [slots, k+1], n_emit [slots], truncated)``.

    ``slab`` column 0 is each slot's last confirmed (pending) token,
    columns ``1..k`` the drafted continuation.  ONE batched forward
    scores every slab position against the cache (the slab's k/v land
    at ``[lengths, lengths + k + 1)`` first — the paged layout makes
    this the same one-scatter-per-layer write decode uses), the
    longest draft prefix matching the target's own greedy tokens is
    accepted, and ``tokens[:, :n_emit]`` is the emitted stream —
    accepted drafts followed by the target's bonus/correction token,
    i.e. ALWAYS the target's greedy stream (a bad draft costs
    speculation upside, never output correctness; ``n_emit`` is in
    ``[1, k+1]``).

    Accept/reject is the length rollback the paged cache was built
    for: lengths advance by ``n_emit`` (``kv_cache.advance_by``), so
    the rejected tail's rows go dead-by-mask — pages were reserved at
    admission, nothing is released device-side, and the page-table
    rows are untouched.  Greedy-only in this round: rejection-sampled
    verification for temperature > 0 needs the draft DISTRIBUTION,
    which the drafter protocol does not carry yet.
    """
    if k < 1:
        raise ValueError(f"speculative verify needs k >= 1, got {k}")
    if not sampling.is_greedy:
        raise ValueError(
            "speculative verify is greedy-only (acceptance compares "
            "drafts against argmax; rejection sampling for "
            "temperature > 0 needs draft probabilities the drafter "
            "protocol does not carry)")

    def verify_fn(cache, params, slab, active, key, step):
        with obs.named_scope("apex_verify_forward"):
            logits, cache = models.verify_forward(kind, cfg, params,
                                                  cache, slab, tp=tp)
        with obs.named_scope("apex_verify_accept"):
            toks = greedy(logits.astype(jnp.float32))    # [slots, k+1]
            match = (toks[:, :-1] == slab[:, 1:]).astype(jnp.int32)
            # leading-match count: cumprod zeroes everything after the
            # first mismatch
            n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
            n_emit = (n_acc + 1).astype(jnp.int32)
        with obs.named_scope("apex_verify_advance"):
            cache, truncated = kv_cache.advance_by(cache, active,
                                                   n_emit)
        return cache, toks, n_emit, truncated

    return verify_fn


def prefill_bucket(n: int, max_seq: int, min_bucket: int = 64) -> int:
    """Smallest power-of-two bucket >= n (clamped to max_seq): prompts
    pad up to it so the prefill executable count stays O(log max_seq)."""
    if n < 1 or n > max_seq:
        raise ValueError(f"prompt length {n} outside [1, {max_seq}]")
    b = min_bucket
    while b < n:
        b *= 2
    return min(b, max_seq)


class PendingSwapOut:
    """In-flight device→host page drain (ISSUE 19): the batched
    gather dispatches have been issued but the blocking
    ``device_get``\\ s have not run yet.  ``resolve()`` fetches (once;
    idempotent) and returns the concatenated ``(k, v)`` numpy slabs.
    Safe to defer across later cache mutations: each batch's output is
    a fresh device buffer, not a view of the (donated) cache."""
    __slots__ = ("_batches", "_resolved")

    def __init__(self, batches):
        self._batches = batches        # [(k_dev, v_dev, valid_rows)]
        self._resolved = None

    @property
    def done(self) -> bool:
        """True once :meth:`resolve` has fetched (the wave-boundary
        drain or a racing hit already paid the ``device_get``)."""
        return self._resolved is not None

    def resolve(self):
        if self._resolved is None:
            ks = [np.asarray(jax.device_get(k_s))[:m]
                  for k_s, _, m in self._batches]
            vs = [np.asarray(jax.device_get(v_s))[:m]
                  for _, v_s, m in self._batches]
            self._resolved = (np.concatenate(ks, axis=0),
                              np.concatenate(vs, axis=0))
            self._batches = None       # free the device buffers
        return self._resolved


class InferenceEngine:
    """Serving engine over a standalone GPT/LLaMA/BERT — single-chip by
    default, tensor-parallel over a ``tp``-wide mesh on request.

    Static shape contract: ``slots`` concurrent sequences, each with a
    ``max_seq``-deep cache line, decode always batched over every slot.
    The host-side request plumbing lives in
    :class:`apex_tpu.inference.scheduler.SlotScheduler`; this class owns
    the device programs and the cache geometry.

    Tensor-parallel serving (ISSUE 17): ``tp=N`` (or
    ``APEX_TPU_SERVE_TP``) shards the param mirrors column/row-wise and
    the paged kv pool over kv heads across a private one-axis mesh
    (:func:`~apex_tpu.transformer.parallel_state.serving_mesh`) — a
    model whose dense mirrors + pool exceed one chip's HBM serves from
    ``tp`` chips at ~1/tp the per-chip footprint and compute.  Each
    step stays ONE donated executable (now a mesh program); the page
    table, allocator, prefix cache, and COW barrier are replicated and
    byte-identical to single-chip, so the scheduler never changes.
    Requires the paged cache and a generative model; per-slot outputs
    are replica-uniform and match the single-chip engine."""

    def __init__(self, kind: str, cfg, params, *, slots: int = 4,
                 max_seq: Optional[int] = None, dtype=None,
                 cache_dtype=jnp.bfloat16,
                 sampling: SamplingConfig = SamplingConfig(),
                 seed: int = 0, paged: bool = False,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 paged_attn_max_pages: Optional[int] = None,
                 decode_fusion=None, fusion_min_pages=None,
                 spec_k: Optional[int] = None,
                 tp: Optional[int] = None,
                 host_tier_bytes: Optional[int] = None,
                 swap_batch_pages: Optional[int] = None):
        if kind not in ("gpt", "llama", "bert"):
            raise ValueError(f"unknown model kind {kind!r}")
        if kind != "bert":
            models.check_supported(kind, cfg)
        self.kind, self.cfg = kind, cfg
        self.slots = int(slots)
        self.max_seq = min(int(max_seq or cfg.max_seq_length),
                           cfg.max_seq_length)
        self.cache_dtype = cache_dtype
        self.sampling = sampling
        # paged mode (ISSUE 6): HBM bounded by the page POOL, not by
        # slots * max_seq — any paged kwarg opts in
        self.paged = bool(paged or page_size is not None
                          or num_pages is not None)
        if kind == "bert" and self.paged:
            raise ValueError("BERT is the encode-only path (no KV "
                             "cache); paged kwargs do not apply")
        if self.paged:
            self.page_size = int(page_size if page_size is not None
                                 else kv_cache.default_page_size())
            if self.page_size < 1 or (self.page_size &
                                      (self.page_size - 1)):
                raise ValueError(
                    f"page_size must be a positive power of two (so "
                    f"prefill buckets tile into whole pages), got "
                    f"{self.page_size}")
            if self.max_seq % self.page_size:
                raise ValueError(
                    f"max_seq ({self.max_seq}) must be a multiple of "
                    f"page_size ({self.page_size})")
            self.max_pages_per_slot = self.max_seq // self.page_size
            # default pool = dense-equivalent capacity; size it SMALLER
            # (the point of paging) to bound HBM by expected load
            self.num_pages = int(
                num_pages if num_pages is not None
                else self.slots * self.max_pages_per_slot)
            if self.num_pages < 1:
                raise ValueError(
                    f"num_pages must be >= 1, got {self.num_pages}")
            self.paged_attn_max_pages = paged_attn_max_pages
            # host-DRAM page tier (ISSUE 18): explicit kwargs win, else
            # the registered env knobs; 0 bytes = tier off (eviction
            # discards, the pre-tier behavior)
            self.host_tier_bytes = int(
                host_tier_bytes if host_tier_bytes is not None
                else host_kv_tier_bytes())
            if self.host_tier_bytes < 0:
                raise ValueError(
                    f"host_tier_bytes must be >= 0, got "
                    f"{self.host_tier_bytes}")
            self.swap_batch_pages = int(
                swap_batch_pages if swap_batch_pages is not None
                else kv_cache.default_swap_batch_pages())
            if self.swap_batch_pages < 1:
                raise ValueError(
                    f"swap_batch_pages must be >= 1, got "
                    f"{self.swap_batch_pages}")
        else:
            if host_tier_bytes:
                raise ValueError(
                    "host_tier_bytes is the paged-mode host page tier; "
                    "this engine runs the dense slot cache")
            self.page_size = self.num_pages = None
            self.max_pages_per_slot = None
            self.paged_attn_max_pages = None
            self.host_tier_bytes = 0
            self.swap_batch_pages = None
        # tensor-parallel serving width (ISSUE 17): explicit kwarg wins,
        # else APEX_TPU_SERVE_TP, else single chip
        self.tp = int(tp) if tp is not None else serve_tp()
        if self.tp < 1:
            raise ValueError(f"tp must be >= 1, got {self.tp}")
        if self.tp > 1:
            if kind == "bert":
                raise ValueError(
                    "tensor-parallel serving is a generative-path "
                    "feature; BERT is the encode-only path")
            if not self.paged:
                raise ValueError(
                    "tensor-parallel serving shards the PAGED kv pool "
                    "over kv heads — pass page_size=/num_pages= (the "
                    "dense slot cache does not shard)")
        if dtype is not None:
            from apex_tpu.optimizers.functional import _cast_floating
            params = _cast_floating(params, dtype)
        self.params = params
        self._key = jax.random.PRNGKey(seed)
        self._step = 0
        # dispatch counters are GLOBAL-registry families (engine-level,
        # process-wide — per-wave serving metrics live in the
        # scheduler's ServeTelemetry registry); cached so declared()'s
        # lock + schema lookup is not per-token work, re-resolved on
        # registry identity so reset_global_registry() can't orphan them
        self._tel_registry = None
        self._refresh_dispatch_counters()
        if kind == "bert":
            # resolve the spelling so every fusion-off value ("0",
            # "off", "false", and "auto" — which can only resolve
            # unfused on a cache-less engine) passes; only an explicit
            # fusion-ON request is a configuration error here
            if spec_k or (decode_fusion is not None
                          and resolve_fusion_mode(decode_fusion) == "1"):
                raise ValueError("speculative decoding / fused-block "
                                 "decode are generative-path features; "
                                 "BERT is the encode-only path")
            self.spec_k = 0
            self.decode_fused = False
            self._encode = jax.jit(self._make_bert_encode())
        else:
            self.dims = models.model_dims(kind, cfg)
            # tensor-parallel serving (ISSUE 17): validate the geometry
            # up front (tp | heads; tp | kvh or kvh | tp), build the
            # private one-axis serving mesh, and expand GQA/MQA kv
            # heads below tp in the SERVED mirrors so the plain column
            # shard hands every rank the kv head its query group reads
            self.tp_dims = models.tp_dims(kind, cfg, self.tp)
            self._param_specs = self._fused_specs = None
            self._cache_specs = None
            if self.tp > 1:
                self.mesh = serving_mesh(self.tp)
                self.params = models.expand_kv_for_tp(
                    kind, cfg, self.params, self.tp)
            else:
                self.mesh = None
            # fused-block decode (ISSUE 15): resolved STATICALLY here —
            # the knob selects which of two lowerings the ONE decode
            # executable compiles, never a per-step branch.  The fused
            # layout is a one-time device-side re-copy of the layer
            # weights (prefill keeps the original tree) — HBM for
            # decode latency, documented beside the knob.
            self.decode_fused = resolve_decode_fusion(
                decode_fusion, paged=self.paged,
                max_pages=self.max_pages_per_slot,
                min_pages=fusion_min_pages)
            self._fused_layers = (
                models.fused_layer_params(kind, cfg, self.params)
                if self.decode_fused else None)
            if self.tp > 1:
                self._place_tp_mirrors()
            P, cs, ps = PartitionSpec, self._cache_specs, self._param_specs
            # the _raw fns are the exact (shard_map-wrapped at tp > 1)
            # step bodies the jits below compile — the SPMD audits
            # trace THESE, so the audited program is the served one
            self._prefill_raw = self._tp_wrap(
                make_prefill_fn(kind, cfg, sampling, paged=self.paged,
                                tp=self.tp),
                in_specs=(cs, ps) + (P(),) * (7 if self.paged else 5),
                out_specs=(cs, P(), P()))
            self._prefill = jax.jit(self._prefill_raw,
                                    donate_argnums=(0,))
            dps = ((ps, self._fused_specs) if self.decode_fused else ps)
            self._decode_raw = self._tp_wrap(
                make_decode_fn(kind, cfg, sampling,
                               fused=self.decode_fused, tp=self.tp),
                in_specs=(cs, dps, P(), P(), P(), P()),
                out_specs=(cs, P(), P(), P()))
            self._decode = jax.jit(self._decode_raw, donate_argnums=(0,))
            # speculative decoding (ISSUE 15): ONE verify executable
            # per (k, engine) — the slab width is static
            self.spec_k = int(spec_k if spec_k is not None
                              else default_spec_k())
            if self.spec_k:
                self._verify_raw = self._tp_wrap(
                    make_verify_fn(kind, cfg, sampling, self.spec_k,
                                   tp=self.tp),
                    in_specs=(cs, ps, P(), P(), P(), P()),
                    out_specs=(cs, P(), P(), P()))
                self._verify = jax.jit(self._verify_raw,
                                       donate_argnums=(0,))
            else:
                self._verify_raw = self._verify = None
            if self.paged:
                # the COW write barrier (ISSUE 12): one donated page
                # copy, compiled once, dispatched only when a slot must
                # privatize a page it still shares
                self._cow_raw = self._tp_wrap(
                    kv_cache.cow_page, in_specs=(cs, P(), P()),
                    out_specs=cs)
                self._cow = jax.jit(self._cow_raw, donate_argnums=(0,))
                # the host-tier swap copy programs (ISSUE 18): one
                # gather out, one scatter in, each compiled ONCE at the
                # static swap batch width (page-ID vectors pad to it).
                # The slab spec mirrors the k/v pool spec — under tp
                # each rank moves its own kv-head shard; device_get of
                # the sharded slab assembles the global page host-side.
                sb = cs.k if self.tp > 1 else None
                self._swap_out_raw = self._tp_wrap(
                    kv_cache.extract_pages, in_specs=(cs, P()),
                    out_specs=(sb, sb))
                # NOT donated: extract is a pure read — the pool stays
                # live (eviction is host-side bookkeeping)
                self._swap_out = jax.jit(self._swap_out_raw)
                self._swap_in_raw = self._tp_wrap(
                    kv_cache.restore_pages,
                    in_specs=(cs, P(), sb, sb), out_specs=cs)
                self._swap_in = jax.jit(self._swap_in_raw,
                                        donate_argnums=(0,))

    def _refresh_dispatch_counters(self) -> None:
        reg = obs.global_registry()
        if reg is not self._tel_registry:
            self._tel_registry = reg
            self._prefill_dispatches = reg.declared(
                "infer_prefill_dispatch_total")
            self._decode_dispatches = reg.declared(
                "infer_decode_dispatch_total")
            self._cow_dispatches = reg.declared(
                "infer_cow_dispatch_total")
            self._fused_decode_dispatches = reg.declared(
                "infer_decode_fused_dispatch_total")
            self._verify_dispatches = reg.declared(
                "infer_verify_dispatch_total")
            self._swap_out_dispatches = reg.declared(
                "infer_swap_out_dispatch_total")
            self._swap_in_dispatches = reg.declared(
                "infer_swap_in_dispatch_total")

    # -- tensor-parallel serving (ISSUE 17) ----------------------------------
    def _tp_wrap(self, fn, *, in_specs, out_specs):
        """Per-rank step body -> mesh program: ``shard_map`` over the
        serving mesh's tensor axis.  tp=1 returns ``fn`` untouched, so
        the single-chip lowering stays bitwise the pre-TP engine.  The
        unjitted wrap is what the ``_*_raw`` attributes hold — the SPMD
        audits trace those, auditing the exact program served."""
        if self.tp == 1:
            return fn
        return jax.shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)

    def _place_tp_mirrors(self) -> None:
        """Column/row-partition the served mirrors onto the mesh: spec
        trees from :func:`models.param_partition_specs` /
        :func:`models.fused_partition_specs`, every leaf ``device_put``
        with its ``NamedSharding`` at construction so dispatch never
        reshards (the jitted steps see already-placed operands)."""
        mesh = self.mesh

        def put(tree, specs):
            return jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
                tree, specs)

        self._param_specs = models.param_partition_specs(
            self.kind, self.cfg, self.params, self.tp)
        self.params = put(self.params, self._param_specs)
        if self._fused_layers is not None:
            self._fused_specs = models.fused_partition_specs(
                self._fused_layers, self.tp)
            self._fused_layers = put(self._fused_layers,
                                     self._fused_specs)
        # page table / lengths / capacity replicated, k/v pool sharded
        # over the kv-head dim — the host-side allocator, prefix cache,
        # COW, and eviction logic never see the shard boundary
        self._cache_specs = kv_cache.paged_cache_partition_specs(
            attn_max_pages=self.paged_attn_max_pages)
        self._key = jax.device_put(
            self._key, NamedSharding(mesh, PartitionSpec()))

    # -- cache ---------------------------------------------------------------
    def init_cache(self):
        if self.kind == "bert":
            raise ValueError("BERT is the encode-only path (no KV "
                             "cache); use encode()")
        d = self.dims
        if self.paged:
            # under tp the GLOBAL pool carries kv_heads_pool heads
            # (kvh * rep — GQA/MQA replicate below tp); the k/v leaves
            # then shard over the kv-head dim, handing each rank
            # kv_heads_pool / tp heads of every page
            cache = kv_cache.init_paged_cache(
                self.num_pages, d["layers"],
                self.tp_dims["kv_heads_pool"],
                self.page_size, d["head_dim"], slots=self.slots,
                max_pages_per_slot=self.max_pages_per_slot,
                dtype=self.cache_dtype,
                attn_max_pages=self.paged_attn_max_pages)
            if self.tp > 1:
                cache = jax.tree_util.tree_map(
                    lambda x, s: jax.device_put(
                        x, NamedSharding(self.mesh, s)),
                    cache, self._cache_specs)
            return cache
        return kv_cache.init_cache(
            self.slots, d["layers"], d["kv_heads"], self.max_seq,
            d["head_dim"], dtype=self.cache_dtype)

    def new_allocator(self) -> kv_cache.PageAllocator:
        """Fresh host-side page allocator matching the engine's pool
        geometry (paged mode only) — one per cache lifetime; the
        scheduler owns it alongside its slot bookkeeping."""
        if not self.paged:
            raise ValueError("new_allocator() is the paged-mode page "
                             "bookkeeping; this engine runs the dense "
                             "slot cache")
        return kv_cache.PageAllocator(self.num_pages, self.page_size,
                                      self.max_pages_per_slot)

    def cache_hbm_bytes(self) -> int:
        """Bytes the KV cache pins in HBM: pool pages (paged, incl. the
        trash page) or slots x max_seq (dense).  Under tensor-parallel
        serving this is PER-RANK bytes — the pool shards over kv heads,
        so each chip pins ``kv_heads_pool / tp`` heads (= 1/tp of the
        tp-divisible pool; an MQA pool replicated below tp pins its one
        kv head per rank)."""
        d = self.dims
        itemsize = jnp.dtype(self.cache_dtype).itemsize
        kvh = self.tp_dims["kv_heads_pool"] // self.tp   # per-rank heads
        per_tok = 2 * d["layers"] * kvh * d["head_dim"] * itemsize
        if self.paged:
            return (self.num_pages + 1) * self.page_size * per_tok
        return self.slots * self.max_seq * per_tok

    # -- generative path -----------------------------------------------------
    def _next_step(self):
        # numpy scalar, not jnp: an eager jnp.asarray of a python int
        # compiles a throwaway convert program per call — a numpy
        # operand binds into the jitted step with no extra executable
        s = self._step
        self._step += 1
        return np.int32(s)

    def bucket_for(self, n: int) -> int:
        """The prefill bucket an ``n``-token prompt pads up to — the
        one place the bucket policy lives (prefill pads with it; the
        scheduler's padding-badput accounting reads it)."""
        min_bucket = max(64, self.page_size) if self.paged else 64
        return prefill_bucket(n, self.max_seq, min_bucket=min_bucket)

    def prefill(self, cache, tokens, slot, pages=None, prefill_from=0):
        """Admit one prompt into ``slot``: returns ``(cache, next_token,
        last_logits)``.  ``tokens`` is the UNPADDED prompt (list/array of
        ints); padding to the executable bucket happens here.

        Paged mode additionally takes ``pages`` — the FULL ordered
        page-ID list backing the prompt + decode headroom (shared
        prefix pages first on a prefix-cache hit, then the privately
        acquired suffix pages) — and ``prefill_from`` (ISSUE 12): how
        many leading prompt tokens are already cached in those pages.
        Only ``tokens[prefill_from:]`` runs the forward (padded to ITS
        bucket, so a short uncached tail rides a small executable),
        attending to the cached prefix through the page window; the
        bucket rounds up freely, positions beyond the reservation spill
        into the pool's trash page by construction.  ``prefill_from``
        is a traced operand — a hit admits with zero new compiles once
        the tail's bucket is warm."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = tokens.shape[0]
        start = int(prefill_from)
        if start < 0 or start >= n:
            raise ValueError(
                f"prefill_from ({start}) must be in [0, prompt length "
                f"{n}) — at least the last prompt token is always "
                f"prefilled (its logits seed the first sampled token)")
        if start and not self.paged:
            raise ValueError(
                "prefill_from needs the paged cache (prefix sharing is "
                "a page-table edit); this engine runs the dense slot "
                "cache")
        suffix = tokens[start:]
        bucket = self.bucket_for(suffix.shape[0])
        padded = np.zeros((bucket,), np.int32)
        padded[:suffix.shape[0]] = suffix
        if self.paged:
            if pages is None:
                raise ValueError(
                    "paged prefill needs the slot's reserved page IDs "
                    "(engine.new_allocator().acquire(...)); the "
                    "scheduler threads them automatically")
            if len(pages) * self.page_size < n:
                raise ValueError(
                    f"reservation of {len(pages)} page(s) x "
                    f"{self.page_size} covers {len(pages) * self.page_size}"
                    f" tokens < the {n}-token prompt — the prompt tail "
                    f"would silently land in the trash page; reserve "
                    f"ceil((prompt + max_new_tokens) / page_size) pages")
            row = kv_cache.page_row(pages, self.max_pages_per_slot,
                                    self.num_pages)
            args = (cache, self.params, padded, np.int32(slot),
                    np.int32(n), row, np.int32(start))
        else:
            args = (cache, self.params, padded, np.int32(slot),
                    np.int32(n))
        # counted AFTER validation: a rejected reservation raised above
        # and dispatched nothing.  The annotation metadata (slot, the
        # chunk origin) lets an xprof capture line up each dispatch
        # with the request tracer's prefill_chunk spans (ISSUE 13).
        self._refresh_dispatch_counters()
        self._prefill_dispatches.inc()
        with obs.trace_annotation("apex_tpu.inference.prefill",
                                  slot=int(slot), prefill_from=start):
            return self._prefill(*args, self._key, self._next_step())

    def cow_page(self, cache, src, dst):
        """Copy-on-write page duplication (paged mode): copy physical
        page ``src`` into ``dst`` and return the cache.  The write
        barrier of the sharing contract — the scheduler calls this
        before a slot writes into a page it still shares (the partial
        boundary page of an unaligned prefix-cache hit), pointing the
        slot's row at ``dst`` in the prefill that follows.  ``src`` and
        ``dst`` are traced int32, so every COW rides ONE compiled copy
        program for the engine's lifetime."""
        if not self.paged:
            raise ValueError("cow_page is the paged-mode write barrier; "
                             "this engine runs the dense slot cache")
        self._refresh_dispatch_counters()
        self._cow_dispatches.inc()
        with obs.trace_annotation("apex_tpu.inference.cow_page",
                                  src=int(src), dst=int(dst)):
            return self._cow(cache, np.int32(src), np.int32(dst))

    def evict_slot(self, cache, slot: int):
        """Device-side metadata evict of one slot (paged or dense):
        zero its length and re-park its page-table row on the trash
        page so the idle slot's masked decode appends can never land
        in another request's pages.  The retire half of the engine's
        device surface — the scheduler releases the slot's page
        REFERENCES host-side only after this returns, so a stub engine
        (protocol audit) can mirror the whole lifecycle without a
        device."""
        return kv_cache.evict(cache, slot)

    def page_host_bytes(self) -> int:
        """Host-DRAM bytes ONE page's k+v slabs occupy in the host
        tier.  GLOBAL geometry even under tensor parallelism: swap-out
        ``device_get``\\ s the sharded slab into the full kv-head dim,
        so the host books (like the page table) are rank-invariant."""
        if not self.paged:
            raise ValueError("page_host_bytes is the paged-mode host "
                             "tier ledger; this engine runs the dense "
                             "slot cache")
        d = self.dims
        itemsize = jnp.dtype(self.cache_dtype).itemsize
        return (2 * d["layers"] * self.tp_dims["kv_heads_pool"]
                * self.page_size * d["head_dim"] * itemsize)

    def swap_out_pages(self, cache, page_ids, defer: bool = False):
        """Copy physical pages ``page_ids`` device→host (ISSUE 18
        eviction offload): returns ``(k, v)`` numpy slabs
        ``[n, layers, kv_heads, page_size, head_dim]``.  Pure read —
        the cache operand stays valid (the HBM pages return to the
        free list host-side).  Batches of ``swap_batch_pages`` are
        dispatched back-to-back (short batches pad with the trash
        page) and fetched only after the LAST dispatch, so the
        device-side gathers pipeline ahead of the host copies; every
        batch rides the ONE compiled extract program.

        ``defer=True`` (ISSUE 19) skips the fetch entirely and returns
        a :class:`PendingSwapOut` instead: the gathers are dispatched
        NOW (into fresh output buffers, so later cache donations
        cannot disturb them) but the blocking ``device_get``\\ s run
        only at ``resolve()`` — the scheduler drains them at the next
        wave boundary instead of stalling the eviction path."""
        if not self.paged:
            raise ValueError("swap_out_pages is the paged-mode host "
                             "tier; this engine runs the dense slot "
                             "cache")
        ids = np.asarray(page_ids, np.int32).reshape(-1)
        n, B = ids.shape[0], self.swap_batch_pages
        if n == 0:
            raise ValueError("swap_out_pages needs at least one page")
        self._refresh_dispatch_counters()
        pending = []
        with obs.trace_annotation("apex_tpu.inference.swap_out",
                                  pages=int(n)):
            for i in range(0, n, B):
                chunk = ids[i:i + B]
                padded = np.full((B,), self.num_pages, np.int32)
                padded[:chunk.shape[0]] = chunk
                self._swap_out_dispatches.inc()
                k_s, v_s = self._swap_out(cache, padded)
                pending.append((k_s, v_s, chunk.shape[0]))
            if defer:
                return PendingSwapOut(pending)
        return PendingSwapOut(pending).resolve()

    def swap_in_pages(self, cache, page_ids, k_slabs, v_slabs):
        """Upload host-tier page slabs back into freshly acquired
        physical pages ``page_ids`` (ISSUE 18 hit-after-eviction):
        returns the cache.  The inverse of :meth:`swap_out_pages` —
        batches pad short with an OUT-OF-BOUNDS page index (dropped by
        the scatter) and zero slabs, so every batch rides the ONE
        compiled restore program; the cache is donated through each
        dispatch like every other mutation.  The scheduler calls this
        BEFORE the uncached tail's first prefill chunk, so uploads
        overlap the tail's compute in the dispatch queue."""
        if not self.paged:
            raise ValueError("swap_in_pages is the paged-mode host "
                             "tier; this engine runs the dense slot "
                             "cache")
        ids = np.asarray(page_ids, np.int32).reshape(-1)
        n, B = ids.shape[0], self.swap_batch_pages
        k_slabs = np.asarray(k_slabs)
        v_slabs = np.asarray(v_slabs)
        if n == 0:
            raise ValueError("swap_in_pages needs at least one page")
        if k_slabs.shape[0] != n or v_slabs.shape[0] != n:
            raise ValueError(
                f"swap-in slabs must carry one entry per page id "
                f"({n}), got k {k_slabs.shape[0]} v {v_slabs.shape[0]}")
        self._refresh_dispatch_counters()
        oob = np.int32(self.num_pages + 1)   # >= cache.pages -> dropped
        with obs.trace_annotation("apex_tpu.inference.swap_in",
                                  pages=int(n)):
            for i in range(0, n, B):
                chunk = ids[i:i + B]
                m = chunk.shape[0]
                padded = np.full((B,), oob, np.int32)
                padded[:m] = chunk
                pk = np.zeros((B,) + k_slabs.shape[1:], k_slabs.dtype)
                pv = np.zeros((B,) + v_slabs.shape[1:], v_slabs.dtype)
                pk[:m] = k_slabs[i:i + B]
                pv[:m] = v_slabs[i:i + B]
                self._swap_in_dispatches.inc()
                cache = self._swap_in(cache, padded, pk, pv)
        return cache

    def decode(self, cache, last_tokens, active=None):
        """One token for every slot: returns ``(cache, next_tokens,
        logits, truncated)``; only ``active`` slots advance their cache
        length.

        Capacity contract: a slot whose length has reached its capacity
        (``max_seq`` dense; its page reservation paged) must be retired
        (deactivated) by the caller before further steps — the
        scheduler tracks this host-side from prompt/output lengths.
        Past capacity the cache clamps (see :func:`kv_cache.advance`)
        rather than corrupting earlier rows, and the returned
        ``truncated`` vector flags every active slot whose token was
        dropped by that clamp so no caller can miss it.
        """
        if active is None:
            active = np.ones((self.slots,), bool)
        self._refresh_dispatch_counters()
        self._decode_dispatches.inc()
        if self.decode_fused:
            self._fused_decode_dispatches.inc()
        params = ((self.params, self._fused_layers) if self.decode_fused
                  else self.params)
        with obs.trace_annotation("apex_tpu.inference.decode"):
            return self._decode(cache, params,
                                np.asarray(last_tokens, np.int32),
                                np.asarray(active, bool),
                                self._key, self._next_step())

    def verify(self, cache, slab, active=None):
        """One speculative-verify step (ISSUE 15): ``slab [slots,
        spec_k + 1]`` (column 0 = each slot's last confirmed token,
        the rest drafts) -> ``(cache, tokens [slots, spec_k + 1],
        n_emit [slots], truncated)``.  ``tokens[:, :n_emit]`` per slot
        is the emitted stream — the target's own greedy continuation
        (accepted drafts + bonus token); lengths advanced by
        ``n_emit`` in-program (the accept/reject rollback).  The same
        capacity contract as :meth:`decode`: the caller clamps emitted
        tokens to the slot's remaining capacity and retires truncated
        slots."""
        if not self.spec_k:
            raise ValueError(
                "speculative decoding is off for this engine; build it "
                "with spec_k > 0 (or APEX_TPU_SPEC_K)")
        slab = np.asarray(slab, np.int32)
        if slab.shape != (self.slots, self.spec_k + 1):
            raise ValueError(
                f"verify slab must be [{self.slots}, "
                f"{self.spec_k + 1}] (last token + {self.spec_k} "
                f"drafts), got {tuple(slab.shape)}")
        if active is None:
            active = np.ones((self.slots,), bool)
        self._refresh_dispatch_counters()
        self._verify_dispatches.inc()
        with obs.trace_annotation("apex_tpu.inference.verify",
                                  k=self.spec_k):
            return self._verify(cache, self.params, slab,
                                np.asarray(active, bool),
                                self._key, self._next_step())

    def generate(self, prompts, max_new_tokens: int = 16,
                 eos_id: Optional[int] = None):
        """Convenience wrapper over the continuous-batching scheduler:
        ``prompts`` (list of token lists) -> list of generated token
        lists, in submission order."""
        from apex_tpu.inference import scheduler
        return scheduler.generate(self, prompts,
                                  max_new_tokens=max_new_tokens,
                                  eos_id=eos_id)

    # -- encode-only path (BERT) --------------------------------------------
    def _make_bert_encode(self):
        from apex_tpu.transformer.testing import bert_model_provider
        model = bert_model_provider(self.cfg, add_binary_head=False)

        def encode(params, tokens, token_types):
            return model.apply(params, tokens, token_types)

        return encode

    def encode(self, tokens, token_types=None):
        """BERT path: one bidirectional forward, logits out."""
        if self.kind != "bert":
            raise ValueError("encode() is the BERT path; use "
                             "prefill()/decode() for generative models")
        tokens = jnp.asarray(tokens, jnp.int32)
        if token_types is None:
            token_types = jnp.zeros(tokens.shape, jnp.int32)
        return self._encode(self.params, tokens, token_types)

    # -- checkpoint boundaries ----------------------------------------------
    @classmethod
    def from_train_state(cls, kind: str, cfg, state, *,
                         dtype=jnp.bfloat16, **kwargs):
        """Build from a :class:`~apex_tpu.train_step.TrainState` (or bare
        ``FlatState``): weights export in ``dtype`` (bf16 serving
        default) via ``FlatState.params(dtype=...)`` — a ZeRO-sharded
        state all-gathers its master, so a checkpoint written at any dp
        restores straight into the engine."""
        opt = getattr(state, "opt", state)
        return cls(kind, cfg, opt.params(dtype=dtype), **kwargs)

    @classmethod
    def from_state_dict(cls, kind: str, cfg, sd, params_template, *,
                        dtype=jnp.bfloat16, **kwargs):
        """Build from a contrib ``DistributedFused*`` shard-aware
        ``state_dict`` (the reassembled full flat master) plus the model
        param template that defines the leaf layout."""
        from apex_tpu.optimizers.functional import export_params
        params = export_params(sd["master"], params_template, dtype=dtype)
        return cls(kind, cfg, params, **kwargs)
