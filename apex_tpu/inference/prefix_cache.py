"""Host-side prefix cache: a radix tree over token ids mapping cached
prompt prefixes to KV page lists (ISSUE 12).

Serving traffic is dominated by shared prompt prefixes — system
prompts, few-shot templates, multi-turn history.  The paged layout
(ISSUE 6) makes reusing them a TABLE-ROW EDIT: page-table indirection
means N requests can point at ONE physical copy of the prefix's pages,
so this cache only has to answer, host-side, "which already-filled
pages cover a prefix of this prompt?"  The device needs no new
executables.

Structure (the SGLang-style radix tree, at PAGE granularity):

* Each FULL-PAGE edge is keyed by its ``page_size`` token ids and
  carries the physical page holding those tokens' k/v.  Walking edges
  from the root yields the longest cached page-aligned prefix.
* A node may additionally hold PARTIAL-TAIL edges (< ``page_size``
  tokens): the unaligned tail of a cached prompt.  At the walk's
  boundary the longest common prefix against any outgoing edge adds
  sub-page coverage — the rows past the match are masked by the
  consumer (``prefix_window_attention`` masks columns ``>= start``),
  so partially matching pages are safely reusable.

Reference counting: the cache holds ONE reference
(:meth:`~apex_tpu.inference.kv_cache.PageAllocator.share`) on every
page it indexes, so cached pages survive their original request's
retirement; :meth:`evict_lru` releases references leaf-first in
least-recently-matched order when the scheduler needs pages back —
BACKPRESSURE drives eviction, never a mid-request free.

The cache never touches the device: matching and insertion are pure
host bookkeeping over ints, performed at the admission points the
scheduler already occupies.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from apex_tpu.inference.kv_cache import PageAllocator

__all__ = ["PrefixCache", "prefix_cache_enabled"]

_PREFIX_CACHE_ENV = "APEX_TPU_PREFIX_CACHE"


def prefix_cache_enabled() -> bool:
    """``APEX_TPU_PREFIX_CACHE``: prefix caching for paged schedulers —
    on by default (sharing is functionally transparent); ``0`` disables
    matching AND insertion (every admission prefills cold)."""
    env = os.environ.get(_PREFIX_CACHE_ENV)
    if env is None:
        return True
    return env.strip() not in ("0", "", "false", "False")


class _Edge:
    """One cached page: the tokens it holds, the physical page id, the
    LRU stamp, and (full-page edges only) the child node continuing the
    prefix."""
    __slots__ = ("page", "child", "stamp")

    def __init__(self, page: int, child: Optional["_Node"], stamp: int):
        self.page = page
        self.child = child
        self.stamp = stamp


class _Node:
    __slots__ = ("children", "partials")

    def __init__(self):
        self.children: Dict[Tuple[int, ...], _Edge] = {}   # ps-token edges
        self.partials: Dict[Tuple[int, ...], _Edge] = {}   # sub-page tails


def _lcp(a: Tuple[int, ...], b: Sequence[int]) -> int:
    n = min(len(a), len(b))
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i


class PrefixCache:
    """Radix tree ``token ids -> page list`` over one
    :class:`~apex_tpu.inference.kv_cache.PageAllocator`'s pages.

    ``min_hit_tokens`` (default ``page_size``) is the smallest coverage
    reported as a hit: sharing less than one page's worth of prefix
    costs a COW copy for near-zero compute savings, so sub-page
    accidental overlaps stay cold.
    """

    def __init__(self, allocator: PageAllocator,
                 min_hit_tokens: Optional[int] = None):
        self._alloc = allocator
        self.page_size = allocator.page_size
        self.min_hit_tokens = (self.page_size if min_hit_tokens is None
                               else int(min_hit_tokens))
        self._root = _Node()
        self._clock = 0
        self.pinned_pages = 0          # pages this cache holds a ref on
        self.evictions = 0             # entries released by evict_lru

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    # -- lookup --------------------------------------------------------------
    def match(self, tokens: Sequence[int]) -> Tuple[int, List[int]]:
        """Longest cached prefix of ``tokens``: ``(covered_tokens,
        pages)`` with ``pages`` covering ``ceil(covered / page_size)``
        physical pages (the last one possibly partial — its rows past
        the coverage are masked by the consumer).  Coverage below
        ``min_hit_tokens`` reports a miss ``(0, [])``.  Matched edges
        are LRU-touched."""
        toks = [int(t) for t in tokens]
        ps = self.page_size
        node, pages, c = self._root, [], 0
        path: List[_Edge] = []
        while len(toks) - c >= ps:
            edge = node.children.get(tuple(toks[c:c + ps]))
            if edge is None:
                break
            path.append(edge)
            pages.append(edge.page)
            c += ps
            node = edge.child
        # boundary: best sub-page overlap against any outgoing edge
        rest = toks[c:]
        best, best_edge = 0, None
        if rest:
            for et, edge in list(node.children.items()) \
                    + list(node.partials.items()):
                n = _lcp(et, rest)
                if n > best:
                    best, best_edge = n, edge
        if best_edge is not None:
            path.append(best_edge)
            pages.append(best_edge.page)
            c += best
        if c < self.min_hit_tokens:
            return 0, []
        stamp = self._tick()
        for edge in path:
            edge.stamp = stamp
        return c, pages

    # -- insertion -----------------------------------------------------------
    def insert(self, tokens: Sequence[int], pages: Sequence[int]) -> int:
        """Register a prefilled prompt: ``pages`` are the physical
        pages backing ``tokens`` in order (``ceil(len(tokens) /
        page_size)`` of them).  New edges take one allocator reference
        per page (the cache's own pin); edges already present are
        deduplicated — the newcomer's identical private pages simply
        stay uncached and die with their request.  Returns the number
        of pages newly pinned."""
        toks = [int(t) for t in tokens]
        ps = self.page_size
        full = len(toks) // ps
        if len(pages) < full + (1 if len(toks) % ps else 0):
            raise ValueError(
                f"{len(pages)} pages cannot back {len(toks)} tokens at "
                f"page size {ps}")
        stamp = self._tick()
        node, new = self._root, 0
        for j in range(full):
            et = tuple(toks[j * ps:(j + 1) * ps])
            edge = node.children.get(et)
            if edge is None:
                self._alloc.share([pages[j]])
                new += 1
                edge = _Edge(int(pages[j]), _Node(), stamp)
                node.children[et] = edge
            edge.stamp = stamp
            node = edge.child
        tail = tuple(toks[full * ps:])
        if tail:
            edge = node.partials.get(tail)
            if edge is None:
                self._alloc.share([pages[full]])
                new += 1
                node.partials[tail] = _Edge(int(pages[full]), None, stamp)
            else:
                edge.stamp = stamp
        self.pinned_pages += new
        return new

    # -- eviction ------------------------------------------------------------
    def _evictable(self):
        """Yield ``(stamp, parent_dict, key)`` for every leaf edge: any
        partial tail, and any full-page edge whose child continues
        nothing — interior pages stay until their subtree drains."""
        out = []

        def walk(node: _Node):
            for key, edge in node.partials.items():
                out.append((edge.stamp, node.partials, key))
            for key, edge in node.children.items():
                child = edge.child
                if not child.children and not child.partials:
                    out.append((edge.stamp, node.children, key))
                else:
                    walk(child)

        walk(self._root)
        return out

    def evict_lru(self, pages_wanted: int) -> int:
        """Release cached references, least-recently-matched leaves
        first, until ``pages_wanted`` pages have RETURNED to the free
        list (a released page still shared by a live request frees
        nothing, so eviction keeps going) or the cache is empty.
        Returns the number of pages actually freed.

        One tree walk evicts a whole BATCH of leaves (oldest first);
        the tree is re-walked only when the batch is exhausted (popping
        a leaf can turn its parent into a leaf) — O(leaves) per level
        instead of a full walk per evicted page."""
        freed0 = self._alloc.free_pages

        def short():
            return self._alloc.free_pages - freed0 >= pages_wanted

        while not short():
            leaves = sorted(self._evictable(), key=lambda t: t[0])
            if not leaves:
                break
            for _, parent, key in leaves:
                if short():
                    break
                edge = parent.pop(key)
                self._alloc.release([edge.page])
                self.pinned_pages -= 1
                self.evictions += 1
        return self._alloc.free_pages - freed0

    def clear(self) -> None:
        """Release every cached reference (cache teardown)."""
        def walk(node: _Node):
            for edge in node.partials.values():
                self._alloc.release([edge.page])
            for edge in node.children.values():
                self._alloc.release([edge.page])
                walk(edge.child)

        walk(self._root)
        self._root = _Node()
        self.pinned_pages = 0
