"""Host-side prefix cache: a radix tree over token ids mapping cached
prompt prefixes to KV page lists (ISSUE 12), with a host-DRAM second
tier under the HBM pool (ISSUE 18).

Serving traffic is dominated by shared prompt prefixes — system
prompts, few-shot templates, multi-turn history.  The paged layout
(ISSUE 6) makes reusing them a TABLE-ROW EDIT: page-table indirection
means N requests can point at ONE physical copy of the prefix's pages,
so this cache only has to answer, host-side, "which already-filled
pages cover a prefix of this prompt?"  The device needs no new
executables for SHARING; the host tier adds exactly two (the swap
copy programs in :mod:`~apex_tpu.inference.kv_cache`).

Structure (the SGLang-style radix tree, at PAGE granularity):

* Each FULL-PAGE edge is keyed by its ``page_size`` token ids and
  carries the physical page holding those tokens' k/v.  Walking edges
  from the root yields the longest cached page-aligned prefix.
* A node may additionally hold PARTIAL-TAIL edges (< ``page_size``
  tokens): the unaligned tail of a cached prompt.  At the walk's
  boundary the longest common prefix against any outgoing edge adds
  sub-page coverage — the rows past the match are masked by the
  consumer (``prefix_window_attention`` masks columns ``>= start``),
  so partially matching pages are safely reusable.

Two-state edges (ISSUE 18): a full-page edge is either HBM-resident
(``page`` set, ``host`` None — the cache holds one allocator ref) or
HOST-resident (``page`` None, ``host`` = a
:class:`~apex_tpu.inference.kv_cache.HostPageStore` handle — the HBM
ref was released at eviction, the content lives in host DRAM).  LRU
eviction under backpressure OFFLOADS full pages device→host instead of
discarding them, so the next hit pays batched page uploads, not
recompute; the host tier has its own byte budget and its own LRU
(true-leaf host edges drop when the budget fills).  Partial-tail edges
are never offloaded — sub-page recompute is cheaper than a swap.
Tier structure invariant: an HBM edge only transitions to host once
its subtree holds no HBM pages, and :meth:`insert` resurrects host
edges along its walk, so below a host edge EVERY edge is host — the
host-tier LRU always finds a true leaf to drop.

Reference counting: the cache holds ONE reference
(:meth:`~apex_tpu.inference.kv_cache.PageAllocator.share`) on every
HBM page it indexes, so cached pages survive their original request's
retirement; :meth:`evict_lru` releases references leaf-first in
least-recently-matched order when the scheduler needs pages back —
BACKPRESSURE drives eviction, never a mid-request free.  Cross-tier
conservation (the churn sweep walks it every step): the allocator's
``free + distinct-live == num_pages`` as always, the cache's
``host_pages`` mirrors the store's entry count, and no page is ever
HBM-pinned and host-resident at once.

The cache never dispatches device work itself: matching and insertion
are pure host bookkeeping over ints, and eviction's offload runs
through an injected callable (the scheduler's engine-backed closure),
performed at the admission points the scheduler already occupies.
"""
from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from apex_tpu.inference.kv_cache import HostPageStore, PageAllocator

__all__ = ["PrefixCache", "prefix_cache_enabled"]

_PREFIX_CACHE_ENV = "APEX_TPU_PREFIX_CACHE"


def prefix_cache_enabled() -> bool:
    """``APEX_TPU_PREFIX_CACHE``: prefix caching for paged schedulers —
    on by default (sharing is functionally transparent); ``0`` disables
    matching AND insertion (every admission prefills cold)."""
    env = os.environ.get(_PREFIX_CACHE_ENV)
    if env is None:
        return True
    return env.strip() not in ("0", "", "false", "False")


class _Edge:
    """One cached page: the tokens it holds, its residency (HBM page id
    XOR host-store handle), the LRU stamp, and (full-page edges only)
    the child node continuing the prefix."""
    __slots__ = ("page", "child", "stamp", "host")

    def __init__(self, page: int, child: Optional["_Node"], stamp: int):
        self.page: Optional[int] = page
        self.child = child
        self.stamp = stamp
        self.host: Optional[int] = None    # HostPageStore handle


class _Node:
    __slots__ = ("children", "partials")

    def __init__(self):
        self.children: Dict[Tuple[int, ...], _Edge] = {}   # ps-token edges
        self.partials: Dict[Tuple[int, ...], _Edge] = {}   # sub-page tails


def _lcp(a: Tuple[int, ...], b: Sequence[int]) -> int:
    n = min(len(a), len(b))
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i


class PrefixCache:
    """Radix tree ``token ids -> page list`` over one
    :class:`~apex_tpu.inference.kv_cache.PageAllocator`'s pages.

    ``min_hit_tokens`` (default ``page_size``) is the smallest coverage
    reported as a hit: sharing less than one page's worth of prefix
    costs a COW copy for near-zero compute savings, so sub-page
    accidental overlaps stay cold.

    ``host_store`` + ``offload`` arm the host tier (ISSUE 18):
    ``offload(page_ids)`` copies the pages' contents device→host and
    returns one store handle per page (or None when it cannot — the
    eviction then discards, exactly the pre-tier behavior).  Both None
    means single-tier operation, bit-identical to ISSUE 12.
    """

    def __init__(self, allocator: PageAllocator,
                 min_hit_tokens: Optional[int] = None, *,
                 host_store: Optional[HostPageStore] = None,
                 offload: Optional[
                     Callable[[List[int]], Optional[List[int]]]] = None):
        self._alloc = allocator
        self.page_size = allocator.page_size
        self.min_hit_tokens = (self.page_size if min_hit_tokens is None
                               else int(min_hit_tokens))
        self._host_store = host_store
        self._offload = offload
        self._root = _Node()
        self._clock = 0
        self.pinned_pages = 0          # HBM pages this cache holds a ref on
        self.evictions = 0             # HBM refs released by evict_lru
        self.host_pages = 0            # edges currently host-resident
        self.host_evictions = 0        # host-tier entries dropped for good
        self.swapped_out = 0           # lifetime pages offloaded to host

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    # -- lookup --------------------------------------------------------------
    def match_tiered(self, tokens: Sequence[int]) \
            -> Tuple[int, List[int], List[Tuple[int, int]]]:
        """Longest cached prefix of ``tokens`` ACROSS BOTH TIERS:
        ``(covered_tokens, pages, host)``.  ``pages[j]`` is the
        physical page backing page-ordinal ``j`` when HBM-resident and
        ``-1`` when host-resident; ``host`` lists the host ordinals as
        ``(ordinal, store_handle)`` pairs — the scheduler backs each
        with a freshly acquired page and swaps the content in before
        the tail's first prefill chunk.  Coverage below
        ``min_hit_tokens`` reports a miss ``(0, [], [])``.  Matched
        edges are LRU-touched in both tiers."""
        toks = [int(t) for t in tokens]
        ps = self.page_size
        node, pages, c = self._root, [], 0
        host: List[Tuple[int, int]] = []
        path: List[_Edge] = []
        while len(toks) - c >= ps:
            edge = node.children.get(tuple(toks[c:c + ps]))
            if edge is None:
                break
            path.append(edge)
            if edge.page is None:
                host.append((len(pages), edge.host))
                pages.append(-1)
            else:
                pages.append(edge.page)
            c += ps
            node = edge.child
        # boundary: best sub-page overlap against any outgoing edge
        rest = toks[c:]
        best, best_edge = 0, None
        if rest:
            for et, edge in list(node.children.items()) \
                    + list(node.partials.items()):
                n = _lcp(et, rest)
                if n > best:
                    best, best_edge = n, edge
        if best_edge is not None:
            path.append(best_edge)
            if best_edge.page is None:
                host.append((len(pages), best_edge.host))
                pages.append(-1)
            else:
                pages.append(best_edge.page)
            c += best
        if c < self.min_hit_tokens:
            return 0, [], []
        stamp = self._tick()
        for edge in path:
            edge.stamp = stamp
        return c, pages, host

    def peek_match(self, tokens: Sequence[int]) \
            -> Tuple[int, int, int]:
        """READ-ONLY coverage probe for routers (ISSUE 19):
        ``(covered_tokens, hbm_pages, host_pages)`` for the longest
        cached prefix of ``tokens`` across both tiers — the same walk
        as :meth:`match_tiered` but with ZERO side effects: no LRU
        touch, no clock tick, no counter.  A fleet front door peeks
        every replica's cache to find where a shared prefix's pages
        live; only the replica that actually ADMITS the request may
        disturb recency (a peek that stamped edges would let routing
        probes pin victims against eviction on replicas that never
        serve them)."""
        toks = [int(t) for t in tokens]
        ps = self.page_size
        node, c = self._root, 0
        hbm = host = 0
        while len(toks) - c >= ps:
            edge = node.children.get(tuple(toks[c:c + ps]))
            if edge is None:
                break
            if edge.page is None:
                host += 1
            else:
                hbm += 1
            c += ps
            node = edge.child
        rest = toks[c:]
        best, best_edge = 0, None
        if rest:
            for et, edge in list(node.children.items()) \
                    + list(node.partials.items()):
                n = _lcp(et, rest)
                if n > best:
                    best, best_edge = n, edge
        if best_edge is not None:
            if best_edge.page is None:
                host += 1
            else:
                hbm += 1
            c += best
        if c < self.min_hit_tokens:
            return 0, 0, 0
        return c, hbm, host

    def walk_edges(self) -> List[dict]:
        """Deterministic READ-ONLY walk of the radix tree, the
        sanctioned external observation surface (APX112: outside
        callers never touch ``_root``): one dict per edge, parents
        before children, siblings in sorted token order —
        ``{"path", "tokens", "kind" ("full"|"partial"), "page",
        "host", "stamp"}``.  The protocol auditor canonicalizes tree
        states and checks the tier invariant through this; no LRU
        touch, no clock tick."""
        out: List[dict] = []

        def walk(node: _Node, path: Tuple[int, ...]):
            for et in sorted(node.children):
                edge = node.children[et]
                out.append({"path": path, "tokens": et, "kind": "full",
                            "page": edge.page, "host": edge.host,
                            "stamp": edge.stamp})
                walk(edge.child, path + et)
            for et in sorted(node.partials):
                edge = node.partials[et]
                out.append({"path": path, "tokens": et,
                            "kind": "partial", "page": edge.page,
                            "host": edge.host, "stamp": edge.stamp})

        walk(self._root, ())
        return out

    def match(self, tokens: Sequence[int]) -> Tuple[int, List[int]]:
        """Single-tier view of :meth:`match_tiered` for callers that
        cannot swap in: coverage truncates at the first host-resident
        ordinal, so every returned page is HBM-live and shareable."""
        c, pages, host = self.match_tiered(tokens)
        if host:
            first = min(j for j, _ in host)
            c = min(c, first * self.page_size)
            pages = pages[:first]
            if c < self.min_hit_tokens:
                return 0, []
        return c, pages

    # -- insertion -----------------------------------------------------------
    def insert(self, tokens: Sequence[int], pages: Sequence[int]) -> int:
        """Register a prefilled prompt: ``pages`` are the physical
        pages backing ``tokens`` in order (``ceil(len(tokens) /
        page_size)`` of them).  New edges take one allocator reference
        per page (the cache's own pin); edges already present are
        deduplicated — the newcomer's identical private pages simply
        stay uncached and die with their request.  A HOST-resident edge
        on the walk is RESURRECTED instead: the newcomer's page (its
        freshly swapped-in or recomputed copy of the same content) is
        pinned and the host-store entry dropped — the swap-in commit
        path and the cold-recompute dedup path are the same move.
        Returns the number of pages newly pinned."""
        toks = [int(t) for t in tokens]
        ps = self.page_size
        full = len(toks) // ps
        if len(pages) < full + (1 if len(toks) % ps else 0):
            raise ValueError(
                f"{len(pages)} pages cannot back {len(toks)} tokens at "
                f"page size {ps}")
        stamp = self._tick()
        node, new = self._root, 0
        for j in range(full):
            et = tuple(toks[j * ps:(j + 1) * ps])
            edge = node.children.get(et)
            if edge is None:
                self._alloc.share([pages[j]])
                new += 1
                edge = _Edge(int(pages[j]), _Node(), stamp)
                node.children[et] = edge
            elif edge.page is None:
                # host -> HBM resurrection with the newcomer's copy
                self._alloc.share([pages[j]])
                new += 1
                edge.page = int(pages[j])
                if self._host_store is not None:
                    self._host_store.pop(edge.host)
                edge.host = None
                self.host_pages -= 1
            edge.stamp = stamp
            node = edge.child
        tail = tuple(toks[full * ps:])
        if tail:
            edge = node.partials.get(tail)
            if edge is None:
                self._alloc.share([pages[full]])
                new += 1
                node.partials[tail] = _Edge(int(pages[full]), None, stamp)
            else:
                edge.stamp = stamp
        self.pinned_pages += new
        return new

    # -- eviction ------------------------------------------------------------
    def _evictable(self):
        """Yield ``(stamp, parent_dict, key)`` for every HBM-evictable
        edge: any partial tail, and any HBM full-page edge whose
        subtree holds no HBM pages (a purely-host subtree no longer
        anchors its ancestors) — interior pages stay until their HBM
        subtree drains."""
        out = []

        def walk(node: _Node) -> bool:
            has_hbm = False
            for key, edge in node.partials.items():
                out.append((edge.stamp, node.partials, key))
                has_hbm = True
            for key, edge in node.children.items():
                child_has = walk(edge.child)
                if edge.page is not None:
                    if not child_has:
                        out.append((edge.stamp, node.children, key))
                    has_hbm = True
                has_hbm = has_hbm or child_has
            return has_hbm

        walk(self._root)
        return out

    def _host_evictable(self):
        """``(stamp, parent_dict, key)`` for true-leaf host edges —
        the only droppable host-tier entries (the tier invariant keeps
        the deepest edges host, so there is always one while
        ``host_pages > 0``)."""
        out = []

        def walk(node: _Node):
            for key, edge in node.children.items():
                child = edge.child
                if edge.page is None and not child.children \
                        and not child.partials:
                    out.append((edge.stamp, node.children, key))
                else:
                    walk(child)

        walk(self._root)
        return out

    def _evict_host_leaf(self) -> bool:
        """Drop the least-recently-matched host-tier leaf (the host
        tier's own LRU, run when its byte budget fills)."""
        leaves = self._host_evictable()
        if not leaves:
            return False
        _, parent, key = min(leaves, key=lambda t: t[0])
        edge = parent.pop(key)
        if self._host_store is not None:
            self._host_store.pop(edge.host)
        self.host_pages -= 1
        self.host_evictions += 1
        return True

    def _drop_host_subtree(self, node: _Node) -> None:
        """Drop every host-tier entry under ``node`` (an HBM-evictable
        victim's subtree holds only host full-page edges — partials
        and HBM pages would have anchored it)."""
        for edge in node.children.values():
            if edge.page is None:
                if self._host_store is not None:
                    self._host_store.pop(edge.host)
                self.host_pages -= 1
                self.host_evictions += 1
            self._drop_host_subtree(edge.child)

    def _offload_batch(self, victims: List[_Edge]) -> Dict[_Edge, int]:
        """Copy full-page victims device→host in ONE batched extract
        BEFORE their HBM refs drop; returns ``{edge: handle}`` for the
        pages parked.  Partial-tail edges are never offloaded (sub-page
        recompute is cheaper than a swap) and victims the host budget
        cannot hold — even after dropping host-LRU leaves — are
        discarded exactly as before the tier existed (oldest first, so
        the budget keeps the most recently matched)."""
        if self._offload is None or self._host_store is None:
            return {}
        full = [e for e in victims if e.child is not None]
        while full and not self._host_store.fits(len(full)):
            if not self._evict_host_leaf():
                store = self._host_store
                room = max(0, (store.capacity_bytes - store.bytes_used)
                           // store.page_bytes)
                full = full[len(full) - room:] if room else []
                break
        if not full:
            return {}
        handles = self._offload([e.page for e in full])
        if handles is None:
            return {}
        self.swapped_out += len(full)
        return dict(zip(full, handles))

    def evict_lru(self, pages_wanted: int) -> int:
        """Release cached HBM references, least-recently-matched
        evictable edges first, until ``pages_wanted`` pages have
        RETURNED to the free list (a released page still shared by a
        live request frees nothing, so eviction keeps going) or the
        cache holds no HBM pages.  Returns the number of pages actually
        freed.

        With the host tier armed, each batch of full-page victims is
        offloaded device→host FIRST (one batched extract while the
        pages are still pinned), then released: the HBM page returns to
        the free list immediately and the edge transitions to its
        ``host`` state instead of being deleted.  One tree walk selects
        a whole BATCH of victims (oldest first, sized by PREDICTED
        frees — a refcount-1 page frees on release, a shared one does
        not); the tree is re-walked only when the batch is exhausted
        (transitioning or popping an edge can expose its parent)."""
        freed0 = self._alloc.free_pages

        def done():
            return self._alloc.free_pages - freed0 >= pages_wanted

        while not done():
            leaves = sorted(self._evictable(), key=lambda t: t[0])
            if not leaves:
                break
            victims = []
            predicted = self._alloc.free_pages - freed0
            for _, parent, key in leaves:
                if predicted >= pages_wanted:
                    break
                victims.append((parent, key))
                if self._alloc.refcount(parent[key].page) == 1:
                    predicted += 1
            handles = self._offload_batch(
                [parent[key] for parent, key in victims])
            for parent, key in victims:
                edge = parent[key]
                self._alloc.release([edge.page])
                self.pinned_pages -= 1
                self.evictions += 1
                if edge in handles:
                    edge.page = None
                    edge.host = handles[edge]
                    self.host_pages += 1
                else:
                    parent.pop(key)
                    if edge.child is not None:
                        self._drop_host_subtree(edge.child)
        return self._alloc.free_pages - freed0

    def clear(self) -> None:
        """Release every cached HBM reference and drop every host-tier
        entry (cache teardown)."""
        def walk(node: _Node):
            for edge in node.partials.values():
                self._alloc.release([edge.page])
            for edge in node.children.values():
                if edge.page is None:
                    if self._host_store is not None:
                        self._host_store.pop(edge.host)
                else:
                    self._alloc.release([edge.page])
                walk(edge.child)

        walk(self._root)
        self._root = _Node()
        self.pinned_pages = 0
        self.host_pages = 0
