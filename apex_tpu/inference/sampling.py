"""Token sampling: greedy / temperature / top-k with explicit key
threading.

JAX PRNG discipline (the analyzer's APX103 rule): a key is a VALUE —
every sampling call consumes exactly one key the caller derived for it,
and nothing here ever reuses a key.  The engine folds the step counter
into its base key (``jax.random.fold_in``) so N decode steps draw N
independent keys from one seed, in-program, with no key array carried in
the device state.

``sample_token`` is the single entry the engine compiles into the
prefill/decode executables: the config is static (a frozen dataclass —
greedy compiles to pure argmax with the PRNG dead-code-eliminated;
sampled configs compile the categorical draw in).
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

__all__ = ["SamplingConfig", "greedy", "sample_token"]


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """Static sampling policy (hashable: lives in jit closures).

    ``temperature = 0`` means greedy (matching the HF convention);
    ``top_k = 0`` means the full vocabulary.
    """
    temperature: float = 0.0
    top_k: int = 0

    def __post_init__(self):
        # fail fast: a negative temperature would silently INVERT the
        # distribution (categorical over -logits samples the least
        # likely tokens), degrading generation with no error anywhere
        if self.temperature < 0.0:
            raise ValueError(
                f"temperature must be >= 0 (0 = greedy), got "
                f"{self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0 (0 = full vocab), "
                             f"got {self.top_k}")

    @property
    def is_greedy(self) -> bool:
        return self.temperature == 0.0


def greedy(logits):
    """Argmax over the last axis -> int32 token ids."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _top_k_mask(logits, k: int):
    """Mask logits outside the per-row top k to -inf (k static)."""
    thresh = jax.lax.top_k(logits, k)[0][..., -1:]        # k-th largest
    return jnp.where(logits >= thresh, logits, -jnp.inf)


def sample_token(logits, key, cfg: SamplingConfig):
    """Draw one token per row of ``logits [..., vocab]``.

    ``key`` is consumed (derive a fresh one per call — the engine folds
    the step index into its base key); it is ignored under greedy but
    kept in the signature so the compiled decode step has ONE shape for
    every policy.
    """
    if cfg.is_greedy:
        return greedy(logits)
    scaled = logits.astype(jnp.float32) / cfg.temperature
    if cfg.top_k:
        scaled = _top_k_mask(scaled, min(cfg.top_k, logits.shape[-1]))
    return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
