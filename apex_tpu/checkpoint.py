"""Checkpoint/resume for train state incl. amp scaler.

Reference contract (SURVEY.md §5): model/optimizer checkpointing is
``torch.save/load`` + ``amp.state_dict()`` persisting the loss-scaler
state, with ``tests/L0/run_amp/test_checkpointing.py`` pinning "resume ⇒
identical continuation".

TPU-native: one orbax-backed (with a numpy fallback) pytree checkpoint
holding params, optimizer state (the fused optimizers' ``state_dict()``),
and scaler scale/growth counters.  Everything is a pytree of arrays, so
one ``save``/``restore`` pair covers the whole train state.
"""
from __future__ import annotations

import os
import pickle
from typing import Any, Optional

import jax
import numpy as np

__all__ = ["save_checkpoint", "load_checkpoint"]


def _to_host(tree):
    return jax.tree.map(lambda x: np.asarray(x), tree)


def save_checkpoint(path: str, state: Any) -> None:
    """Persist a pytree train state (params / optimizer ``state_dict()`` /
    amp ``state_dict()`` / step counters).

    Uses orbax when available (sharded-array aware), else a plain
    numpy-pickle of the host-transferred tree.
    """
    path = os.path.abspath(path)
    try:
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(path, _to_host(state), force=True)
    except Exception:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            pickle.dump(_to_host(state), f)


def load_checkpoint(path: str, like: Optional[Any] = None) -> Any:
    """Restore the pytree saved by :func:`save_checkpoint`.

    ``like`` (optional) provides the target structure/dtypes for orbax
    restoration; without it the raw stored tree is returned.
    """
    path = os.path.abspath(path)
    if os.path.isdir(path):
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        if like is not None:
            return ckptr.restore(path, item=_to_host(like))
        return ckptr.restore(path)
    with open(path, "rb") as f:
        return pickle.load(f)
